"""Logging, stdout/stderr tee, and reproducibility diagnostics.

Capability parity with /root/reference/dmlcloud/util/logging.py:
``IORedirector`` tee into the checkpoint dir (:18-81), ``DevNullIO`` (:84-90),
rank-aware log handlers (:93-108), experiment header (:119-128), and the deep
diagnostics block (:131-173) — with the CUDA/`nvidia-smi` section replaced by
its TPU equivalent: device kind & count, process topology, default backend,
libtpu/jaxlib versions, and the mesh shape when one is active.
"""

from __future__ import annotations

import io
import logging
import os
import sys
from pathlib import Path

import jax

from . import slurm
from .git import git_hash
from .thirdparty import ML_MODULES, is_imported, try_get_version

logger = logging.getLogger("dmlcloud_tpu")

BANNER = r"""
     _           _                 _      _
  __| |_ __ ___ | | ___ | ___  _  _| | __ | |_ _ __  _  _
 / _` | '_ ` _ \| |/ __|/ / _ \| || | |/ _` | __| '_ \| || |
| (_| | | | | | | | (__| | (_) | || | | (_) | |_| |_) | || |
 \__,_|_| |_| |_|_|\___|\_\___/ \_,_|_|\__,_|\__| .__/ \_,_|
                                                |_|   on TPU
"""


class _RemoteLogFile:
    """log.txt tee for object stores (no append support): buffers writes,
    prepends any PREVIOUS attempt's log (a requeued run must not destroy the
    crashed attempt's history, which open('w') would), and re-uploads the
    whole object at most every ``flush_interval`` seconds and at close."""

    def __init__(self, path_str: str, flush_interval: float = 30.0):
        import time

        from etils import epath

        self._path = epath.Path(path_str)
        self._flush_interval = flush_interval
        self._time = time
        self._last_upload = 0.0
        try:
            self._parts: list[str] = [self._path.read_text()] if self._path.exists() else []
        except Exception:
            self._parts = []

    def write(self, s: str) -> int:
        self._parts.append(s)
        return len(s)

    def flush(self) -> None:
        now = self._time.monotonic()
        if now - self._last_upload >= self._flush_interval:
            self._upload()
            self._last_upload = now

    def _upload(self) -> None:
        try:
            self._path.write_text("".join(self._parts))
        except Exception:  # pragma: no cover - log upload must never kill the run
            pass

    def close(self) -> None:
        self._upload()


class IORedirector:
    """Tee ``sys.stdout``/``sys.stderr`` into a log file while still writing to
    the original streams (reference util/logging.py:18-81). Installed root-only
    once the checkpoint dir exists; uninstall restores the originals."""

    class _Tee(io.TextIOBase):
        def __init__(self, parent: "IORedirector", stream):
            self.parent = parent
            self.stream = stream

        def write(self, s) -> int:
            n = self.stream.write(s)
            if self.parent.file is not None:
                try:
                    self.parent.file.write(s)
                except ValueError:  # file already closed
                    pass
            return n

        def flush(self) -> None:
            self.stream.flush()
            if self.parent.file is not None:
                try:
                    self.parent.file.flush()
                except ValueError:
                    pass

        @property
        def encoding(self):
            return getattr(self.stream, "encoding", "utf-8")

        def isatty(self) -> bool:
            return self.stream.isatty()

        def fileno(self) -> int:
            return self.stream.fileno()

    def __init__(self, log_file: str | Path):
        self.log_path = log_file
        self.file = None
        self._orig_stdout = None
        self._orig_stderr = None

    def install(self) -> None:
        if self.file is not None:
            return
        path_str = os.fspath(self.log_path)
        if "://" in path_str:
            self.file = _RemoteLogFile(path_str)
        else:
            self.file = open(path_str, "a", buffering=1)
        self._orig_stdout = sys.stdout
        self._orig_stderr = sys.stderr
        sys.stdout = IORedirector._Tee(self, self._orig_stdout)
        sys.stderr = IORedirector._Tee(self, self._orig_stderr)

    def uninstall(self) -> None:
        if self.file is None:
            return
        sys.stdout = self._orig_stdout
        sys.stderr = self._orig_stderr
        self.file.close()
        self.file = None


class DevNullIO(io.TextIOBase):
    """A sink that swallows writes (reference util/logging.py:84-90)."""

    def write(self, s) -> int:
        return len(s)

    def flush(self) -> None:
        pass


def add_log_handlers(logger_: logging.Logger | None = None, is_root: bool | None = None) -> None:
    """Attach the rank-aware handlers: root logs at INFO, non-root at WARNING;
    records below WARNING go to stdout, WARNING+ to stderr (reference
    util/logging.py:93-108)."""
    logger_ = logger_ or logger
    # Rebuild rather than keep handlers: existing ones may be bound to a
    # stream that no longer exists (redirected/captured stdout from an
    # earlier run in the same process).
    for h in list(logger_.handlers):
        logger_.removeHandler(h)
    if is_root is None:
        from ..parallel.runtime import is_root as _is_root

        is_root = _is_root()
    logger_.setLevel(logging.INFO if is_root else logging.WARNING)

    stdout_handler = logging.StreamHandler(sys.stdout)
    stdout_handler.setLevel(logging.DEBUG)
    stdout_handler.addFilter(lambda rec: rec.levelno < logging.WARNING)
    stdout_handler.setFormatter(logging.Formatter("%(message)s"))
    logger_.addHandler(stdout_handler)

    stderr_handler = logging.StreamHandler(sys.stderr)
    stderr_handler.setLevel(logging.WARNING)
    stderr_handler.setFormatter(logging.Formatter("%(levelname)s: %(message)s"))
    logger_.addHandler(stderr_handler)


def flush_log_handlers(logger_: logging.Logger | None = None) -> None:
    for h in (logger_ or logger).handlers:
        h.flush()


def experiment_header(name: str | None, checkpoint_path: str | None, start_time) -> str:
    """Banner + run identity block (reference util/logging.py:119-128)."""
    lines = [BANNER]
    lines.append(f"Experiment: {name if name else '[unnamed]'}")
    lines.append(f"Checkpoint: {checkpoint_path if checkpoint_path else '[disabled]'}")
    lines.append(f"Start time: {start_time}")
    return "\n".join(lines)


def accelerator_info() -> dict:
    """Structured accelerator probe — ONE source for the text diagnostics
    block and the ``python -m dmlcloud_tpu --json`` CLI. Returns
    ``{"error": ...}`` instead of raising when backend init fails
    (diagnostics must never kill a run — or the CLI that debugs one)."""
    try:
        devices = jax.devices()
        kinds = sorted({d.device_kind for d in devices})
        info = {
            "backend": jax.default_backend(),
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "global_devices": len(devices),
            "local_devices": jax.local_device_count(),
            "device_kinds": kinds,
            "device_kind_counts": {k: sum(1 for d in devices if d.device_kind == k) for k in kinds},
        }
        coords = getattr(devices[0], "coords", None)
        if coords is not None:
            info["device0_coords"] = list(coords)
        return info
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def general_diagnostics() -> str:
    """The reproducibility block logged at run start (reference
    util/logging.py:131-173) — argv, cwd, host, user, git state, Python env,
    then TPU topology in place of `nvidia-smi`, imported ML module versions,
    and the Slurm environment dump."""
    import getpass
    import socket

    lines = []
    lines.append("* GENERAL:")
    lines.append(f"    - argv: {sys.argv}")
    lines.append(f"    - cwd: {os.getcwd()}")
    try:
        lines.append(f"    - host: {socket.gethostname()}")
        lines.append(f"    - user: {getpass.getuser()}")
    except Exception:
        pass
    h = git_hash()
    if h:
        lines.append(f"    - git-hash: {h}")
    conda = os.environ.get("CONDA_DEFAULT_ENV")
    if conda:
        lines.append(f"    - conda-env: {conda}")
    lines.append(f"    - sys-prefix: {sys.prefix}")
    lines.append(f"    - python: {sys.version.split()[0]}")

    lines.append("* ACCELERATORS:")
    acc = accelerator_info()
    if "error" in acc:
        lines.append(f"    - <error probing devices: {acc['error']}>")
    else:
        lines.append(f"    - backend: {acc['backend']}")
        lines.append(f"    - process: {acc['process_index']}/{acc['process_count']}")
        lines.append(f"    - devices: {acc['global_devices']} global, {acc['local_devices']} local")
        for kind, n in acc["device_kind_counts"].items():
            lines.append(f"    - {n}x {kind}")
        if "device0_coords" in acc:
            lines.append(f"    - device 0 coords: {acc['device0_coords']}")

    lines.append("* VERSIONS:")
    for mod in ML_MODULES:
        if is_imported(mod):
            v = try_get_version(mod)
            if v:
                lines.append(f"    - {mod}: {v}")
    libtpu = try_get_version("libtpu")
    if libtpu:
        lines.append(f"    - libtpu: {libtpu}")

    if slurm.slurm_available():
        lines.append("* SLURM:")
        for key in sorted(k for k in os.environ if k.startswith("SLURM")):
            lines.append(f"    - {key}: {os.environ[key]}")

    return "\n".join(lines)
