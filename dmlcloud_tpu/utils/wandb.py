"""Weights & Biases glue: lazy import so wandb stays an optional dependency.

Parity with /root/reference/dmlcloud/util/wandb.py:5-30 — a module proxy that
defers the (slow, network-touching) ``import wandb`` until first attribute
access, plus the startup-timeout knob and imported/initialized probes.
"""

from __future__ import annotations

import os
import sys


class WandbModuleWrapper:
    """Proxy object that imports wandb on first attribute access."""

    def _module(self):
        import wandb as _wandb  # deferred: may not be installed

        return _wandb

    def __getattr__(self, name: str):
        return getattr(self._module(), name)

    def __setattr__(self, name: str, value) -> None:
        setattr(self._module(), name, value)


wandb = WandbModuleWrapper()


def wandb_set_startup_timeout(seconds: int) -> None:
    """Raise the wandb service wait (``WANDB__SERVICE_WAIT``) — slow shared
    filesystems on clusters routinely exceed the default."""
    if not isinstance(seconds, int) or seconds <= 0:
        raise ValueError("seconds must be a positive int")
    os.environ["WANDB__SERVICE_WAIT"] = str(seconds)


def wandb_is_imported() -> bool:
    return "wandb" in sys.modules


def wandb_is_initialized() -> bool:
    if not wandb_is_imported():
        return False
    import wandb as _wandb

    return _wandb.run is not None
