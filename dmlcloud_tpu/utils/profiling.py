"""Profiling helpers — the idiomatic upgrade over the reference's wall-clock
timers (reference stage.py:299,303,314 tracks only ``misc/step_time_ms``;
SURVEY.md §5.1): capture real XLA traces viewable in TensorBoard/Perfetto.

- ``trace(logdir)``: context manager around ``jax.profiler`` — wrap any block
  (a few train steps) to record device timelines, HLO op breakdown, and memory.
- ``profile_steps(fn, n, logdir)``: run a callable ``n`` times under a trace.
- ``StepTimer``: dispatch-to-dispatch wall timer with p50/p95 summaries, the
  host-side complement used by bench.py.
"""

from __future__ import annotations

import time
from contextlib import contextmanager

import numpy as np

__all__ = ["trace", "profile_steps", "StepTimer"]


@contextmanager
def trace(logdir: str, create_perfetto_link: bool = False):
    """Record a JAX profiler trace into ``logdir`` (TensorBoard-compatible).

    Traces include the TPU device timeline, HLO-level op costs, and host
    activity — strictly more than the reference's per-step wall timers.
    """
    import jax

    jax.profiler.start_trace(logdir, create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def profile_steps(fn, n: int, logdir: str, *args, **kwargs):
    """Run ``fn(*args, **kwargs)`` ``n`` times under a trace; returns the last
    result (blocked until ready so the trace covers real device work)."""
    import jax

    result = None
    with trace(logdir):
        for _ in range(n):
            result = fn(*args, **kwargs)
        jax.block_until_ready(result)
    return result


class StepTimer:
    """Dispatch-to-dispatch step timer with percentile summaries."""

    def __init__(self):
        self._t: list[float] = []
        self._last: float | None = None

    def tick(self) -> None:
        now = time.perf_counter_ns()
        if self._last is not None:
            self._t.append((now - self._last) / 1e6)
        self._last = now

    @property
    def count(self) -> int:
        return len(self._t)

    def summary(self) -> dict[str, float]:
        if not self._t:
            return {}
        arr = np.asarray(self._t)
        return {
            "mean_ms": float(arr.mean()),
            "p50_ms": float(np.percentile(arr, 50)),
            "p95_ms": float(np.percentile(arr, 95)),
            "max_ms": float(arr.max()),
        }
