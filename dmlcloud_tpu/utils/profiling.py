"""Profiling helpers — the idiomatic upgrade over the reference's wall-clock
timers (reference stage.py:299,303,314 tracks only ``misc/step_time_ms``;
SURVEY.md §5.1): capture real XLA traces viewable in TensorBoard/Perfetto.

- ``trace(logdir)``: context manager around ``jax.profiler`` — wrap any block
  (a few train steps) to record device timelines, HLO op breakdown, and memory.
- ``profile_steps(fn, n, logdir)``: run a callable ``n`` times under a trace.
- ``roofline(trace_dir)``: parse the trace's own per-op hardware counters
  (hlo_category / flops / bytes_accessed) into a per-category roofline
  table next to the chip's peaks — the analysis that settled whether the
  ResNet bench was MXU- or HBM-bound (doc/performance.md §6).
- ``StepTimer``: dispatch-to-dispatch wall timer with p50/p95 summaries, the
  host-side complement used by bench.py.
- ``StallTimer``: accumulates the wall-clock the host spends *blocked* on
  device results or pending checkpoint commits — the overlap engine's
  ``misc/host_stall_ms`` metric (stage.py) and the host-stall fraction
  ``bench.py --overlap-child`` reports.
"""

from __future__ import annotations

import collections
import glob
import os
import time
from contextlib import contextmanager

import numpy as np

__all__ = ["trace", "profile_steps", "roofline", "format_roofline", "StepTimer", "StallTimer"]


class StallTimer:
    """Accumulates host-stall time: every block the training loop spends
    waiting on the device (value fetches, ``block_until_ready``, waiting for
    a previous async checkpoint to commit) runs under ``measure()`` and adds
    to one counter. The epoch loop resets it per epoch and publishes the
    total as ``misc/host_stall_ms`` — the number the overlap engine exists
    to drive toward zero."""

    def __init__(self):
        self._ns = 0
        self._depth = 0
        self._outer_t0 = 0
        self._outer_label: str | None = None
        #: label -> accumulated ns for spans measured with ``measure(label=)``
        #: — how the goodput ledger splits checkpoint waits from metric
        #: readbacks inside one total (telemetry/goodput.py)
        self._label_ns: dict[str, int] = {}

    @contextmanager
    def measure(self, label: str | None = None):
        """Time a host-blocked span. Nesting-safe: a ``measure()`` (or
        ``block()``/``fetch()``) inside an outer ``measure()`` contributes
        nothing of its own — only the outermost span accumulates, so nested
        blocks are never double-counted. ``label`` attributes the outermost
        span to a named bucket (``label_ms``) and, when the telemetry
        journal is armed, emits it as a typed span.

        Measured spans are also *sanctioned* for the runtime sanitizer
        (lint/sanitize.py) — the same exemption the static DML101 rule
        grants ``with <x>.measure():`` blocks: an accounted sync is the
        framework's own pattern, never a violation."""
        from ..lint.sanitize import sanctioned

        self._depth += 1
        if self._depth == 1:
            self._outer_t0 = time.perf_counter_ns()
            self._outer_label = label
        try:
            with sanctioned():
                yield
        finally:
            self._depth -= 1
            if self._depth == 0:
                t1 = time.perf_counter_ns()
                dt = t1 - self._outer_t0
                self._ns += dt
                label = self._outer_label
                if label is not None:
                    self._label_ns[label] = self._label_ns.get(label, 0) + dt
                    from ..telemetry import journal as _journal

                    if _journal.active_journal() is not None:
                        kind = label if label in _journal.SPAN_KINDS else "host_stall"
                        _journal.emit(
                            kind,
                            self._outer_t0 / 1e9,
                            t1 / 1e9,
                            label=None if kind == label else label,
                        )

    def block(self, tree, label: str | None = "metric_readback"):
        """``jax.block_until_ready`` under the timer (the epoch-end sync)."""
        import jax

        with self.measure(label=label):
            return jax.block_until_ready(tree)

    def fetch(self, value, label: str | None = "metric_readback"):
        """Fetch ``value`` to host under the timer, returning a numpy array."""
        with self.measure(label=label):
            return np.asarray(value)

    @property
    def ms(self) -> float:
        return self._ns / 1e6

    def label_ms(self, label: str) -> float:
        """Accumulated ms of outermost spans measured under ``label``."""
        return self._label_ns.get(label, 0) / 1e6

    def reset(self) -> None:
        self._ns = 0
        self._label_ns.clear()


@contextmanager
def trace(logdir: str, create_perfetto_link: bool = False):
    """Record a JAX profiler trace into ``logdir`` (TensorBoard-compatible).

    Traces include the TPU device timeline, HLO-level op costs, and host
    activity — strictly more than the reference's per-step wall timers.
    """
    import jax

    jax.profiler.start_trace(logdir, create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def profile_steps(fn, n: int, logdir: str, *args, **kwargs):
    """Run ``fn(*args, **kwargs)`` ``n`` times under a trace; returns the last
    result (blocked until ready so the trace covers real device work)."""
    import jax

    result = None
    with trace(logdir):
        for _ in range(n):
            result = fn(*args, **kwargs)
        jax.block_until_ready(result)
    return result


#: bf16 peak FLOP/s by TPU device_kind substring (fallback: v5e's 197e12).
#: The same table bench.py uses for its MFU lines.
PEAK_BF16_FLOPS = {
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}


def peak_flops_for_kind(kind: str) -> float | None:
    """Peak bf16 FLOP/s for a ``device_kind`` string, or None if unknown
    (callers decide whether to fall back — an unknowing fallback turns MFU
    numbers on non-TPU backends into nonsense)."""
    kind = kind.lower()
    for key, peak in PEAK_BF16_FLOPS.items():
        if key in kind:
            return peak
    return None


def chip_peak_flops(device=None) -> float:
    """Peak bf16 FLOP/s of ``device`` (default: the first local device);
    unknown device kinds fall back to the v5e peak."""
    import jax

    kind = (device or jax.local_devices()[0]).device_kind
    return peak_flops_for_kind(kind) or 197e12


def _xplane_pb2():
    # generated protos predate protobuf 5's C++ descriptor pool checks
    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except ImportError as e:  # tensorflow ships the xplane schema
        raise ImportError(
            "roofline analysis parses the trace's xplane.pb, which needs the "
            "tensorflow package for the proto schema only"
        ) from e
    return xplane_pb2


def _stat_value(plane, st):
    """Decode an XStat across its value oneof (incl. uint64 and interned refs)."""
    kind = st.WhichOneof("value")
    if kind is None:
        return None
    if kind == "ref_value":  # string interned in stat_metadata
        return plane.stat_metadata[st.ref_value].name
    return getattr(st, kind)


def roofline(trace_dir: str, steps: int = 1) -> tuple[dict, list[dict]]:
    """Aggregate a ``jax.profiler`` trace by HLO category from the chip's own
    op counters. Returns ``(peaks, rows)``: ``peaks`` has the device type and
    hardware peaks (TFLOP/s, HBM GB/s); each row has ``category``,
    ``time_frac``, ``ms_per_step``, ``tflops`` (achieved), ``gbps``
    (achieved), ``n_per_step``. ``steps`` = timed steps inside the trace.

    Counter conventions: ``flops`` counts multiply-add as TWO ops (the MFU
    convention — compare against peak directly); ``bytes_accessed`` includes
    VMEM-resident reads, so aggregates may exceed the HBM peak while per-op
    numbers near it still identify bandwidth-bound ops."""
    xplane_pb2 = _xplane_pb2()
    paths = sorted(glob.glob(os.path.join(trace_dir, "plugins/profile/*/*.xplane.pb")))
    if not paths:
        raise FileNotFoundError(f"no xplane.pb under {trace_dir} (not a jax.profiler trace dir?)")
    xs = xplane_pb2.XSpace()
    with open(paths[-1], "rb") as f:
        xs.ParseFromString(f.read())
    plane = next(
        (
            p
            for p in xs.planes
            if p.name.startswith("/device:TPU") and any(l.name == "XLA Ops" for l in p.lines)
        ),
        None,
    )
    if plane is None:
        raise ValueError("no TPU device plane with an 'XLA Ops' line in this trace")

    def stats_of(stats):
        return {plane.stat_metadata[st.metadata_id].name: _stat_value(plane, st) for st in stats}

    pstats = stats_of(plane.stats)
    peaks = {
        "device": pstats.get("device_type_string", "?"),
        "peak_tflops": float(pstats.get("peak_teraflops_per_second", 0) or 0),
        "peak_hbm_gbps": float(pstats.get("peak_hbm_bw_gigabytes_per_second", 0) or 0),
    }
    (ops_line,) = [l for l in plane.lines if l.name == "XLA Ops"]
    agg = collections.defaultdict(lambda: [0.0, 0.0, 0.0, 0])  # ps, flops, bytes, n
    for ev in ops_line.events:
        s = stats_of(plane.event_metadata[ev.metadata_id].stats)
        row = agg[s.get("hlo_category", "?")]
        row[0] += ev.duration_ps
        row[1] += float(s.get("flops", 0) or 0)
        row[2] += float(s.get("bytes_accessed", 0) or 0)
        row[3] += 1
    total_ps = sum(v[0] for v in agg.values()) or 1.0
    rows = [
        {
            "category": cat,
            "time_frac": ps / total_ps,
            "ms_per_step": ps / 1e9 / steps,
            "tflops": fl / ps if ps else 0.0,  # flops/ps == TFLOP/s
            "gbps": by / (ps / 1e12) / 1e9 if ps else 0.0,
            "n_per_step": n // steps,
        }
        for cat, (ps, fl, by, n) in sorted(agg.items(), key=lambda kv: -kv[1][0])
    ]
    return peaks, rows


def format_roofline(peaks: dict, rows: list[dict], min_frac: float = 0.001) -> str:
    """Human-readable roofline table (what scripts/analyze_trace.py prints)."""
    out = [
        f"device: {peaks['device']}  peak {peaks['peak_tflops']:.0f} TF/s, "
        f"HBM {peaks['peak_hbm_gbps']:.0f} GB/s",
        f"{'category':<28}{'time%':>7}{'ms/step':>9}{'TFLOP/s':>9}{'GB/s':>8}{'n/step':>8}",
    ]
    for r in rows:
        if r["time_frac"] < min_frac:
            continue
        out.append(
            f"{r['category']:<28}{r['time_frac'] * 100:>6.1f}%{r['ms_per_step']:>8.2f}"
            f"{r['tflops']:>9.1f}{r['gbps']:>8.0f}{r['n_per_step']:>8}"
        )
    total_ms = sum(r["ms_per_step"] for r in rows)
    tf = sum(r["tflops"] * r["ms_per_step"] for r in rows) / total_ms if total_ms else 0.0
    pct = f" ({tf / peaks['peak_tflops'] * 100:.0f}% of peak)" if peaks["peak_tflops"] else ""
    out.append(f"total: {total_ms:.2f} ms/step on device; aggregate {tf:.1f} TFLOP/s{pct}")
    return "\n".join(out)


class StepTimer:
    """Dispatch-to-dispatch step timer with percentile summaries."""

    def __init__(self):
        self._t: list[float] = []
        self._last: float | None = None

    def tick(self) -> None:
        now = time.perf_counter_ns()
        if self._last is not None:
            self._t.append((now - self._last) / 1e6)
        self._last = now

    @property
    def count(self) -> int:
        return len(self._t)

    def summary(self) -> dict[str, float]:
        if not self._t:
            return {}
        arr = np.asarray(self._t)
        return {
            "mean_ms": float(arr.mean()),
            "p50_ms": float(np.percentile(arr, 50)),
            "p95_ms": float(np.percentile(arr, 95)),
            "p99_ms": float(np.percentile(arr, 99)),
            "max_ms": float(arr.max()),
            "total_ms": float(arr.sum()),
        }

    def reset(self) -> None:
        """Forget all recorded intervals AND the last tick, so the next
        ``tick()`` starts a fresh dispatch-to-dispatch sequence (no phantom
        interval spanning the reset)."""
        self._t.clear()
        self._last = None
