"""argparse extensions.

Parity with /root/reference/dmlcloud/util/argparse.py:5-31 — an ``EnumAction``
that exposes an Enum as a choice flag, mapping by lowercase member name.
"""

from __future__ import annotations

import argparse
import enum


class EnumAction(argparse.Action):
    """Argparse action for Enum-valued flags: ``--reduction mean``.

    Usage::

        parser.add_argument('--reduction', type=Reduction, action=EnumAction)
    """

    def __init__(self, **kwargs):
        enum_type = kwargs.pop("type", None)
        if enum_type is None or not issubclass(enum_type, enum.Enum):
            raise TypeError("EnumAction requires `type=<Enum subclass>`")
        kwargs.setdefault("choices", tuple(e.name.lower() for e in enum_type))
        super().__init__(**kwargs)
        self._enum = enum_type

    def __call__(self, parser, namespace, values, option_string=None):
        setattr(namespace, self.dest, self._enum[values.upper()])
