"""JSON-safe encoding for numeric pytrees (resume sidecars).

Resume metadata used to ride a pickle sidecar; unpickling executes arbitrary
code, so a tampered checkpoint directory became a code-execution vector on
resume. The payload is purely numeric — epoch counters, stop flags, metric
histories — so JSON plus a tagged ndarray encoding covers it with no code
execution on load.
"""

from __future__ import annotations

from typing import Any

import numpy as np

__all__ = ["to_jsonable", "from_jsonable"]

_ND = "__ndarray__"
_SCALAR = "__npscalar__"


def to_jsonable(obj: Any) -> Any:
    """Recursively convert a numeric pytree (dicts with str keys, lists,
    tuples, numpy arrays/scalars, Python scalars, None) into JSON-encodable
    structures. Tuples become lists; numpy values are tagged so
    ``from_jsonable`` restores dtype and shape exactly."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, (np.bool_, np.integer, np.floating)):
        return {_SCALAR: obj.item(), "dtype": str(obj.dtype)}
    if isinstance(obj, np.generic):  # complex/datetime/str_/... have no JSON form
        raise TypeError(f"numpy scalar of dtype {obj.dtype} is not JSON-encodable")
    if isinstance(obj, np.ndarray):
        if obj.dtype.kind not in "biuf":
            raise TypeError(f"ndarray of dtype {obj.dtype} is not JSON-encodable")
        return {_ND: obj.tolist(), "dtype": str(obj.dtype), "shape": list(obj.shape)}
    if isinstance(obj, (list, tuple)):
        return [to_jsonable(v) for v in obj]
    if isinstance(obj, dict):
        out = {}
        for k, v in obj.items():
            if not isinstance(k, str):
                raise TypeError(f"JSON sidecars require str keys, got {type(k).__name__}: {k!r}")
            if k in (_ND, _SCALAR):
                raise TypeError(f"dict key {k!r} collides with the ndarray encoding tag")
            out[k] = to_jsonable(v)
        return out
    # jax.Arrays and anything array-like; np.asarray of an unknown object
    # yields an object-dtype array, which the ndarray branch rejects cleanly
    # rather than recursing
    return to_jsonable(np.asarray(obj))


def from_jsonable(obj: Any) -> Any:
    """Inverse of ``to_jsonable``. Pure data transformation — never executes
    anything from the payload."""
    if isinstance(obj, dict):
        if _ND in obj:
            return np.asarray(obj[_ND], dtype=np.dtype(obj["dtype"])).reshape(obj["shape"])
        if _SCALAR in obj:
            return np.dtype(obj["dtype"]).type(obj[_SCALAR])
        return {k: from_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [from_jsonable(v) for v in obj]
    return obj
