"""Git capture for reproducibility diagnostics.

Parity with /root/reference/dmlcloud/util/git.py:4-14 — hash + uncontextualised
diff of the *user project* (see utils/project.py), recorded into the experiment
header so every run is attributable to an exact source state.
"""

from __future__ import annotations

from .project import run_in_project


def git_hash(short: bool = False) -> str | None:
    cmd = ["git", "rev-parse", "--short" if short else "--verify", "HEAD"]
    if short:
        cmd = ["git", "rev-parse", "--short", "HEAD"]
    proc = run_in_project(cmd)
    if proc is None or proc.returncode != 0:
        return None
    return proc.stdout.strip() or None


def git_diff() -> str | None:
    """``git diff -U0 --no-color HEAD`` in the user project — the minimal diff
    that, with the hash, exactly reconstructs the launched source."""
    proc = run_in_project(["git", "diff", "-U0", "--no-color", "HEAD"])
    if proc is None or proc.returncode != 0:
        return None
    return proc.stdout
