"""Third-party module probing for the diagnostics block.

Parity with /root/reference/dmlcloud/util/thirdparty.py:7-36, with the module
list re-centred on the JAX/TPU ecosystem.
"""

from __future__ import annotations

import importlib
import sys
from types import ModuleType

ML_MODULES = [
    "jax",
    "jaxlib",
    "flax",
    "optax",
    "orbax.checkpoint",
    "chex",
    "haiku",
    "einops",
    "numpy",
    "torch",
    "transformers",
    "xarray",
    "wandb",
    "pandas",
    "scipy",
]


def is_imported(name: str) -> bool:
    return name in sys.modules


def try_import(name: str) -> ModuleType | None:
    try:
        return importlib.import_module(name)
    except ImportError:
        return None


def try_get_version(name: str) -> str | None:
    mod = sys.modules.get(name)
    if mod is None:
        return None
    return getattr(mod, "__version__", None)
