"""Version-compat layer over Orbax's preservation-policy API.

The keep-best retention path (stage.py ``checkpoint_best_metric``) composes
``AnyPreservationPolicy([LatestN(1), BestN(...)])`` — an API that newer Orbax
ships as ``orbax.checkpoint.checkpoint_managers`` but that older releases
(e.g. 0.7.x) do not have at all. Import the policy classes from HERE, never
from orbax directly:

- on new Orbax the names re-export the real classes and
  ``CheckpointDir.state_manager`` passes ``preservation_policy`` straight
  through to ``CheckpointManagerOptions``;
- on old Orbax the names are lightweight dataclass stand-ins with identical
  fields, and ``CheckpointDir`` evaluates the policy itself after every save
  (``steps_to_keep`` below) and deletes the rest via ``manager.delete`` —
  same retention semantics, implemented host-side.

The shim deliberately covers only the combinators this codebase uses
(``LatestN``, ``BestN``, ``AnyPreservationPolicy`` = keep if ANY member
keeps); anything fancier should require new Orbax for real.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

__all__ = [
    "HAS_PRESERVATION_POLICIES",
    "AnyPreservationPolicy",
    "BestN",
    "LatestN",
    "is_shim_policy",
    "steps_to_keep",
]

try:
    from orbax.checkpoint import checkpoint_managers as _ocm

    HAS_PRESERVATION_POLICIES = True
    AnyPreservationPolicy = _ocm.AnyPreservationPolicy
    LatestN = _ocm.LatestN
    BestN = _ocm.BestN
except ImportError:  # old orbax: dataclass stand-ins, retention applied by us
    HAS_PRESERVATION_POLICIES = False

    @dataclasses.dataclass
    class LatestN:  # noqa: F811 — intentional shadowing of the real class
        """Keep the ``n`` most recent steps."""

        n: int = 1

    @dataclasses.dataclass
    class BestN:  # noqa: F811
        """Keep the ``n`` best steps by ``get_metric_fn`` over the metrics
        dict passed to ``save``. ``reverse=False`` means larger is better
        (matching Orbax); metricless steps survive only when
        ``keep_checkpoints_without_metrics``."""

        get_metric_fn: Callable[[dict], float] = None
        reverse: bool = False
        n: int | None = None
        keep_checkpoints_without_metrics: bool = True

    @dataclasses.dataclass
    class AnyPreservationPolicy:  # noqa: F811
        """Keep a step if ANY member policy keeps it (union semantics)."""

        policies: Sequence[Any] = ()


def is_shim_policy(policy: Any) -> bool:
    """Whether ``policy`` must be evaluated host-side (old Orbax): the real
    API is absent and the object is one of the stand-ins above."""
    if HAS_PRESERVATION_POLICIES or policy is None:
        return False
    return isinstance(policy, (LatestN, BestN, AnyPreservationPolicy))


def steps_to_keep(policy: Any, steps: Sequence[int], metrics_by_step: dict[int, dict]) -> set[int]:
    """Evaluate a (shim) preservation policy over committed ``steps``.

    Returns the set of steps to KEEP; the caller deletes the complement.
    Union over ``AnyPreservationPolicy`` members, mirroring Orbax.
    """
    steps = sorted(set(int(s) for s in steps))
    members = list(policy.policies) if isinstance(policy, AnyPreservationPolicy) else [policy]
    keep: set[int] = set()
    for member in members:
        if isinstance(member, LatestN):
            keep.update(steps[-int(member.n):] if member.n else [])
        elif isinstance(member, BestN):
            ranked = [s for s in steps if s in metrics_by_step]
            unranked = [s for s in steps if s not in metrics_by_step]
            if member.keep_checkpoints_without_metrics:
                keep.update(unranked)
            # ascending sort; larger-is-better keeps the tail, reverse=True
            # (smaller is better) keeps the head — same convention as Orbax
            ranked.sort(key=lambda s: member.get_metric_fn(metrics_by_step[s]))
            if member.n is None:
                keep.update(ranked)
            elif member.n > 0:
                keep.update(ranked[-member.n:] if not member.reverse else ranked[: member.n])
        else:
            raise TypeError(
                f"unsupported preservation policy {type(member).__name__!r} on this orbax "
                "version; upgrade orbax or use LatestN/BestN/AnyPreservationPolicy from "
                "dmlcloud_tpu.utils.orbax_compat"
            )
    return keep
