from . import argparse_ext, config, git, logging, profiling, project, seed, slurm, table, tcp, thirdparty, wandb

__all__ = [
    "argparse_ext",
    "config",
    "git",
    "logging",
    "profiling",
    "project",
    "seed",
    "slurm",
    "table",
    "tcp",
    "thirdparty",
    "wandb",
]
