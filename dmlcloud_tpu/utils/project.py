"""User-project introspection: where is the script that launched this run?

Parity with /root/reference/dmlcloud/util/project.py:35-79 — resolves the
entry-point script, the enclosing project directory (walking up past package
``__init__.py`` files), and runs subprocesses rooted there. Used by the git
capture in diagnostics so the recorded hash/diff is the *user's* project, not
the framework's install dir.
"""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path


def script_path() -> Path | None:
    """Absolute path of the ``__main__`` script, or None in REPL/embedded use."""
    main = sys.modules.get("__main__")
    path = getattr(main, "__file__", None)
    if path is None:
        # setuptools console-script entry point: argv[0] is the shim.
        if sys.argv and sys.argv[0] not in ("", "-c"):
            p = Path(sys.argv[0])
            if p.exists():
                return p.resolve()
        return None
    return Path(path).resolve()


def script_dir() -> Path | None:
    p = script_path()
    return p.parent if p is not None else None


def project_dir() -> Path | None:
    """Walk upwards from the script dir past any package ``__init__.py`` files,
    returning the first non-package ancestor (the project root)."""
    d = script_dir()
    if d is None:
        return None
    while (d / "__init__.py").exists() and d.parent != d:
        d = d.parent
    return d


def run_in_project(cmd: list[str], **kwargs) -> subprocess.CompletedProcess | None:
    """Run ``cmd`` with cwd=the user's project dir (None-safe)."""
    d = project_dir()
    if d is None:
        return None
    kwargs.setdefault("capture_output", True)
    kwargs.setdefault("text", True)
    try:
        return subprocess.run(cmd, cwd=str(d), **kwargs)
    except OSError:
        return None
