"""Seeding & determinism, re-designed for JAX's functional PRNG.

The reference seeds three global RNGs (/root/reference/dmlcloud/util/seed.py:7-15).
JAX has no global RNG for traced code: randomness is an explicit ``PRNGKey``
threaded through the step function. The mapping implemented here:

- ``seed_all(seed)`` seeds the *host-side* RNGs (numpy, random — used by data
  sharding shuffles) exactly like the reference, AND returns a root
  ``jax.random.PRNGKey(seed)`` for traced code. Pass ``None`` to draw a fresh
  seed (broadcast from process 0 so all hosts agree).
- ``worker_key(key)`` folds the process index into a key so each host gets a
  distinct-but-deterministic stream (the analog of per-rank seed offsets).
- ``enable_determinism()`` turns on the XLA/JAX flags that make runs bitwise
  reproducible (deterministic reductions; partitionable threefry so sharded
  random bits don't depend on mesh layout).
"""

from __future__ import annotations

import random

import jax
import numpy as np


def seed_all(seed: int | None = None) -> jax.Array:
    """Seed host RNGs and return the root PRNG key for traced code.

    With ``seed=None``, process 0 draws a seed and broadcasts it so every host
    derives the same root key.
    """
    if seed is None:
        seed = int(np.random.SeedSequence().entropy % (2**31))
        if jax.process_count() > 1:
            from ..parallel.runtime import broadcast_object

            seed = broadcast_object(seed)
    random.seed(seed)
    np.random.seed(seed % (2**32))
    return jax.random.PRNGKey(seed)


def worker_key(key: jax.Array, process_index: int | None = None) -> jax.Array:
    """A per-host key: fold the process index into the root key."""
    if process_index is None:
        process_index = jax.process_index()
    return jax.random.fold_in(key, process_index)


def step_key(key: jax.Array, step: int) -> jax.Array:
    """A per-step key, deterministic in (root key, step)."""
    return jax.random.fold_in(key, step)


def enable_determinism() -> None:
    """Make runs bitwise-reproducible across restarts (same topology)."""
    jax.config.update("jax_threefry_partitionable", True)
    try:
        jax.config.update("jax_default_matmul_precision", "highest")
    except Exception:
        pass
