"""Minimal live console table for per-epoch progress.

The reference renders epochs through the third-party ``progress_table``
package (/root/reference/dmlcloud/stage.py:147,188-205). That dependency isn't
assumed here; this is a self-contained equivalent with the subset of the API
the Stage layer needs: named columns, cell assignment, one printed row per
epoch, live in-place updates of the in-progress row DURING the epoch
(reference stage.py:188-205 UX), and a close that draws the bottom border.

Live updates are carriage-return rewrites sent ONLY to a real terminal: when
stdout is the IORedirector tee, the rewrite targets the underlying console
stream so ``log.txt`` stays a clean one-row-per-epoch plain-ASCII record,
and when output is not a TTY at all (Slurm files, CI) live rendering is
disabled entirely.
"""

from __future__ import annotations

import sys
from typing import Any, Callable


class ProgressTable:
    def __init__(self, file=None, min_width: int = 10):
        self.file = file or sys.stdout
        self.min_width = min_width
        self.columns: list[str] = []
        self.widths: dict[str, int] = {}
        self.formatters: dict[str, Callable[[Any], str]] = {}
        self.row: dict[str, Any] = {}
        self._header_printed = False
        self._closed = False
        self._live_pending = False

    def add_column(self, name: str, width: int | None = None, formatter: Callable[[Any], str] | None = None) -> None:
        if self._header_printed:
            raise RuntimeError("cannot add columns after the first row")
        if name in self.columns:
            return
        self.columns.append(name)
        self.widths[name] = max(width or 0, len(name), self.min_width)
        if formatter:
            self.formatters[name] = formatter

    def __setitem__(self, name: str, value: Any) -> None:
        if name not in self.columns:
            self.add_column(name)
        self.row[name] = value

    def update(self, name: str, value: Any) -> None:
        self[name] = value

    def _fmt(self, name: str, value: Any) -> str:
        if value is None:
            return ""
        if name in self.formatters:
            return self.formatters[name](value)
        if isinstance(value, float):
            return f"{value:.5g}"
        try:
            import numpy as np

            if isinstance(value, np.floating) or (isinstance(value, np.ndarray) and value.ndim == 0):
                return f"{float(value):.5g}"
        except Exception:
            pass
        return str(value)

    def _border(self, left: str, mid: str, right: str) -> str:
        return left + mid.join("─" * (self.widths[c] + 2) for c in self.columns) + right

    def _print(self, s: str) -> None:
        print(s, file=self.file, flush=True)

    def _print_header(self) -> None:
        self._print(self._border("┌", "┬", "┐"))
        cells = " │ ".join(f"{c:^{self.widths[c]}}" for c in self.columns)
        self._print(f"│ {cells} │")
        self._print(self._border("├", "┼", "┤"))
        self._header_printed = True

    def live_target(self):
        """The raw console stream for in-place rewrites, or None when live
        rendering is off (not a TTY / non-root DevNullIO). Unwraps the
        IORedirector tee so the rewrites never reach log.txt."""
        stream = self.file
        inner = getattr(stream, "stream", None)  # IORedirector._Tee wraps the console
        if inner is not None and hasattr(inner, "write"):
            stream = inner
        try:
            return stream if stream.isatty() else None
        except Exception:
            return None

    def live(self, values: dict[str, Any]) -> None:
        """Rewrite the in-progress row in place with ``values`` (unknown
        column names ignored). No-op without a live console."""
        target = self.live_target()
        if target is None or self._closed or not self.columns:
            return
        for name, value in values.items():
            if name in self.columns:
                self.row[name] = value
        if not self._header_printed:
            self._print_header()
        cells = " │ ".join(f"{self._fmt(c, self.row.get(c)):>{self.widths[c]}}" for c in self.columns)
        target.write(f"\r│ {cells} │")
        target.flush()
        self._live_pending = True

    def _finish_live(self) -> None:
        if not self._live_pending:
            return
        target = self.live_target()
        if target is not None:
            target.write("\r")  # final row overwrites the live one (same width)
            target.flush()
        self._live_pending = False

    def next_row(self) -> None:
        if not self.columns:
            return
        if not self._header_printed:
            self._print_header()
        self._finish_live()
        cells = " │ ".join(f"{self._fmt(c, self.row.get(c)):>{self.widths[c]}}" for c in self.columns)
        self._print(f"│ {cells} │")
        self.row = {}

    def close(self) -> None:
        if self._closed:
            return
        self._finish_live()
        if self._header_printed:
            self._print(self._border("└", "┴", "┘"))
        self._closed = True
