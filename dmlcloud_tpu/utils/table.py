"""Minimal live console table for per-epoch progress.

The reference renders epochs through the third-party ``progress_table``
package (/root/reference/dmlcloud/stage.py:147,188-205). That dependency isn't
assumed here; this is a self-contained equivalent with the subset of the API
the Stage layer needs: named columns, cell assignment, one printed row per
epoch, and a close that draws the bottom border. Output is plain ASCII so it
stays readable in ``log.txt`` tees and Slurm output files.
"""

from __future__ import annotations

import sys
from typing import Any, Callable


class ProgressTable:
    def __init__(self, file=None, min_width: int = 10):
        self.file = file or sys.stdout
        self.min_width = min_width
        self.columns: list[str] = []
        self.widths: dict[str, int] = {}
        self.formatters: dict[str, Callable[[Any], str]] = {}
        self.row: dict[str, Any] = {}
        self._header_printed = False
        self._closed = False

    def add_column(self, name: str, width: int | None = None, formatter: Callable[[Any], str] | None = None) -> None:
        if self._header_printed:
            raise RuntimeError("cannot add columns after the first row")
        if name in self.columns:
            return
        self.columns.append(name)
        self.widths[name] = max(width or 0, len(name), self.min_width)
        if formatter:
            self.formatters[name] = formatter

    def __setitem__(self, name: str, value: Any) -> None:
        if name not in self.columns:
            self.add_column(name)
        self.row[name] = value

    def update(self, name: str, value: Any) -> None:
        self[name] = value

    def _fmt(self, name: str, value: Any) -> str:
        if value is None:
            return ""
        if name in self.formatters:
            return self.formatters[name](value)
        if isinstance(value, float):
            return f"{value:.5g}"
        try:
            import numpy as np

            if isinstance(value, np.ndarray) and value.ndim == 0:
                return f"{float(value):.5g}"
        except Exception:
            pass
        return str(value)

    def _border(self, left: str, mid: str, right: str) -> str:
        return left + mid.join("─" * (self.widths[c] + 2) for c in self.columns) + right

    def _print(self, s: str) -> None:
        print(s, file=self.file, flush=True)

    def _print_header(self) -> None:
        self._print(self._border("┌", "┬", "┐"))
        cells = " │ ".join(f"{c:^{self.widths[c]}}" for c in self.columns)
        self._print(f"│ {cells} │")
        self._print(self._border("├", "┼", "┤"))
        self._header_printed = True

    def next_row(self) -> None:
        if not self.columns:
            return
        if not self._header_printed:
            self._print_header()
        cells = " │ ".join(f"{self._fmt(c, self.row.get(c)):>{self.widths[c]}}" for c in self.columns)
        self._print(f"│ {cells} │")
        self.row = {}

    def close(self) -> None:
        if self._closed:
            return
        if self._header_printed:
            self._print(self._border("└", "┴", "┘"))
        self._closed = True
