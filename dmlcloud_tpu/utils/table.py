"""Minimal live console table for per-epoch progress.

The reference renders epochs through the third-party ``progress_table``
package (/root/reference/dmlcloud/stage.py:147,188-205). That dependency isn't
assumed here; this is a self-contained equivalent with the subset of the API
the Stage layer needs: named columns, cell assignment, one printed row per
epoch, live in-place updates of the in-progress row DURING the epoch
(reference stage.py:188-205 UX), and a close that draws the bottom border.

Live updates are carriage-return rewrites sent ONLY to a real terminal: when
stdout is the IORedirector tee, the rewrite targets the underlying console
stream so ``log.txt`` stays a clean one-row-per-epoch plain-ASCII record,
and when output is not a TTY at all (Slurm files, CI) live rendering is
disabled entirely.
"""

from __future__ import annotations

import logging
import sys
from typing import Any, Callable

_logger = logging.getLogger(__name__)

#: ANSI foreground codes for the ``color`` column option (progress_table
#: API parity). Colors are applied ONLY to live console rewrites — final
#: rows go through the IORedirector tee and must keep log.txt plain-ASCII.
_ANSI = {
    "black": "30", "red": "31", "green": "32", "yellow": "33",
    "blue": "34", "magenta": "35", "cyan": "36", "white": "37",
}

_ALIGN = {"left": "<", "center": "^", "right": ">"}

_AGGREGATES: dict[str, Callable[[Any, Any, int], Any]] = {
    "sum": lambda acc, v, n: acc + v,
    "mean": lambda acc, v, n: acc + (v - acc) / n,
    "min": lambda acc, v, n: min(acc, v),
    "max": lambda acc, v, n: max(acc, v),
}


class ProgressTable:
    def __init__(self, file=None, min_width: int = 10):
        self.file = file or sys.stdout
        self.min_width = min_width
        self.columns: list[str] = []
        self.widths: dict[str, int] = {}
        self.formatters: dict[str, Callable[[Any], str]] = {}
        self.colors: dict[str, str] = {}
        self.aligns: dict[str, str] = {}
        self.aggregates: dict[str, str] = {}
        self._agg_counts: dict[str, int] = {}
        self.row: dict[str, Any] = {}
        self._live_values: dict[str, Any] = {}  # display overlay, never committed
        self._header_printed = False
        self._closed = False
        self._live_pending = False

    def add_column(
        self,
        name: str,
        width: int | None = None,
        formatter: Callable[[Any], str] | None = None,
        color: str | None = None,
        alignment: str | None = None,
        aggregate: str | None = None,
        **extra: Any,
    ) -> None:
        """Register a column. ``color``/``alignment``/``aggregate`` follow
        the third-party ``progress_table`` API the reference forwards its
        ``table_columns`` dicts to (reference stage.py:113-130,188-205):
        aggregate in {sum, mean, min, max} folds repeated assignments within
        an epoch; unknown extras are ignored with a debug note instead of
        breaking a ``table_columns`` override written for that package."""
        if self._header_printed:
            raise RuntimeError("cannot add columns after the first row")
        if name in self.columns:
            return
        self.columns.append(name)
        self.widths[name] = max(width or 0, len(name), self.min_width)
        if formatter:
            self.formatters[name] = formatter
        if color is not None:
            if str(color).lower() in _ANSI:
                self.colors[name] = _ANSI[str(color).lower()]
            else:
                _logger.debug("ProgressTable: unknown color %r for column %r ignored", color, name)
        if alignment is not None:
            if str(alignment).lower() in _ALIGN:
                self.aligns[name] = _ALIGN[str(alignment).lower()]
            else:
                _logger.debug("ProgressTable: unknown alignment %r for column %r ignored", alignment, name)
        if aggregate is not None:
            if str(aggregate).lower() in _AGGREGATES:
                self.aggregates[name] = str(aggregate).lower()
            else:
                _logger.debug("ProgressTable: unknown aggregate %r for column %r ignored", aggregate, name)
        if extra:
            _logger.debug("ProgressTable: ignoring unsupported column options %s for %r", sorted(extra), name)

    def __setitem__(self, name: str, value: Any) -> None:
        if name not in self.columns:
            self.add_column(name)
        agg = self.aggregates.get(name)
        if agg is not None and name in self.row and self.row[name] is not None and value is not None:
            n = self._agg_counts.get(name, 1) + 1
            self._agg_counts[name] = n
            self.row[name] = _AGGREGATES[agg](self.row[name], value, n)
        else:
            self._agg_counts[name] = 1
            self.row[name] = value

    def update(self, name: str, value: Any) -> None:
        self[name] = value

    def _fmt(self, name: str, value: Any) -> str:
        if value is None:
            return ""
        if name in self.formatters:
            return self.formatters[name](value)
        if isinstance(value, float):
            return f"{value:.5g}"
        try:
            import numpy as np

            if isinstance(value, np.floating) or (isinstance(value, np.ndarray) and value.ndim == 0):
                return f"{float(value):.5g}"
        except Exception:
            pass
        return str(value)

    def _border(self, left: str, mid: str, right: str) -> str:
        return left + mid.join("─" * (self.widths[c] + 2) for c in self.columns) + right

    def _print(self, s: str) -> None:
        print(s, file=self.file, flush=True)

    def _print_header(self) -> None:
        self._print(self._border("┌", "┬", "┐"))
        cells = " │ ".join(f"{c:^{self.widths[c]}}" for c in self.columns)
        self._print(f"│ {cells} │")
        self._print(self._border("├", "┼", "┤"))
        self._header_printed = True

    def live_target(self):
        """The raw console stream for in-place rewrites, or None when live
        rendering is off (not a TTY / non-root DevNullIO). Unwraps the
        IORedirector tee so the rewrites never reach log.txt."""
        stream = self.file
        inner = getattr(stream, "stream", None)  # IORedirector._Tee wraps the console
        if inner is not None and hasattr(inner, "write"):
            stream = inner
        try:
            return stream if stream.isatty() else None
        except Exception:
            return None

    def live(self, values: dict[str, Any]) -> None:
        """Rewrite the in-progress row in place with ``values`` (unknown
        column names ignored). No-op without a live console."""
        target = self.live_target()
        if target is None or self._closed or not self.columns:
            return
        for name, value in values.items():
            if name in self.columns:
                self._live_values[name] = value
        if not self._header_printed:
            self._print_header()
        cells = " │ ".join(self._cell(c, live=True) for c in self.columns)
        target.write(f"\r│ {cells} │")
        target.flush()
        self._live_pending = True

    def _finish_live(self) -> None:
        if not self._live_pending:
            return
        target = self.live_target()
        if target is not None:
            target.write("\r")  # final row overwrites the live one (same width)
            target.flush()
        self._live_pending = False

    def _cell(self, name: str, live: bool = False) -> str:
        # live rewrites read the display overlay first; committed rows use
        # only real assignments, so live() can never pollute an aggregate
        value = self._live_values.get(name, self.row.get(name)) if live else self.row.get(name)
        text = f"{self._fmt(name, value):{self.aligns.get(name, '>')}{self.widths[name]}}"
        # color only the live console rewrite — final rows ride the tee and
        # log.txt must stay plain-ASCII
        code = self.colors.get(name) if live else None
        return f"\x1b[{code}m{text}\x1b[0m" if code else text

    def next_row(self) -> None:
        if not self.columns:
            return
        if not self._header_printed:
            self._print_header()
        self._finish_live()
        cells = " │ ".join(self._cell(c) for c in self.columns)
        self._print(f"│ {cells} │")
        self.row = {}
        self._agg_counts = {}
        self._live_values = {}

    def close(self) -> None:
        if self._closed:
            return
        self._finish_live()
        if self._header_printed:
            self._print(self._border("└", "┴", "┘"))
        self._closed = True
