"""TensorBoard metrics sink — a third observability channel next to the
console table and W&B (the reference has only those two; SURVEY.md §5.5).

Writes per-epoch tracker scalars as TensorBoard event files via
``tensorboardX`` (lazy-imported, optional — the same pattern as the wandb
glue). Pairs naturally with the profiler: ``jax.profiler`` traces land in
the same logdir, so one ``tensorboard --logdir`` shows curves AND the
XProf timeline of the exact same run."""

from __future__ import annotations

from typing import Any

__all__ = ["TensorBoardWriter", "tensorboard_available"]


def tensorboard_available() -> bool:
    try:
        import tensorboardX  # noqa: F401

        return True
    except ImportError:
        return False


class TensorBoardWriter:
    """Root-only scalar writer over a tracker's per-epoch histories."""

    def __init__(self, logdir: str):
        from tensorboardX import SummaryWriter  # deferred: optional dependency

        self._writer = SummaryWriter(str(logdir))

    def log_epoch(self, metrics: dict[str, Any], epoch: int) -> None:
        for name, value in metrics.items():
            try:
                self._writer.add_scalar(name, float(value), global_step=epoch)
            except (TypeError, ValueError):
                continue  # non-scalar tracked values stay console/wandb-only
        self._writer.flush()

    def close(self) -> None:
        self._writer.close()
