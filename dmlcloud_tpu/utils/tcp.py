"""TCP helpers for rendezvous address exchange.

Parity with /root/reference/dmlcloud/util/tcp.py:5-27 (free-port discovery and
local-IP enumeration), used by the MPI bootstrap path to agree on a
jax.distributed coordinator address.
"""

from __future__ import annotations

import socket
import subprocess


def find_free_port() -> int:
    """Bind port 0 to let the OS pick a free TCP port, and return it."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("", 0))
        return s.getsockname()[1]


def get_local_ips(use_hostname: bool = True) -> list[str]:
    """All IPs of this host. Tries ``hostname -I`` first (covers multi-NIC
    cluster nodes), then falls back to a DNS lookup of the hostname."""
    if use_hostname:
        try:
            out = subprocess.run(["hostname", "-I"], capture_output=True, text=True, timeout=5)
            ips = out.stdout.strip().split()
            if ips:
                return ips
        except Exception:
            pass
    try:
        return socket.gethostbyname_ex(socket.gethostname())[2]
    except OSError:
        return ["127.0.0.1"]
