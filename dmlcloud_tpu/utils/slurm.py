"""Slurm environment introspection.

Capability parity with the reference's Slurm probing
(/root/reference/dmlcloud/util/slurm.py:4-13), extended with the fields the
TPU bootstrap ladder needs (node lists, tasks-per-node) so that
``jax.distributed.initialize`` can be fed from Slurm alone.
"""

from __future__ import annotations

import os
import re
import subprocess


def slurm_job_id() -> str | None:
    """The current Slurm job id (``SLURM_JOB_ID``), or None outside Slurm."""
    return os.environ.get("SLURM_JOB_ID")


def slurm_step_id() -> str | None:
    """The current Slurm step id (``SLURM_STEP_ID``), or None outside Slurm."""
    return os.environ.get("SLURM_STEP_ID")


def slurm_available() -> bool:
    """True if this process runs inside a Slurm step (``SLURM_PROCID`` set)."""
    return "SLURM_PROCID" in os.environ


def slurm_rank() -> int | None:
    v = os.environ.get("SLURM_PROCID")
    return int(v) if v is not None else None


def slurm_world_size() -> int | None:
    v = os.environ.get("SLURM_NTASKS") or os.environ.get("SLURM_STEP_NUM_TASKS")
    return int(v) if v is not None else None


def slurm_local_rank() -> int | None:
    v = os.environ.get("SLURM_LOCALID")
    return int(v) if v is not None else None


def slurm_node_id() -> int | None:
    v = os.environ.get("SLURM_NODEID")
    return int(v) if v is not None else None


def slurm_tasks_per_node() -> int | None:
    """Tasks on this node, parsed from ``SLURM_STEP_TASKS_PER_NODE`` (e.g. ``"4(x2),3"``)."""
    spec = os.environ.get("SLURM_STEP_TASKS_PER_NODE") or os.environ.get("SLURM_TASKS_PER_NODE")
    if spec is None:
        return None
    node = slurm_node_id() or 0
    counts: list[int] = []
    for part in spec.split(","):
        m = re.fullmatch(r"(\d+)(?:\(x(\d+)\))?", part.strip())
        if not m:
            continue
        counts.extend([int(m.group(1))] * int(m.group(2) or 1))
    if node < len(counts):
        return counts[node]
    return counts[0] if counts else None


def slurm_head_node() -> str | None:
    """Hostname of the first node in the allocation — used as the jax.distributed
    coordinator host. Prefers ``SLURM_SRUN_COMM_HOST``; falls back to expanding
    ``SLURM_JOB_NODELIST`` via ``scontrol``."""
    host = os.environ.get("SLURM_SRUN_COMM_HOST")
    if host:
        return host
    nodelist = os.environ.get("SLURM_JOB_NODELIST") or os.environ.get("SLURM_NODELIST")
    if not nodelist:
        return None
    # Cheap expansion for the common "prefix[a-b,...]" pattern; shell out only if needed.
    m = re.match(r"^([^\[,]+)\[(\d+)", nodelist)
    if m:
        return f"{m.group(1)}{m.group(2)}"
    if "[" not in nodelist:
        return nodelist.split(",")[0]
    try:
        out = subprocess.run(
            ["scontrol", "show", "hostnames", nodelist],
            capture_output=True, text=True, timeout=10, check=True,
        )
        return out.stdout.splitlines()[0].strip()
    except Exception:
        return None
