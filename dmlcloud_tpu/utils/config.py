"""Lightweight hierarchical config with YAML round-tripping.

The reference leans on OmegaConf (/root/reference/dmlcloud/pipeline.py:21-27,
checkpoint.py:105-117). OmegaConf is not a baked dependency here, so the
framework ships its own minimal equivalent: a dict-like, attribute-accessible,
YAML-serialisable config container. ``as_config`` accepts ``Config | dict |
None`` the way the reference pipeline accepts ``OmegaConf | dict | None``, and
transparently uses OmegaConf objects if the user passes one (duck-typed via
``to_container``).
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Iterator, Mapping

import yaml


class Config(Mapping):
    """Nested dict with attribute access: ``cfg.model.lr`` == ``cfg['model']['lr']``."""

    def __init__(self, data: Mapping | None = None):
        object.__setattr__(self, "_data", {})
        if data:
            for k, v in dict(data).items():
                self[k] = v

    # -- mapping protocol ---------------------------------------------------
    def __getitem__(self, key: str) -> Any:
        return self._data[key]

    def __setitem__(self, key: str, value: Any) -> None:
        if isinstance(value, Mapping) and not isinstance(value, Config):
            value = Config(value)
        self._data[key] = value

    def __delitem__(self, key: str) -> None:
        del self._data[key]

    def __iter__(self) -> Iterator[str]:
        return iter(self._data)

    def __len__(self) -> int:
        return len(self._data)

    def __contains__(self, key: object) -> bool:
        return key in self._data

    # -- attribute access ---------------------------------------------------
    def __getattr__(self, key: str) -> Any:
        try:
            return self._data[key]
        except KeyError:
            raise AttributeError(key) from None

    def __setattr__(self, key: str, value: Any) -> None:
        self[key] = value

    def get(self, key: str, default: Any = None) -> Any:
        return self._data.get(key, default)

    def setdefault(self, key: str, default: Any = None) -> Any:
        if key not in self._data:
            self[key] = default
        return self._data[key]

    def update(self, other: Mapping) -> None:
        for k, v in dict(other).items():
            self[k] = v

    # -- conversion ---------------------------------------------------------
    def to_dict(self) -> dict:
        out = {}
        for k, v in self._data.items():
            out[k] = v.to_dict() if isinstance(v, Config) else v
        return out

    def to_yaml(self) -> str:
        return yaml.safe_dump(self.to_dict(), sort_keys=False)

    def save(self, path: str | Path) -> None:
        _as_epath(path).write_text(self.to_yaml())

    @classmethod
    def load(cls, path: str | Path) -> "Config":
        data = yaml.safe_load(_as_epath(path).read_text())
        return cls(data or {})

    def __repr__(self) -> str:
        return f"Config({self.to_dict()!r})"


def _as_epath(path):
    """URI-capable path coercion (``pathlib.Path("gs://b")`` would collapse
    the double slash); local strings behave exactly as before."""
    from etils import epath

    return path if isinstance(path, epath.Path) else epath.Path(str(path))


def as_config(obj: Any) -> Config:
    """Coerce ``Config | dict | OmegaConf | None`` to a Config."""
    if obj is None:
        return Config()
    if isinstance(obj, Config):
        return obj
    if isinstance(obj, Mapping):
        return Config(obj)
    # OmegaConf duck-typing without importing omegaconf.
    if hasattr(obj, "_content") or type(obj).__name__ in ("DictConfig",):
        try:
            from omegaconf import OmegaConf  # type: ignore

            return Config(OmegaConf.to_container(obj, resolve=True))
        except Exception:
            pass
    raise TypeError(f"cannot convert {type(obj)!r} to Config")
