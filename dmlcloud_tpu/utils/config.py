"""Lightweight hierarchical config with YAML round-tripping and
OmegaConf-style ``${...}`` interpolation.

The reference leans on OmegaConf (/root/reference/dmlcloud/pipeline.py:21-27,
checkpoint.py:105-117). OmegaConf is not a baked dependency here, so the
framework ships its own minimal equivalent: a dict-like,
attribute-accessible, YAML-serialisable config container supporting the
OmegaConf idioms the reference relies on —

- ``${a.b.c}``: reference to another key (absolute dotted path from the
  root), resolved at ACCESS time with the referenced value's type when the
  whole string is one interpolation, string-substituted otherwise.
- ``${env:VAR}`` / ``${env:VAR,default}``: environment-variable resolver.
- ``to_yaml(resolve=True)`` / ``to_dict(resolve=True)``: fully-resolved
  dumps (the reference's ``OmegaConf.to_yaml(config, resolve=True)`` at
  pipeline.py:269-270 and the resolved wandb upload at pipeline.py:154);
  saving a config keeps interpolations intact, like ``OmegaConf.save``.

``as_config`` accepts ``Config | dict | None`` the way the reference pipeline
accepts ``OmegaConf | dict | None``, and transparently converts OmegaConf
objects if the user passes one (duck-typed via ``to_container``).
"""

from __future__ import annotations

import copy
import os
import re
from pathlib import Path
from typing import Any, Iterator, Mapping

import yaml

_INTERP = re.compile(r"\$\{([^${}]+)\}")


class InterpolationError(ValueError):
    pass


def _needs_resolution(value: Any) -> bool:
    if isinstance(value, str):
        return "${" in value
    if isinstance(value, (list, tuple)):
        return any(_needs_resolution(v) for v in value)
    if isinstance(value, dict):
        return any(_needs_resolution(v) for v in value.values())
    return False


def _resolve_ref(expr: str, root: "Config", active: frozenset) -> Any:
    expr = expr.strip()
    if expr.startswith("env:"):
        name, sep, default = expr[4:].partition(",")
        value = os.environ.get(name.strip())
        if value is not None:
            return value
        if sep:
            return default.strip()
        raise InterpolationError(f"environment variable {name.strip()!r} is not set and has no default")
    if expr in active:
        raise InterpolationError(f"interpolation cycle through ${{{expr}}}")
    active = active | {expr}
    node: Any = root
    for part in expr.split("."):
        if isinstance(node, str) and "${" in node:
            # an intermediate segment may itself be an alias ("${alias.lr}"
            # where alias = "${model}") — resolve before indexing into it
            node = _resolve_value(node, root, active)
        try:
            node = node._data[part] if isinstance(node, Config) else node[part]
        except (KeyError, TypeError, IndexError):
            raise InterpolationError(f"interpolation ${{{expr}}} does not resolve to a key") from None
    return _resolve_value(node, root, active)


def _substitute(match: "re.Match", root: "Config", active: frozenset) -> str:
    value = _resolve_ref(match.group(1), root, active)
    if isinstance(value, (Config, dict, list, tuple)):
        raise InterpolationError(
            f"cannot substitute ${{{match.group(1).strip()}}} into a string: "
            f"it resolves to a {type(value).__name__} node, not a scalar"
        )
    return str(value)


def _resolve_value(value: Any, root: "Config", active: frozenset = frozenset()) -> Any:
    """Resolve interpolations in a raw value, recursing into lists/tuples and
    plain dicts. A string that is exactly one ``${...}`` keeps the referenced
    value's type; embedded occurrences are substituted as strings (scalar
    targets only). Values with no interpolation anywhere are returned AS
    STORED — container reads stay live objects that callers may mutate."""
    if not _needs_resolution(value):
        return value
    if isinstance(value, str):
        whole = _INTERP.fullmatch(value.strip())
        if whole:
            return _resolve_ref(whole.group(1), root, active)
        return _INTERP.sub(lambda m: _substitute(m, root, active), value)
    if isinstance(value, (list, tuple)):
        return type(value)(_resolve_value(v, root, active) for v in value)
    if isinstance(value, dict):
        return {k: _resolve_value(v, root, active) for k, v in value.items()}
    return value


def _plainify(value: Any) -> Any:
    """Convert any Config nodes a resolution produced (e.g. a whole-string
    ``${model}`` alias to a mapping node) into plain dicts for serialisation."""
    if isinstance(value, Config):
        return value.to_dict(resolve=True)
    if isinstance(value, (list, tuple)):
        return type(value)(_plainify(v) for v in value)
    if isinstance(value, dict):
        return {k: _plainify(v) for k, v in value.items()}
    return value


class Config(Mapping):
    """Nested dict with attribute access: ``cfg.model.lr`` == ``cfg['model']['lr']``.
    Values read through any access path have their ``${...}`` interpolations
    resolved against the root config."""

    def __init__(self, data: Mapping | None = None):
        object.__setattr__(self, "_data", {})
        object.__setattr__(self, "_parent", None)
        if data:
            # read RAW items when copying a Config — going through its
            # resolving __getitem__ would eagerly materialise (or raise on)
            # interpolations that should be copied verbatim
            items = data._data.items() if isinstance(data, Config) else dict(data).items()
            for k, v in items:
                self[k] = v

    def _root(self) -> "Config":
        node = self
        while node._parent is not None:
            node = node._parent
        return node

    # -- mapping protocol ---------------------------------------------------
    def __getitem__(self, key: str) -> Any:
        return _resolve_value(self._data[key], self._root())

    def __setitem__(self, key: str, value: Any) -> None:
        if isinstance(value, Config):
            # copy by value (OmegaConf node-assignment semantics): re-parenting
            # the original object would silently detach it from ITS tree and
            # break every ${...} in the source config
            value = Config(value)
        elif isinstance(value, Mapping):
            value = Config(value)
        elif isinstance(value, (list, tuple)):
            # lists are stored by value too — reads return the stored object
            # live (mutation persists), so sharing it across configs would
            # let a "copy" mutate its source
            value = copy.deepcopy(value)
        if isinstance(value, Config):
            object.__setattr__(value, "_parent", self)
        self._data[key] = value

    def __delitem__(self, key: str) -> None:
        del self._data[key]

    def __iter__(self) -> Iterator[str]:
        return iter(self._data)

    def __len__(self) -> int:
        return len(self._data)

    def __contains__(self, key: object) -> bool:
        return key in self._data

    # -- attribute access ---------------------------------------------------
    def __getattr__(self, key: str) -> Any:
        try:
            return self[key]
        except KeyError:
            raise AttributeError(key) from None

    def __setattr__(self, key: str, value: Any) -> None:
        self[key] = value

    def get(self, key: str, default: Any = None) -> Any:
        if key not in self._data:
            return default
        return self[key]

    def setdefault(self, key: str, default: Any = None) -> Any:
        if key not in self._data:
            self[key] = default
        return self[key]

    def update(self, other: Mapping) -> None:
        items = other._data.items() if isinstance(other, Config) else dict(other).items()
        for k, v in items:
            self[k] = v

    # -- conversion ---------------------------------------------------------
    def to_dict(self, resolve: bool = False) -> dict:
        out = {}
        for k, raw in self._data.items():
            if isinstance(raw, Config):
                out[k] = raw.to_dict(resolve=resolve)
            elif resolve:
                out[k] = _plainify(_resolve_value(raw, self._root()))
            else:
                out[k] = raw
        return out

    def resolve(self) -> "Config":
        """A new Config with every interpolation materialised (raises
        ``InterpolationError`` on dangling references or cycles)."""
        return Config(self.to_dict(resolve=True))

    def to_yaml(self, resolve: bool = False) -> str:
        return yaml.safe_dump(self.to_dict(resolve=resolve), sort_keys=False)

    def save(self, path: str | Path) -> None:
        """Write YAML with interpolations INTACT (like ``OmegaConf.save``) —
        a reloaded config keeps resolving against its current context."""
        _as_epath(path).write_text(self.to_yaml())

    @classmethod
    def load(cls, path: str | Path) -> "Config":
        data = yaml.safe_load(_as_epath(path).read_text())
        return cls(data or {})

    def __repr__(self) -> str:
        return f"Config({self.to_dict()!r})"


def _as_epath(path):
    """URI-capable path coercion (``pathlib.Path("gs://b")`` would collapse
    the double slash); local strings behave exactly as before."""
    from etils import epath

    return path if isinstance(path, epath.Path) else epath.Path(str(path))


def as_config(obj: Any) -> Config:
    """Coerce ``Config | dict | OmegaConf | None`` to a Config."""
    if obj is None:
        return Config()
    if isinstance(obj, Config):
        return obj
    if isinstance(obj, Mapping):
        return Config(obj)
    # OmegaConf duck-typing without importing omegaconf.
    if hasattr(obj, "_content") or type(obj).__name__ in ("DictConfig",):
        try:
            from omegaconf import OmegaConf  # type: ignore

            return Config(OmegaConf.to_container(obj, resolve=True))
        except Exception:
            pass
    raise TypeError(f"cannot convert {type(obj)!r} to Config")
