"""Shape bucketing: bound the compiled-signature set under ragged batches.

Every distinct batch shape that reaches a jitted step is a full XLA
compile. Real data is ragged — a partial last batch every epoch, variable
sequence lengths — so without intervention the signature count grows with
the data, and each growth event is a mid-run compile stall (the hazard
DML104/TraceGuard flags but cannot prevent). Bucketing prevents it: pad the
ragged dim up to the smallest member of a small, fixed bucket set, so the
step only ever sees ``len(buckets)`` signatures — all of which the AOT
precompiler can compile before the loop.

Padding must not change the math. For mapping batches ``pad_to_bucket``
injects a ``sample_mask`` leaf (1.0 for real rows, 0.0 for padding); a step
that reduces its per-sample loss with :func:`masked_mean` (or counts with
:func:`masked_sum`) produces losses, metrics, AND gradients identical to
the unpadded batch — padded rows multiply everything they touch by zero.
Non-mapping batches are padded without a mask (there is nowhere to put
one); masking is then the step's own responsibility, and a one-time warning
says so.
"""

from __future__ import annotations

import logging
import math
from typing import Any, Iterable, Iterator, Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "DEFAULT_MASK_KEY",
    "bucket_for",
    "bucket_iterator",
    "bucket_spec",
    "masked_mean",
    "masked_sum",
    "pad_to_bucket",
    "resolve_buckets",
]

_logger = logging.getLogger("dmlcloud_tpu")

DEFAULT_MASK_KEY = "sample_mask"


def resolve_buckets(buckets: Iterable[int]) -> tuple[int, ...]:
    """Normalise a bucket set: ints, deduplicated, ascending, all positive.
    Include your full batch size as the largest bucket — batches above it
    are an error, not a silent extra signature."""
    sizes = sorted({int(b) for b in buckets})
    if not sizes:
        raise ValueError("buckets must contain at least one size")
    if sizes[0] <= 0:
        raise ValueError(f"bucket sizes must be positive, got {sizes[0]}")
    return tuple(sizes)


def bucket_for(size: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= ``size``."""
    for b in buckets:
        if b >= size:
            return int(b)
    raise ValueError(
        f"batch size {size} exceeds the largest bucket {buckets[-1]}; include the "
        "full batch size in the bucket set"
    )


def _pad_leaf(x: Any, pad: int, axis: int):
    if pad == 0:
        return x
    ndim = getattr(x, "ndim", 0)
    if ndim <= axis:
        return x  # scalars / low-rank leaves carry no batch dim to pad
    widths = [(0, 0)] * ndim
    widths[axis] = (0, pad)
    if isinstance(x, jax.Array):
        return jnp.pad(x, widths)
    return np.pad(np.asarray(x), widths)


def pad_to_bucket(
    batch: Any,
    buckets: Sequence[int],
    axis: int = 0,
    mask_key: str = DEFAULT_MASK_KEY,
) -> Any:
    """Pad ``batch``'s ``axis`` dim (zeros at the end) up to its bucket.

    Mapping batches come back as a dict with a float32 ``mask_key`` leaf of
    length ``bucket`` (1.0 real / 0.0 padded); a pre-existing ``mask_key``
    leaf is respected — padded with zeros like any other leaf, never
    overwritten (its padding rows are zero-weight either way). Other batch
    pytrees are padded in place with no mask."""
    leaves = jax.tree_util.tree_leaves(batch)
    sizes = {leaf.shape[axis] for leaf in leaves if getattr(leaf, "ndim", 0) > axis}
    if not sizes:
        return batch
    if len(sizes) > 1:
        raise ValueError(
            f"batch leaves disagree on the size of dim {axis} ({sorted(sizes)}); "
            "bucketing pads one consistent batch dim"
        )
    size = sizes.pop()
    bucket = bucket_for(size, buckets)
    pad = bucket - size
    padded = jax.tree_util.tree_map(lambda x: _pad_leaf(x, pad, axis), batch)
    if isinstance(batch, Mapping):
        padded = dict(padded)
        if mask_key not in padded:
            mask = np.zeros(bucket, np.float32)
            mask[:size] = 1.0
            padded[mask_key] = mask
    return padded


def bucket_spec(spec: Any, bucket: int, axis: int = 0, mask_key: str = DEFAULT_MASK_KEY) -> Any:
    """The abstract (``ShapeDtypeStruct``) batch a bucket produces: every
    batched leaf's ``axis`` dim set to ``bucket``, plus the mask leaf for
    mapping specs — what the AOT precompiler lowers against, one per
    bucket."""

    def leaf(s):
        shape = list(s.shape)
        if len(shape) > axis:
            shape[axis] = int(bucket)
        return jax.ShapeDtypeStruct(tuple(shape), s.dtype)

    out = jax.tree_util.tree_map(leaf, spec)
    if isinstance(spec, Mapping):
        out = dict(out)
        if mask_key not in out:
            out[mask_key] = jax.ShapeDtypeStruct((int(bucket),), np.float32)
    return out


def bucket_iterator(
    it: Iterable[Any],
    buckets: Iterable[int],
    axis: int = 0,
    mask_key: str = DEFAULT_MASK_KEY,
) -> Iterator[Any]:
    """Wrap a host-batch iterator so every yielded batch is bucket-padded
    (mapping batches gain the mask leaf). Sits BEFORE the device transfer in
    the feeding path, so the device only ever sees bucket shapes."""
    buckets = resolve_buckets(buckets)
    warned = False
    for batch in it:
        if not warned and not isinstance(batch, Mapping):
            warned = True
            _logger.warning(
                "bucketing a non-mapping batch (%s): rows are padded but no mask "
                "leaf can be injected — the step must zero-weight padded rows "
                "itself or the loss is diluted",
                type(batch).__name__,
            )
        yield pad_to_bucket(batch, buckets, axis=axis, mask_key=mask_key)


def masked_mean(values: Any, mask: Any):
    """Mean of ``values`` over REAL rows only: ``values`` is ``[B, ...]``,
    ``mask`` is ``[B]`` (1.0 real / 0.0 padded). Padded rows contribute
    exactly zero to the value and to its gradients; the divisor is the real
    element count, so the result equals the plain mean of the unpadded
    batch."""
    values = jnp.asarray(values)
    mask = jnp.asarray(mask, values.dtype)
    mb = mask.reshape(mask.shape + (1,) * (values.ndim - mask.ndim))
    per_row = math.prod(values.shape[mask.ndim:]) if values.ndim > mask.ndim else 1
    denom = jnp.maximum(jnp.sum(mask), 1.0) * per_row
    return jnp.sum(values * mb) / denom


def masked_sum(values: Any, mask: Any):
    """Sum of ``values`` over real rows only (counters, token totals)."""
    values = jnp.asarray(values)
    mask = jnp.asarray(mask, values.dtype)
    mb = mask.reshape(mask.shape + (1,) * (values.ndim - mask.ndim))
    return jnp.sum(values * mb)
