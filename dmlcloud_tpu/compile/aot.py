"""Ahead-of-time compilation of jitted step functions.

The jit-on-first-call model puts the whole XLA compile bill inside step 1 of
the data loop — an unbounded, unannounced stall, and the place where a
sharding/shape mistake surfaces after minutes of setup. The MaxText/levanter
answer is to compile *before* the loop against abstract inputs::

    lowered = jitted_fn.lower(state_spec, batch_spec)   # trace only
    compiled = lowered.compile()                        # XLA (or cache hit)

``PrecompiledStep`` wraps one jitted step function in a registry of such
compiled executables keyed by the *call signature* (pytree structure +
per-leaf shape/dtype/sharding):

- ``precompile(*specs)`` compiles one signature ahead of time (timed, and
  accounted against the persistent cache as a hit or miss);
- calling it routes a matching signature straight to its compiled
  executable (no retrace, no dispatch-path cache probe of unknown cost) and
  falls back to the plain jitted function for anything else, counting each
  *new* unseen signature once — the ``misc/recompiles`` metric;
- ``_cache_size()`` reports distinct signatures seen, which is exactly the
  probe ``lint.TraceGuard`` reads, so the runtime retrace guard works
  unchanged on top.

Abstract specs come from ``abstract_spec`` (any concrete or abstract pytree
-> ``ShapeDtypeStruct`` skeleton) and ``global_batch_spec`` (the sharded
layout ``make_global_batch`` will produce for a host batch on a mesh).
``validate_global_batch_spec`` moves the classic step-1 crash — a batch dim
the mesh cannot divide — to stage start.

Quantized-training states precompile unchanged: the int8 step's params stay
a plain fp32 tree (the ``QuantTrainTensor`` wrap happens INSIDE the traced
loss closure, stage.py) and the delayed amax tree in
``extras[models.quant.QUANT_AMAX_KEY]`` is ordinary array leaves, so the
signature — and therefore the AOT cache key and the TraceGuard budget —
is exactly the full-precision one.
"""

from __future__ import annotations

import time
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel import mesh as mesh_lib
from . import cache as cache_lib

__all__ = [
    "PrecompiledStep",
    "abstract_spec",
    "global_batch_spec",
    "signature_of",
    "validate_global_batch_spec",
]


def abstract_spec(tree: Any) -> Any:
    """``ShapeDtypeStruct`` skeleton of a pytree: concrete jax.Arrays keep
    their sharding, host arrays/scalars contribute shape+dtype only, and
    existing ``ShapeDtypeStruct`` leaves pass through."""

    def leaf(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        arr = x if hasattr(x, "shape") and hasattr(x, "dtype") else np.asarray(x)
        return jax.ShapeDtypeStruct(arr.shape, arr.dtype)

    return jax.tree_util.tree_map(leaf, tree)


def global_batch_spec(batch_or_spec: Any, mesh: Mesh, pspec: P | None = None) -> Any:
    """The abstract layout ``make_global_batch`` produces for a host batch:
    every leaf carries the mesh's batch sharding. Accepts a concrete batch
    or an ``abstract_spec``-style skeleton."""
    if pspec is None:
        pspec = mesh_lib.batch_pspec(mesh)
    sharding = NamedSharding(mesh, pspec)
    spec = abstract_spec(batch_or_spec)
    return jax.tree_util.tree_map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sharding), spec
    )


def validate_global_batch_spec(spec: Any, mesh: Mesh, pspec: P | None = None) -> None:
    """Raise the step-1 sharding crash at stage start instead: every leaf's
    leading (batch) dim must divide over the mesh's data-parallel axes."""
    dp = mesh_lib.data_parallel_size(mesh)
    if dp <= 1:
        return
    for path, leaf in jax.tree_util.tree_flatten_with_path(abstract_spec(spec))[0]:
        shape = leaf.shape
        if len(shape) >= 1 and shape[0] % dp != 0:
            raise ValueError(
                f"batch leaf {mesh_lib.path_str(path) or '<root>'} has leading dim "
                f"{shape[0]}, not divisible by the mesh's data-parallel size {dp} "
                f"(axes {mesh_lib.data_axes(mesh)}); this would crash at step 1 — fix "
                "the batch size, the bucket set, or the mesh"
            )


def _leaf_signature(x: Any) -> tuple:
    shape = tuple(getattr(x, "shape", ()))
    dtype = str(getattr(x, "dtype", type(x).__name__))
    sharding = getattr(x, "sharding", None)
    if not isinstance(sharding, NamedSharding):
        sharding = None  # single-device/unspecified: match on shape+dtype only
    return (shape, dtype, sharding)


def signature_of(args: tuple) -> tuple:
    """Hashable call signature: pytree structure + per-leaf
    shape/dtype/(named) sharding. Two calls with equal signatures reuse the
    same compiled executable."""
    leaves, treedef = jax.tree_util.tree_flatten(args)
    return (treedef, tuple(_leaf_signature(x) for x in leaves))


class PrecompiledStep:
    """Signature-keyed registry of AOT-compiled executables over one jitted
    function (see module docstring). Thread-compatible with the single
    training thread; not locked."""

    def __init__(self, fn: Any, name: str = "step"):
        if not hasattr(fn, "lower"):
            raise TypeError(
                f"PrecompiledStep needs a jitted function (got {type(fn).__name__}); "
                "wrap the fn with jax.jit first"
            )
        self._fn = fn
        self.name = name
        self._compiled: dict[tuple, Any] = {}
        self._seen: set[tuple] = set()
        self._recompiles = 0
        self.compile_ms = 0.0

    def precompile(self, *abstract_args: Any) -> float:
        """Lower + compile one signature ahead of the data loop; returns the
        wall-clock ms this compilation took (0.0 if already registered).
        Accounts a persistent-cache hit when the compile added no new cache
        entry (the executable was deserialized, not built)."""
        sig = signature_of(abstract_args)
        if sig in self._compiled:
            return 0.0
        from ..telemetry import journal as _journal

        entries_before = cache_lib.entry_count()
        t0 = time.perf_counter()
        with _journal.span("compile", label=self.name, signature=len(self._compiled) + 1):
            compiled = self._fn.lower(*abstract_args).compile()
        elapsed_ms = (time.perf_counter() - t0) * 1e3
        entries_after = cache_lib.entry_count()
        hit = (
            entries_before is not None
            and entries_after is not None
            and entries_after == entries_before
        )
        cache_lib.record_compile(hit=hit, elapsed_ms=elapsed_ms)
        self._compiled[sig] = compiled
        self._seen.add(sig)
        self.compile_ms += elapsed_ms
        return elapsed_ms

    def __call__(self, *args: Any):
        sig = signature_of(args)
        compiled = self._compiled.get(sig)
        if compiled is not None:
            return compiled(*args)
        if sig not in self._seen:
            self._seen.add(sig)
            self._recompiles += 1
        return self._fn(*args)  # jit path: compiles (or cache-hits) on its own

    # -- introspection ------------------------------------------------------
    def any_compiled(self) -> Any:
        """One AOT-compiled executable (arbitrary signature), or None —
        enough for per-step cost analysis (telemetry/goodput.py), which is
        signature-independent to first order."""
        return next(iter(self._compiled.values()), None)

    @property
    def signatures(self) -> int:
        """Distinct signatures precompiled (the bounded set buckets target)."""
        return len(self._compiled)

    @property
    def recompiles(self) -> int:
        """Signatures that arrived at call time without a precompiled
        executable (counted once each) since the last ``pop_recompiles``."""
        return self._recompiles

    def pop_recompiles(self) -> int:
        n = self._recompiles
        self._recompiles = 0
        return n

    def _cache_size(self) -> int:
        """Distinct signatures seen (precompiled + fallback) — the probe
        ``lint.TraceGuard`` reads across calls."""
        return len(self._seen)
