"""Persistent XLA compilation cache: wiring, stats, and AOT hit accounting.

Every fresh process pays the full XLA compile bill at step 1 unless the
compiled executable can be fetched from somewhere — jax's persistent
compilation cache is that somewhere: a content-addressed directory of
serialized executables, safe for concurrent writers (each entry is written
once under a hash key), which makes it exactly right for a shared
filesystem on a multi-host pod: every host points at the same directory and
the first job to compile pays for everyone.

``configure_cache`` is the one entry point (called by
``TrainingPipeline(compile_cache=...)`` before any compilation, or directly
at program start). Resolution order for the directory:

1. an explicit path argument,
2. ``$DMLCLOUD_COMPILE_CACHE_DIR``,
3. whatever ``jax_compilation_cache_dir`` is already configured to,
4. ``~/.cache/dmlcloud_tpu/xla``.

Stats are two-layered: ``cache_stats()`` reports the on-disk population
(entries/bytes — shared across every process using the dir) plus this
process's AOT-phase counters (hits = programs the precompiler loaded from
the cache, misses = programs it had to compile). On shared filesystems only
process 0 should log them (``TrainingPipeline`` does).
"""

from __future__ import annotations

import os
import threading
from typing import Any

import jax

__all__ = [
    "ENV_VAR",
    "DEFAULT_CACHE_DIR",
    "configure_cache",
    "resolve_cache_dir",
    "configured_cache_dir",
    "entry_count",
    "record_compile",
    "cache_stats",
    "reset_process_stats",
]

ENV_VAR = "DMLCLOUD_COMPILE_CACHE_DIR"
DEFAULT_CACHE_DIR = "~/.cache/dmlcloud_tpu/xla"

_lock = threading.Lock()
_aot_hits = 0
_aot_misses = 0
_aot_compile_ms = 0.0


def configured_cache_dir() -> str | None:
    """The directory jax's persistent cache currently writes to, or None."""
    value = getattr(jax.config, "jax_compilation_cache_dir", None)
    return value or None


def resolve_cache_dir(cache_dir: Any = True) -> str | None:
    """Resolve the cache directory per the module docstring's order without
    touching jax config. ``None``/``False`` disables (returns None)."""
    if cache_dir in (None, False):
        return None
    if isinstance(cache_dir, (str, os.PathLike)):
        chosen = os.fspath(cache_dir)
    else:  # True / anything truthy: env var, existing config, default
        chosen = os.environ.get(ENV_VAR) or configured_cache_dir() or DEFAULT_CACHE_DIR
    return os.path.abspath(os.path.expanduser(chosen))


def configure_cache(cache_dir: Any = True, aggressive: bool = True) -> str | None:
    """Point jax's persistent compilation cache at ``cache_dir`` (resolved as
    above), creating the directory. Must run before the first compilation of
    the programs it should cover. Returns the resolved directory (None when
    disabled).

    ``aggressive`` (default) also drops jax's minimum-compile-time /
    minimum-entry-size thresholds so every program is persisted — the right
    trade for training jobs, where a cache entry costs kilobytes and a cold
    recompile costs seconds to minutes. Flags missing on older jax are
    skipped silently (the cache still works, with jax's own thresholds)."""
    resolved = resolve_cache_dir(cache_dir)
    if resolved is None:
        return None
    os.makedirs(resolved, exist_ok=True)
    previous = configured_cache_dir()
    jax.config.update("jax_compilation_cache_dir", resolved)
    if previous != resolved:
        # jax latches the cache backend on the FIRST compilation of the
        # process; if anything compiled before this call (it usually has —
        # even an import-time jnp op), the new dir is ignored until the
        # latched state is dropped. Private API, so best-effort by version.
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:
            pass
    if aggressive:
        for flag, value in (
            ("jax_persistent_cache_min_compile_time_secs", 0),
            ("jax_persistent_cache_min_entry_size_bytes", -1),
        ):
            try:
                jax.config.update(flag, value)
            except (AttributeError, ValueError):
                pass
    return resolved


def _entry_files(directory: str) -> list[str]:
    # jax writes `<key>-cache` payloads (some versions add `<key>-atime`
    # bookkeeping files and tmp files mid-write; neither is an entry)
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    return [
        os.path.join(directory, n)
        for n in names
        if not n.endswith("-atime") and not n.endswith(".tmp") and not n.startswith(".")
    ]


def entry_count(directory: str | None = None) -> int | None:
    """Number of persisted executables in the cache dir (None if disabled)."""
    directory = directory or configured_cache_dir()
    if directory is None:
        return None
    return len(_entry_files(directory))


def record_compile(hit: bool, elapsed_ms: float) -> None:
    """Account one AOT-phase compilation for this process's stats."""
    global _aot_hits, _aot_misses, _aot_compile_ms
    with _lock:
        if hit:
            _aot_hits += 1
        else:
            _aot_misses += 1
        _aot_compile_ms += float(elapsed_ms)


def reset_process_stats() -> None:
    global _aot_hits, _aot_misses, _aot_compile_ms
    with _lock:
        _aot_hits = _aot_misses = 0
        _aot_compile_ms = 0.0


def cache_stats() -> dict:
    """On-disk population + this process's AOT counters, JSON-encodable.

    When the cache is not enabled yet, ``dir`` still reports what
    ``configure_cache(True)`` *would* use (env var or default) so ``diag``
    shows an actionable path either way."""
    enabled_dir = configured_cache_dir()
    directory = enabled_dir or resolve_cache_dir(True)
    entries = size = 0
    if enabled_dir and os.path.isdir(enabled_dir):
        files = _entry_files(enabled_dir)
        entries = len(files)
        for f in files:
            try:
                size += os.path.getsize(f)
            except OSError:
                pass
    with _lock:
        hits, misses, ms = _aot_hits, _aot_misses, _aot_compile_ms
    return {
        "enabled": enabled_dir is not None,
        "dir": directory,
        "entries": entries,
        "size_bytes": size,
        "aot_hits": hits,
        "aot_misses": misses,
        "aot_compile_ms": round(ms, 3),
    }
