"""dmlcloud_tpu.compile — the cold-start killer.

Three parts, composable but independent (doc/performance.md §4):

- :mod:`.cache` — persistent XLA compilation cache wiring + stats: compile
  once per *cluster*, not once per process (``TrainingPipeline(
  compile_cache=...)``, ``$DMLCLOUD_COMPILE_CACHE_DIR``).
- :mod:`.aot` — ahead-of-time compilation of the jitted train/val steps
  against abstract batch specs: compile cost lands in a timed ``precompile``
  phase before the data loop (``misc/compile_ms``), and sharding/shape
  mismatches error at stage start instead of step 1
  (``TrainingPipeline(precompile=True)`` / ``Stage.precompile()``).
- :mod:`.buckets` — shape bucketing for ragged batches: pad to a small fixed
  bucket set with a zero-weight ``sample_mask``, so the compiled-signature
  count is bounded by ``len(buckets)`` and ``misc/recompiles`` stays 0
  (``TrainingPipeline(buckets=(...,))`` / ``Stage.buckets()``).
"""

from .aot import (
    PrecompiledStep,
    abstract_spec,
    global_batch_spec,
    signature_of,
    validate_global_batch_spec,
)
from .buckets import (
    DEFAULT_MASK_KEY,
    bucket_for,
    bucket_iterator,
    bucket_spec,
    masked_mean,
    masked_sum,
    pad_to_bucket,
    resolve_buckets,
)
from .cache import cache_stats, configure_cache, configured_cache_dir, resolve_cache_dir

__all__ = [
    "PrecompiledStep",
    "abstract_spec",
    "global_batch_spec",
    "signature_of",
    "validate_global_batch_spec",
    "DEFAULT_MASK_KEY",
    "bucket_for",
    "bucket_iterator",
    "bucket_spec",
    "masked_mean",
    "masked_sum",
    "pad_to_bucket",
    "resolve_buckets",
    "cache_stats",
    "configure_cache",
    "configured_cache_dir",
    "resolve_cache_dir",
]
