"""dmlcloud_tpu — a TPU-native distributed-training framework.

Same capabilities as sehoffmann/dmlcloud (Pipeline/Stage lifecycle, one-call
cluster bootstrap, distributed metrics, checkpoint dirs with requeue-resume,
reproducibility diagnostics, W&B glue, dataset sharding), rebuilt idiomatically
on JAX/XLA: device meshes + NamedSharding instead of DDP, one compiled donated
step instead of hook-driven allreduce, the jax.distributed coordination
service instead of c10d rendezvous, and Orbax for sharded tensor state.
"""

from . import compile, data, lint, metrics, parallel, telemetry, utils
from .checkpoint import CheckpointDir, find_slurm_checkpoint, generate_checkpoint_path
from .metrics import MetricReducer, MetricTracker, Reduction
from .pipeline import TrainingPipeline
from .stage import DatasetNotFoundError, Stage, TrainValStage
from .train_state import TrainState

__version__ = "0.5.0"

__all__ = [
    "compile",
    "data",
    "lint",
    "metrics",
    "parallel",
    "telemetry",
    "utils",
    "CheckpointDir",
    "find_slurm_checkpoint",
    "generate_checkpoint_path",
    "MetricReducer",
    "MetricTracker",
    "Reduction",
    "TrainingPipeline",
    "DatasetNotFoundError",
    "Stage",
    "TrainValStage",
    "TrainState",
    "__version__",
]
