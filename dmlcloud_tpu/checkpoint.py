"""Checkpoint-directory management + sharded tensor state via Orbax.

Capability parity with /root/reference/dmlcloud/checkpoint.py: collision-free
run-directory naming ``{name}-{YYYY.MM.DD-HH.MM}-{id}`` (:16-34), Slurm-requeue
rediscovery by job id (:37-48), and the directory contract — indicator file,
``config.yaml``, ``log.txt``, ``.slurm-jobid`` (:56-117).

It then closes the reference's honest gap: the reference never serialises
model/optimizer state (only config + logs; SURVEY.md §3.5). Here
``CheckpointDir.state_manager`` exposes an Orbax ``CheckpointManager`` rooted
at ``<dir>/state`` — async, sharded (every host writes its own shards; a
multi-host TPU pod checkpoints in parallel), GCS-path capable, with retention.
The directory-contract files stay root-only; tensor state saves are
collective.
"""

from __future__ import annotations

import logging
import os
import random
import string
import time
from datetime import datetime
from pathlib import Path
from typing import Any

from etils import epath

from .utils import slurm
from .utils.config import Config, as_config

_logger = logging.getLogger("dmlcloud_tpu")


def as_run_path(path: Any) -> epath.Path:
    """Normalise to an ``etils.epath.Path``. URI paths (``gs://``, ``s3://``,
    ...) pass through untouched — ``Path.resolve()`` would mangle the scheme
    into ``gs:/bucket`` before any backend saw it; local paths are expanded
    and absolutised for stable equality across processes."""
    if isinstance(path, epath.Path):
        return path
    s = os.fspath(path)
    if "://" in s:
        return epath.Path(s)
    return epath.Path(os.path.abspath(os.path.expanduser(s)))


def is_remote_path(path: Any) -> bool:
    return "://" in os.fspath(path)


def _normalize_opt(v: Any, _seen: frozenset = frozenset()) -> Any:
    """Structural key for an Orbax option value, comparable across calls.
    Callables (e.g. a ``BestN.get_metric_fn`` lambda rebuilt per call) map to
    their qualname PLUS their captured closure values (two lambdas from the
    same source line closing over different metric names must not compare
    equal) and dataclass policies to their field structure, so re-specifying
    an identical configuration is idempotent instead of tripping the
    changed-options guard on lambda identity. The result contains only
    plain comparable values — arbitrary objects (arrays!) reduce to
    ``(type, repr)`` so ``==`` never goes ambiguous — and self-referential
    closures terminate via the ``_seen`` id-set."""
    import dataclasses

    if id(v) in _seen:
        return "<recursive>"
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        sub = _seen | {id(v)}
        return (
            type(v).__name__,
            tuple((f.name, _normalize_opt(getattr(v, f.name), sub)) for f in dataclasses.fields(v)),
        )
    if callable(v):
        key: Any = getattr(v, "__qualname__", repr(type(v)))
        cells = getattr(v, "__closure__", None)
        if cells:
            sub = _seen | {id(v)}
            try:
                key = (key, tuple(_normalize_opt(c.cell_contents, sub) for c in cells))
            except ValueError:  # an empty (yet-unassigned) cell
                pass
        return key
    if isinstance(v, (list, tuple)):
        sub = _seen | {id(v)}
        return tuple(_normalize_opt(x, sub) for x in v)
    if isinstance(v, (str, int, float, bool, bytes, type(None))):
        return v
    return (type(v).__name__, repr(v))


def atomic_write_text(target: epath.Path, text: str) -> None:
    """Crash-safe small-file write. Local filesystems get tmp-file +
    ``os.replace``; object stores commit whole objects atomically already,
    so a direct write is equivalent there (and rename is not atomic on GCS)."""
    if is_remote_path(target):
        target.write_text(text)
        return
    tmp = target.parent / f".{target.name}.tmp"
    tmp.write_text(text)
    os.replace(os.fspath(tmp), os.fspath(target))

#: Indicator file marking a valid run directory (reference: ``.dmlcloud``,
#: checkpoint.py:58-60).
INDICATOR_FILE = ".dmlcloud_tpu"

#: The requeue-verdict file a run leaves behind (doc/elasticity.md): one JSON
#: object answering the only question the requeue wrapper asks — should this
#: job be resubmitted, and why.
REQUEUE_FILE = "requeue.json"


def write_requeue_verdict(
    run_dir: Any, requeue: bool, reason: str, kind: str, **extra
) -> None:
    """Atomically write the requeue verdict for ``run_dir`` (schema v1)::

        {"v": 1, "requeue": true|false, "kind": "preemption"|"hang"|
         "exception"|"user-interrupt"|"completed", "reason": "...",
         "written_at": iso8601, ...extra}

    Call from ONE process (the root). ``extra`` carries kind-specific fields
    (epoch/global_step/save latency for preemptions, stragglers for hangs).
    A requeue wrapper (Slurm epilog, k8s controller) reads this instead of
    guessing from exit codes; see doc/elasticity.md for the contract."""
    import json

    record = {
        "v": 1,
        "requeue": bool(requeue),
        "kind": kind,
        "reason": reason,
        "written_at": datetime.now().isoformat(timespec="seconds"),
    }
    record.update(extra)
    target = as_run_path(run_dir) / REQUEUE_FILE
    atomic_write_text(target, json.dumps(record, indent=1))


def read_requeue_verdict(run_dir: Any) -> dict | None:
    """The run's requeue verdict, or None when absent/corrupt."""
    import json

    try:
        raw = json.loads((as_run_path(run_dir) / REQUEUE_FILE).read_text())
        if raw.get("v") == 1 and isinstance(raw.get("requeue"), bool):
            return raw
    except Exception:
        pass
    return None


def sanitize_filename(filename: str) -> str:
    return filename.replace("/", "_")


def generate_id(length: int = 8) -> str:
    """URL-safe random id (reference checkpoint.py:16-18)."""
    alphabet = string.ascii_lowercase + string.digits
    return "".join(random.choices(alphabet, k=length))


def generate_checkpoint_path(
    root: str | Path | epath.Path, name: str | None = None, dt: datetime | None = None
) -> epath.Path:
    """``{root}/{name}-{YYYY.MM.DD-HH.MM}-{id}`` — collision-free, sortable
    (reference checkpoint.py:21-34). ``root`` may be a ``gs://`` URI."""
    root = as_run_path(root)
    if name is None:
        name = "run"
    if dt is None:
        dt = datetime.now()
    stamp = dt.strftime("%Y.%m.%d-%H.%M")
    return root / sanitize_filename(f"{name}-{stamp}-{generate_id()}")


def find_slurm_checkpoint(root: str | Path | epath.Path) -> epath.Path | None:
    """Scan ``root`` for a run dir whose recorded Slurm job id matches the
    current job — how a requeued job finds its own previous checkpoint
    (reference checkpoint.py:37-48)."""
    job_id = slurm.slurm_job_id()
    if job_id is None:
        return None
    root = as_run_path(root)
    if not root.exists():
        return None
    for child in root.iterdir():
        ckpt = CheckpointDir(child)
        if ckpt.is_valid and ckpt.slurm_job_id == job_id:
            return child
    return None


class CheckpointDir:
    """A single run directory and its contract files.

    Layout (parity with reference checkpoint.py:56-70, plus ``state/``)::

        <path>/
          .dmlcloud_tpu     # indicator
          config.yaml       # experiment config snapshot
          log.txt           # stdout/stderr tee (utils/logging.py)
          .slurm-jobid      # written iff launched under Slurm
          state/            # Orbax CheckpointManager root (sharded tensors)
    """

    def __init__(self, path: str | Path | epath.Path):
        self.path = as_run_path(path)
        self._state_managers: dict[str | None, Any] = {}
        self._manager_opts: dict[str | None, tuple] = {}
        #: scope -> shim preservation policy evaluated host-side (old orbax
        #: without the preservation-policy API; utils/orbax_compat.py)
        self._retention_policies: dict[str | None, Any] = {}
        #: scope -> {step: metrics dict} backing the shim BestN ranking
        self._policy_metrics: dict[str | None, dict[int, dict]] = {}
        #: transient-filesystem-error policy for Orbax save dispatch: total
        #: attempts and the first backoff (doubles per retry, capped at 8s).
        #: Instance attributes so tests (and callers on flaky object stores)
        #: can tune them without process-global state.
        self.save_retries = 3
        self.save_backoff_s = 0.5

    # -- contract files -----------------------------------------------------
    @property
    def config_file(self) -> epath.Path:
        return self.path / "config.yaml"

    @property
    def indicator_file(self) -> epath.Path:
        return self.path / INDICATOR_FILE

    @property
    def log_file(self) -> epath.Path:
        return self.path / "log.txt"

    @property
    def slurm_file(self) -> epath.Path:
        return self.path / ".slurm-jobid"

    @property
    def requeue_file(self) -> epath.Path:
        return self.path / REQUEUE_FILE

    @property
    def state_dir(self) -> epath.Path:
        return self.path / "state"

    # -- validity (reference checkpoint.py:76-92) ---------------------------
    @property
    def exists(self) -> bool:
        return self.path.exists()

    @property
    def is_valid(self) -> bool:
        return self.path.is_dir() and self.indicator_file.exists()

    @property
    def slurm_job_id(self) -> str | None:
        if not self.slurm_file.exists():
            return None
        return self.slurm_file.read_text().strip()

    # -- creation (reference checkpoint.py:94-103; root-only by convention) --
    def create(self) -> None:
        if self.exists:
            raise RuntimeError(f"checkpoint dir already exists: {self.path}")
        self.path.mkdir(parents=True)
        self.indicator_file.touch()
        self.log_file.touch()
        if slurm.slurm_job_id() is not None:
            self.slurm_file.write_text(slurm.slurm_job_id())

    # -- config round-trip (reference checkpoint.py:105-117) ----------------
    def save_config(self, config: Any) -> None:
        as_config(config).save(self.config_file)

    def load_config(self) -> Config:
        return Config.load(self.config_file)

    # -- tensor state via Orbax (new capability vs reference) ---------------
    def has_state_manager(self, scope: str | None = None) -> bool:
        """Whether an Orbax manager for ``scope`` was already created (and
        its options therefore already bound)."""
        return scope in self._state_managers

    def state_manager(
        self, scope: str | None = None, max_to_keep: int | None = None, async_save: bool | None = None, **options
    ):
        """An Orbax CheckpointManager rooted at ``state/`` (or
        ``state/<scope>`` — stages checkpoint under their own scope so step
        ids never collide across stages). Collective: every process must
        participate in save/restore calls. Async saves copy device→host
        synchronously, so donated step buffers are safe.

        Defaults: ``max_to_keep=3``, ``async_save=True``. Options bind at
        FIRST creation per scope (e.g. in ``pre_stage``); explicitly passing
        different options for an existing scope raises."""
        explicit = max_to_keep is not None or async_save is not None or bool(options)
        # a preservation_policy owns retention outright — orbax rejects it
        # combined with max_to_keep, so the default only applies without one
        default_keep = None if "preservation_policy" in options else 3
        requested = (
            default_keep if max_to_keep is None else max_to_keep,
            True if async_save is None else async_save,
            tuple(sorted((k, _normalize_opt(v)) for k, v in options.items())),
        )
        if scope in self._state_managers:
            cached = self._manager_opts[scope]
            if explicit and requested != cached:
                raise RuntimeError(
                    f"Orbax manager for scope {scope!r} already exists with options "
                    f"{cached}; configure it via state_manager(...) BEFORE the first "
                    "save/restore for that scope (e.g. in pre_stage)"
                )
            return self._state_managers[scope]
        import orbax.checkpoint as ocp

        from .utils import orbax_compat

        # old orbax has no preservation_policy option: strip it, remember it,
        # and apply the retention ourselves after each save (identical keep
        # semantics, host-side). ``requested`` above already includes the
        # policy, so the changed-options guard behaves the same either way.
        orbax_options = dict(options)
        shim_policy = orbax_options.get("preservation_policy")
        if orbax_compat.is_shim_policy(shim_policy):
            orbax_options.pop("preservation_policy")
        else:
            shim_policy = None

        opts = ocp.CheckpointManagerOptions(
            max_to_keep=requested[0],
            enable_async_checkpointing=requested[1],
            **orbax_options,
        )
        root = self.state_dir / scope if scope else self.state_dir
        self._state_managers[scope] = ocp.CheckpointManager(root, options=opts)
        self._manager_opts[scope] = requested
        if shim_policy is not None:
            self._retention_policies[scope] = shim_policy
        return self._state_managers[scope]

    def save_state(self, step: int, state: Any, scope: str | None = None, **kwargs) -> None:
        """Save a pytree of (possibly sharded) arrays under ``state/<step>``.

        Two durability features ride every save:

        - **bounded retry**: a transient filesystem error (``OSError``) at
          save dispatch is retried ``save_retries`` times with exponential
          backoff before the ORIGINAL error surfaces — an NFS hiccup or GCS
          503 at minute 590 of a 600-minute job must not cost the job.
        - **sharding sidecar**: the root records each leaf's PartitionSpec
          and the mesh shape (``meta/_sharding/<scope>/<step>.json``) so a
          later :meth:`restore_state` can rebuild shardings for a DIFFERENT
          mesh — the elastic-resume contract (doc/elasticity.md)."""
        import orbax.checkpoint as ocp

        from .telemetry import journal as _journal

        with _journal.span("checkpoint", label=scope, op="save", step=int(step)):
            self._retry_transient(
                lambda: self.state_manager(scope).save(
                    step, args=ocp.args.StandardSave(state), **kwargs
                ),
                what=f"save of step {step} (scope {scope!r})",
            )
        self._write_sharding_sidecar(scope, int(step), state)
        if scope in self._retention_policies:
            self._apply_retention(scope, step, kwargs.get("metrics"))

    def _retry_transient(self, fn, what: str):
        """Run ``fn``, retrying transient filesystem errors (``OSError``)
        with bounded exponential backoff; the original error re-raises after
        the last attempt."""
        attempts = max(int(self.save_retries), 1)
        delay = float(self.save_backoff_s)
        first: OSError | None = None
        for attempt in range(1, attempts + 1):
            try:
                return fn()
            except OSError as e:
                first = first or e
                if attempt == attempts:
                    break
                _logger.warning(
                    "checkpoint %s hit a transient filesystem error (%s: %s); "
                    "retry %d/%d in %.1fs",
                    what, type(e).__name__, e, attempt, attempts - 1, delay,
                )
                time.sleep(delay)
                delay = min(delay * 2, 8.0)
        raise first

    # -- sharding sidecar (elastic resharded restore; doc/elasticity.md) -----
    def _sharding_sidecar_file(self, scope: str | None, step: int) -> epath.Path:
        # a dedicated subtree: ``meta/<scope>/`` belongs to the stage's
        # resume sidecars (stage.py _write_resume_sidecar enumerates it)
        return self.path / "meta" / "_sharding" / (scope or "_root") / f"{int(step)}.json"

    def _write_sharding_sidecar(self, scope: str | None, step: int, state: Any) -> None:
        """Root-only: record the mesh shape and every leaf's PartitionSpec at
        save time, then prune sidecars whose step Orbax no longer keeps.
        Best-effort — a failed sidecar write degrades restore to
        template/policy mode, never fails the save."""
        import json

        import jax
        from jax.sharding import NamedSharding

        if jax.process_index() != 0:
            return
        from .parallel import mesh as mesh_lib

        try:
            specs: dict[str, list] = {}
            mesh_shape: dict[str, int] = {}
            for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
                sharding = getattr(leaf, "sharding", None)
                if not isinstance(sharding, NamedSharding):
                    continue
                specs[mesh_lib.path_str(path)] = mesh_lib.spec_to_jsonable(sharding.spec)
                if not mesh_shape:
                    mesh_shape = {str(k): int(v) for k, v in sharding.mesh.shape.items()}
            record = {"v": 1, "mesh": mesh_shape, "specs": specs}
            target = self._sharding_sidecar_file(scope, step)
            target.parent.mkdir(parents=True, exist_ok=True)
            atomic_write_text(target, json.dumps(record))
            kept = set(int(s) for s in self.state_manager(scope).all_steps()) | {int(step)}
            for f in target.parent.glob("*.json"):
                if f.stem.isdigit() and int(f.stem) not in kept:
                    f.unlink(missing_ok=True)
        except Exception:
            _logger.warning(
                "could not write sharding sidecar for scope %r step %d "
                "(resharded restore will need an explicit template/policy)",
                scope, step, exc_info=True,
            )

    def read_sharding_sidecar(self, scope: str | None, step: int) -> dict | None:
        """The save-time sharding record for ``step`` (``{"mesh": {axis:
        size}, "specs": {leaf-path: spec}}``), or None when absent/corrupt."""
        import json

        try:
            raw = json.loads(self._sharding_sidecar_file(scope, step).read_text())
            if raw.get("v") == 1 and isinstance(raw.get("specs"), dict):
                return raw
        except Exception:
            pass
        return None

    def restore_template(
        self, step: int, scope: str | None = None, mesh: Any = None, policy: Any = None
    ) -> Any:
        """Build the abstract restore template for ``step`` targeted at
        ``mesh`` — WITHOUT the caller hand-building the state pytree. Tree
        structure, shapes, and dtypes come from Orbax's own checkpoint
        metadata; each leaf's sharding is the save-time PartitionSpec
        (sharding sidecar) re-targeted onto ``mesh`` via
        :func:`parallel.mesh.respec_for_mesh` — axes the new mesh lacks
        restore replicated, axes that stopped dividing relocate or drop.
        Without a sidecar (pre-elastic checkpoints), ``policy`` (a
        ``make_param_policy`` accepted value; default ``'replicate'``)
        decides the layout instead."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        from .parallel import mesh as mesh_lib

        if mesh is None:
            raise ValueError("restore_template needs the target mesh")
        meta = self.state_manager(scope).item_metadata(step)
        if meta is None:
            raise ValueError(f"no checkpoint metadata for step {step} (scope {scope!r})")
        sidecar = self.read_sharding_sidecar(scope, step)
        specs = (sidecar or {}).get("specs", {})
        if sidecar is None:
            _logger.warning(
                "no sharding sidecar for scope %r step %d (checkpoint predates "
                "elastic resume?); restoring with policy %r",
                scope, step, policy or "replicate",
            )
        policy_fn = mesh_lib.make_param_policy(policy or "replicate")

        def leaf(path, m):
            p = mesh_lib.path_str(path)
            shape = tuple(m.shape)
            if p in specs:
                spec = mesh_lib.respec_for_mesh(
                    mesh_lib.spec_from_jsonable(specs[p]), shape, mesh
                )
            elif sidecar is not None:
                spec = PartitionSpec()  # saved unsharded (or spec unrecorded)
            else:
                spec = policy_fn(p, m, mesh)
            return jax.ShapeDtypeStruct(shape, m.dtype, sharding=NamedSharding(mesh, spec))

        return jax.tree_util.tree_map_with_path(leaf, meta)

    # -- host-side retention (old orbax; utils/orbax_compat.py) -------------
    def _policy_metrics_file(self, scope: str | None) -> epath.Path:
        # under meta/ (not state/) so orbax's step scan never sees it; the
        # non-digit stem survives the stage's sidecar retention cleanup
        return self.path / "meta" / (scope or "_root") / "_policy_metrics.json"

    def _apply_retention(self, scope: str | None, step: int, metrics: Any) -> None:
        """Evaluate the shim preservation policy after a save and delete the
        steps it does not keep. Every process computes the same keep set (the
        metrics kwarg is identical across ranks); orbax's ``delete`` does the
        actual (primary-host) filesystem work. Rankings persist across
        restarts via a root-written JSON sidecar."""
        import json

        import jax

        from .utils import orbax_compat

        known = self._policy_metrics.setdefault(scope, {})
        if not known:
            try:
                raw = json.loads(self._policy_metrics_file(scope).read_text())
                known.update({int(k): v for k, v in raw.items()})
            except Exception:
                pass  # fresh run dir, or pre-shim checkpoints: rank what we have
        if metrics is not None:
            known[int(step)] = metrics
        mgr = self._state_managers[scope]
        steps = set(int(s) for s in mgr.all_steps()) | {int(step)}
        keep = orbax_compat.steps_to_keep(self._retention_policies[scope], steps, known)
        for old in sorted(steps - keep):
            mgr.delete(old)
            known.pop(old, None)
        if jax.process_index() == 0:
            meta_file = self._policy_metrics_file(scope)
            meta_file.parent.mkdir(parents=True, exist_ok=True)
            atomic_write_text(meta_file, json.dumps({str(k): v for k, v in known.items()}))

    def restore_state(
        self,
        step: int | None = None,
        template: Any = None,
        scope: str | None = None,
        *,
        mesh: Any = None,
        policy: Any = None,
    ) -> Any:
        """Restore the latest (or a given) step.

        Three modes, most- to least-specified:

        - ``template=``: arrays restore with the template's exact
          shardings/dtypes (a template on a DIFFERENT mesh than the save is
          fine — Orbax reshards on read; this is how stages resume).
        - ``mesh=`` (no template): **elastic resharded restore** — the
          template is rebuilt from the checkpoint's own metadata plus the
          save-time sharding sidecar, re-targeted at ``mesh``
          (:meth:`restore_template`), so a save taken on N devices restores
          onto M devices without the caller knowing the state's structure.
          ``policy`` covers sidecar-less checkpoints.
        - neither: host numpy arrays with the SAVED shardings' layout —
          wrong on any other mesh (lint rule DML207 flags this pattern in
          mesh-building code)."""
        import orbax.checkpoint as ocp

        mgr = self.state_manager(scope)
        if step is None:
            step = mgr.latest_step()
        if step is None:
            return None
        if template is None and mesh is not None:
            import jax

            template = self.restore_template(step, scope=scope, mesh=mesh, policy=policy)
            restored = mgr.restore(step, args=ocp.args.StandardRestore(template))
            # Orbax may hand abstract-template restores back in host memory
            # (memory_kind=unpinned_host); re-place on the mesh's default
            # memory so the arrays are ready for the next compiled step.
            shardings = jax.tree_util.tree_map(lambda t: t.sharding, template)
            return jax.device_put(restored, shardings)
        if template is not None:
            return mgr.restore(step, args=ocp.args.StandardRestore(template))
        return mgr.restore(step)

    def latest_step(self, scope: str | None = None) -> int | None:
        return self.state_manager(scope).latest_step()

    _ALL_SCOPES = object()  # sentinel: scope=None names a real scope

    def wait_until_finished(self, scope: Any = _ALL_SCOPES) -> None:
        """Block until pending async saves commit — for one ``scope``, or for
        every manager (the default). The overlap engine's sync points
        (pre-save single-flight wait, stage end, run end, preemption exit)
        all land here; a scope with no manager yet is a no-op."""
        from .telemetry import journal as _journal

        if scope is not CheckpointDir._ALL_SCOPES:
            mgr = self._state_managers.get(scope)
            if mgr is not None:
                with _journal.span("checkpoint", label=scope, op="wait"):
                    mgr.wait_until_finished()
            return
        with _journal.span("checkpoint", op="wait_all"):
            for mgr in self._state_managers.values():
                mgr.wait_until_finished()

    def close(self) -> None:
        for mgr in self._state_managers.values():
            mgr.close()
        self._state_managers = {}
        self._manager_opts = {}
        self._retention_policies = {}
        self._policy_metrics = {}

    def __str__(self) -> str:
        return str(self.path)

    def __repr__(self) -> str:
        return f"CheckpointDir({self.path!r})"
