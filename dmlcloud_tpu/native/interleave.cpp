// libdmltpu: host-side native kernels for dmlcloud_tpu.
//
// dmltpu_interleave: the inner loop of data.interleave_batches — re-slices
// num_batches consecutive batches into num_batches mixed batches through one
// preallocated buffer. Layout contract (matches the numpy fallback in
// data/datasets.py):
//
//   dst[i * batch_bytes + j * slice_bytes .. +slice_bytes]
//     = srcs[j][i * slice_bytes .. +slice_bytes]
//
// Pure memcpy, parallelised over the destination batches with std::thread —
// bandwidth-bound, no interpreter in the loop. Build: native/build.sh.

#include <cstring>
#include <thread>
#include <vector>

extern "C" {

int dmltpu_interleave(void* dst_v, void** srcs_v, long num_batches,
                      long slice_bytes, long batch_bytes) {
  if (dst_v == nullptr || srcs_v == nullptr || num_batches <= 0 ||
      slice_bytes <= 0 || batch_bytes <= 0) {
    return 1;
  }
  char* dst = static_cast<char*>(dst_v);
  char** srcs = reinterpret_cast<char**>(srcs_v);

  auto copy_row = [&](long i) {
    char* out = dst + i * batch_bytes;
    for (long j = 0; j < num_batches; ++j) {
      std::memcpy(out + j * slice_bytes, srcs[j] + i * slice_bytes,
                  static_cast<size_t>(slice_bytes));
    }
  };

  // Small groups: threads cost more than they save.
  const long total_bytes = num_batches * batch_bytes;
  if (num_batches == 1 || total_bytes < (1L << 20)) {
    for (long i = 0; i < num_batches; ++i) copy_row(i);
    return 0;
  }

  unsigned hw = std::thread::hardware_concurrency();
  long n_threads = static_cast<long>(hw > 0 ? hw : 2);
  if (n_threads > num_batches) n_threads = num_batches;

  std::vector<std::thread> threads;
  threads.reserve(static_cast<size_t>(n_threads));
  for (long t = 0; t < n_threads; ++t) {
    threads.emplace_back([&, t]() {
      for (long i = t; i < num_batches; i += n_threads) copy_row(i);
    });
  }
  for (auto& th : threads) th.join();
  return 0;
}

}  // extern "C"
