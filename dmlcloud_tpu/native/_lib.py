"""Shared ctypes loader for ``libdmltpu.so`` — one canonical copy of the
load/cache/fallback boilerplate (a second copy had already started to
drift between the interleave and pack bindings)."""

from __future__ import annotations

import ctypes
from pathlib import Path

_LIB = None
_TRIED = False


def load_symbol(name: str, restype, argtypes):
    """The named function from libdmltpu.so with its signature bound, or
    None when the library isn't built / the symbol is missing (e.g. a stale
    .so predating the symbol) — callers fall back to their Python path."""
    global _LIB, _TRIED
    if not _TRIED:
        _TRIED = True
        so = Path(__file__).parent / "libdmltpu.so"
        if so.exists():
            try:
                _LIB = ctypes.CDLL(str(so))
            except OSError:
                _LIB = None
    if _LIB is None:
        return None
    try:
        fn = getattr(_LIB, name)
    except AttributeError:
        return None
    fn.restype = restype
    fn.argtypes = argtypes
    return fn
