// Native sequence packer: the C++ twin of data/datasets.py pack_sequences
// (same greedy fill, same split/truncate semantics, bit-identical output —
// asserted against the Python packer in tests/test_native.py).
//
// Why native: packing an LM corpus is a per-example Python loop over
// millions of mostly-small documents — interpreter-bound exactly like the
// batch-interleave path. Here it is one pass of memcpy/std::fill over a
// flattened token buffer.
//
// One function serves both phases: with null outputs it only simulates the
// row layout and returns the row count (the caller then allocates); with
// outputs it fills pre-zeroed [rows, seq_len] int32 buffers.

#include <algorithm>
#include <cstdint>
#include <cstring>

extern "C" {

// Returns the number of packed rows, or -1 on invalid arguments.
// flat: concatenated tokens of all examples (only read when filling).
// lengths[n]: per-example token counts (entries of 0 are skipped).
// out_tokens/out_segs: pre-zeroed [rows * seq_len] int32, or null to count.
long dmltpu_pack(const int32_t* flat, const long* lengths, long n, long seq_len,
                 int split_long, int32_t* out_tokens, int32_t* out_segs) {
    if (seq_len < 1 || n < 0) return -1;
    const bool filling = out_tokens != nullptr && out_segs != nullptr;
    long row = 0, fill = 0;
    int32_t seg = 0;
    long offset = 0;  // read position in flat

    auto flush = [&]() {
        ++row;
        fill = 0;
        seg = 0;
    };
    auto place = [&](long src, long count) {
        ++seg;
        if (filling) {
            int32_t* trow = out_tokens + row * seq_len;
            int32_t* srow = out_segs + row * seq_len;
            std::memcpy(trow + fill, flat + src, count * sizeof(int32_t));
            std::fill(srow + fill, srow + fill + count, seg);
        }
        fill += count;
    };

    for (long i = 0; i < n; ++i) {
        const long len = lengths[i];
        if (len <= 0) continue;  // mirrors the Python packer's empty-skip
        if (len <= seq_len) {
            if (len > seq_len - fill) flush();
            place(offset, len);
            if (fill == seq_len) flush();
        } else if (split_long) {
            long done = 0;
            while (done < len) {
                if (fill == seq_len) flush();
                const long take = (len - done) < (seq_len - fill) ? (len - done) : (seq_len - fill);
                place(offset + done, take);
                done += take;
            }
        } else {
            if (fill) flush();
            place(offset, seq_len);  // truncate
            flush();
        }
        offset += len;
    }
    if (fill) flush();
    return row;
}

}  // extern "C"
