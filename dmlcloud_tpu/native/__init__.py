"""Native (C++) runtime components, loaded via ctypes with Python fallbacks.

The reference is pure Python and delegates native work to torch's C++
(SURVEY.md §2.1 language note). Here the host-side hot paths that torch used
to cover get their own small C++ library (``libdmltpu.so``, built by
``native/build.sh`` or ``python -m dmlcloud_tpu.native.build``):

- ``interleave``: parallel strided memcpy batch interleaving (the inner loop
  of ``data.interleave_batches``).

Every entry point degrades gracefully to numpy when the library isn't built.
"""

from . import interleave

__all__ = ["interleave"]
