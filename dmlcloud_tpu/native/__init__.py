"""Native (C++) runtime components, loaded via ctypes with Python fallbacks.

The reference is pure Python and delegates native work to torch's C++
(SURVEY.md §2.1 language note). Here the host-side hot paths that torch used
to cover get their own small C++ library (``libdmltpu.so``, built by
``native/build.sh`` or ``python -m dmlcloud_tpu.native.build``):

- ``interleave``: parallel strided memcpy batch interleaving (the inner loop
  of ``data.interleave_batches``).
- ``pack``: the greedy sequence packer (``pack_sequences_fast`` /
  ``pack_flat``) — bit-identical to ``data.pack_sequences``, one memcpy
  pass instead of a per-document Python loop (19x on a 200k-doc corpus
  via the flat-buffer path).

Every entry point degrades gracefully to Python/numpy when the library
isn't built.
"""

from . import interleave, pack

__all__ = ["interleave", "pack"]
