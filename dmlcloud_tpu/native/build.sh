#!/bin/sh
# Build libdmltpu.so next to this script. Requires g++ (baked in the image).
set -e
cd "$(dirname "$0")"
g++ -O3 -fPIC -shared -std=c++17 -pthread -o libdmltpu.so interleave.cpp pack.cpp
echo "built $(pwd)/libdmltpu.so"
