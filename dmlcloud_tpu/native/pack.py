"""ctypes binding for the C++ sequence packer (falls back to the Python
packer when the library isn't built).

``pack_sequences`` (data/datasets.py) is a per-example Python loop — fine
for thousands of documents, interpreter-bound for millions. This path
flattens the corpus once (numpy concatenate) and hands the greedy fill to
native/pack.cpp, which produces BIT-IDENTICAL rows (asserted in
tests/test_native.py). Build with ``sh dmlcloud_tpu/native/build.sh``.
"""

from __future__ import annotations

import ctypes
from typing import Iterable, Sequence

import numpy as np

from ._lib import load_symbol


def _load():
    return load_symbol(
        "dmltpu_pack",
        ctypes.c_long,
        [
            ctypes.c_void_p,  # flat tokens (null when counting)
            ctypes.c_void_p,  # lengths
            ctypes.c_long,  # n examples
            ctypes.c_long,  # seq_len
            ctypes.c_int,  # split_long
            ctypes.c_void_p,  # out tokens (null when counting)
            ctypes.c_void_p,  # out segs (null when counting)
        ],
    )


def available() -> bool:
    return _load() is not None


def pack_flat(
    flat: np.ndarray,
    lengths: np.ndarray,
    seq_len: int,
    *,
    split_long: bool = True,
) -> tuple[np.ndarray, np.ndarray]:
    """Pack a pre-flattened corpus: ``flat`` is every example's tokens
    concatenated, ``lengths`` the per-example counts (what a tokenizer's
    offsets give directly — no per-example Python objects at all). Returns
    ``(tokens, segment_ids)`` as ``[rows, seq_len]`` int32 arrays with the
    exact ``pack_sequences`` semantics. This is the zero-overhead path: the
    whole corpus is two numpy buffers and one C call each for count + fill.

    Requires the native library (``sh dmlcloud_tpu/native/build.sh``)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(
            "native packer not built — run `sh dmlcloud_tpu/native/build.sh` "
            "(or use data.pack_sequences / pack_sequences_fast, which fall back)"
        )
    if seq_len < 1:
        raise ValueError(f"seq_len must be >= 1, got {seq_len}")
    flat = np.ascontiguousarray(flat, np.int32)
    lengths = np.ascontiguousarray(lengths, np.int64)
    if lengths.size and int(lengths.min()) < 0:
        raise ValueError("lengths must be non-negative")  # a negative entry would OOB-read flat
    if int(lengths.sum()) != flat.size:
        raise ValueError(f"lengths sum to {int(lengths.sum())} but flat has {flat.size} tokens")
    n_rows = lib(
        None, lengths.ctypes.data, lengths.size, seq_len, int(split_long), None, None
    )
    if n_rows < 0:
        raise ValueError("invalid packing arguments")
    tokens = np.zeros((n_rows, seq_len), np.int32)
    segs = np.zeros((n_rows, seq_len), np.int32)
    filled = lib(
        flat.ctypes.data, lengths.ctypes.data, lengths.size, seq_len, int(split_long),
        tokens.ctypes.data, segs.ctypes.data,
    )
    assert filled == n_rows, (filled, n_rows)
    return tokens, segs


def pack_sequences_fast(
    examples: Iterable[Sequence[int] | np.ndarray],
    seq_len: int,
    *,
    split_long: bool = True,
) -> list[dict]:
    """Native-path ``pack_sequences``: same inputs, same row dicts
    (``{"tokens", "segment_ids"}``), bit-identical packing — as a list
    (the corpus is flattened up front, so there is nothing to stream).
    Falls back to the Python packer when the library isn't built."""
    if seq_len < 1:
        raise ValueError(f"seq_len must be >= 1, got {seq_len}")
    arrays = [np.asarray(ex, np.int32).ravel() for ex in examples]
    lib = _load()
    if lib is None:
        from ..data.datasets import pack_sequences

        return list(pack_sequences(arrays, seq_len, split_long=split_long))
    lengths = np.fromiter((a.size for a in arrays), np.int64, count=len(arrays))
    flat = np.concatenate(arrays) if arrays else np.zeros(0, np.int32)
    tokens, segs = pack_flat(flat, lengths, seq_len, split_long=split_long)
    return [{"tokens": tokens[i], "segment_ids": segs[i]} for i in range(len(tokens))]
