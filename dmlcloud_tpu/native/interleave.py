"""ctypes binding for the C++ batch-interleave kernel (falls back to numpy).

See native/interleave.cpp. The Python loop in ``interleave_batches``
(data/datasets.py) does num_batches^2 strided copies per group through the
interpreter; the C++ path does the same copies with std::memcpy across a
thread pool — bandwidth-bound instead of interpreter-bound.
"""

from __future__ import annotations

import ctypes

import numpy as np

from ._lib import load_symbol


def _load():
    return load_symbol(
        "dmltpu_interleave",
        ctypes.c_int,
        [
            ctypes.c_void_p,  # dst
            ctypes.POINTER(ctypes.c_void_p),  # srcs
            ctypes.c_long,  # num_batches
            ctypes.c_long,  # slice_bytes
            ctypes.c_long,  # batch_bytes
        ],
    )


def available() -> bool:
    return _load() is not None


def interleave_into(memory: np.ndarray, batches: list[np.ndarray], slice_size: int) -> None:
    """memory[i, j*s:(j+1)*s] = batches[j][i*s:(i+1)*s] for all i, j — in C++."""
    lib = _load()
    n = len(batches)
    itemsize = batches[0].itemsize
    row_bytes = int(np.prod(batches[0].shape[1:])) * itemsize if batches[0].ndim > 1 else itemsize
    slice_bytes = slice_size * row_bytes
    batch_bytes = batches[0].shape[0] * row_bytes
    srcs = (ctypes.c_void_p * n)(*[b.ctypes.data for b in batches])
    rc = lib(
        memory.ctypes.data, srcs, n, slice_bytes, batch_bytes
    )
    if rc != 0:  # pragma: no cover
        raise RuntimeError(f"dmltpu_interleave failed with code {rc}")
