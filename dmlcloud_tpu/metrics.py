"""Distributed metric tracking with epoch-wise reduction.

Capability parity with /root/reference/dmlcloud/metrics.py (Reduction enum :7,
``reduce_tensor`` :24, ``MetricReducer`` :44, ``MetricTracker`` :158) with two
TPU-first redesigns:

1. **No per-step device sync.** The reference detaches and copies every tracked
   tensor to CPU inside the hot loop (metrics.py:66-73). Here ``append`` keeps
   jax.Arrays as-is — device->host transfer happens once per epoch in a single
   batched ``jax.device_get`` at reduce time, so tracking a metric never
   stalls the TPU pipeline.

2. **One fused collective per epoch.** The reference issues one
   ``all_gather_object`` (emptiness consensus) plus one ``all_reduce`` *per
   metric per epoch* (metrics.py:121-141) — 2·N collectives over gloo. Here
   ``MetricTracker.reduce_all`` packs every scalar metric's locally-reduced
   value, emptiness bit, and a name-set fingerprint into ONE float32 vector,
   allgathers it in a single XLA collective over ICI/DCN
   (``runtime.all_gather_array``), and combines on host — epoch-end sync cost
   is O(1) in the number of metrics and never touches the KV store. This is
   the "metrics allreduce" latency target of BASELINE.md. Non-scalar metrics
   (rare) fall back to one object exchange with concurrent fetches.

The ragged-tracking consensus error (some ranks tracked a metric, some did
not — a symptom of diverged control flow; reference metrics.py:124-130) is
preserved exactly.
"""

from __future__ import annotations

import logging
from enum import Enum
from typing import Any, Iterable

import jax
import numpy as np

from .parallel import runtime

_logger = logging.getLogger(__name__)


class Reduction(Enum):
    MEAN = "MEAN"
    SUM = "SUM"
    MIN = "MIN"
    MAX = "MAX"

    def combine(self, stacked: np.ndarray, axis) -> np.ndarray:
        if self is Reduction.MEAN:
            return stacked.mean(axis=axis)
        if self is Reduction.SUM:
            return stacked.sum(axis=axis)
        if self is Reduction.MIN:
            return stacked.min(axis=axis)
        if self is Reduction.MAX:
            return stacked.max(axis=axis)
        raise ValueError(f"unknown reduction {self}")


def reduce_tensor(tensor: Any, reduction: Reduction, dim: int | list[int] | None = None) -> np.ndarray:
    """Reduce an array over ``dim`` (all dims if None) — host-side numpy.
    Parity with reference ``reduce_tensor`` (metrics.py:24-41)."""
    arr = np.asarray(tensor)
    if dim is None:
        axis: Any = tuple(range(arr.ndim))
    elif isinstance(dim, int):
        axis = (dim,)
    else:
        axis = tuple(dim)
    return reduction.combine(arr, axis)


def _to_host(value: Any) -> np.ndarray:
    return np.asarray(jax.device_get(value))


class MetricReducer:
    """Buffers per-step values, reduces them locally + across processes at
    epoch end. ``dim`` indexes dimensions of the *individual* appended values
    (dim 0 = usually the batch dim); the stacking dimension is always reduced.
    Parity with reference ``MetricReducer`` (metrics.py:44-155)."""

    def __init__(self, reduction: Reduction = Reduction.MEAN, dim=None, globally: bool = True):
        if reduction not in (Reduction.MEAN, Reduction.SUM, Reduction.MIN, Reduction.MAX):
            raise ValueError(f"unknown reduction {reduction}")
        self.values: list[Any] = []
        self.reduction = reduction
        self.globally = globally
        if isinstance(dim, int):
            self.dim: list[int] | None = [dim]
        elif dim is not None:
            self.dim = list(dim)
        else:
            self.dim = None

    # -- buffering ----------------------------------------------------------
    def append(self, value: Any) -> None:
        """Append a value. jax.Arrays are kept as-is — NOT synced to host here
        (the device->host copy is batched at epoch end), so this never blocks
        the async dispatch queue mid-epoch. A non-blocking D2H copy is
        *started* immediately though: it rides the dispatch queue behind the
        step that produces the value, so by reduce time the batched
        ``device_get`` mostly finds the bytes already on host instead of
        draining a whole epoch of readbacks at the sync point."""
        copy_async = getattr(value, "copy_to_host_async", None)
        if copy_async is not None:
            try:
                copy_async()
            except Exception:  # committed/donated edge cases must never break tracking
                pass
        self.values.append(value)

    def extend(self, values: Iterable[Any]) -> None:
        for v in values:
            self.append(v)

    def __iadd__(self, value: Any) -> "MetricReducer":
        self.append(value)
        return self

    def __setitem__(self, idx: int, value: Any) -> None:
        self.values[idx] = value

    def __getitem__(self, idx: int) -> Any:
        return self.values[idx]

    def __delitem__(self, idx: int) -> None:
        del self.values[idx]

    def __len__(self) -> int:
        return len(self.values)

    def __iter__(self):
        return iter(self.values)

    def clear(self) -> None:
        self.values.clear()

    def reduce_and_append(self, value: Any) -> None:
        self.values.append(reduce_tensor(value, self.reduction, dim=self.dim))

    # -- reduction ----------------------------------------------------------
    def _stack_axis(self):
        if self.dim is None:
            return None
        return [0] + [d + 1 for d in self.dim]

    def reduce_locally(self) -> np.ndarray | None:
        """Stack buffered values and reduce on this process only."""
        if len(self.values) == 0:
            return None
        host_vals = jax.device_get(self.values)  # one batched transfer
        stacked = np.stack([np.asarray(v) for v in host_vals])
        axis = self._stack_axis()
        axis = tuple(range(stacked.ndim)) if axis is None else tuple(axis)
        return self.reduction.combine(stacked, axis)

    def reduce_globally(self) -> np.ndarray | None:
        """Reduce across all processes (standalone path — ``MetricTracker``
        uses the fused exchange instead). Raises if ranks disagree on whether
        this metric was tracked (reference metrics.py:124-130)."""
        if self.globally:
            empty = runtime.all_gather_object(len(self.values) == 0)
            if any(empty):
                if len(empty) > 1 and not all(empty):
                    raise ValueError(
                        "Some workers tracked values this epoch and some did not. This is likely a bug."
                    )
                return None
        elif len(self.values) == 0:
            return None

        local = self.reduce_locally()
        if self.globally and runtime.world_size() > 1:
            gathered = runtime.all_gather_object(local)
            local = _combine_across(gathered, self.reduction)
        return local

    # -- serialization ------------------------------------------------------
    def state_dict(self) -> dict:
        # reduction stored by value so the state is JSON-encodable (resume
        # sidecars are JSON, not pickle — utils/serialization.py)
        return {
            "reduction": self.reduction.value,
            "dim": self.dim,
            "globally": self.globally,
            "values": [_to_host(v) for v in self.values],
        }

    def load_state_dict(self, state: dict) -> None:
        red = state["reduction"]
        self.reduction = red if isinstance(red, Reduction) else Reduction(red)
        self.dim = state["dim"]
        self.globally = bool(state["globally"])
        self.values = list(state["values"])


def _combine_across(per_rank: list[np.ndarray], reduction: Reduction) -> np.ndarray:
    """Combine already-locally-reduced values from each rank. MEAN is the
    unweighted mean of rank-local means — identical to the reference's
    SUM/world_size convention (metrics.py:136-138)."""
    stacked = np.stack([np.asarray(v) for v in per_rank])
    return reduction.combine(stacked, axis=0)


def _name_fingerprint(names: list[str]) -> np.float32:
    """Order-sensitive fingerprint of the metric-name set, packed into the
    exchange vector so ranks that diverged on WHICH metrics they track get a
    diagnostic instead of silently combining mismatched columns. The modulus
    keeps the value exactly representable in float32."""
    import zlib

    return np.float32(zlib.crc32("\x00".join(names).encode()) % (2**24 - 3))


#: Process-wide fallback of metrics already warned about for float32
#: exactness loss — used only when the caller passes no ``warned`` set.
#: ``MetricTracker`` owns a per-tracker set instead, so a second pipeline
#: (or test) in the same process warns again for its own metrics.
_INEXACT_SUM_WARNED: set[str] = set()


def _pack_scalar_metrics(
    names: list[str],
    local: dict[str, tuple[bool, Any]],
    reductions: dict[str, Reduction] | None = None,
    warned: set[str] | None = None,
) -> np.ndarray:
    """``[fingerprint | empty bits | values]`` as one float32 vector — the
    payload of the single-collective epoch exchange.

    Values transit as float32, so an integer SUM counter loses exactness past
    2**24. Rerouting such a metric at runtime is NOT safe (routing must be
    identical on every rank or the collective shapes diverge), so the guard
    is a loud once-per-metric warning naming the exact fix; the cross-rank
    combine itself happens in float64 (``_unpack_scalar_metrics``), so the
    pack-time rounding checked here is the only loss point. ``warned``
    scopes the once-per-metric dedupe (default: the process-wide set)."""
    if warned is None:
        warned = _INEXACT_SUM_WARNED
    n = len(names)
    vec = np.zeros(1 + 2 * n, np.float32)
    vec[0] = _name_fingerprint(names)
    empties = np.array([bool(local[name][0]) for name in names], bool)
    # one host conversion pass; both the packed f32 payload and the
    # exactness check below read from this vector
    vals = np.array(
        [0.0 if e else float(np.asarray(local[nm][1])) for nm, e in zip(names, empties)],
        np.float64,
    )
    vec[1 : 1 + n] = empties
    vec[1 + n :] = vals  # f64 -> f32 cast happens here, once
    if reductions is not None:
        lossy = (vals == np.round(vals)) & (vec[1 + n :].astype(np.float64) != vals) & ~empties
        for i in np.nonzero(lossy)[0]:
            name = names[int(i)]
            if reductions.get(name) is Reduction.SUM and name not in warned:
                warned.add(name)
                _logger.warning(
                    "Metric %r: integer SUM counter %.0f exceeds float32's exact "
                    "range (2**24) and loses precision in the packed metric "
                    "exchange. Register it with dim=() to route it through the "
                    "exact object exchange, or track a float statistic instead.",
                    name, vals[int(i)],
                )
    return vec


def _unpack_scalar_metrics(
    names: list[str], gathered: np.ndarray, reductions: dict[str, Reduction]
) -> dict[str, np.ndarray | None]:
    """Combine the ``[world, 1+2n]`` gathered exchange vectors on host,
    preserving the reference's ragged-tracking diagnostics (metrics.py:124-130)."""
    n = len(names)
    if not np.all(gathered[:, 0] == gathered[0, 0]):
        raise ValueError(
            "Workers disagree on the set of metrics tracked this epoch. This is likely a bug."
        )
    out: dict[str, np.ndarray | None] = {}
    for i, name in enumerate(names):
        empties = gathered[:, 1 + i] != 0.0
        if empties.any():
            if not empties.all():
                raise ValueError(
                    f"Metric '{name}': some workers tracked values this epoch and some did not. "
                    "This is likely a bug."
                )
            out[name] = None
        else:
            # float64 combine: the f32-exact per-rank values sum exactly up
            # to 2**53, so cross-rank accumulation adds no further rounding
            out[name] = _combine_across(list(gathered[:, 1 + n + i].astype(np.float64)), reductions[name])
    return out


class MetricTracker:
    """Tracks named metric histories keyed by epoch.

    Usage::

        tracker = MetricTracker()
        tracker.register_metric('loss', reduction=Reduction.MEAN)
        tracker.track('loss', loss_value)
        tracker.next_epoch()
        tracker['loss']  # history

    Parity with reference ``MetricTracker`` (metrics.py:158-306); epoch-end
    cross-process sync is a single fused exchange (see module docstring).
    """

    def __init__(self):
        self.histories: dict[str, list] = {}
        self.reducers: dict[str, MetricReducer] = {}
        self.epoch = 1
        #: per-tracker once-per-metric dedupe for the inexact-SUM warning —
        #: a second pipeline/test in the same process warns again (not
        #: persisted: a resumed run re-warning once is correct)
        self._inexact_sum_warned: set[str] = set()

    def __getitem__(self, name: str) -> list:
        """History of a metric for *completed* epochs (current epoch's
        already-reduced value excluded — reference metrics.py:176-183)."""
        if name not in self:
            raise ValueError(f"Metric {name} does not exist")
        return list(self.histories[name])[: self.epoch - 1]

    def __contains__(self, name: str) -> bool:
        return name in self.histories

    def __len__(self) -> int:
        return len(self.histories)

    def __iter__(self):
        return iter(self.histories)

    def current_value(self, name: str):
        """The already-reduced value for the current epoch, else None."""
        if name not in self:
            raise ValueError(f"Metric {name} does not exist")
        if self.has_value(name):
            return self.histories[name][-1]
        return None

    def is_reduced_metric(self, name: str) -> bool:
        if name not in self:
            raise ValueError(f"Metric {name} does not exist")
        return name in self.reducers

    def has_value(self, name: str) -> bool:
        """True if the metric already has a final value for the current epoch."""
        if name not in self:
            raise ValueError(f"Metric {name} does not exist")
        return len(self.histories[name]) >= self.epoch

    def register_metric(self, name: str, reduction: Reduction | None = None, dim=None, globally: bool = True) -> None:
        if name in self:
            raise ValueError(f"Metric {name} already exists")
        if dim is not None and reduction is None:
            raise ValueError("If dim is specified, reduction must be specified as well")
        self.histories[name] = [None] * (self.epoch - 1)
        if reduction is not None:
            self.reducers[name] = MetricReducer(reduction=reduction, dim=dim, globally=globally)

    def track(self, name: str, value: Any) -> None:
        if name not in self:
            raise ValueError(f"Metric {name} does not exist")
        if self.has_value(name):
            raise ValueError(f"History for {name} already has a value for epoch {self.epoch}")
        reducer = self.reducers.get(name)
        if reducer is not None:
            reducer.append(value)
        else:
            self.histories[name].append(jax.device_get(value))

    def bump(self, name: str, value: int | float = 1, globally: bool = True) -> None:
        """Epoch-scoped event counter: register-on-first-use as a SUM
        reduction and add ``value``. The idiom for counts where MEAN would
        be meaningless (recompiles, skipped batches, retries); with
        ``globally`` the epoch total sums across processes in the fused
        exchange. Safe to call any number of times per epoch."""
        if name not in self:
            self.register_metric(name, Reduction.SUM, globally=globally)
        self.track(name, value)

    def reduce_all(self, prefix: str | None = None, strict: bool = True) -> None:
        """Reduce all (or prefix-filtered) metrics and append to histories.

        Cross-process cost: ONE object exchange for every globally-reduced
        metric together (vs 2 collectives per metric in the reference,
        metrics.py:258-271). Raises under ``strict`` if a metric was already
        reduced this epoch.
        """
        selected = []
        for name in self.histories:
            if prefix is not None and not name.startswith(prefix):
                continue
            if self.has_value(name):
                if strict:
                    raise ValueError(f"History for {name} has already been reduced for epoch {self.epoch}")
                continue
            selected.append(name)

        # Phase 1: local reductions (one batched device_get per metric).
        local: dict[str, tuple[bool, np.ndarray | None]] = {}
        for name in selected:
            reducer = self.reducers.get(name)
            if reducer is not None and reducer.globally:
                local[name] = (len(reducer.values) == 0, reducer.reduce_locally())

        # Phase 2: cross-process exchange. Scalar metrics (the overwhelming
        # common case) ride ONE XLA collective over ICI as a packed float32
        # vector — zero KV-store round trips; non-scalar metrics fall back to
        # one object exchange over the coordination service (with concurrent
        # fetches). Caveat of the packed path: values transit as float32, so
        # integer SUM counters are exact up to 2**24 per epoch.
        fused: dict[str, np.ndarray | None] = {}
        if local and runtime.world_size() > 1:
            # Scalar = registered with dim=None (full reduction), which is a
            # REGISTRATION-time property — classifying by the runtime value's
            # shape would let an empty buffer on one rank route the same
            # metric through different exchanges on different ranks, turning
            # the ragged-tracking diagnostic into a collective shape
            # mismatch. dim=None guarantees a scalar local reduction.
            scalar_names = sorted(n for n in local if self.reducers[n].dim is None)
            other = {n: local[n] for n in local if n not in scalar_names}
            if scalar_names:
                reductions = {n: self.reducers[n].reduction for n in scalar_names}
                packed = _pack_scalar_metrics(
                    scalar_names, local, reductions, warned=self._inexact_sum_warned
                )
                gathered = runtime.all_gather_array(packed)
                fused.update(_unpack_scalar_metrics(scalar_names, gathered, reductions))
            if other:
                gathered_obj = runtime.all_gather_object(other)  # list over ranks
                for name in other:
                    # a rank that never registered the metric counts as "empty" so
                    # the ragged-tracking diagnostic below fires instead of KeyError
                    empties = [g.get(name, (True, None))[0] for g in gathered_obj]
                    if any(empties):
                        if not all(empties):
                            raise ValueError(
                                f"Metric '{name}': some workers tracked values this epoch and some did not. "
                                "This is likely a bug."
                            )
                        fused[name] = None
                    else:
                        reducer = self.reducers[name]
                        fused[name] = _combine_across([g[name][1] for g in gathered_obj], reducer.reduction)
        else:
            for name, (is_empty, val) in local.items():
                fused[name] = None if is_empty else val

        # Phase 3: append results.
        for name in selected:
            reducer = self.reducers.get(name)
            if reducer is None:
                self.histories[name].append(None)
            elif reducer.globally:
                self.histories[name].append(fused[name])
                reducer.clear()
            else:
                self.histories[name].append(reducer.reduce_locally())
                reducer.clear()

    def next_epoch(self) -> None:
        """Reduce anything un-reduced and advance the epoch counter."""
        self.reduce_all(strict=False)
        self.epoch += 1

    def fast_forward(self, epoch: int) -> None:
        """Jump the tracker to ``epoch``, padding every history with None
        for the skipped epochs (no-op when already there or past).

        Used by mid-epoch step-save resume when the restored tracker
        sidecar is older than the epoch being resumed (sparse
        ``checkpoint_every``): the gap epochs trained in the interrupted
        run but their reduced values were never persisted, so they appear
        as None instead of shifting every later epoch's alignment."""
        if epoch <= self.epoch:
            return
        for name in self.histories:
            hist = self.histories[name]
            while len(hist) < epoch - 1:
                hist.append(None)
        self.epoch = epoch

    def state_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "histories": {k: list(v) for k, v in self.histories.items()},
            "reducers": {name: r.state_dict() for name, r in self.reducers.items()},
        }

    def load_state_dict(self, state: dict) -> None:
        self.epoch = int(state["epoch"])
        self.histories = {k: list(v) for k, v in state["histories"].items()}
        self.reducers = {}
        for name, rstate in state["reducers"].items():
            r = MetricReducer()
            r.load_state_dict(rstate)
            self.reducers[name] = r

    def __str__(self) -> str:
        s = "MetricTracker("
        for name, history in self.histories.items():
            s += f"\n  {name}: {history}"
        s += "\n)" if self.histories else ")"
        return s
