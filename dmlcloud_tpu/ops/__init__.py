"""Custom ops: Pallas TPU kernels and sharded collective ops (flash attention,
ring attention for sequence/context parallelism, fused cross-entropy)."""
