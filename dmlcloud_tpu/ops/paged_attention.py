"""Paged KV-cache indexing: gather/scatter between a block pool and
per-sequence block tables.

The serving engine (``dmlcloud_tpu/serve/``) keeps the KV cache as a fixed
pool of ``[num_blocks, block_size, KH, D]`` pages per layer instead of one
dense ``[B, max_len, KH, D]`` buffer per request batch: each sequence owns
a short list of pool blocks (its *block table*), so cache memory scales
with the tokens actually live and a finished sequence's blocks recycle to
the next request immediately. These two functions are the traced index
arithmetic that makes the pool usable from inside a jitted decode step:

- :func:`scatter_tokens` writes a batch of new K/V rows into the pages the
  block tables name (one vectorized scatter — the paged twin of the dense
  path's ``dynamic_update_slice``);
- :func:`gather_pages` reassembles each sequence's pages into a contiguous
  ``[B, NB*block_size, KH, D]`` view for attention, which then runs through
  the SAME masked GQA attention as the dense decode path
  (``models/transformer._dot_attention`` with the causal/window predicate
  ``_window_keep`` — the Mistral-convention machinery the flash kernels in
  ``ops/flash_attention.py`` block-tile).

Both functions are multi-token per row by construction — ``T`` is just a
shape axis. Chunked prefill writes ``prefill_chunk`` positions per call,
and the speculative engine's rounds lean on the same property: a draft
pass writes 2 then 1 positions, the verification pass scatters all
``k+1`` proposal positions per sequence through the block tables in ONE
call (and gathers once for the whole round) — the multi-token round cost
that replaces plain decode's per-token cost (serve/engine.py).

Prefix sharing (``serve/prefix_cache.py``) adds one asymmetric contract:
the SAME physical block may appear in many rows' tables (and in many
concurrent batches) — :func:`gather_pages` needs nothing special for
that, every row just reads the shared page. :func:`scatter_tokens` is the
dangerous half: a write through a table entry whose block has
``refcount > 1`` would corrupt every other reader's prefix, so the
serving engine copy-on-write forks (or refcount-checks) BEFORE building
the tables it scatters through — refcounts are host state, invisible to
this traced code, which is exactly why the ordering is enforced
statically by lint rule DML211 rather than here.

Out-of-range handling is the whole trick for static shapes: block tables
are padded with a SENTINEL entry equal to ``num_blocks`` (one past the
pool). jax clips out-of-bounds *gather* indices — the sentinel reads the
last real block, and the caller's ``kv_pos <= q_pos`` mask hides whatever
it read — and ``mode="drop"`` discards out-of-bounds *scatter* updates, so
a padded batch row (or a prefill chunk's padded tail spilling past its
allocation) writes nothing at all. A NEGATIVE position maps below the
table and is redirected to the sentinel the same way — it can never wrap
into a real block (tests/test_serve.py locks both). Inactive rows
therefore cost index arithmetic only; no branch, no dynamic shape.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["gather_pages", "scatter_tokens"]


def gather_pages(pool: jnp.ndarray, tables: jnp.ndarray) -> jnp.ndarray:
    """Reassemble each row's pages into a contiguous KV view.

    ``pool`` is ``[num_blocks, block_size, KH, D]``; ``tables`` is
    ``[B, NB]`` int32 physical block ids (sentinel ``num_blocks`` for
    unused entries — clipped by the gather, masked by the caller).
    Returns ``[B, NB * block_size, KH, D]``: row ``b``'s token position
    ``p`` lives at gathered index ``p`` for every ``p < fill[b]``, exactly
    the dense cache layout attention already understands.
    """
    g = pool[tables]  # [B, NB, bs, KH, D]; OOB table entries clip
    return g.reshape(tables.shape[0], tables.shape[1] * pool.shape[1], *pool.shape[2:])


def scatter_tokens(
    pool: jnp.ndarray, tables: jnp.ndarray, positions: jnp.ndarray, values: jnp.ndarray
) -> jnp.ndarray:
    """Write per-token K/V rows into the pages their block tables name.

    ``positions`` is ``[B, T]`` absolute token positions (position ``p``
    lands in logical block ``p // block_size``, slot ``p % block_size``);
    ``values`` is ``[B, T, KH, D]``. A position whose logical block falls
    outside its table row — a padded batch row carrying a sentinel-only
    table, a prefill pad tail past the row's allocation, or a negative
    position — maps to the out-of-bounds sentinel and is DROPPED by the
    scatter, not written. Returns the updated pool.
    """
    num_blocks, block_size = pool.shape[0], pool.shape[1]
    nb = tables.shape[1]
    block = positions // block_size  # [B, T] logical block index
    slot = positions % block_size
    phys = jnp.take_along_axis(tables, jnp.clip(block, 0, nb - 1), axis=1)
    # a logical block past the table's width must not clip INTO the row's
    # last real block — redirect it to the drop sentinel explicitly
    phys = jnp.where((block >= 0) & (block < nb), phys, num_blocks)
    return pool.at[phys, slot].set(values.astype(pool.dtype), mode="drop")
