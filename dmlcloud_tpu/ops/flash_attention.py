"""Flash attention as a Pallas TPU kernel.

The reference framework has no attention code at all (SURVEY.md §5.7); models
were user-space. The TPU build ships attention as a first-class fused op
because it is *the* hot op of the transformer configs in BASELINE.json.

Kernel design (online-softmax, Dao-style but TPU-shaped):

- Grid: ``(batch*heads, T/block_q)`` — each program owns one query block and
  streams the K/V sequence through VMEM with ``pl.ds`` slices, keeping the
  running max/denominator in fp32 registers (carried through a
  ``lax.fori_loop``). O(T) HBM traffic for K/V, no [T, S] score matrix ever
  materialises.
- MXU does q@k^T and p@v in bf16 with fp32 accumulation
  (``preferred_element_type``); VPU does the exp/renormalisation.
- Causal masking skips *entire* K blocks past the diagonal (loop bound
  depends on ``program_id``), and masks only inside the diagonal block.
- GQA: the K/V block index map folds the query head onto its KV head, so
  grouped heads reread the same VMEM block instead of materialising repeats.

Falls back to interpret mode off-TPU (tests run it on CPU for bit-accurate
comparison against the reference einsum path).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only imports on TPU-capable builds; interpret mode needs none of it
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    _VMEM = None


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool, sm_scale: float, q_block: int):
    # q_ref: [1, block_q, D]; k_ref/v_ref: [1, S, D]; o_ref: [1, block_q, D]
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale  # [bq, D]
    seq_len = k_ref.shape[1]
    num_kb = seq_len // block_k

    q_pos = qi * q_block + jax.lax.broadcasted_iota(jnp.int32, (q_block, block_k), 0)

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :]  # [bk, D]
        v = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k.astype(jnp.float32), (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        if causal:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (q_block, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -1e30)
        blk_max = jnp.max(s, axis=-1)  # [bq]
        new_m = jnp.maximum(m, blk_max)
        correction = jnp.exp(m - new_m)
        p = jnp.exp(s - new_m[:, None])  # [bq, bk]
        l = l * correction + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc = acc * correction[:, None] + pv
        return new_m, l, acc

    d = q_ref.shape[-1]
    m0 = jnp.full((q_block,), -1e30, jnp.float32)
    l0 = jnp.zeros((q_block,), jnp.float32)
    acc0 = jnp.zeros((q_block, d), jnp.float32)

    if causal:
        # only K blocks up to (and including) the diagonal participate
        upper = jax.lax.div((qi + 1) * q_block + block_k - 1, block_k)
        upper = jnp.minimum(upper, num_kb)
    else:
        upper = num_kb
    m, l, acc = jax.lax.fori_loop(0, upper, body, (m0, l0, acc0))
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)


def _reference_attention(q, k, v, causal: bool, sm_scale: float):
    """Unfused GQA attention (fp32 softmax) — the backward-pass recompute path
    and the numerical reference for tests."""
    b, t, h, d = q.shape
    s, kh = k.shape[1], k.shape[2]
    group = h // kh
    qg = q.reshape(b, t, kh, group, d)
    scores = jnp.einsum("btkgd,bskd->bkgts", qg, k).astype(jnp.float32) * sm_scale
    if causal:
        mask = jnp.tril(jnp.ones((t, s), dtype=bool), k=s - t)
        scores = jnp.where(mask[None, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, v)
    return out.reshape(b, t, h, d)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    sm_scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """q: [B, T, H, D]; k/v: [B, S, KH, D] with H % KH == 0. Returns [B, T, H, D].

    Sequence lengths must be multiples of the block sizes (pad upstream);
    block sizes auto-shrink for short sequences. Differentiable: the backward
    pass recomputes attention flash-style (activations are never saved), via
    ``jax.custom_vjp``.
    """
    b, t, h, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash(q, k, v, causal, float(sm_scale), min(block_q, t), min(block_k, k.shape[1]), bool(interpret))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    return _flash_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k, interpret)


def _flash_vjp_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out = _flash_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_vjp_bwd(causal, sm_scale, block_q, block_k, interpret, residuals, g):
    q, k, v = residuals
    # Recompute-based backward: O(1) saved activations. A dedicated Pallas
    # backward kernel can replace this without touching the public API.
    _, vjp = jax.vjp(lambda q, k, v: _reference_attention(q, k, v, causal, sm_scale), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _flash_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    b, t, h, d = q.shape
    s, kh = k.shape[1], k.shape[2]
    if h % kh:
        raise ValueError(f"query heads {h} not a multiple of kv heads {kh}")
    group = h // kh
    if t % block_q or s % block_k:
        raise ValueError(f"seq lens ({t}, {s}) must be multiples of block sizes ({block_q}, {block_k})")

    # [B, T, H, D] -> [B*H, T, D] so the grid's leading axis is one (batch, head)
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * kh, s, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * kh, s, d)

    def kv_index(bh, qi):
        return (bh // h) * kh + (bh % h) // group

    kernel = functools.partial(
        _attn_kernel, block_k=block_k, causal=causal, sm_scale=sm_scale, q_block=block_q
    )
    vmem = {} if _VMEM is None else {"memory_space": _VMEM}
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        grid=(b * h, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0), **vmem),
            pl.BlockSpec((1, s, d), lambda bh, qi: (kv_index(bh, qi), 0, 0), **vmem),
            pl.BlockSpec((1, s, d), lambda bh, qi: (kv_index(bh, qi), 0, 0), **vmem),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0), **vmem),
        interpret=interpret,
    )(qt, kt, vt)

    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
