"""Flash attention as Pallas TPU kernels (forward AND backward).

The reference framework has no attention code at all (SURVEY.md §5.7); models
were user-space. The TPU build ships attention as a first-class fused op
because it is *the* hot op of the transformer configs in BASELINE.json.

Kernel design (online-softmax, Dao-style but TPU-shaped):

- Forward grid: ``(batch*heads, T/block_q, S/block_k)`` — K/V stream through
  the innermost *grid* axis, so VMEM holds one [block_k, D] tile of each at a
  time (Mosaic double-buffers the pipeline); sequence length never enters the
  VMEM footprint. The online-softmax carry (running max/denominator/output
  accumulator, fp32) lives in VMEM scratch, persisting across the K-block
  axis. No [T, S] score matrix ever materialises. The differentiable path
  also writes the per-row logsumexp (the FlashAttention-2 residual: O and
  LSE, nothing else).
- Backward: two kernels sharing the saved LSE and the precomputed
  ``delta = rowsum(dO * O)``. The dQ kernel mirrors the forward grid
  (one query block, K/V on the innermost grid axis, dq in scratch); the
  dK/dV kernel transposes it (one KV block, Q/dO on the innermost axis).
  Probabilities are recomputed as ``exp(s - lse)`` — no second softmax pass,
  no saved [T, S] matrix.
- MXU does the matmuls with fp32 accumulation (``preferred_element_type``);
  VPU does the exp/renormalisation.
- Causal masking skips *entire* blocks past the diagonal in both directions
  (loop bounds depend on ``program_id``), and masks only the diagonal block.
- GQA: the K/V block index map folds the query head onto its KV head, so
  grouped heads reread the same VMEM block instead of materialising repeats;
  the backward accumulates per-query-head dK/dV and group-sums outside the
  kernel.

Off-TPU the op does NOT interpret the Pallas kernels by default any more:
interpret mode emulates the grid step by step and LOSES to the unfused
einsum path (measured 0.90x fwd / 0.48x fwd+bwd on the CPU smoke config —
the PR 6 receipts). Instead ``impl="xla"`` (the off-TPU default) lowers the
SAME blockwise algorithm to plain XLA ops: a static Python loop over query
blocks, causal/window K-truncation per block (the compute saving survives),
the identical LSE residual, and the identical recompute-from-statistics
custom backward — so training off-TPU pays the flash algorithm, not the
interpreter. ``impl="pallas"`` with ``interpret=True`` keeps the bit-exact
kernel emulation for kernel-logic tests. Both Pallas modes need
``jax.experimental.pallas.tpu`` importable — the scratch accumulators are
``pltpu.VMEM`` allocations even under interpretation.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # degrade to a clear RuntimeError at call time if this jax lacks pltpu
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    _VMEM = None

_NEG_INF = -1e30
#: TPU vector lane count: per-row stats (LSE, delta) are stored broadcast
#: across one lane tile, the layout Mosaic can store without dynamic
#: sublane indexing (same scheme as jax.experimental.pallas.ops.tpu).
_LANES = 128

#: default query-block of the XLA (off-TPU) path: small enough that causal
#: K-truncation prunes ~40% of the score matmuls at CPU-bench sequence
#: lengths, large enough to keep per-block dispatch negligible. Measured on
#: the CPU smoke config (S=512): 128-blocks run the fwd at ~1.4x the unfused
#: einsum where a single 512 block only breaks even.
_XLA_BLOCK_Q = 128


def _default_mode(interpret: bool | None):
    """Resolve the execution mode shared by this module and ring_attention:
    an explicit ``interpret`` pins the Pallas kernels (compiled or
    emulated); otherwise TPU runs them compiled and every other backend
    takes the blockwise-XLA path."""
    if interpret is not None:
        return bool(interpret)
    return False if jax.default_backend() == "tpu" else "xla"


def _window_mask(s, q0, k0, q_block, block_k, causal: bool, window: int | None):
    """Apply causal (and optional sliding-window) masking to a [bq, bk] score
    block whose top-left element is (q0, k0). ``window`` = W keeps
    ``q_pos - k_pos < W`` (self + W-1 predecessors), the Mistral convention."""
    if not causal and window is None:
        return s
    q_pos = q0 + jax.lax.broadcasted_iota(jnp.int32, (q_block, block_k), 0)
    k_pos = k0 + jax.lax.broadcasted_iota(jnp.int32, (q_block, block_k), 1)
    keep = q_pos >= k_pos if causal else None
    if window is not None:
        wkeep = (q_pos - k_pos) < window
        keep = wkeep if keep is None else keep & wkeep
    return jnp.where(keep, s, _NEG_INF)


#: sublane count for the kv-side segment-id layout ([B, _SUBLANES, S]): a
#: (1, 8, block_k) block yields the [1, bk] ROW the mask comparison needs
#: without an in-kernel transpose (the q side is lane-broadcast instead).
_SUBLANES = 8


def _segment_mask(s, seg_q_ref, seg_kv_ref):
    """Mask cross-segment pairs: seg_q_ref [1, bq, _LANES] (lane-broadcast),
    seg_kv_ref [1, _SUBLANES, bk] (sublane-broadcast)."""
    if seg_q_ref is None:
        return s
    q_ids = seg_q_ref[0][:, :1]  # [bq, 1]
    k_ids = seg_kv_ref[0][:1, :]  # [1, bk]
    return jnp.where(q_ids == k_ids, s, _NEG_INF)


def _maybe_when(cond, fn):
    """Run ``fn`` under ``pl.when`` unless the condition is statically True."""
    if cond is True:
        fn()
    else:
        pl.when(cond)(fn)


def _kv_skip_cond(qi, kb, q_block: int, block_k: int, causal: bool, window: int | None):
    """Participation condition for a (q-block, streamed K-block) pair —
    shared by the forward and dQ kernels so their skip bounds can never
    drift from each other (a divergence would feed exp(s - lse) garbage
    into whichever side still ran the block)."""
    cond = True
    if causal:
        cond = kb * block_k <= qi * q_block + q_block - 1
    if window is not None:
        cond &= kb * block_k + block_k - 1 >= qi * q_block - window + 1
    return cond


def _q_skip_cond(qb, kb, block_q: int, k_block: int, causal: bool, window: int | None):
    """The dK/dV kernel's transposed participation condition (fixed KV
    block, streamed Q block) — the mirror of :func:`_kv_skip_cond`."""
    cond = True
    if causal:
        cond = (qb + 1) * block_q - 1 >= kb * k_block
    if window is not None:
        cond &= qb * block_q <= kb * k_block + k_block + window - 2
    return cond


def _attn_kernel(
    q_ref, k_ref, v_ref, *rest, block_k: int, causal: bool, sm_scale: float, q_block: int,
    num_kb: int, window: int | None, with_segments: bool = False
):
    # Grid (B*H, T/block_q, S/block_k) — K/V STREAM through the innermost
    # grid axis, so VMEM holds one [block_k, D] tile of each at a time (plus
    # Mosaic's pipeline double-buffer) regardless of sequence length; the
    # whole-sequence layout of the first design collided with the ~16 MB VMEM
    # budget around S≈32k. The online-softmax carry (m, l, acc) lives in VMEM
    # scratch, persisting across the kb axis for a fixed (bh, qi).
    #
    # q_ref: [1, block_q, D]; k_ref/v_ref: [1, block_k, D]; o_ref: [1, block_q, D];
    # optional lse_ref: [1, block_q, _LANES] — the FlashAttention-2 residual,
    # lane-broadcast (TPU tiling forbids (1, bq) blocks); scratch m/l are
    # lane-broadcast too, acc is [block_q, D] fp32.
    if with_segments:
        seg_q_ref, seg_kv_ref, *rest = rest
    else:
        seg_q_ref = seg_kv_ref = None
    o_ref, *rest = rest
    if len(rest) == 4:
        lse_ref, m_ref, l_ref, acc_ref = rest
    else:
        (m_ref, l_ref, acc_ref), lse_ref = rest, None
    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _accumulate():
        q = q_ref[0]  # [bq, D] — native dtype: bf16 operands keep the MXU fast
        k = k_ref[0]  # [bk, D]
        v = v_ref[0]
        s = (
            jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
            * sm_scale
        )  # [bq, bk] fp32
        s = _window_mask(s, qi * q_block, kb * block_k, q_block, block_k, causal, window)
        s = _segment_mask(s, seg_q_ref, seg_kv_ref)
        m_prev = m_ref[:, :1]  # [bq, 1]
        l_prev = l_ref[:, :1]
        blk_max = jnp.max(s, axis=-1, keepdims=True)  # [bq, 1]
        new_m = jnp.maximum(m_prev, blk_max)
        correction = jnp.exp(m_prev - new_m)
        p = jnp.exp(s - new_m)  # [bq, bk]
        # a row fully masked within this visited block has s == new_m ==
        # _NEG_INF, making p == exp(0) == 1 per masked entry — zero it so
        # dead rows really keep l == 0 / out == 0 (not a mean of V)
        p = jnp.where(blk_max > _NEG_INF / 2, p, 0.0)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[...] = jnp.broadcast_to(new_m, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_prev * correction + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape)
        acc_ref[...] = acc_ref[...] * correction + pv

    # K blocks fully past the diagonal (causal) or entirely older than the
    # window contribute nothing — skip them (window applies without causal
    # too: the ring's behind-hops call with causal=False and a shifted
    # window)
    _maybe_when(_kv_skip_cond(qi, kb, q_block, block_k, causal, window), _accumulate)

    @pl.when(kb == num_kb - 1)
    def _write():
        # dead rows (every K block skipped, or fully masked in every block
        # actually visited — both possible for windowed non-causal ring
        # hops) keep l == 0 thanks to the dead-row p-zeroing above: the tiny
        # floor makes their output 0 and their lse ~ -1e30 - 69 (FINITE, so
        # the ring merge weight underflows to exactly 0 and the backward's
        # exp(s - lse) stays finite); live rows always have l >~ 1, untouched
        l_safe = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l_safe[:, :1]).astype(o_ref.dtype)
        if lse_ref is not None:
            lse_ref[0] = m_ref[...] + jnp.log(l_safe)


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    block_k: int, causal: bool, sm_scale: float, q_block: int, num_kb: int, window: int | None,
    with_segments: bool = False
):
    if with_segments:
        seg_q_ref, seg_kv_ref, dq_ref, acc_ref = rest
    else:
        (dq_ref, acc_ref), seg_q_ref, seg_kv_ref = rest, None, None
    # Grid (B*H, T/block_q, S/block_k): K/V stream through the innermost grid
    # axis (same VMEM-bounded layout as the forward); dq accumulates in fp32
    # VMEM scratch across kb and is written once at the last K block.
    # lse_ref/delta_ref: [1, block_q, _LANES], lane-broadcast per-row stats.
    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _accumulate():
        q = q_ref[0]  # [bq, D] — native dtype operands, fp32 accumulation
        do = do_ref[0]  # [bq, D]
        lse = lse_ref[0][:, :1]  # [bq, 1]
        delta = delta_ref[0][:, :1]  # [bq, 1]
        k = k_ref[0]  # [bk, D]
        v = v_ref[0]
        s = (
            jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
            * sm_scale
        )  # [bq, bk]
        s = _window_mask(s, qi * q_block, kb * block_k, q_block, block_k, causal, window)
        s = _segment_mask(s, seg_q_ref, seg_kv_ref)
        p = jnp.exp(s - lse)  # [bq, bk] fp32; masked entries underflow to 0
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * sm_scale).astype(k.dtype)
        acc_ref[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    _maybe_when(_kv_skip_cond(qi, kb, q_block, block_k, causal, window), _accumulate)

    @pl.when(kb == num_kb - 1)
    def _write():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    block_q: int, causal: bool, sm_scale: float, k_block: int, window: int | None,
    with_segments: bool = False
):
    if with_segments:
        seg_q_ref, seg_kv_ref, dk_ref, dv_ref = rest
    else:
        (dk_ref, dv_ref), seg_q_ref, seg_kv_ref = rest, None, None
    # grid (B*H, S/block_k, T/block_q): one KV block accumulates across the
    # innermost q-block dimension (dk/dv output blocks are revisited — they
    # stay resident in VMEM until kb advances). Q/dO/stats stream per step,
    # so VMEM use is O(block) regardless of sequence length.
    kb = pl.program_id(1)
    qb = pl.program_id(2)

    @pl.when(qb == 0)
    def _init():
        dk_ref[0] = jnp.zeros_like(dk_ref[0])
        dv_ref[0] = jnp.zeros_like(dv_ref[0])

    def _accumulate():
        k = k_ref[0]  # [bk, D] — native dtype operands, fp32 accumulation
        v = v_ref[0]
        q = q_ref[0]  # [bq, D]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]  # [bq, 1]
        delta = delta_ref[0][:, :1]
        s = (
            jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
            * sm_scale
        )  # [bq, bk]
        s = _window_mask(s, qb * block_q, kb * k_block, block_q, k_block, causal, window)
        s = _segment_mask(s, seg_q_ref, seg_kv_ref)
        p = jnp.exp(s - lse)  # [bq, bk] fp32
        dv_ref[0] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        ).astype(dv_ref.dtype)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        dk_ref[0] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        ).astype(dk_ref.dtype)

    # skip q blocks entirely above the diagonal (causal — their p is all
    # zero) or entirely past k_last + window (windowed, causal or not)
    _maybe_when(_q_skip_cond(qb, kb, block_q, k_block, causal, window), _accumulate)


def _auto_block(requested: int, seq: int) -> int:
    """Largest block <= requested that divides ``seq`` (halving the request
    until it divides), so the large default blocks serve any seq len that is
    a multiple of 64 — e.g. a 640-token sequence gets 128-blocks instead of
    an error, and a 384-token one uses a single 384 block. Never shrinks
    below 64 (or below an explicit smaller request): a seq len not divisible
    by 64 still raises, instead of silently degrading to a tile too small
    for the MXU — pad upstream."""
    blk = min(requested, seq)
    floor = min(requested, 64)
    while blk > floor and seq % blk:
        blk //= 2
    return blk


def _reference_attention(q, k, v, causal: bool, sm_scale: float, window: int | None = None):
    """Unfused GQA attention (fp32 softmax) — the numerical reference for tests."""
    b, t, h, d = q.shape
    s, kh = k.shape[1], k.shape[2]
    group = h // kh
    qg = q.reshape(b, t, kh, group, d)
    scores = jnp.einsum("btkgd,bskd->bkgts", qg, k).astype(jnp.float32) * sm_scale
    if causal or window is not None:
        mask = jnp.tril(jnp.ones((t, s), dtype=bool), k=s - t) if causal else jnp.ones((t, s), bool)
        if window is not None:
            dist = jnp.arange(t)[:, None] - jnp.arange(s)[None, :] + (s - t)
            mask = mask & (dist < window)
        scores = jnp.where(mask[None, None, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, v)
    return out.reshape(b, t, h, d)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    sm_scale: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    return_lse: bool = False,
    window: int | None = None,
    segment_ids: jnp.ndarray | None = None,
    impl: str | None = None,
) -> jnp.ndarray | tuple[jnp.ndarray, jnp.ndarray]:
    """q: [B, T, H, D]; k/v: [B, S, KH, D] with H % KH == 0. Returns [B, T, H, D].

    ``window`` = W enables sliding-window attention (requires ``causal``):
    each query attends to itself and its W-1 predecessors
    (``q_pos - k_pos < W``, the Mistral convention). K/V blocks entirely
    older than the window are skipped in the grid AND their DMAs elided, so
    compute and HBM traffic scale with O(T·W) instead of O(T²).

    ``segment_ids`` ([B, T] int32, requires T == S) masks cross-segment
    pairs for packed-sequence training; composes with ``causal`` and
    ``window``. The ids ride into the kernels lane-/sublane-broadcast
    (extra ~(128+8)·4 bytes/token of HBM), and fully-masked rows follow
    the same lse-floor self-healing as windowed calls.

    Sequence lengths must be multiples of the block sizes (pad upstream);
    block sizes auto-shrink for short sequences. Differentiable end-to-end in
    Pallas: the forward saves only O and the per-row logsumexp, and the
    backward recomputes probabilities flash-style in two kernels (dQ;
    dK/dV) — activations never materialise in HBM.

    ``impl`` picks the lowering: ``"pallas"`` (the TPU kernels; honoured in
    interpret mode off-TPU) or ``"xla"`` (the same blockwise algorithm as
    plain XLA ops — the off-TPU default, since interpret mode loses to the
    unfused path; see the module docstring). ``None`` auto-selects, except
    an explicit ``interpret`` pins ``"pallas"``.

    Default Pallas blocks are large (512x1024) because the grid-step
    overhead, not VMEM, is the binding constraint on TPU: measured on v5e,
    256x256 blocks LOSE to the unfused einsum path while 512x1024 is ~1.5x
    faster at S=4k and ~2.3x at S=8k (fwd, causal, d=64..128). The XLA path
    defaults to 128-row query blocks (block_k is ignored there: each query
    block reads its causally/window-truncated K slice in one piece).

    With ``return_lse=True`` returns ``(out, lse)`` where ``lse`` is the
    per-row logsumexp of the scaled scores, shape [B, T, H] — the residual a
    blockwise/ring combiner needs to merge partial attention outputs. This
    path is differentiable in BOTH outputs (the lse cotangent folds into the
    backward kernels' delta term, since d lse/d s = p).
    """
    b, t, h, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if impl is None:
        mode = _default_mode(interpret)
    elif impl == "xla":
        mode = "xla"
    elif impl == "pallas":
        mode = bool(interpret) if interpret is not None else jax.default_backend() != "tpu"
    else:
        raise ValueError(f"impl must be 'pallas', 'xla' or None, got {impl!r}")
    if block_q is None:
        block_q = _XLA_BLOCK_Q if mode == "xla" else 512
    if block_k is None:
        block_k = 1024
    if causal and t != k.shape[1]:
        # the kernels mask with top-left alignment (q_pos >= k_pos); a
        # KV-cache-style bottom-right alignment for T != S is a different
        # mask — reject instead of silently attending to the wrong keys
        raise ValueError(
            f"causal flash attention requires equal Q/KV sequence lengths, got {t} != {k.shape[1]}"
        )
    if window is not None:
        if not causal:
            raise ValueError("window (sliding-window attention) requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        window = int(window)
    if segment_ids is not None:
        segment_ids = jnp.asarray(segment_ids, jnp.int32)
        if segment_ids.shape != (b, t):
            raise ValueError(f"segment_ids must be [B, T] == {(b, t)}, got {segment_ids.shape}")
        if t != k.shape[1]:
            raise ValueError("segment_ids require equal Q/KV sequence lengths (self-attention packing)")
    bq, bk = _auto_block(block_q, t), _auto_block(block_k, k.shape[1])
    if return_lse:
        out, lse = _flash_lse(q, k, v, segment_ids, causal, float(sm_scale), bq, bk, mode, window)
        return out, lse.reshape(b, h, t).transpose(0, 2, 1)  # [B, T, H]
    return _flash(q, k, v, segment_ids, causal, float(sm_scale), bq, bk, mode, window)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash(q, k, v, seg, causal, sm_scale, block_q, block_k, mode, window):
    # ``mode`` is the static lowering selector: False/True run the Pallas
    # kernels (compiled/interpreted), "xla" the blockwise-XLA twin
    return _flash_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k, mode, window, seg)


def _flash_vjp_fwd(q, k, v, seg, causal, sm_scale, block_q, block_k, mode, window):
    out, lse = _flash_fwd_impl(
        q, k, v, causal, sm_scale, block_q, block_k, mode, window, seg, with_residuals=True
    )
    return out, (q, k, v, seg, out, lse)


def _flash_vjp_bwd(causal, sm_scale, block_q, block_k, mode, window, residuals, g):
    q, k, v, seg, out, lse = residuals
    dq, dk, dv = _flash_bwd_impl(
        q, k, v, out, lse, g, causal, sm_scale, block_q, block_k, mode, window, seg
    )
    return dq, dk, dv, None  # integer segment ids carry no cotangent


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash_lse(q, k, v, seg, causal, sm_scale, block_q, block_k, mode, window):
    """(out, lse[B*H, T]) variant for blockwise/ring combiners."""
    return _flash_fwd_impl(
        q, k, v, causal, sm_scale, block_q, block_k, mode, window, seg, with_residuals=True
    )


def _flash_lse_vjp_fwd(q, k, v, seg, causal, sm_scale, block_q, block_k, mode, window):
    out, lse = _flash_fwd_impl(
        q, k, v, causal, sm_scale, block_q, block_k, mode, window, seg, with_residuals=True
    )
    return (out, lse), (q, k, v, seg, out, lse)


def _flash_lse_vjp_bwd(causal, sm_scale, block_q, block_k, mode, window, residuals, gs):
    g_out, g_lse = gs
    q, k, v, seg, out, lse = residuals
    # d lse_i / d s_ij = p_ij, so the lse cotangent enters the existing
    # backward as ds += p * g_lse — algebraically a shift of the delta term:
    # ds = p * (dp - (delta - g_lse)). Zero kernel changes needed.
    dq, dk, dv = _flash_bwd_impl(
        q, k, v, out, lse, g_out, causal, sm_scale, block_q, block_k, mode, window, seg,
        lse_cotangent=g_lse,
    )
    return dq, dk, dv, None


_flash_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


def _fold_heads(x):
    """[B, T, H, D] -> [B*H, T, D] (grid leading axis = one (batch, head))."""
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _make_kv_index(h: int, kh: int):
    """Block index map folding a query head onto its KV head (GQA) — shared
    by the forward and both backward pallas_calls so the folding can never
    desynchronise."""
    group = h // kh

    def kv_index(bh, *_):
        return (bh // h) * kh + (bh % h) // group

    return kv_index


def _clamp_kv_stream(kb, qi, block_q: int, block_k: int, causal: bool, window: int | None = None, num_kb: int = 1):
    """Clamp the streamed K-block index under causal masking so fully skipped
    grid steps (past the diagonal — and, with a sliding window, older than
    the window) re-request an adjacent participating block index — Mosaic
    elides the DMA when consecutive steps map to the same block, saving the
    K/V HBM traffic that `pl.when` alone would still copy and discard."""
    if not causal and window is None:
        return kb
    lo = None
    if window is not None:
        # cap inside the grid: a strongly negative shifted window can push
        # the raw lo past the last block — the pl.when skip covers those
        # steps, but the INDEX handed to the DMA must still be in range
        lo = jnp.minimum(jnp.maximum(qi * block_q - window + 1, 0) // block_k, num_kb - 1)
    if not causal:
        return jnp.maximum(kb, lo)
    hi = ((qi + 1) * block_q - 1) // block_k
    if lo is not None:
        return jnp.clip(kb, lo, hi)
    return jnp.minimum(kb, hi)


def _clamp_q_stream(qb, kb, block_q: int, block_k: int, causal: bool, window: int | None = None):
    """Same trick for the dK/dV kernel's streamed Q axis: Q blocks entirely
    above the diagonal (or, with a sliding window, entirely past
    k_last + window) for this KV block are clamped to an adjacent
    participating block."""
    if not causal and window is None:
        return qb
    hi = None
    if window is not None:
        hi = jnp.maximum(kb * block_k + block_k - 1 + window - 1, 0) // block_q
    if not causal:
        return jnp.clip(qb, 0, hi)
    lo = (kb * block_k) // block_q
    if hi is not None:
        return jnp.clip(qb, lo, hi)
    return jnp.maximum(qb, lo)


def _seg_layouts(seg, b, t, s):
    """[B, T] segment ids -> the two kernel layouts (see _SUBLANES note)."""
    seg = jnp.asarray(seg, jnp.int32)
    seg_q3 = jnp.broadcast_to(seg[:, :, None], (b, t, _LANES))
    seg_kv3 = jnp.broadcast_to(seg[:, None, :], (b, _SUBLANES, s))
    return seg_q3, seg_kv3


def _xla_bounds(q0: int, block_q: int, s: int, causal: bool, window: int | None):
    """Static K-range [lo, hi) a query block [q0, q0+block_q) can attend to —
    the XLA path's analogue of the kernels' grid skipping (causal prunes
    everything past the diagonal block, a window everything older than the
    FIRST row's reach; a negative ring-shifted window can empty the range)."""
    hi = min(s, q0 + block_q) if causal else s
    lo = 0
    if window is not None:
        lo = max(0, q0 - window + 1)
    return min(lo, hi), hi


def _xla_keep(q0, block_q, lo, hi, causal, window, seg):
    """Boolean keep-mask [1 or B, block_q, hi-lo] for one query block, or
    None when nothing is masked. Mirrors _window_mask/_segment_mask."""
    keep = None
    if causal or window is not None:
        q_pos = q0 + jnp.arange(block_q)[:, None]
        k_pos = lo + jnp.arange(hi - lo)[None, :]
        if causal:
            keep = q_pos >= k_pos
        if window is not None:
            wkeep = (q_pos - k_pos) < window
            keep = wkeep if keep is None else keep & wkeep
        keep = keep[None]
    if seg is not None:
        same = (
            jax.lax.slice_in_dim(seg, q0, q0 + block_q, axis=1)[:, :, None]
            == jax.lax.slice_in_dim(seg, lo, hi, axis=1)[:, None, :]
        )
        keep = same if keep is None else keep & same
    return keep


def _xla_fwd(q, k, v, causal, sm_scale, block_q, window=None, seg=None, with_residuals=False):
    """Blockwise flash attention as plain XLA ops (the off-TPU lowering):
    a static loop over query blocks, each reading only its causally/window-
    truncated K/V slice. Same GQA einsum grouping as the reference (K/V are
    never materialised per query head), same dead-row self-healing and LSE
    residual semantics as the kernels."""
    b, t, h, d = q.shape
    s, kh = k.shape[1], k.shape[2]
    if h % kh:
        raise ValueError(f"query heads {h} not a multiple of kv heads {kh}")
    if t % block_q:
        raise ValueError(f"seq len {t} must be a multiple of block size {block_q}")
    group = h // kh
    qf = q.reshape(b, t, kh, group, d)
    outs, lses = [], []
    for q0 in range(0, t, block_q):
        lo, hi = _xla_bounds(q0, block_q, s, causal, window)
        if lo >= hi:  # fully dead block (ring hop outside the window)
            outs.append(jnp.zeros((b, block_q, h, d), q.dtype))
            lses.append(jnp.full((b, block_q, h), _NEG_INF + math.log(1e-30), jnp.float32))
            continue
        qb = jax.lax.slice_in_dim(qf, q0, q0 + block_q, axis=1)
        kb = jax.lax.slice_in_dim(k, lo, hi, axis=1)
        vb = jax.lax.slice_in_dim(v, lo, hi, axis=1)
        sc = (
            jnp.einsum("btkgd,bskd->bkgts", qb, kb, preferred_element_type=jnp.float32)
            * sm_scale
        )  # [B, KH, G, bq, hi-lo] fp32
        keep = _xla_keep(q0, block_q, lo, hi, causal, window, seg)
        if keep is not None:
            sc = jnp.where(keep[:, None, None], sc, _NEG_INF)
        m = jnp.max(sc, axis=-1)  # [B, KH, G, bq]
        p = jnp.exp(sc - m[..., None])
        # dead rows (fully masked): zero p so out == 0, matching the kernels
        p = jnp.where((m > _NEG_INF / 2)[..., None], p, 0.0)
        l_safe = jnp.maximum(jnp.sum(p, axis=-1), 1e-30)
        o = jnp.einsum(
            "bkgts,bskd->btkgd", (p / l_safe[..., None]).astype(v.dtype), vb
        )
        outs.append(o.reshape(b, block_q, h, d).astype(q.dtype))
        if with_residuals:
            lses.append((m + jnp.log(l_safe)).transpose(0, 3, 1, 2).reshape(b, block_q, h))
    out = jnp.concatenate(outs, axis=1) if len(outs) > 1 else outs[0]
    if with_residuals:
        lse = jnp.concatenate(lses, axis=1) if len(lses) > 1 else lses[0]
        return out, lse.transpose(0, 2, 1).reshape(b * h, t)  # kernel residual layout
    return out


def _xla_bwd(
    q, k, v, out, lse, g, causal, sm_scale, block_q, window=None, seg=None, lse_cotangent=None
):
    """Backward of the XLA path: per query block, recompute the probabilities
    from the saved LSE (never a forward replay), then the standard
    dq/dk/dv flash formulas with dk/dv accumulated into their static K
    slices. fp32 accumulation, operands in the input dtype — mirrors the
    Pallas backward kernels' dataflow."""
    b, t, h, d = q.shape
    s, kh = k.shape[1], k.shape[2]
    group = h // kh
    # delta_i = rowsum(dO_i * O_i); an lse cotangent folds in as a shift
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)  # [B, T, H]
    if lse_cotangent is not None:
        delta = delta - lse_cotangent.astype(jnp.float32).reshape(b, h, t).transpose(0, 2, 1)
    lse_bth = lse.reshape(b, h, t).transpose(0, 2, 1)  # [B, T, H]
    qf = q.reshape(b, t, kh, group, d)
    gf = g.reshape(b, t, kh, group, d)
    dq_blocks = []
    dk = jnp.zeros((b, s, kh, d), jnp.float32)
    dv = jnp.zeros((b, s, kh, d), jnp.float32)
    for q0 in range(0, t, block_q):
        lo, hi = _xla_bounds(q0, block_q, s, causal, window)
        if lo >= hi:
            dq_blocks.append(jnp.zeros((b, block_q, h, d), q.dtype))
            continue
        qb = jax.lax.slice_in_dim(qf, q0, q0 + block_q, axis=1)
        dob = jax.lax.slice_in_dim(gf, q0, q0 + block_q, axis=1)
        kb = jax.lax.slice_in_dim(k, lo, hi, axis=1)
        vb = jax.lax.slice_in_dim(v, lo, hi, axis=1)
        to_kg = lambda x: x.transpose(0, 2, 3, 1)  # [B,bq,KH,G] -> [B,KH,G,bq]
        lse_b = to_kg(
            jax.lax.slice_in_dim(lse_bth, q0, q0 + block_q, axis=1).reshape(b, block_q, kh, group)
        )
        delta_b = to_kg(
            jax.lax.slice_in_dim(delta, q0, q0 + block_q, axis=1).reshape(b, block_q, kh, group)
        )
        sc = (
            jnp.einsum("btkgd,bskd->bkgts", qb, kb, preferred_element_type=jnp.float32)
            * sm_scale
        )
        keep = _xla_keep(q0, block_q, lo, hi, causal, window, seg)
        if keep is not None:
            sc = jnp.where(keep[:, None, None], sc, _NEG_INF)
        p = jnp.exp(sc - lse_b[..., None])  # masked entries underflow to 0
        dp = jnp.einsum("btkgd,bskd->bkgts", dob, vb, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_b[..., None]) * sm_scale).astype(k.dtype)
        dqb = jnp.einsum("bkgts,bskd->btkgd", ds, kb, preferred_element_type=jnp.float32)
        dq_blocks.append(dqb.reshape(b, block_q, h, d).astype(q.dtype))
        # group (GQA) summation happens inside the einsum contraction
        dk = dk.at[:, lo:hi].add(
            jnp.einsum("bkgts,btkgd->bskd", ds, qb, preferred_element_type=jnp.float32)
        )
        dv = dv.at[:, lo:hi].add(
            jnp.einsum("bkgts,btkgd->bskd", p.astype(g.dtype), dob, preferred_element_type=jnp.float32)
        )
    dq = jnp.concatenate(dq_blocks, axis=1) if len(dq_blocks) > 1 else dq_blocks[0]
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


def _flash_fwd_impl(
    q, k, v, causal, sm_scale, block_q, block_k, mode, window=None, seg=None, with_residuals=False
):
    if mode == "xla":
        return _xla_fwd(q, k, v, causal, sm_scale, block_q, window, seg, with_residuals)
    interpret = bool(mode)
    if _VMEM is None:
        raise RuntimeError(
            "flash_attention needs jax.experimental.pallas.tpu (VMEM scratch accumulators); "
            "it failed to import in this jax build"
        )
    b, t, h, d = q.shape
    s, kh = k.shape[1], k.shape[2]
    if h % kh:
        raise ValueError(f"query heads {h} not a multiple of kv heads {kh}")
    if t % block_q or s % block_k:
        raise ValueError(f"seq lens ({t}, {s}) must be multiples of block sizes ({block_q}, {block_k})")

    qt = _fold_heads(q)
    kt = _fold_heads(k)
    vt = _fold_heads(v)
    kv_index = _make_kv_index(h, kh)
    num_kb = s // block_k

    kernel = functools.partial(
        _attn_kernel, block_k=block_k, causal=causal, sm_scale=sm_scale, q_block=block_q,
        num_kb=num_kb, window=window, with_segments=seg is not None,
    )
    vmem = {"memory_space": _VMEM}

    def kv_block(bh, qi, kb):
        return (kv_index(bh), _clamp_kv_stream(kb, qi, block_q, block_k, causal, window, num_kb), 0)

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi, kb: (bh, qi, 0), **vmem),
        pl.BlockSpec((1, block_k, d), kv_block, **vmem),
        pl.BlockSpec((1, block_k, d), kv_block, **vmem),
    ]
    operands = [qt, kt, vt]
    if seg is not None:
        seg_q3, seg_kv3 = _seg_layouts(seg, b, t, s)
        in_specs.append(pl.BlockSpec((1, block_q, _LANES), lambda bh, qi, kb: (bh // h, qi, 0), **vmem))
        in_specs.append(
            pl.BlockSpec(
                (1, _SUBLANES, block_k),
                lambda bh, qi, kb: (bh // h, 0, _clamp_kv_stream(kb, qi, block_q, block_k, causal, window, num_kb)),
                **vmem,
            )
        )
        operands += [seg_q3, seg_kv3]

    out_shape = [jax.ShapeDtypeStruct((b * h, t, d), q.dtype)]
    out_specs = [pl.BlockSpec((1, block_q, d), lambda bh, qi, kb: (bh, qi, 0), **vmem)]
    if with_residuals:
        out_shape.append(jax.ShapeDtypeStruct((b * h, t, _LANES), jnp.float32))
        out_specs.append(pl.BlockSpec((1, block_q, _LANES), lambda bh, qi, kb: (bh, qi, 0), **vmem))
    results = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=(b * h, t // block_q, num_kb),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max m
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running denominator l
            pltpu.VMEM((block_q, d), jnp.float32),  # output accumulator
        ],
        interpret=interpret,
    )(*operands)

    out = results[0].reshape(b, h, t, d).transpose(0, 2, 1, 3)
    if with_residuals:
        # slim the residual to [B*H, T]: the lane-broadcast copy need not
        # live for the whole backward graph
        return out, results[1][:, :, 0]
    return out


def _flash_bwd_impl(
    q, k, v, out, lse, g, causal, sm_scale, block_q, block_k, mode, window=None, seg=None,
    lse_cotangent=None,
):
    if mode == "xla":
        return _xla_bwd(q, k, v, out, lse, g, causal, sm_scale, block_q, window, seg, lse_cotangent)
    interpret = bool(mode)
    b, t, h, d = q.shape
    s, kh = k.shape[1], k.shape[2]
    group = h // kh

    qt = _fold_heads(q)
    kt = _fold_heads(k)
    vt = _fold_heads(v)
    dot = _fold_heads(g)
    ot = _fold_heads(out)
    # delta_i = rowsum(dO_i * O_i) — the softmax-jacobian diagonal term;
    # stats enter the kernels lane-broadcast ([B*H, T, _LANES], TPU tiling)
    delta = jnp.sum(dot.astype(jnp.float32) * ot.astype(jnp.float32), axis=-1)  # [B*H, T]
    if lse_cotangent is not None:
        # lse's own cotangent folds in as a delta shift (see _flash_lse_vjp_bwd)
        delta = delta - lse_cotangent.astype(jnp.float32)
    delta3 = jnp.broadcast_to(delta[:, :, None], (b * h, t, _LANES))
    lse3 = jnp.broadcast_to(lse[:, :, None], (b * h, t, _LANES))
    kv_index = _make_kv_index(h, kh)

    vmem = {"memory_space": _VMEM}

    def kv_block(bh, qi, kb):
        return (kv_index(bh), _clamp_kv_stream(kb, qi, block_q, block_k, causal, window, num_kb), 0)

    num_kb = s // block_k
    dq_in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi, kb: (bh, qi, 0), **vmem),  # q
        pl.BlockSpec((1, block_k, d), kv_block, **vmem),  # k
        pl.BlockSpec((1, block_k, d), kv_block, **vmem),  # v
        pl.BlockSpec((1, block_q, d), lambda bh, qi, kb: (bh, qi, 0), **vmem),  # dO
        pl.BlockSpec((1, block_q, _LANES), lambda bh, qi, kb: (bh, qi, 0), **vmem),  # lse
        pl.BlockSpec((1, block_q, _LANES), lambda bh, qi, kb: (bh, qi, 0), **vmem),  # delta
    ]
    dq_operands = [qt, kt, vt, dot, lse3, delta3]
    if seg is not None:
        seg_q3, seg_kv3 = _seg_layouts(seg, b, t, s)
        dq_in_specs.append(pl.BlockSpec((1, block_q, _LANES), lambda bh, qi, kb: (bh // h, qi, 0), **vmem))
        dq_in_specs.append(
            pl.BlockSpec(
                (1, _SUBLANES, block_k),
                lambda bh, qi, kb: (bh // h, 0, _clamp_kv_stream(kb, qi, block_q, block_k, causal, window, num_kb)),
                **vmem,
            )
        )
        dq_operands += [seg_q3, seg_kv3]
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, block_k=block_k, causal=causal, sm_scale=sm_scale, q_block=block_q,
            num_kb=num_kb, window=window, with_segments=seg is not None,
        ),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        grid=(b * h, t // block_q, num_kb),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi, kb: (bh, qi, 0), **vmem),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],  # dq accumulator
        interpret=interpret,
    )(*dq_operands)

    # per-query-head dK/dV; group-summed below for GQA. 3D grid: the q-block
    # axis is innermost so dk/dv output blocks accumulate in VMEM.
    def q_stream(qb, kb):
        return _clamp_q_stream(qb, kb, block_q, block_k, causal, window)

    dkv_in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, kb, qb: (bh, q_stream(qb, kb), 0), **vmem),  # q
        pl.BlockSpec((1, block_k, d), lambda bh, kb, qb: (kv_index(bh, kb), kb, 0), **vmem),  # k
        pl.BlockSpec((1, block_k, d), lambda bh, kb, qb: (kv_index(bh, kb), kb, 0), **vmem),  # v
        pl.BlockSpec((1, block_q, d), lambda bh, kb, qb: (bh, q_stream(qb, kb), 0), **vmem),  # dO
        pl.BlockSpec((1, block_q, _LANES), lambda bh, kb, qb: (bh, q_stream(qb, kb), 0), **vmem),  # lse
        pl.BlockSpec((1, block_q, _LANES), lambda bh, kb, qb: (bh, q_stream(qb, kb), 0), **vmem),  # delta
    ]
    dkv_operands = [qt, kt, vt, dot, lse3, delta3]
    if seg is not None:
        seg_q3, seg_kv3 = _seg_layouts(seg, b, t, s)
        dkv_in_specs.append(
            pl.BlockSpec((1, block_q, _LANES), lambda bh, kb, qb: (bh // h, q_stream(qb, kb), 0), **vmem)
        )
        dkv_in_specs.append(
            pl.BlockSpec((1, _SUBLANES, block_k), lambda bh, kb, qb: (bh // h, 0, kb), **vmem)
        )
        dkv_operands += [seg_q3, seg_kv3]
    dk_h, dv_h = pl.pallas_call(
        functools.partial(
            _dkv_kernel, block_q=block_q, causal=causal, sm_scale=sm_scale, k_block=block_k,
            window=window, with_segments=seg is not None,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), jnp.float32),
            jax.ShapeDtypeStruct((b * h, s, d), jnp.float32),
        ],
        grid=(b * h, s // block_k, t // block_q),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, kb, qb: (bh, kb, 0), **vmem),
            pl.BlockSpec((1, block_k, d), lambda bh, kb, qb: (bh, kb, 0), **vmem),
        ],
        interpret=interpret,
    )(*dkv_operands)

    dq = dq.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    dk = dk_h.reshape(b, kh, group, s, d).sum(axis=2).transpose(0, 2, 1, 3).astype(k.dtype)
    dv = dv_h.reshape(b, kh, group, s, d).sum(axis=2).transpose(0, 2, 1, 3).astype(v.dtype)
    return dq, dk, dv
