"""Ring attention: sequence/context parallelism over a mesh axis.

The reference has no sequence-parallel machinery (SURVEY.md §5.7); this is the
TPU build's long-context path. Activations are sharded along the sequence
dimension over the ``seq`` mesh axis; K/V blocks rotate around the ring with
``ppermute`` over ICI while each device merges its queries' attention against
each visiting block. Memory per device is O(T/n); no device ever holds the
full sequence — exact attention at arbitrary context length.

The per-block attention IS the fused Pallas flash kernel
(ops/flash_attention.py) called with ``return_lse=True``: operands stay in
their native dtype (bf16 on the MXU), no [Tl, Tk] score matrix ever reaches
HBM, and the visiting blocks' normalized outputs are merged with the
standard blockwise combination — running max over block LSEs, exp-corrected
weighted sum — carried in fp32. Under causal masking, ``lax.switch`` runs
the non-causal kernel for blocks behind this device, the causal kernel for
the diagonal block, and skips blocks ahead entirely (weight exp(-inf)).
Gradients flow through the merge AND through the kernel's lse output
(``_flash_lse`` custom_vjp).

Two entry points:

- ``ring_attention(q, k, v, axis_name=...)``: call *inside* an existing
  ``shard_map`` over the seq axis (the usual case when the whole train step is
  shard_mapped).
- ``ring_attention_sharded(q, k, v, mesh, axis_name=...)``: wraps itself in a
  ``shard_map`` over ``mesh`` for use under plain ``jit`` — activations get
  resharded to P(None, 'seq') around the call.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .flash_attention import flash_attention

_NEG_INF = -1e30


def _axis_size(axis_name) -> int:
    """``jax.lax.axis_size`` across jax versions: 0.4.x has no such
    function — ``psum(1, axis)`` is the classic idiom there (folded to a
    compile-time constant for a concrete mesh axis)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def _merge_partials(m, w, acc, out_b, lse_b):
    """Blockwise combination of normalized attention partials:
    out = Σ_b exp(lse_b)·out_b / Σ_b exp(lse_b), carried with a running max
    for stability. The ONE numerically sensitive merge, shared by the
    scanned and the windowed-unrolled ring loops."""
    new_m = jnp.maximum(m, lse_b)
    c_prev = jnp.exp(m - new_m)
    c_new = jnp.exp(lse_b - new_m)
    acc = acc * c_prev[..., None] + out_b.astype(jnp.float32) * c_new[..., None]
    return new_m, w * c_prev + c_new, acc


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = "seq",
    causal: bool = True,
    sm_scale: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    window: int | None = None,
) -> jnp.ndarray:
    """Exact attention over a sequence sharded on ``axis_name``.

    Shapes (per device): q [B, Tl, H, D]; k/v [B, Tl, KH, D] where Tl is the
    local sequence block. Must be called inside shard_map/pmap with
    ``axis_name`` mapped. Returns [B, Tl, H, D].

    ``window`` = W (requires ``causal``) makes the attention sliding-window
    over GLOBAL positions — and because the ring step distance is static,
    the ring visits only ``1 + ceil((W-1)/Tl)`` blocks instead of all n:
    long-context windowed training communicates O(W), not O(T).
    """
    b, tl, h, d = q.shape
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)

    if window is not None:
        if not causal:
            raise ValueError("window (sliding-window ring attention) requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        return _ring_attention_windowed(
            q, k, v, axis_name, int(window), sm_scale, block_q, block_k, interpret
        )

    flash = partial(
        flash_attention,
        sm_scale=sm_scale,
        block_q=block_q,
        block_k=block_k,
        interpret=interpret,
        return_lse=True,
    )

    def behind_block(q, kb, vb):  # src strictly before this device: no mask
        return flash(q, kb, vb, causal=False)

    def diagonal_block(q, kb, vb):  # this device's own block: causal mask
        return flash(q, kb, vb, causal=True)

    def ahead_block(q, kb, vb):  # src strictly after: fully masked, skip
        return (
            jnp.zeros((b, tl, h, d), q.dtype),
            jnp.full((b, tl, h), _NEG_INF, jnp.float32),
        )

    m0 = jnp.full((b, tl, h), _NEG_INF, jnp.float32)
    w0 = jnp.zeros((b, tl, h), jnp.float32)
    acc0 = jnp.zeros((b, tl, h, d), jnp.float32)

    def body(carry, step):
        m, w, acc, kb, vb = carry
        src = (idx - step) % n  # which sequence block kb/vb holds

        if causal:
            branch = jnp.where(src == idx, 1, jnp.where(src < idx, 0, 2))
            out_b, lse_b = jax.lax.switch(
                branch, [behind_block, diagonal_block, ahead_block], q, kb, vb
            )
        else:
            out_b, lse_b = behind_block(q, kb, vb)

        m, w, acc = _merge_partials(m, w, acc, out_b, lse_b)

        # rotate K/V around the ring (ICI neighbour exchange, overlaps compute)
        perm = [(i, (i + 1) % n) for i in range(n)]
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        return (m, w, acc, kb, vb), None

    # the scan is over ring HOPS, not layers: the carry is O(1) merge stats
    # (m/w/acc) and the heavy per-block attention is the flash custom-vjp,
    # which already recomputes instead of saving
    # dmllint: disable-next-line=DML206 -- ring hops, remat would re-run the whole ring
    (m, w, acc, _, _), _ = jax.lax.scan(body, (m0, w0, acc0, k, v), jnp.arange(n))
    return (acc / w[..., None]).astype(q.dtype)


def _ring_attention_windowed(q, k, v, axis_name, window, sm_scale, block_q, block_k, interpret):
    """Causal sliding-window ring attention.

    The ring step distance is STATIC (at hop ``step``, a device either holds
    the block exactly ``step`` positions behind it, or a wrapped-around
    ahead-block it must skip), so the loop unrolls in Python: hop 0 is the
    diagonal (causal + window), hop ``step`` uses the flash kernel with the
    distance-shifted relative cutoff ``window - step*Tl``, and hops whose
    nearest pair is already outside the window never run — the loop AND the
    ppermutes stop after ``1 + ceil((window-1)/Tl)`` hops. Dead rows (no
    valid key in a visiting block — every kernel block skipped) get a
    floored lse of ~ -1e30 from the kernel write, so their merge weight
    underflows to exactly zero, forward and backward."""
    import math as _math

    from .flash_attention import _auto_block, _flash_lse

    b, tl, h, d = q.shape
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    if sm_scale is None:
        sm_scale = 1.0 / _math.sqrt(d)
    from .flash_attention import _XLA_BLOCK_Q, _default_mode

    mode = _default_mode(interpret)
    if block_q is None:
        block_q = _XLA_BLOCK_Q if mode == "xla" else 512
    if block_k is None:
        block_k = 1024
    bq, bk = _auto_block(block_q, tl), _auto_block(block_k, tl)

    # hop `step` >= 1 participates iff its closest pair distance
    # (step-1)*Tl + 1 is still inside the window
    steps_needed = min(n, max(1, (window - 2) // tl + 2))

    m0 = jnp.full((b, tl, h), _NEG_INF, jnp.float32)
    w0 = jnp.zeros((b, tl, h), jnp.float32)
    acc0 = jnp.zeros((b, tl, h, d), jnp.float32)
    m, w, acc, kb, vb = m0, w0, acc0, k, v

    def to_bth(lse):  # [B*H, Tl] kernel residual -> [B, Tl, H]
        return lse.reshape(b, h, tl).transpose(0, 2, 1)

    for step in range(steps_needed):
        if step == 0:
            out_b, lse_b = _flash_lse(q, kb, vb, None, True, float(sm_scale), bq, bk, mode, window)
            lse_b = to_bth(lse_b)
        else:
            # a device holds the block `step` behind it iff idx >= step;
            # otherwise the wrapped block is AHEAD and fully masked
            w_eff = window - step * tl  # static relative cutoff in local coords

            def behind(q, kb, vb):
                o, l = _flash_lse(q, kb, vb, None, False, float(sm_scale), bq, bk, mode, w_eff)
                return o, to_bth(l)

            def ahead(q, kb, vb):
                return (
                    jnp.zeros((b, tl, h, d), q.dtype),
                    jnp.full((b, tl, h), _NEG_INF, jnp.float32),
                )

            out_b, lse_b = jax.lax.cond(idx >= step, behind, ahead, q, kb, vb)
        m, w, acc = _merge_partials(m, w, acc, out_b, lse_b)

        if step < steps_needed - 1:  # no rotation after the last used hop
            perm = [(i, (i + 1) % n) for i in range(n)]
            kb = jax.lax.ppermute(kb, axis_name, perm)
            vb = jax.lax.ppermute(vb, axis_name, perm)

    return (acc / w[..., None]).astype(q.dtype)


def ring_attention_sharded(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "seq",
    causal: bool = True,
    sm_scale: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    window: int | None = None,
) -> jnp.ndarray:
    """Ring attention callable under plain jit: shard_maps itself over
    ``mesh`` with the sequence dim (axis 1) split on ``axis_name`` and batch
    on the data axes when they divide it (a batch too small for the data
    axes — e.g. module.init's example input — stays replicated)."""
    if axis_name in mesh.shape and q.shape[1] % mesh.shape[axis_name]:
        raise ValueError(
            f"sequence length {q.shape[1]} is not divisible by mesh axis "
            f"{axis_name!r} of size {mesh.shape[axis_name]}"
        )
    batch_axes, rem = [], q.shape[0]
    for a in ("data", "fsdp"):
        if a in mesh.axis_names and rem % mesh.shape[a] == 0:
            batch_axes.append(a)
            rem //= mesh.shape[a]
    spec_q = P(tuple(batch_axes) or None, axis_name, None, None)

    fn = partial(
        ring_attention,
        axis_name=axis_name,
        causal=causal,
        sm_scale=sm_scale,
        block_q=block_q,
        block_k=block_k,
        interpret=interpret,
        window=window,
    )
    from ..parallel.mesh import shard_map_compat

    return shard_map_compat(
        fn, mesh=mesh, in_specs=(spec_q, spec_q, spec_q), out_specs=spec_q
    )(q, k, v)
