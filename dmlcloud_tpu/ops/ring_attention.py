"""Ring attention: sequence/context parallelism over a mesh axis.

The reference has no sequence-parallel machinery (SURVEY.md §5.7); this is the
TPU build's long-context path. Activations are sharded along the sequence
dimension over the ``seq`` mesh axis; K/V blocks rotate around the ring with
``ppermute`` over ICI while each device accumulates its queries' attention
online (flash-style running max/denominator), overlapping the collective with
the blockwise compute. Memory per device is O(T/n); no device ever holds the
full sequence — exact attention at arbitrary context length.

Two entry points:

- ``ring_attention(q, k, v, axis_name=...)``: call *inside* an existing
  ``shard_map`` over the seq axis (the usual case when the whole train step is
  shard_mapped).
- ``ring_attention_sharded(q, k, v, mesh, axis_name=...)``: wraps itself in a
  ``shard_map`` over ``mesh`` for use under plain ``jit`` — activations get
  resharded to P(None, 'seq') around the call.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = "seq",
    causal: bool = True,
    sm_scale: float | None = None,
) -> jnp.ndarray:
    """Exact attention over a sequence sharded on ``axis_name``.

    Shapes (per device): q [B, Tl, H, D]; k/v [B, Tl, KH, D] where Tl is the
    local sequence block. Must be called inside shard_map/pmap with
    ``axis_name`` mapped. Returns [B, Tl, H, D].
    """
    b, tl, h, d = q.shape
    kh = k.shape[2]
    group = h // kh
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)

    qg = (q.astype(jnp.float32) * sm_scale).reshape(b, tl, kh, group, d)

    m0 = jnp.full((b, kh, group, tl), -1e30, jnp.float32)
    l0 = jnp.zeros((b, kh, group, tl), jnp.float32)
    acc0 = jnp.zeros((b, tl, kh, group, d), jnp.float32)

    local_pos = jax.lax.broadcasted_iota(jnp.int32, (tl, tl), 0)
    local_kpos = jax.lax.broadcasted_iota(jnp.int32, (tl, tl), 1)

    def body(carry, step):
        m, l, acc, kb, vb = carry
        src = (idx - step) % n  # which sequence block kb/vb holds

        s = jnp.einsum("btkgd,bskd->bkgts", qg, kb.astype(jnp.float32))  # [B,KH,G,Tl,Tl]
        if causal:
            # whole-block ordering + intra-block causal on the diagonal block
            q_pos = idx * tl + local_pos
            k_pos = src * tl + local_kpos
            mask = q_pos >= k_pos
            s = jnp.where(mask[None, None, None], s, -1e30)

        blk_max = jnp.max(s, axis=-1)
        new_m = jnp.maximum(m, blk_max)
        corr = jnp.exp(m - new_m)
        p = jnp.exp(s - new_m[..., None])  # [B,KH,G,Tl,Tk]
        l = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bkgts,bskd->btkgd", p.astype(vb.dtype), vb).astype(jnp.float32)
        acc = acc * corr.transpose(0, 3, 1, 2)[..., None] + pv

        # rotate K/V around the ring (ICI neighbour exchange, overlaps compute)
        perm = [(i, (i + 1) % n) for i in range(n)]
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        return (new_m, l, acc, kb, vb), None

    (m, l, acc, _, _), _ = jax.lax.scan(body, (m0, l0, acc0, k, v), jnp.arange(n))
    out = acc / l.transpose(0, 3, 1, 2)[..., None]
    return out.reshape(b, tl, h, d).astype(q.dtype)


def ring_attention_sharded(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "seq",
    causal: bool = True,
    sm_scale: float | None = None,
) -> jnp.ndarray:
    """Ring attention callable under plain jit: shard_maps itself over
    ``mesh`` with the sequence dim (axis 1) split on ``axis_name`` and batch
    on the data axes when present."""
    batch_axes = tuple(a for a in ("data", "fsdp") if a in mesh.axis_names) or None
    spec_q = P(batch_axes, axis_name, None, None)

    fn = partial(ring_attention, axis_name=axis_name, causal=causal, sm_scale=sm_scale)
    return jax.shard_map(
        fn, mesh=mesh, in_specs=(spec_q, spec_q, spec_q), out_specs=spec_q, check_vma=False
    )(q, k, v)
