"""Ring attention: sequence/context parallelism over a mesh axis.

The reference has no sequence-parallel machinery (SURVEY.md §5.7); this is the
TPU build's long-context path. Activations are sharded along the sequence
dimension over the ``seq`` mesh axis; K/V blocks rotate around the ring with
``ppermute`` over ICI while each device merges its queries' attention against
each visiting block. Memory per device is O(T/n); no device ever holds the
full sequence — exact attention at arbitrary context length.

The per-block attention IS the fused Pallas flash kernel
(ops/flash_attention.py) called with ``return_lse=True``: operands stay in
their native dtype (bf16 on the MXU), no [Tl, Tk] score matrix ever reaches
HBM, and the visiting blocks' normalized outputs are merged with the
standard blockwise combination — running max over block LSEs, exp-corrected
weighted sum — carried in fp32. Under causal masking, ``lax.switch`` runs
the non-causal kernel for blocks behind this device, the causal kernel for
the diagonal block, and skips blocks ahead entirely (weight exp(-inf)).
Gradients flow through the merge AND through the kernel's lse output
(``_flash_lse`` custom_vjp).

Two entry points:

- ``ring_attention(q, k, v, axis_name=...)``: call *inside* an existing
  ``shard_map`` over the seq axis (the usual case when the whole train step is
  shard_mapped).
- ``ring_attention_sharded(q, k, v, mesh, axis_name=...)``: wraps itself in a
  ``shard_map`` over ``mesh`` for use under plain ``jit`` — activations get
  resharded to P(None, 'seq') around the call.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .flash_attention import flash_attention

_NEG_INF = -1e30


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = "seq",
    causal: bool = True,
    sm_scale: float | None = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Exact attention over a sequence sharded on ``axis_name``.

    Shapes (per device): q [B, Tl, H, D]; k/v [B, Tl, KH, D] where Tl is the
    local sequence block. Must be called inside shard_map/pmap with
    ``axis_name`` mapped. Returns [B, Tl, H, D].
    """
    b, tl, h, d = q.shape
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)

    flash = partial(
        flash_attention,
        sm_scale=sm_scale,
        block_q=block_q,
        block_k=block_k,
        interpret=interpret,
        return_lse=True,
    )

    def behind_block(q, kb, vb):  # src strictly before this device: no mask
        return flash(q, kb, vb, causal=False)

    def diagonal_block(q, kb, vb):  # this device's own block: causal mask
        return flash(q, kb, vb, causal=True)

    def ahead_block(q, kb, vb):  # src strictly after: fully masked, skip
        return (
            jnp.zeros((b, tl, h, d), q.dtype),
            jnp.full((b, tl, h), _NEG_INF, jnp.float32),
        )

    m0 = jnp.full((b, tl, h), _NEG_INF, jnp.float32)
    w0 = jnp.zeros((b, tl, h), jnp.float32)
    acc0 = jnp.zeros((b, tl, h, d), jnp.float32)

    def body(carry, step):
        m, w, acc, kb, vb = carry
        src = (idx - step) % n  # which sequence block kb/vb holds

        if causal:
            branch = jnp.where(src == idx, 1, jnp.where(src < idx, 0, 2))
            out_b, lse_b = jax.lax.switch(
                branch, [behind_block, diagonal_block, ahead_block], q, kb, vb
            )
        else:
            out_b, lse_b = behind_block(q, kb, vb)

        # blockwise merge of normalized partials: out = Σ_b exp(lse_b) out_b
        # / Σ_b exp(lse_b), computed with a running max for stability
        new_m = jnp.maximum(m, lse_b)
        c_prev = jnp.exp(m - new_m)
        c_new = jnp.exp(lse_b - new_m)
        acc = acc * c_prev[..., None] + out_b.astype(jnp.float32) * c_new[..., None]
        w = w * c_prev + c_new

        # rotate K/V around the ring (ICI neighbour exchange, overlaps compute)
        perm = [(i, (i + 1) % n) for i in range(n)]
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        return (new_m, w, acc, kb, vb), None

    (m, w, acc, _, _), _ = jax.lax.scan(body, (m0, w0, acc0, k, v), jnp.arange(n))
    return (acc / w[..., None]).astype(q.dtype)


def ring_attention_sharded(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "seq",
    causal: bool = True,
    sm_scale: float | None = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Ring attention callable under plain jit: shard_maps itself over
    ``mesh`` with the sequence dim (axis 1) split on ``axis_name`` and batch
    on the data axes when present."""
    batch_axes = tuple(a for a in ("data", "fsdp") if a in mesh.axis_names) or None
    spec_q = P(batch_axes, axis_name, None, None)

    fn = partial(
        ring_attention,
        axis_name=axis_name,
        causal=causal,
        sm_scale=sm_scale,
        block_q=block_q,
        block_k=block_k,
        interpret=interpret,
    )
    return jax.shard_map(
        fn, mesh=mesh, in_specs=(spec_q, spec_q, spec_q), out_specs=spec_q, check_vma=False
    )(q, k, v)
