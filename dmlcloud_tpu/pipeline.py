"""TrainingPipeline: the experiment orchestrator.

Capability parity with /root/reference/dmlcloud/pipeline.py:20-331 — config
container, registries for models/optimizers/schedulers/datasets/stages,
checkpoint + wandb enablement, run lifecycle with cleanup guard, barriers with
timeout, diagnostics — re-based on the TPU runtime:

- device selection (pipeline.py:231-242) becomes mesh construction: the
  pipeline owns a ``jax.sharding.Mesh`` (default: one ``data`` axis over all
  devices — DDP semantics) that every stage's compiled step is sharded over.
- ``register_model``'s DDP wrap (pipeline.py:72-74) becomes laying params out
  on the mesh under a sharding policy ('replicate' == DDP, 'fsdp' == ZeRO-3,
  rule list == tensor parallel).
- the gloo side-group for timeout barriers (pipeline.py:226-229) becomes the
  coordination-service monitored barrier (parallel/runtime.py).
- optimizers are optax transformations; schedulers are optax schedules.
- checkpointing keeps the directory contract and adds Orbax tensor state
  (checkpoint.py).
"""

from __future__ import annotations

import logging
import re
from contextlib import contextmanager
from dataclasses import dataclass, field
from datetime import datetime
from typing import Any, Callable, Optional

import jax

from .checkpoint import CheckpointDir, find_slurm_checkpoint, generate_checkpoint_path
from .metrics import MetricTracker, Reduction
from .parallel import mesh as mesh_lib
from .parallel import runtime
from .stage import Stage
from .utils.config import Config, as_config
from .utils.logging import IORedirector, add_log_handlers, experiment_header, general_diagnostics
from .utils.wandb import wandb, wandb_is_initialized, wandb_set_startup_timeout


@dataclass
class ModelEntry:
    name: str
    module: Any  # flax module or None
    apply_fn: Callable
    params: Any
    policy: Any = "replicate"
    extras: Any = None  # non-trained collections (batch_stats, ...)
    ema: Any = None  # EMA shadow published by a stage with ema_decay() > 0


class TrainingPipeline:
    def __init__(
        self,
        config: Any = None,
        name: Optional[str] = None,
        lint: Optional[str] = None,
        verify: Optional[str] = None,
        hbm_budget: Optional[int] = None,
        sanitize: Optional[str] = None,
        compile_cache: Any = None,
        precompile: bool = False,
        buckets: Any = None,
        telemetry: Any = None,
    ):
        """``lint`` arms the TPU-hazard linter (dmlcloud_tpu.lint) over every
        registered Stage subclass's source at run start: ``"warn"`` logs the
        findings, ``"error"`` raises ``lint.LintError`` before any device
        work happens. None (default) skips linting — the CLI
        (``python -m dmlcloud_tpu lint``) and the self-lint test remain the
        review-time nets.

        ``verify`` arms the IR-level verifier (dmlcloud_tpu.lint.ir; doc/
        lint.md DML6xx) over the precompiled step executables at stage
        start: each AOT-compiled train/val signature is re-audited as the
        program XLA will actually run — donation that jit silently
        dropped (DML601), collective/sharding axes that don't resolve
        against the mesh (DML602), host callbacks baked into the step
        (DML603), and — when ``hbm_budget`` (bytes) is declared —
        estimated peak memory over budget (DML604). The arm re-uses the
        executables ``precompile=True`` already built, so it adds zero
        compiles; it therefore only runs where precompilation runs.
        ``"warn"`` logs findings, ``"error"`` raises ``lint.LintError``
        before the data loop. None (default) skips it — the CLI
        (``python -m dmlcloud_tpu verify``) remains the review-time net.

        ``sanitize`` arms the RUNTIME sanitizer (dmlcloud_tpu.lint.sanitize)
        — the dynamic companion of the static pass: each stage's epoch runs
        under a device-to-host conversion probe (implicit ``np.asarray`` of
        a device value outside a StallTimer-accounted block), step dispatch
        is checked for host numpy leaves (an implicit host-to-device
        transfer), and ``"error"`` additionally arms jax's
        ``transfer_guard`` + ``jax_debug_nans`` for the window. ``"warn"``
        reports each violation site once (log + ``sanitizer`` telemetry
        span + ``pipeline.sanitizer_findings``) and continues; ``"error"``
        raises ``lint.SanitizerError`` at the violation. None/``"off"``
        (default) changes nothing — not even a context manager enters.

        The cold-start killers (dmlcloud_tpu.compile; doc/performance.md §4):

        - ``compile_cache``: persistent XLA compilation cache. ``True`` uses
          ``$DMLCLOUD_COMPILE_CACHE_DIR`` (default
          ``~/.cache/dmlcloud_tpu/xla``); a path selects the directory —
          point every host of a pod at the same shared-FS dir (entries are
          content-addressed; concurrent writers are safe; only process 0
          logs stats). None (default) leaves jax's config untouched.
        - ``precompile``: default for ``Stage.precompile()`` — AOT-compile
          the train/val steps at stage start against the first batch's
          abstract spec, before the data loop.
        - ``buckets``: default for ``Stage.buckets()`` — pad ragged batch
          dims to this ascending size set (with a zero-weight sample mask)
          so the compiled-signature count stays bounded.

        ``telemetry`` arms the flight recorder (dmlcloud_tpu.telemetry;
        doc/observability.md): a per-host span journal (JSONL, merged by
        ``python -m dmlcloud_tpu timeline <run_dir>``), the goodput/MFU
        ledger (``misc/goodput``/``misc/mfu`` + a root-only end-of-run
        table), and the hang watchdog (forensics dump when step/span
        progress stops). ``True`` journals into ``<checkpoint_dir>/
        telemetry`` (or ``./telemetry`` without checkpointing / on remote
        checkpoint paths); a path selects the directory; a dict configures
        ``{"dir", "hang_threshold_s" (default 600), "watchdog_interval_s"
        (10), "ring_size" (1024)}``. None/False (default): fully off — the
        instrumentation points reduce to one attribute read."""
        if lint not in (None, "warn", "error"):
            raise ValueError(f'lint must be None, "warn" or "error", got {lint!r}')
        if verify not in (None, "warn", "error"):
            raise ValueError(f'verify must be None, "warn" or "error", got {verify!r}')
        if sanitize not in (None, "off", "warn", "error"):
            raise ValueError(f'sanitize must be None, "off", "warn" or "error", got {sanitize!r}')
        self.config: Config = as_config(config)
        self.name = name
        self._lint_mode = lint
        self._verify_mode = verify
        self._hbm_budget = None if hbm_budget is None else int(hbm_budget)
        #: findings of the last verify preflight (stage.py fills this)
        self.verify_findings: list = []
        from .lint.sanitize import Sanitizer

        self._sanitizer = Sanitizer(sanitize or "off", logger=logging.getLogger("dmlcloud_tpu"))
        self._compile_cache = compile_cache
        self._compile_cache_dir: str | None = None
        self._precompile = bool(precompile)
        self._buckets = tuple(buckets) if buckets else None
        if telemetry is not None and not isinstance(telemetry, (bool, str, dict)) and not hasattr(telemetry, "__fspath__"):
            raise ValueError(
                f"telemetry must be None/bool, a directory path, or a config dict, got {telemetry!r}"
            )
        self._telemetry_cfg = telemetry
        self.telemetry_dir: str | None = None
        self._journal = None
        self._watchdog = None
        self._run_span_t0: float | None = None

        self.logger = logging.getLogger("dmlcloud_tpu")
        self.checkpoint_dir: CheckpointDir | None = None
        self.io_redirector = None
        self.resumed: bool | None = None
        self.tracker = MetricTracker()
        self.mesh = None
        self.root_key = None
        self.start_time = None
        self.stop_time = None
        self.current_stage = None

        self.wandb = False
        self._wandb_opts: dict | None = None
        self._wandb_timeout = 360
        self._tensorboard_dir: str | None = None
        self._tb_writer = None

        self._preemption = runtime.PreemptionGuard(signals=())
        self._verdict_written = False
        self._verdict_kind: Optional[str] = None

        self.stages: list[Stage] = []
        self.datasets: dict[str, Any] = {}
        self.models: dict[str, ModelEntry] = {}
        self.optimizers: dict[str, Any] = {}
        self.schedulers: dict[str, Any] = {}
        self._optimizer_model: dict[str, str | None] = {}

    # ------------------------------------------------------------------ mesh
    @property
    def checkpointing_enabled(self) -> bool:
        return self.checkpoint_dir is not None

    @property
    def telemetry_armed(self) -> bool:
        """True between telemetry arming at run start and teardown."""
        return self._journal is not None

    @property
    def sanitizer_findings(self):
        """Violations the runtime sanitizer recorded this run (Finding
        schema; empty when ``sanitize`` is off or nothing tripped)."""
        return list(self._sanitizer.findings)

    def set_mesh(self, mesh_or_axes) -> None:
        """Set the device mesh (a ``jax.sharding.Mesh`` or an axes dict like
        ``{'data': -1}`` / ``{'data': 2, 'model': 4}``). Default if never
        called: a single ``data`` axis over all devices."""
        if isinstance(mesh_or_axes, dict):
            self.mesh = mesh_lib.create_mesh(mesh_or_axes)
        else:
            self.mesh = mesh_or_axes

    # ----------------------------------------------------------- registries
    def register_model(
        self,
        name: str,
        model: Any = None,
        params: Any = None,
        apply_fn: Callable | None = None,
        sharding: Any = "replicate",
        init_args: tuple | None = None,
        init_rng: int | jax.Array = 0,
        verbose: bool = True,
    ):
        """Register a model and lay its params out on the mesh.

        Accepts a flax module (``apply_fn = model.apply``; if ``params`` is
        None they are initialised from ``init_args`` example inputs), or an
        explicit ``(apply_fn, params)`` pair. ``sharding`` is the param
        policy: 'replicate' (DDP semantics, reference pipeline.py:72-74),
        'fsdp', a T5X-style rule list, or a callable.
        """
        if name in self.models:
            raise ValueError(f"Model with name {name} already exists")
        if self.mesh is None:
            self._init_mesh()

        extras = None
        if model is not None and hasattr(model, "apply") and hasattr(model, "init"):
            apply_fn = model.apply
            if params is None:
                if init_args is None:
                    raise ValueError("params=None requires init_args example inputs for module.init")
                rng = jax.random.PRNGKey(init_rng) if isinstance(init_rng, int) else init_rng
                params = model.init(rng, *init_args)
        elif apply_fn is None:
            if not callable(model):
                raise ValueError("register_model needs a flax module, or apply_fn + params")
            apply_fn = model

        # flax variables: split trained params from mutable collections
        if isinstance(params, dict) and "params" in params:
            variables = dict(params)
            params = variables.pop("params")
            extras = variables or None

        params = mesh_lib.shard_pytree(params, self.mesh, sharding)
        if extras is not None:
            extras = mesh_lib.shard_pytree(extras, self.mesh, sharding)
        self.models[name] = ModelEntry(
            name=name, module=model, apply_fn=apply_fn, params=params, policy=sharding, extras=extras
        )

        if verbose:
            n_params = sum(int(x.size) for x in jax.tree_util.tree_leaves(params) if hasattr(x, "size"))
            msg = f'Model "{name}":\n'
            msg += f"    - Parameters: {n_params / 1e6:.1f} M\n"
            msg += f"    - Sharding policy: {sharding if isinstance(sharding, str) else 'custom rules'}\n"
            msg += f"    - Mesh: {dict(self.mesh.shape) if self.mesh is not None else None}"
            self.logger.info(msg)

    def register_optimizer(self, name: str, optimizer, scheduler=None, model: str | None = None):
        """Register an optax transformation (and optionally its schedule, for
        LR tracking parity with reference stage.py:316-318)."""
        if name in self.optimizers:
            raise ValueError(f"Optimizer with name {name} already exists")
        self.optimizers[name] = optimizer
        self._optimizer_model[name] = model
        if scheduler is not None:
            self.schedulers[name] = scheduler

    def register_dataset(self, name: str, dataset: Any, verbose: bool = True):
        """Register a per-process dataset shard under ``name`` ('train'/'val'
        are the names TrainValStage looks up). Any iterable of batches works:
        a DataPipeline, a DataLoader shim, or a plain list."""
        if name in self.datasets:
            raise ValueError(f"Dataset with name {name} already exists")
        self.datasets[name] = dataset
        if verbose:
            try:
                per_worker: Any = len(dataset)
                total: Any = f"~{per_worker * runtime.world_size()}"
            except TypeError:  # iterable-only pipelines carry no length
                per_worker = total = "unknown"
            self.logger.info(
                'Dataset "%s": %s batches/worker, %s total across %d processes',
                name, per_worker, total, runtime.world_size(),
            )

    def append_stage(self, stage: Stage, max_epochs: Optional[int] = None, name: Optional[str] = None):
        if not isinstance(stage, Stage):
            raise ValueError("stage must be a Stage object")
        stage.pipeline = self
        stage.max_epochs = max_epochs
        # unique name: it keys the stage's checkpoint scope (state/<name>).
        # Explicit duplicates are an error (like register_model); anonymous
        # same-class stages get a numeric suffix.
        existing = {s.name for s in self.stages}
        if name is not None:
            # the name keys filesystem paths (state/<name>, meta/<name>); an
            # unconstrained string like "../other" would escape the checkpoint dir
            if not re.fullmatch(r"[A-Za-z0-9._-]+", name) or name in (".", ".."):
                raise ValueError(
                    f"Stage name {name!r} is invalid: must match [A-Za-z0-9._-]+ "
                    "(it names checkpoint subdirectories)"
                )
            if name in existing:
                raise ValueError(f"Stage with name {name!r} already exists")
            stage.name = name
        else:
            base = type(stage).__name__
            unique, i = base, 2
            while unique in existing:
                unique, i = f"{base}_{i}", i + 1
            stage.name = unique
        self.stages.append(stage)

    # -- registry lookups used by TrainValStage -----------------------------
    def _model_entry(self, name: str | None = None) -> ModelEntry:
        if name is not None:
            if name not in self.models:
                raise ValueError(f"No model named {name!r} registered")
            return self.models[name]
        if len(self.models) == 1:
            return next(iter(self.models.values()))
        if not self.models:
            raise ValueError("No model registered. Call register_model() (e.g. in pre_stage).")
        raise ValueError("Multiple models registered; override Stage.model_name() to pick one.")

    def _optimizer_for(self, model_name: str):
        if not self.optimizers:
            raise ValueError("No optimizer registered. Call register_optimizer() (e.g. in pre_stage).")
        explicit = [n for n, m in self._optimizer_model.items() if m == model_name]
        if len(explicit) > 1:
            raise ValueError(
                f"Multiple optimizers ({explicit}) registered for model {model_name!r}; "
                "a model can only be trained by one optimizer per stage."
            )
        if explicit:
            return self.optimizers[explicit[0]]
        unbound = [n for n, m in self._optimizer_model.items() if m is None]
        # mirror _model_entry's ambiguity error: with several models AND
        # several unbound optimizers there is no defensible pairing — the old
        # behavior silently trained every model with the first optimizer
        if len(unbound) > 1 and len(self.models) > 1:
            raise ValueError(
                f"Multiple unbound optimizers ({unbound}) and multiple models registered; "
                "pass model=... to register_optimizer() to bind each optimizer to its model."
            )
        if unbound:
            return self.optimizers[unbound[0]]
        raise ValueError(
            f"No optimizer registered for model {model_name!r} and no unbound optimizer "
            "to fall back on. Call register_optimizer(model=...)."
        )

    # -------------------------------------------------------- checkpointing
    def enable_checkpointing(self, root: str, resume: bool = False):
        """Reference pipeline.py:116-137: reuse a valid dir when resuming,
        rediscover by Slurm job id on requeue, else generate a fresh path
        agreed across processes via broadcast."""
        if self.checkpointing_enabled:
            raise ValueError("Checkpointing already enabled")

        path = None
        if resume and CheckpointDir(root).is_valid:
            path = root
            self.resumed = True
        elif resume and (slurm_path := find_slurm_checkpoint(root)):
            path = slurm_path
            self.resumed = True

        if path is None:
            path = generate_checkpoint_path(root=root, name=self.name)
            path = runtime.broadcast_object(path)
            self.resumed = False

        self.checkpoint_dir = CheckpointDir(path)

    def enable_wandb(
        self,
        project: str | None = None,
        entity: str | None = None,
        group: str | None = None,
        tags: list[str] | None = None,
        startup_timeout: int = 360,
        **kwargs,
    ):
        """Send the tracker's per-epoch metrics to Weights & Biases.

        Only stores the run options here; the root process opens the actual
        wandb run during ``_pre_run`` (after the runtime and config are
        final). Extra ``kwargs`` pass straight through to ``wandb.init``."""
        import wandb as _wandb  # noqa: F401 — surface a missing install at call time

        self._wandb_opts = dict(
            entity=entity,
            project=project or self.name,
            group=group,
            tags=tags,
            **kwargs,
        )
        self._wandb_timeout = startup_timeout
        self.wandb = True

    def enable_tensorboard(self, logdir: str | None = None):
        """Write per-epoch tracker scalars as TensorBoard event files (the
        writer itself is root-only; needs ``tensorboardX``). Default logdir:
        ``<checkpoint_dir>/tb`` resolved at run start — alongside any
        ``jax.profiler`` traces, so one ``tensorboard --logdir`` shows the
        curves and the device timeline of the same run. A third
        observability channel the reference lacks (console table + wandb
        are the other two)."""
        import tensorboardX  # noqa: F401 — surface a missing install at call time

        self._tensorboard_dir = logdir if logdir is not None else "__checkpoint__"
        return self

    @runtime.root_only
    def _start_wandb(self):
        import wandb as _wandb

        wandb_set_startup_timeout(self._wandb_timeout)
        _wandb.init(
            config=self.config.to_dict(resolve=True),
            name=self.name,
            **self._wandb_opts,
        )

    # -------------------------------------------------------------- metrics
    def track_reduce(
        self,
        name: str,
        value: Any,
        step: int | None = None,
        reduction: Reduction = Reduction.MEAN,
        dim: list[int] | None = None,
        reduce_globally: bool = True,
    ):
        """Buffer ``value`` under an epoch-end reduction. The metric is
        registered on first use; the reduction arguments only take effect
        then (subsequent calls just append)."""
        if name not in self.tracker:
            self.tracker.register_metric(name, reduction, dim, reduce_globally)
        self.tracker.track(name, value)

    def track(self, name: str, value: Any, step: int | None = None):
        """Record an already-final (unreduced, process-local) value for the
        current epoch."""
        if name not in self.tracker:
            self.tracker.register_metric(name)
        self.tracker.track(name, value)

    def barrier(self, timeout=None):
        """All-process barrier with timeout (reference pipeline.py:191-196)."""
        runtime.barrier("pipeline", timeout if timeout is not None else 600.0)

    # -------------------------------------------------------- preemption
    #: back-compat views over the PreemptionGuard (parallel/runtime.py),
    #: which owns the signal handlers and the cross-rank drain decision
    @property
    def _preempted(self) -> bool:
        return self._preemption.triggered

    @_preempted.setter
    def _preempted(self, v: bool) -> None:
        self._preemption.triggered = bool(v)

    @property
    def _preemption_enabled(self) -> bool:
        return self._preemption.armed

    @_preemption_enabled.setter
    def _preemption_enabled(self, v: bool) -> None:
        self._preemption.armed = bool(v)

    @property
    def _prev_signal_handlers(self) -> dict:
        return self._preemption._prev

    def enable_preemption_handling(self, signals: tuple[str, ...] | None = ("SIGTERM",)):
        """Exit cleanly at the next save boundary when any of ``signals``
        arrives on ANY rank (Cloud TPU preemption sends SIGTERM; Slurm jobs
        typically arrange ``--signal=USR1@60`` -> pass ``("SIGUSR1",)``, or
        pass ``signals=None`` for the guard's environment-aware default:
        SIGTERM + SIGINT, plus SIGUSR1 inside a Slurm step).

        With epoch checkpointing the drain lands at the epoch boundary
        (the finished epoch has already auto-saved); with
        ``checkpoint_every_steps()`` armed it lands at the next step-save
        boundary mid-epoch. Either way the stage is NOT marked stopped and
        the root writes a requeue verdict (``requeue.json``,
        doc/elasticity.md) so a requeued run resumes where this one drained
        — on whatever mesh the new allocation provides (resharded restore).
        This is TPU-side scope: the reference's fault model is Slurm
        requeue after the fact (reference checkpoint.py:37-48) with no
        in-flight signal handling."""
        # re-arming: restore the ORIGINAL dispositions first, so the new
        # guard's install records them (not our previous handler) as prev
        self._preemption.uninstall()
        self._preemption = runtime.PreemptionGuard(signals=signals).install()

    def _preemption_coordinated(self) -> bool:
        """Whether ANY rank caught a preemption signal (see
        ``PreemptionGuard.coordinated``)."""
        return self._preemption.coordinated()

    def _write_requeue_verdict(
        self, requeue: bool, kind: str, reason: str, force: bool = False, **extra
    ) -> None:
        """Root-only, first-writer-wins requeue verdict for this run (the
        preemption/hang verdict must not be stomped by the teardown's
        generic classification; ``force`` is for the one legitimate
        supersession — a run that RECOVERED from a watchdog-flagged stall
        and completed). No-op without a checkpoint dir — there is nowhere
        durable to resume from, so a verdict would be noise."""
        if (self._verdict_written and not force) or self.checkpoint_dir is None or not runtime.is_root():
            return
        from .checkpoint import is_remote_path, write_requeue_verdict

        try:
            if not is_remote_path(self.checkpoint_dir.path) and not self.checkpoint_dir.exists:
                return  # e.g. run failed before _init_checkpointing created it
            write_requeue_verdict(self.checkpoint_dir.path, requeue, reason, kind, **extra)
            self._verdict_written = True
            self._verdict_kind = kind
            self.logger.info(
                "requeue verdict: requeue=%s (%s) — %s", requeue, kind, reason
            )
        except Exception:
            self.logger.warning("could not write requeue verdict", exc_info=True)

    def _classify_failure(self, exc: BaseException) -> tuple[bool, str, str]:
        """(requeue, kind, reason) for an uncaught exception — the automated
        half of the flight recorder's post-mortem: deterministic failures
        (NaN loss, lint errors) must NOT be requeued (they recur), while
        transient infrastructure failures (stragglers/hangs, filesystem
        errors) should be."""
        if isinstance(exc, KeyboardInterrupt):
            return False, "user-interrupt", "run aborted by user (KeyboardInterrupt)"
        if isinstance(exc, runtime.BarrierTimeout):
            return True, "hang", (
                f"barrier '{exc.tag}' timed out; straggler ranks {exc.stragglers or 'unknown'}"
                " — transient by default, forensics dumped"
            )
        if isinstance(exc, FloatingPointError):
            return False, "exception", f"non-finite loss is deterministic: {exc}"
        if isinstance(exc, OSError):
            return True, "exception", (
                f"filesystem/IO error ({type(exc).__name__}: {exc}) — transient by default"
            )
        return False, "exception", f"{type(exc).__name__}: {exc}"

    # ------------------------------------------------------------ lifecycle
    def run(self):
        """Run all registered stages sequentially."""
        with _run_guard(self):
            self._pre_run()
            for stage in self.stages:
                self.current_stage = stage
                stage.run()
                # the stage's own coordinated decision — already in lockstep
                # across ranks, no extra collective needed here
                if getattr(stage, "_preempt_exit", False):
                    self.logger.info("preemption requested; skipping remaining stages")
                    extra = {
                        "stage": stage.name,
                        "epoch": stage.current_epoch,
                        "mid_epoch": bool(getattr(stage, "_mid_epoch_exit", False)),
                    }
                    lat = getattr(stage, "_last_save_latency_s", None)
                    if lat is not None:
                        extra["save_on_preempt_latency_s"] = round(float(lat), 4)
                    sig = self._preemption.signal_name or "coordinated-drain"
                    self._write_requeue_verdict(
                        True, "preemption",
                        f"drained cleanly on {sig}; state saved at the last boundary, resumable",
                        **extra,
                    )
                    break
            self._post_run()

    # user hooks (reference pipeline.py:208-215)
    def pre_run(self):
        pass

    def post_run(self):
        pass

    def resume_run(self):
        pass

    # internals
    def _init_mesh(self):
        if self.mesh is None:
            self.mesh = mesh_lib.create_mesh({mesh_lib.DATA: -1})
        runtime._cpu_safety_flags()

    def _lint_stages(self) -> None:
        """Lint every registered Stage subclass's source (the runtime arm of
        dmlcloud_tpu.lint — catches hazards in stages assembled dynamically,
        where no CLI run ever sees the file). Classes whose source is
        unavailable (REPL, exec) are skipped: the linter is a net, not a
        gate on how code gets defined."""
        if self._lint_mode is None:
            return
        import inspect
        import textwrap

        from .lint import LintError, lint_source

        findings = []
        seen: set[type] = set()
        for stage in self.stages:
            cls = type(stage)
            # framework-shipped stages are covered by the repo's own
            # self-lint gate; lint only user subclasses, each class once
            if cls in seen or cls.__module__.startswith("dmlcloud_tpu."):
                continue
            seen.add(cls)
            try:
                lines, start = inspect.getsourcelines(cls)
                path = inspect.getsourcefile(cls) or f"<{cls.__name__}>"
            except (OSError, TypeError):
                continue
            # re-anchor to the original line numbers so findings are clickable
            src = "\n" * (start - 1) + textwrap.dedent("".join(lines))
            findings.extend(lint_source(src, path=path))
        if not findings:
            return
        report = "\n".join(f.format() for f in findings)
        if self._lint_mode == "error":
            raise LintError(
                f"TPU-hazard linter found {len(findings)} problem(s) in registered "
                f"stages (doc/lint.md; suppress with '# dmllint: disable=ID'):\n{report}",
                findings,
            )
        self.logger.warning("TPU-hazard linter findings in registered stages:\n%s", report)

    def _pre_run(self):
        if len(self.stages) == 0:
            raise ValueError("No stages defined. Use append_stage() to add stages to the pipeline.")
        self._verdict_written = False
        self._verdict_kind = None
        self._lint_stages()
        if self._compile_cache not in (None, False):
            # before ANY compilation (incl. the collectives the runtime
            # bootstrap below may compile) so every program is cacheable
            from .compile.cache import configure_cache

            self._compile_cache_dir = configure_cache(self._compile_cache)
        if not runtime.is_initialized():
            runtime.init_auto()

        self._init_mesh()
        if self.root_key is None:
            self.root_key = jax.random.PRNGKey(int(self.config.get("seed", 0)))

        # prevent checkpoint-dir creation before every process searched for it
        # (reference pipeline.py:244-246)
        self.barrier(timeout=600)
        if self.checkpointing_enabled:
            self._init_checkpointing()
        self._arm_telemetry()

        if self.wandb:
            self._start_wandb()
        if self._tensorboard_dir is not None and runtime.is_root():
            from .utils.tensorboard import TensorBoardWriter

            tb_dir = self._tensorboard_dir
            if tb_dir == "__checkpoint__":
                if self.checkpoint_dir is None:
                    raise ValueError(
                        "enable_tensorboard() without a logdir needs checkpointing enabled "
                        "(the default logdir is <checkpoint_dir>/tb) — pass an explicit logdir"
                    )
                tb_dir = str(self.checkpoint_dir.path / "tb")
            self._tb_writer = TensorBoardWriter(tb_dir)

        self.barrier(timeout=600)
        self.start_time = datetime.now()

        add_log_handlers(self.logger)
        header = "\n" + experiment_header(self.name, str(self.checkpoint_dir) if self.checkpoint_dir else None, self.start_time)
        self.logger.info(header)

        if self.resumed:
            self._resume_run()

        diagnostics = general_diagnostics()
        diagnostics += "\n* MESH:\n"
        diagnostics += f"    - axes: {dict(self.mesh.shape)}\n"
        local_desc = f"{runtime.local_device_count()}x {jax.local_devices()[0].device_kind}"
        devices = runtime.all_gather_object(local_desc)
        diagnostics += "\n".join(f"    - [Process {i}] {d}" for i, d in enumerate(devices))
        diagnostics += "\n* CONFIG:\n"
        diagnostics += "\n".join(f"    {line}" for line in self.config.to_yaml(resolve=True).splitlines())
        self.logger.info(diagnostics)
        if self._compile_cache_dir is not None and runtime.is_root():
            self.logger.info("persistent compilation cache: %s", self._compile_cache_dir)

        self.pre_run()

    def _arm_telemetry(self):
        """Start the flight recorder: journal + goodput + hang watchdog
        (dmlcloud_tpu.telemetry). Per-host — every rank journals and
        watches; only the root prints the end-of-run ledger."""
        cfg = self._telemetry_cfg
        if cfg is None or cfg is False:
            return
        import os

        from .checkpoint import is_remote_path
        from .telemetry import journal as journal_mod
        from .telemetry.watchdog import HangWatchdog

        opts = dict(cfg) if isinstance(cfg, dict) else {}
        tdir = opts.get("dir")
        if tdir is None and not isinstance(cfg, (bool, dict)):
            tdir = os.fspath(cfg)
        if tdir is None:
            # journals are plain local appends; a gs://... checkpoint root
            # cannot take them, so fall back to the working directory
            if self.checkpoint_dir is not None and not is_remote_path(self.checkpoint_dir.path):
                tdir = str(self.checkpoint_dir.path / "telemetry")
            else:
                tdir = os.path.abspath("telemetry")
        self.telemetry_dir = str(tdir)
        self._journal = journal_mod.SpanJournal(
            self.telemetry_dir,
            rank=runtime.rank(),
            ring_size=int(opts.get("ring_size", 1024)),
        )
        journal_mod.activate(self._journal)
        self._journal.start()
        forensics_dir = os.path.join(self.telemetry_dir, os.pardir, "forensics")
        if self.checkpoint_dir is not None and not is_remote_path(self.checkpoint_dir.path):
            forensics_dir = str(self.checkpoint_dir.path / "forensics")
        self._watchdog = HangWatchdog(
            os.path.normpath(forensics_dir),
            rank=runtime.rank(),
            world_size=runtime.world_size(),
            threshold_s=float(opts.get("hang_threshold_s", 600.0)),
            interval_s=float(opts.get("watchdog_interval_s", 10.0)),
            journal=self._journal,
        )
        self._journal.on_emit = self._watchdog.notify

        def _hang_verdict(reason: str) -> None:
            # the forensics dump's requeue-wrapper counterpart: a hang is
            # transient by default (requeue and let the watchdog's evidence
            # drive a deeper look), and the verdict names the stragglers
            extra = {}
            stragglers = runtime.barrier_state().get("stragglers")
            if stragglers:
                extra["stragglers"] = stragglers
            self._write_requeue_verdict(True, "hang", reason, **extra)

        self._watchdog.on_dump = _hang_verdict
        self._watchdog.start()
        self._run_span_t0 = journal_mod.now()
        if runtime.is_root():
            self.logger.info(
                "telemetry armed: journal %s, forensics %s (hang threshold %.0fs)",
                self.telemetry_dir, self._watchdog.dump_dir, self._watchdog.threshold_s,
            )

    def _telemetry_ledger(self):
        """Root-only end-of-run goodput ledger: log the table and persist
        ``goodput.json`` next to the journals."""
        from .telemetry import journal as journal_mod
        from .telemetry.goodput import ledger_from_tracker

        if self._run_span_t0 is not None:
            journal_mod.emit("run", self._run_span_t0, label=self.name or "run")
        ledger = ledger_from_tracker(self.tracker)
        if not runtime.is_root():
            return
        if ledger.rows:
            self.logger.info("\n%s", ledger.format_table())
            # advisory-only knob suggestions (goodput advisor): printed,
            # never auto-applied — the same lines `diag --run` derives
            for line in ledger.advise():
                self.logger.warning("goodput advisor: %s", line)
        import json
        import os

        try:
            with open(os.path.join(self.telemetry_dir, "goodput.json"), "w", encoding="utf-8") as f:
                json.dump(ledger.to_dict(), f)
        except OSError:
            self.logger.warning("could not write %s/goodput.json", self.telemetry_dir, exc_info=True)

    def _disarm_telemetry(self, exc: BaseException | None = None):
        """Teardown half of ``_arm_telemetry`` — always runs (run guard).
        An uncaught exception triggers a forensics dump first: the flight
        recorder's whole point is that the crash leaves evidence behind."""
        from .telemetry import journal as journal_mod

        if self._watchdog is not None:
            if exc is not None and not isinstance(exc, KeyboardInterrupt):
                try:
                    path = self._watchdog.dump(f"uncaught exception: {type(exc).__name__}: {exc}")
                    self.logger.info("forensics dumped to %s", path)
                except Exception:
                    self.logger.warning("forensics dump failed", exc_info=True)
            self._watchdog.stop()
            self._watchdog = None
        if self._journal is not None:
            if journal_mod.active_journal() is self._journal:
                journal_mod.deactivate()
            self._journal.close()
            self._journal = None

    @runtime.root_only
    def _init_checkpointing(self):
        if not self.checkpoint_dir.is_valid:
            self.checkpoint_dir.create()
            self.checkpoint_dir.save_config(self.config)
        self.io_redirector = IORedirector(self.checkpoint_dir.log_file)
        self.io_redirector.install()

    def _resume_run(self):
        self.logger.info(f"Resuming training from checkpoint: {self.checkpoint_dir}")
        self.resume_run()

    def _post_run(self):
        self.stop_time = datetime.now()
        if self.checkpoint_dir is not None:
            self.checkpoint_dir.wait_until_finished()
        if self.telemetry_armed:
            self._telemetry_ledger()
        # shared-FS aware: every process shares the cache dir, process 0 logs
        if self._compile_cache_dir is not None and runtime.is_root():
            from .compile.cache import cache_stats

            s = cache_stats()
            self.logger.info(
                "compile cache: %d entries (%.1f MB) at %s — this process: "
                "%d AOT hit(s), %d miss(es), %.0f ms compiling",
                s["entries"], s["size_bytes"] / 1e6, s["dir"],
                s["aot_hits"], s["aot_misses"], s["aot_compile_ms"],
            )
        self.logger.info(f"Finished training in {self.stop_time - self.start_time} ({self.stop_time})")
        if self.checkpointing_enabled:
            self.logger.info(f"Outputs have been saved to {self.checkpoint_dir}")
        # a run that got here without a preemption verdict finished for real:
        # tell the requeue wrapper to stand down. A survived watchdog stall
        # is the one verdict completion supersedes (the run recovered).
        self._write_requeue_verdict(
            False, "completed", "run finished all stages",
            force=(self._verdict_kind == "hang"),
        )
        self.post_run()

    def _pre_epoch(self):
        pass

    def _post_epoch(self):
        need = (self.wandb or self._tb_writer is not None) and runtime.is_root()
        if need:
            metrics = {name: self.tracker[name][-1] for name in self.tracker if self.tracker[name]}
            if self.wandb:
                wandb.log(metrics)
            if self._tb_writer is not None:
                # the stage's _reduce_metrics has already advanced the
                # tracker, so the just-completed epoch is epoch - 1
                self._tb_writer.log_epoch(metrics, epoch=self.tracker.epoch - 1)

    def _teardown(self, exc: BaseException | None) -> None:
        """Guaranteed teardown — runs whether the stages finished, raised, or
        were interrupted; the exception (if any) propagates afterwards."""
        if isinstance(exc, KeyboardInterrupt):
            self.logger.info("=== run aborted by user (KeyboardInterrupt) ===")
        elif exc is not None:
            self.logger.error("=== run failed; traceback follows ===", exc_info=exc)
        if exc is not None:
            # the failure's requeue verdict (first-writer-wins: a preemption
            # or hang verdict already written this run is not stomped)
            requeue, kind, reason = self._classify_failure(exc)
            self._write_requeue_verdict(requeue, kind, reason)
        try:
            self._disarm_telemetry(exc)
        except Exception:
            self.logger.warning("telemetry teardown failed", exc_info=True)
        if self.checkpoint_dir is not None:
            # a failed/interrupted run may still have an async save in
            # flight: let it commit (or surface its own error to the log)
            # rather than orphan a half-written checkpoint behind the
            # exception that is about to propagate
            try:
                self.checkpoint_dir.wait_until_finished()
            except Exception:
                self.logger.warning("pending async checkpoint save failed during teardown", exc_info=True)
        if self.wandb and wandb_is_initialized():
            wandb.finish(exit_code=0 if exc is None else 1)
        if self._tb_writer is not None:
            self._tb_writer.close()
            self._tb_writer = None
        if self.io_redirector is not None:
            self.io_redirector.uninstall()
        # restore process-wide signal dispositions: a stale handler would
        # make post-run SIGTERM a silent no-op and pin this pipeline alive
        self._preemption.uninstall()


@contextmanager
def _run_guard(pipeline: TrainingPipeline):
    try:
        yield
    except BaseException as exc:
        pipeline._teardown(exc)
        raise
    else:
        pipeline._teardown(None)
