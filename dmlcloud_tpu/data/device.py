"""Host->device feeding: sharded transfer + double-buffered device prefetch.

The reference leaves host->device transfer to user code / DDP; on TPU the
transfer schedule matters: overlapping the next batch's host->HBM copy with
the current step hides DCN/PCIe latency entirely. ``device_iterator`` wraps
any host-batch iterator into a pipeline that keeps ``prefetch`` batches
resident on device, already laid out with the mesh's batch sharding.
"""

from __future__ import annotations

import collections
from typing import Any, Iterable, Iterator

from jax.sharding import Mesh, PartitionSpec as P

from ..parallel import mesh as mesh_lib


def device_iterator(
    it: Iterable[Any],
    mesh: Mesh,
    pspec: P | None = None,
    prefetch: int = 2,
) -> Iterator[Any]:
    """Yield device-resident, mesh-sharded batches, keeping ``prefetch``
    transfers in flight ahead of consumption.

    jax transfers are async: ``device_put`` returns immediately and the copy
    overlaps compute, so a small ``prefetch`` suffices to fully hide it.
    """
    queue: collections.deque = collections.deque()
    src = iter(it)

    def enqueue(n: int) -> None:
        for _ in range(n):
            try:
                batch = next(src)
            except StopIteration:
                return
            queue.append(mesh_lib.make_global_batch(batch, mesh, pspec))

    enqueue(max(prefetch, 1))
    while queue:
        yield queue.popleft()
        enqueue(1)
