"""Host->device feeding: sharded transfer + double-buffered device prefetch.

The reference leaves host->device transfer to user code / DDP; on TPU the
transfer schedule matters: overlapping the next batch's host->HBM copy with
the current step hides DCN/PCIe latency entirely. ``device_iterator`` wraps
any host-batch iterator into a pipeline that keeps ``prefetch`` batches
resident on device, already laid out with the mesh's batch sharding.

Two overlap stages, both optional:

1. **Device prefetch** (``prefetch``, default 2): ``jax.device_put`` is
   async — the H2D copy of batch N+1/N+2 runs while the device computes on
   batch N, so a depth of 2 (double buffering) hides the transfer entirely.
2. **Host prefetch** (``host_prefetch``, default 0): drain the *source*
   iterator on a background thread (bounded queue), so host-side batch prep
   (augmentation, numpy collation, disk reads) overlaps the training
   thread's dispatch work too. JAX calls (``make_global_batch``) stay on the
   consuming thread — only pure host work moves off it.
"""

from __future__ import annotations

import collections
import itertools
from typing import Any, Iterable, Iterator

from jax.sharding import Mesh, PartitionSpec as P

from ..parallel import mesh as mesh_lib
from ..telemetry import journal as _journal


def peek_spec(it: Iterable[Any]) -> tuple[Any, Iterable[Any]]:
    """Abstract spec (``ShapeDtypeStruct`` pytree) of the first batch,
    WITHOUT consuming it: returns ``(spec, iterable)`` where the iterable
    still yields every batch including the peeked one.

    Re-iterable sources (lists, ``DataPipeline``\\ s — anything whose
    ``iter()`` returns a fresh iterator) come back untouched; one-shot
    iterators come back as a chain that replays the peeked batch first. The
    AOT precompiler (compile/aot.py) uses this to derive the batch signature
    at stage start when no ``batch_spec()`` is declared."""
    from ..compile.aot import abstract_spec

    src = iter(it)
    try:
        first = next(src)
    except StopIteration:
        raise ValueError("cannot peek the batch spec of an empty dataset") from None
    spec = abstract_spec(first)
    if src is it:  # one-shot iterator: replay the consumed batch
        return spec, itertools.chain([first], src)
    return spec, it


def device_iterator(
    it: Iterable[Any],
    mesh: Mesh,
    pspec: P | None = None,
    prefetch: int = 2,
    host_prefetch: int = 0,
) -> Iterator[Any]:
    """Yield device-resident, mesh-sharded batches, keeping ``prefetch``
    transfers in flight ahead of consumption (and, with ``host_prefetch > 0``,
    that many host batches prepared ahead on a background thread).

    jax transfers are async: ``device_put`` returns immediately and the copy
    overlaps compute, so a small ``prefetch`` suffices to fully hide it.
    """
    queue: collections.deque = collections.deque()
    if host_prefetch > 0:
        from .datasets import _prefetch_iter

        src = _prefetch_iter(iter(it), host_prefetch)
    else:
        src = iter(it)

    # Shutdown hardening (the preemption drain path): a consumer that
    # abandons this iterator mid-epoch — a break out of the step loop, a
    # generator .close(), GC — must tear down the host-prefetch machinery
    # PROMPTLY. Closing ``src`` here runs _prefetch_iter's finally (stop
    # event + queue drain), so its background thread exits within one put
    # timeout instead of lingering blocked on a full queue until interpreter
    # exit. Without host_prefetch the close is a harmless no-op/absent.
    try:
        if prefetch <= 0:
            # strictly synchronous: one transfer per consumed batch, nothing
            # pulled from the source (or put on device) ahead of the step
            for batch in src:
                with _journal.span("h2d", prefetch=0):
                    yield_batch = mesh_lib.make_global_batch(batch, mesh, pspec)
                yield yield_batch
            return

        def enqueue(n: int) -> None:
            for _ in range(n):
                try:
                    batch = next(src)
                except StopIteration:
                    return
                # the span covers the host-side put dispatch only — the copy
                # itself is async and overlaps compute (that's the point)
                with _journal.span("h2d", prefetch=prefetch):
                    queue.append(mesh_lib.make_global_batch(batch, mesh, pspec))

        enqueue(prefetch)
        while queue:
            yield queue.popleft()
            enqueue(1)
    finally:
        close = getattr(src, "close", None)
        if close is not None:
            close()
