"""Rank-sharding index math — pure functions, identical semantics to the
reference (/root/reference/dmlcloud/util/data.py:11-67).

- ``shard_indices``: strided slice ``indices[rank::world_size]`` with optional
  MT19937 shuffle and drop-remainder (``even_shards``).
- ``chunk_and_shard_indices``: chunk grid over a long dimension, sharded by
  rank, with ``chunk_overlap`` for windowed time-series context.
- ``shard_sequence``: materialised per-rank subsequence.

These shard *across processes*; on TPU the per-process batch is then stitched
into one globally-sharded array by ``parallel.mesh.make_global_batch``.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


def shard_indices(
    num_elements: int,
    rank: int,
    world_size: int,
    shuffle: bool = False,
    even_shards: bool = True,
    seed: int = 0,
) -> list[int]:
    """Per-rank element indices. ``even_shards=True`` drops the tail so every
    rank gets the same count (required for lock-step SPMD training)."""
    indices = np.arange(num_elements)

    if shuffle:
        np.random.Generator(np.random.MT19937(seed)).shuffle(indices)

    if even_shards:
        indices = indices[: num_elements - num_elements % world_size]

    return indices[rank::world_size].tolist()


def chunk_and_shard_indices(
    num_elements: int,
    chunk_size: int,
    rank: int,
    world_size: int,
    chunk_overlap: int = 0,
    even_shards: bool = True,
    equal_chunks: bool = True,
    shuffle: bool = False,
    seed: int = 0,
) -> list[tuple[int, int]]:
    """Shard a chunk grid over ranks; returns per-rank ``(start, end)`` slices
    (end exclusive, extended by ``chunk_overlap``)."""
    if equal_chunks:
        num_chunks = num_elements // chunk_size
    else:
        num_chunks = (num_elements + chunk_size - 1) // chunk_size

    chunk_indices = shard_indices(
        num_chunks, rank, world_size, shuffle=shuffle, even_shards=even_shards, seed=seed
    )
    chunks = []
    for chunk_idx in chunk_indices:
        start = chunk_idx * chunk_size
        end = start + chunk_size + chunk_overlap
        chunks.append((start, end))
    return chunks


def shard_sequence(
    sequence: Sequence,
    rank: int,
    world_size: int,
    shuffle: bool = False,
    even_shards: bool = True,
    seed: int = 0,
) -> list:
    indices = shard_indices(
        len(sequence), rank, world_size, shuffle=shuffle, even_shards=even_shards, seed=seed
    )
    return [sequence[i] for i in indices]
