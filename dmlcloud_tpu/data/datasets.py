"""Sharded iterable datasets, prefetching, batching, and batch interleaving.

Capability parity with /root/reference/dmlcloud/util/data.py:70-341, torch-free
at the core (numpy buffers instead of pinned torch tensors) but compatible
with ``torch.utils.data.DataLoader``: when torch is importable the dataset
base class is ``torch.utils.data.IterableDataset`` and worker sub-sharding
via ``get_worker_info`` works exactly like the reference (effective rank =
``rank * num_workers + worker_id``, data.py:133-138).

The xarray chunk reader is duck-typed (anything with ``.isel``/indexable dims
works), so xarray stays an optional dependency.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Any, Iterable, Iterator, Sequence

import numpy as np

from ..parallel import runtime
from .sharding import chunk_and_shard_indices, shard_sequence

try:  # torch is optional; used only for DataLoader interop
    from torch.utils.data import IterableDataset as _TorchIterableDataset, get_worker_info as _get_worker_info

    _DatasetBase = _TorchIterableDataset
except ImportError:  # pragma: no cover
    _DatasetBase = object

    def _get_worker_info():
        return None


def _effective_rank_world(rank: int, world_size: int) -> tuple[int, int]:
    """Sub-shard across DataLoader workers: each (rank, worker) pair becomes a
    distinct effective rank (reference data.py:131-138)."""
    info = _get_worker_info()
    if info is None:
        return rank, world_size
    return rank * info.num_workers + info.id, world_size * info.num_workers


def sharded_xr_dataset(
    ds: Any,
    dim: str,
    chunk_size: int,
    chunk_overlap: int = 0,
    even_shards: bool = True,
    equal_chunks: bool = True,
    shuffle: bool = False,
    seed: int = 0,
    rank: int | None = None,
    world_size: int | None = None,
    load: bool = False,
    load_kwargs: dict | None = None,
) -> Iterator[Any]:
    """Lazily slice an xarray Dataset/DataArray (or any ``.isel``-capable
    object) along ``dim`` into per-rank chunks (reference data.py:70-107).
    ``chunk_overlap`` yields overlapping windows for time-series context."""
    if rank is None:
        rank = runtime.rank()
    if world_size is None:
        world_size = runtime.world_size()

    num_elements = len(ds[dim]) if hasattr(ds, "__getitem__") and not isinstance(ds, np.ndarray) else ds.sizes[dim]
    chunks = chunk_and_shard_indices(
        num_elements,
        chunk_size,
        rank,
        world_size,
        chunk_overlap=chunk_overlap,
        even_shards=even_shards,
        equal_chunks=equal_chunks,
        shuffle=shuffle,
        seed=seed,
    )
    for start, end in chunks:
        chunk = ds.isel({dim: slice(start, end)})
        if load:
            chunk.load(**(load_kwargs or {}))
        yield chunk


class ShardedSequenceDataset(_DatasetBase):
    """Iterable over this rank's share of a sequence, reshuffled per epoch via
    ``set_epoch`` (reference data.py:110-147)."""

    def __init__(
        self,
        sequence: Sequence,
        shuffle: bool = False,
        even_shards: bool = True,
        seed: int = 0,
        rank: int | None = None,
        world_size: int | None = None,
    ):
        self.sequence = sequence
        self.shuffle = shuffle
        self.even_shards = even_shards
        self.seed = seed
        self.rank = rank if rank is not None else runtime.rank()
        self.world_size = world_size if world_size is not None else runtime.world_size()
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        if self.even_shards:
            return len(self.sequence) // self.world_size
        n, r = divmod(len(self.sequence), self.world_size)
        return n + (1 if self.rank < r else 0)

    def __iter__(self):
        rank, world_size = _effective_rank_world(self.rank, self.world_size)
        shards = shard_sequence(
            self.sequence,
            rank,
            world_size,
            shuffle=self.shuffle,
            even_shards=self.even_shards,
            seed=self.seed + self.epoch,
        )
        return iter(shards)


class ShardedXrDataset(_DatasetBase):
    """Iterable over this rank's chunks of an xarray-like dataset
    (reference data.py:150-207)."""

    def __init__(
        self,
        ds: Any,
        dim: str,
        chunk_size: int,
        chunk_overlap: int = 0,
        even_shards: bool = True,
        equal_chunks: bool = True,
        shuffle: bool = False,
        seed: int = 0,
        rank: int | None = None,
        world_size: int | None = None,
        load: bool = False,
        load_kwargs: dict | None = None,
    ):
        self.ds = ds
        self.dim = dim
        self.chunk_size = chunk_size
        self.chunk_overlap = chunk_overlap
        self.even_shards = even_shards
        self.equal_chunks = equal_chunks
        self.shuffle = shuffle
        self.seed = seed
        self.load = load
        self.load_kwargs = load_kwargs
        self.rank = rank if rank is not None else runtime.rank()
        self.world_size = world_size if world_size is not None else runtime.world_size()
        self._num_iters = 0

    def set_epoch(self, epoch: int) -> None:
        self._num_iters = epoch

    def __iter__(self):
        rank, world_size = _effective_rank_world(self.rank, self.world_size)
        return sharded_xr_dataset(
            self.ds,
            self.dim,
            self.chunk_size,
            chunk_overlap=self.chunk_overlap,
            even_shards=self.even_shards,
            equal_chunks=self.equal_chunks,
            shuffle=self.shuffle,
            seed=self.seed + self._num_iters,
            rank=rank,
            world_size=world_size,
            load=self.load,
            load_kwargs=self.load_kwargs,
        )


class DownstreamDataset(_DatasetBase):
    """Base for dataset wrappers: forwards ``set_epoch`` and ``__len__``
    (reference data.py:210-219)."""

    def __init__(self, source_ds: Iterable):
        self.source_ds = source_ds

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.source_ds, "set_epoch"):
            self.source_ds.set_epoch(epoch)

    def __len__(self) -> int:
        return len(self.source_ds)


class PrefetchDataset(DownstreamDataset):
    """Background-thread lookahead of ``num_elements`` items (reference
    data.py:222-240) — keeps host-side IO off the training thread's critical
    path so the TPU dispatch queue stays full."""

    def __init__(self, source_ds: Iterable, num_elements: int):
        super().__init__(source_ds)
        self.num_elements = num_elements

    def __iter__(self):
        pool = ThreadPoolExecutor(max_workers=1)
        iter_ = iter(self.source_ds)
        with pool:
            futures = [pool.submit(next, iter_) for _ in range(self.num_elements)]
            while True:
                future = futures.pop(0)
                try:
                    element = future.result()
                except StopIteration:
                    return
                futures.append(pool.submit(next, iter_))
                yield element


class BatchDataset(DownstreamDataset):
    """Group consecutive elements into lists of ``batch_size`` (reference
    data.py:243-263)."""

    def __init__(self, source_ds: Iterable, batch_size: int, drop_remainder: bool = False):
        super().__init__(source_ds)
        self.batch_size = batch_size
        self.drop_remainder = drop_remainder

    def __len__(self) -> int:
        n = len(self.source_ds)
        if self.drop_remainder:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def __iter__(self):
        batch = []
        for element in self.source_ds:
            batch.append(element)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_remainder:
            yield batch


def interleave_batches(
    iterable: Iterable[np.ndarray], num_batches: int
) -> Iterator[np.ndarray]:
    """Re-slice ``num_batches`` consecutive batches into ``num_batches`` mixed
    batches through one preallocated buffer (reference data.py:266-301).
    Yielded views alias the buffer — consume or copy immediately.

    Useful when chunked sequential reads (e.g. xarray time chunks) would give
    each batch correlated content: interleaving restores within-batch mixing
    at memcpy cost, no extra allocation per batch. See also
    ``dmlcloud_tpu.native.fast_interleave`` for the C++ path used
    automatically when the extension is built.
    """
    if num_batches < 1:
        raise ValueError("num_batches must be greater than 0")
    if num_batches == 1:
        yield from iterable
        return

    try:
        from ..native import interleave as _native
    except Exception:
        _native = None

    batches: list[np.ndarray] = []
    memory = None
    slice_size = None
    for batch in iterable:
        batch = np.asarray(batch)
        if memory is None:
            batch_size = batch.shape[0]
            slice_size = batch_size // num_batches
            if batch_size % num_batches != 0:
                raise ValueError(
                    f"Batch dimension ({batch_size}) must be divisible by num_batches={num_batches}"
                )
            memory = np.empty((num_batches, *batch.shape), dtype=batch.dtype)

        batches.append(batch)

        if len(batches) == num_batches:
            if (
                _native is not None
                and _native.available()
                and all(b.flags.c_contiguous for b in batches)
            ):
                _native.interleave_into(memory, batches, slice_size)
            else:
                for i in range(num_batches):
                    for j in range(num_batches):
                        memory[i, j * slice_size : (j + 1) * slice_size] = batches[j][
                            i * slice_size : (i + 1) * slice_size
                        ]
            batches = []
            for i in range(num_batches):
                yield memory[i]


def interleave_dict_batches(
    iterable: Iterable[dict[str, np.ndarray]], num_batches: int
) -> Iterator[dict[str, np.ndarray]]:
    """Dict-of-arrays variant of ``interleave_batches`` (reference
    data.py:304-341). Yielded dicts alias the buffers — consume immediately."""
    if num_batches < 1:
        raise ValueError("num_batches must be greater than 0")
    if num_batches == 1:
        yield from iterable
        return

    batches: list[dict[str, np.ndarray]] = []
    memory: dict[str, np.ndarray] = {}
    slice_size: dict[str, int] = {}
    for batch in iterable:
        batch = {k: np.asarray(v) for k, v in batch.items()}
        if not memory:
            for k, arr in batch.items():
                batch_size = arr.shape[0]
                if batch_size % num_batches != 0:
                    raise ValueError(
                        f"Batch dimension ({batch_size}) must be divisible by num_batches={num_batches}"
                    )
                slice_size[k] = batch_size // num_batches
                memory[k] = np.empty((num_batches, *arr.shape), dtype=arr.dtype)

        batches.append(batch)

        if len(batches) == num_batches:
            for k in memory:
                s = slice_size[k]
                for i in range(num_batches):
                    for j in range(num_batches):
                        memory[k][i, j * s : (j + 1) * s] = batches[j][k][i * s : (i + 1) * s]
            batches = []
            for i in range(num_batches):
                yield {k: memory[k][i] for k in memory}
