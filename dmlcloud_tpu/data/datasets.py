"""Host-side data pipelines: sharding, batching, prefetch, interleave.

Covers the capabilities of /root/reference/dmlcloud/util/data.py:70-341, but
the architecture is a composable pipeline (tf.data / grain idiom) instead of
the reference's one-wrapper-class-per-transform stack:

- ``DataPipeline`` is the core: an epoch-aware iterator factory plus a chain
  of combinators (``shard -> batch -> map -> interleave -> prefetch ->
  to_device``). Every stage receives the epoch at iteration time, so
  ``set_epoch`` needs no per-wrapper forwarding protocol — one call on the
  pipeline re-seeds every shuffling stage.
- Batch interleaving is ONE pytree-generic implementation (arrays, dicts, or
  any nesting) with the C++ kernel (native/interleave.cpp) engaged for every
  contiguous leaf — the reference maintains two near-identical Python-loop
  variants and pins torch buffers.
- ``to_device(mesh)`` ends a pipeline on-device: batches leave as
  mesh-sharded global jax.Arrays with transfers running ahead of consumption
  (data/device.py) — the reference stops at host tensors and leaves the
  device copy to DDP/user code.

The reference's class names (``ShardedSequenceDataset``, ``ShardedXrDataset``,
``PrefetchDataset``, ``BatchDataset``, ``DownstreamDataset``) remain as thin
shims over the combinators, including torch ``DataLoader`` worker
sub-sharding via ``get_worker_info`` (effective rank = ``rank * num_workers
+ worker_id``, matching reference data.py:133-138 exactly).
"""

from __future__ import annotations

import queue as _queue
import threading
from typing import Any, Callable, Iterable, Iterator, Sequence

import numpy as np

from ..parallel import runtime
from .sharding import chunk_and_shard_indices, shard_sequence

try:  # torch is optional; used only for DataLoader interop
    from torch.utils.data import IterableDataset as _TorchIterableDataset, get_worker_info as _get_worker_info

    _DatasetBase = _TorchIterableDataset
except ImportError:  # pragma: no cover
    _DatasetBase = object

    def _get_worker_info():
        return None


def _effective_rank_world(rank: int, world_size: int) -> tuple[int, int]:
    """Sub-shard across DataLoader workers: each (rank, worker) pair becomes a
    distinct effective rank (reference data.py:131-138)."""
    info = _get_worker_info()
    if info is None:
        return rank, world_size
    return rank * info.num_workers + info.id, world_size * info.num_workers


# ---------------------------------------------------------------------------
# pipeline core
# ---------------------------------------------------------------------------

class DataPipeline(_DatasetBase):
    """An epoch-aware, composable host-data pipeline.

    Built from a ``make_iter(epoch) -> iterator`` factory; every combinator
    returns a NEW pipeline whose factory pulls from this one's, threading the
    epoch through the whole chain. Iteration state never lives on the
    pipeline object, so one pipeline can be iterated repeatedly (one pass per
    epoch — the TrainValStage contract).
    """

    def __init__(self, make_iter: Callable[[int | None], Iterator], length_fn: Callable[[], int] | None = None):
        self._make_iter = make_iter
        self._length_fn = length_fn
        #: None until set_epoch is called — sources distinguish "caller never
        #: drives epochs through this pipeline" (leave wrapped datasets'
        #: own epoch state alone) from an explicit epoch 0.
        self.epoch: int | None = None
        #: elements this pipeline's CURRENT pass has yielded — the cursor
        #: ``state_dict`` checkpoints (reset at each ``__iter__``)
        self._consumed = 0
        #: one-shot fast-forward applied by the next ``__iter__`` (set by
        #: ``load_state_dict``)
        self._pending_skip = 0

    # -- protocol -----------------------------------------------------------
    def set_epoch(self, epoch: int) -> None:
        """Re-seed every shuffling stage for this epoch (the reference's
        DistributedSampler.set_epoch analog)."""
        self.epoch = epoch

    def __iter__(self) -> Iterator:
        return self._tracked(self._make_iter(self.epoch))

    def _tracked(self, it: Iterator) -> Iterator:
        """Count yields (the resumable cursor) and apply a pending
        fast-forward. The skip REPLAYS the upstream chain and discards —
        every stateful stage (shuffle reservoirs, pack/interleave buffers,
        per-epoch RNG) re-derives its exact state deterministically, so the
        elements after the skip are bit-identical to an uninterrupted pass."""
        self._consumed = 0
        skip = self._pending_skip
        self._pending_skip = 0
        if skip:
            import itertools

            for _ in itertools.islice(it, skip):
                pass
            self._consumed = skip
        for x in it:
            self._consumed += 1
            yield x

    # -- resumable iteration state (elastic resume; doc/elasticity.md) ------
    def state_dict(self) -> dict:
        """Checkpointable iteration state: the epoch and the GLOBAL element
        offset (``local consumed x world_size`` — every rank consumes in
        lockstep, so the globally-consumed prefix is world-size-independent).
        Save it alongside the model (the stage's step-save sidecar does this
        automatically) and feed it to :meth:`load_state_dict` on resume —
        including a resume on a DIFFERENT world size, where the per-rank
        skip is re-derived from the global offset."""
        ws = runtime.world_size()
        return {
            "v": 1,
            "epoch": self.epoch,
            "global_offset": int(self._consumed) * ws,
            "world_size": ws,
        }

    def load_state_dict(self, state: dict) -> None:
        """Restore :meth:`state_dict` output: re-seeds the epoch and arms a
        fast-forward so the next pass resumes at the exact next element. A
        global offset not divisible by the new world size cannot be resumed
        exactly (the remainder straddles ranks) — the skip rounds DOWN and
        warns, replaying at most ``world_size - 1`` global elements."""
        if not isinstance(state, dict) or state.get("v") != 1:
            raise ValueError(f"unrecognised DataPipeline state: {state!r}")
        if state.get("epoch") is not None:
            self.set_epoch(int(state["epoch"]))
        ws = runtime.world_size()
        skip, rem = divmod(int(state["global_offset"]), ws)
        if rem:
            import logging

            logging.getLogger("dmlcloud_tpu").warning(
                "DataPipeline resume: global offset %d is not divisible by the new "
                "world size %d; rounding down (up to %d element(s) replay)",
                state["global_offset"], ws, ws - 1,
            )
        self._pending_skip = skip

    def __len__(self) -> int:
        if self._length_fn is None:
            raise TypeError(f"{type(self).__name__} has no length")
        return self._length_fn()

    # -- sources ------------------------------------------------------------
    @classmethod
    def from_source(cls, iterable: Iterable) -> "DataPipeline":
        """Wrap any (re-)iterable; its ``set_epoch`` is honored if present."""

        def make(epoch: int | None) -> Iterator:
            # forward only an EXPLICIT epoch — a pipeline nobody drives must
            # not stomp an epoch the user set directly on the inner dataset
            if epoch is not None and hasattr(iterable, "set_epoch"):
                iterable.set_epoch(epoch)
            return iter(iterable)

        # length evaluated lazily — a source whose len changes after wrapping
        # (list extended before training, curriculum datasets) stays truthful
        length = (lambda: len(iterable)) if hasattr(iterable, "__len__") else None
        return cls(make, length)

    @classmethod
    def from_sequence(
        cls,
        sequence: Sequence,
        shuffle: bool = False,
        even_shards: bool = True,
        seed: int = 0,
        rank: int | None = None,
        world_size: int | None = None,
    ) -> "DataPipeline":
        """This process's share of ``sequence``, reshuffled per epoch; the
        shard is computed lazily at iteration time so torch DataLoader
        workers sub-shard correctly."""
        rank = runtime.rank() if rank is None else rank
        world_size = runtime.world_size() if world_size is None else world_size

        def make(epoch: int | None) -> Iterator:
            r, w = _effective_rank_world(rank, world_size)
            e = 0 if epoch is None else epoch
            return iter(
                shard_sequence(sequence, r, w, shuffle=shuffle, even_shards=even_shards, seed=seed + e)
            )

        def length() -> int:
            if even_shards:
                return len(sequence) // world_size
            n, rem = divmod(len(sequence), world_size)
            return n + (1 if rank < rem else 0)

        return cls(make, length)

    @classmethod
    def from_chunked(
        cls,
        ds: Any,
        dim: str,
        chunk_size: int,
        chunk_overlap: int = 0,
        even_shards: bool = True,
        equal_chunks: bool = True,
        shuffle: bool = False,
        seed: int = 0,
        rank: int | None = None,
        world_size: int | None = None,
        load: bool = False,
        load_kwargs: dict | None = None,
    ) -> "DataPipeline":
        """This process's chunks of an xarray-like (``.isel``-capable) dataset
        along ``dim`` — overlapping windows supported for time-series context
        (capability of reference data.py:70-107)."""
        rank = runtime.rank() if rank is None else rank
        world_size = runtime.world_size() if world_size is None else world_size

        def make(epoch: int | None) -> Iterator:
            r, w = _effective_rank_world(rank, world_size)
            e = 0 if epoch is None else epoch
            return _iter_chunks(
                ds, dim, chunk_size, chunk_overlap, even_shards, equal_chunks,
                shuffle, seed + e, r, w, load, load_kwargs,
            )

        return cls(make)

    # -- combinators --------------------------------------------------------
    def _chain(self, wrap: Callable[[Iterator, int], Iterator], length_fn=None) -> "DataPipeline":
        parent_make = self._make_iter
        return DataPipeline(lambda epoch: wrap(parent_make(epoch), epoch), length_fn)

    def map(self, fn: Callable[[Any], Any]) -> "DataPipeline":
        return self._chain(lambda it, _e: (fn(x) for x in it), self._length_fn)

    def pack(self, seq_len: int, *, split_long: bool = True) -> "DataPipeline":
        """Pack a stream of variable-length token sequences into fixed
        ``seq_len`` rows of ``{"tokens", "segment_ids"}`` (see
        :func:`pack_sequences`) — compose as
        ``pipeline.shuffle(...).pack(2048).batch(8)``."""
        if seq_len < 1:
            raise ValueError(f"seq_len must be >= 1, got {seq_len}")
        return self._chain(lambda it, _e: _pack_sequences_iter(it, seq_len, split_long))

    def pack_stream(
        self,
        seq_len: int,
        chunk_docs: int = 1024,
        *,
        split_long: bool = True,
        pack_window: int = 0,
        stats: "PackStats | None" = None,
    ) -> "DataPipeline":
        """Streaming chunked packing: buffer up to ``chunk_docs`` documents,
        flatten them to the two-numpy-buffer form, and hand the greedy fill
        to the C++ packer (``native.pack.pack_flat``; the Python
        ``pack_sequences`` loop when the library isn't built — bit-identical
        either way), emitting ``{"tokens", "segment_ids"}`` rows that feed
        the packed-attention path (``DecoderLM(segment_ids=...)`` +
        ``lm_loss(..., segment_ids=...)``).

        Unlike ``pack()`` (per-example Python loop) this is the production
        input path for ragged corpora: memory stays O(``chunk_docs`` docs)
        no matter how long the stream runs, and the packer works on flat
        buffers instead of per-example Python objects. The cost of
        chunking is a *boundary loss*: each chunk's final partially-filled
        row is emitted padded instead of borrowing the next chunk's first
        document, wasting at most ``seq_len - 1`` slots per chunk — a
        fraction that shrinks as ``chunk_docs`` grows. The returned
        pipeline's ``pack_stats`` (a :class:`PackStats`, live-updated
        during iteration) accounts for it: total padding-waste fraction
        and the chunk-boundary share, the numbers the ``BENCH_data_*``
        receipts report (doc/data.md).

        ``pack_window > 0`` switches to **window-based first-fit-decreasing
        packing** (:func:`_pack_ffd_iter`): documents are buffered in
        windows of ``pack_window``, sorted longest-first (stable — arrival
        order breaks ties), and first-fit placed into open rows that
        persist ACROSS windows, so there is no chunk-boundary tail waste
        at all — the only padding left is the end-of-stream flush and the
        slivers no remaining document fits. This reclaims most of the
        ~19% greedy pad_fraction (BENCH_data_pr18 measures ≤ 0.10 on the
        pinned corpus) at the cost of reordering rows WITHIN a window
        horizon; the emitted row sequence is still bit-deterministic given
        the input stream and ``pack_window`` (doc/data.md, "FFD window
        semantics"). ``chunk_docs`` is ignored in this mode."""
        if seq_len < 1:
            raise ValueError(f"seq_len must be >= 1, got {seq_len}")
        if chunk_docs < 1:
            raise ValueError(f"chunk_docs must be >= 1, got {chunk_docs}")
        if pack_window < 0:
            raise ValueError(f"pack_window must be >= 0, got {pack_window}")
        st = stats if stats is not None else PackStats()

        def wrap(it: Iterator, _e) -> Iterator:
            if pack_window:
                return _pack_ffd_iter(it, seq_len, pack_window, split_long, st)
            return _pack_stream_iter(it, seq_len, chunk_docs, split_long, st)

        out = self._chain(wrap)
        out.pack_stats = st
        return out

    @classmethod
    def mix(
        cls,
        sources: Sequence["DataPipeline"],
        weights: Sequence[float] | None = None,
        seed: int = 0,
    ) -> "MixPipeline":
        """Deterministic weighted sampling over child pipelines: element
        ``t`` of the mixed stream comes from the source a counter-based
        draw — a pure function of ``(seed, t)`` — selects by cumulative
        weight. See :class:`MixPipeline` for the determinism and resume
        contract (doc/data.md)."""
        return MixPipeline(sources, weights=weights, seed=seed)

    def shuffle(self, buffer_size: int, seed: int = 0) -> "DataPipeline":
        """Streaming shuffle through a ``buffer_size`` reservoir (the
        tf.data idiom): each yield swaps a random buffer slot with the next
        upstream element, so memory stays O(buffer) on unbounded streams.
        Reshuffles per epoch via ``set_epoch`` (seed + epoch). Sequence
        sources already shuffle exactly via index permutation
        (``from_sequence(shuffle=True)``); this is for iterable sources."""
        if buffer_size < 1:
            raise ValueError(f"buffer_size must be >= 1, got {buffer_size}")

        def wrap(it: Iterator, epoch: int | None) -> Iterator:
            rng = np.random.default_rng(seed + (0 if epoch is None else epoch))
            buf = []
            for x in it:
                buf.append(x)
                if len(buf) == buffer_size:
                    j = rng.integers(len(buf))
                    buf[j], out = buf[-1], buf[j]
                    buf.pop()
                    yield out
            for i in rng.permutation(len(buf)):  # drain in a random order
                yield buf[i]

        return self._chain(wrap, self._length_fn)

    def batch(self, batch_size: int, drop_remainder: bool = False, collate: Callable | None = None) -> "DataPipeline":
        """Group consecutive elements into lists of ``batch_size`` (optionally
        collated, e.g. ``np.stack``)."""

        def wrap(it: Iterator, _e: int) -> Iterator:
            buf: list = []
            for x in it:
                buf.append(x)
                if len(buf) == batch_size:
                    yield collate(buf) if collate else buf
                    buf = []
            if buf and not drop_remainder:
                yield collate(buf) if collate else buf

        parent_len = self._length_fn

        def length() -> int:
            if parent_len is None:
                raise TypeError("unsized pipeline")
            n = parent_len()
            return n // batch_size if drop_remainder else -(-n // batch_size)

        return self._chain(wrap, length if parent_len is not None else None)

    def interleave(self, num_batches: int, copy: bool = True) -> "DataPipeline":
        """Re-mix groups of ``num_batches`` consecutive batches (see
        ``interleave_batches``). Batches are COPIED out of the interleave
        buffer by default, because downstream lookahead stages (``prefetch``,
        ``to_device``) hold several batches concurrently and would otherwise
        observe the buffer being rewritten by the next window. Pass
        ``copy=False`` only for a pipeline consumed strictly one batch at a
        time."""
        return self._chain(lambda it, _e: _interleave_pytrees(it, num_batches, copy=copy), self._length_fn)

    def prefetch(self, num_elements: int) -> "DataPipeline":
        """Read ahead ``num_elements`` items on a background thread, keeping
        host IO off the training thread's critical path."""
        return self._chain(lambda it, _e: _prefetch_iter(it, num_elements), self._length_fn)

    def to_device(self, mesh, pspec=None, prefetch: int = 2, host_prefetch: int = 0) -> "DataPipeline":
        """End the pipeline on-device: batches become mesh-sharded global
        jax.Arrays with ``prefetch`` transfers in flight ahead of the step;
        ``host_prefetch > 0`` additionally prepares that many host batches
        ahead on a background thread (device.py)."""
        from .device import device_iterator

        return self._chain(
            lambda it, _e: device_iterator(
                it, mesh, pspec=pspec, prefetch=prefetch, host_prefetch=host_prefetch
            ),
            self._length_fn,
        )


# ---------------------------------------------------------------------------
# streaming chunked packing (the production ragged-corpus input path)
# ---------------------------------------------------------------------------

class PackStats:
    """Live packing accounting of one ``pack_stream`` stage.

    Updated as chunks are packed (cumulative across epochs unless
    :meth:`reset` is called), readable at any point during iteration:

    - ``docs`` / ``chunks`` / ``rows``: documents consumed, chunks packed,
      fixed-shape rows emitted
    - ``tokens_in``: real tokens entering the packer
    - ``tokens_placed``: real tokens placed into rows (less than
      ``tokens_in`` only when ``split_long=False`` truncates)
    - ``slots``: ``rows * seq_len`` — every token slot emitted
    - ``pad_slots``: slots holding padding (``segment_ids == 0``)
    - ``boundary_pad_slots``: the subset of ``pad_slots`` in each chunk's
      final row — the price of never packing across a chunk boundary
    """

    def __init__(self):
        self.docs = 0
        self.chunks = 0
        self.rows = 0
        self.tokens_in = 0
        self.tokens_placed = 0
        self.slots = 0
        self.pad_slots = 0
        self.boundary_pad_slots = 0

    def reset(self) -> None:
        self.__init__()

    @property
    def pad_fraction(self) -> float:
        """Fraction of emitted slots that are padding (0.0 before any row)."""
        return self.pad_slots / self.slots if self.slots else 0.0

    @property
    def boundary_fraction(self) -> float:
        """Fraction of emitted slots wasted specifically on chunk-boundary
        tail rows — the part a larger ``chunk_docs`` would reclaim."""
        return self.boundary_pad_slots / self.slots if self.slots else 0.0

    def as_dict(self) -> dict:
        return {
            "docs": self.docs,
            "chunks": self.chunks,
            "rows": self.rows,
            "tokens_in": self.tokens_in,
            "tokens_placed": self.tokens_placed,
            "slots": self.slots,
            "pad_slots": self.pad_slots,
            "boundary_pad_slots": self.boundary_pad_slots,
            "pad_fraction": round(self.pad_fraction, 6),
            "boundary_fraction": round(self.boundary_fraction, 6),
        }

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"PackStats({self.as_dict()})"


def _pack_stream_iter(docs: Iterator, seq_len: int, chunk_docs: int, split_long: bool, stats: PackStats) -> Iterator[dict]:
    """Chunked packing core: per window of ``chunk_docs`` documents, one
    flatten + one native ``pack_flat`` call (Python packer fallback —
    bit-identical, asserted in tests), rows yielded one at a time so
    downstream stages stream. Each chunk packs independently; the
    resulting per-chunk rows are exactly ``pack_sequences(chunk)``."""
    try:
        from ..native import pack as _native_pack

        native_ok = _native_pack.available()
    except Exception:  # pragma: no cover - import guard
        _native_pack, native_ok = None, False

    def pack_chunk(buf: list) -> Iterator[dict]:
        arrays = [np.asarray(d, np.int32).ravel() for d in buf]
        stats.docs += len(arrays)
        arrays = [a for a in arrays if a.size]  # the packer skips empty docs
        if not arrays:
            return
        n_in = sum(int(a.size) for a in arrays)
        stats.tokens_in += n_in
        if native_ok:
            lengths = np.fromiter((a.size for a in arrays), np.int64, count=len(arrays))
            flat = np.concatenate(arrays)
            tokens, segs = _native_pack.pack_flat(flat, lengths, seq_len, split_long=split_long)
            rows = [{"tokens": tokens[i], "segment_ids": segs[i]} for i in range(len(tokens))]
        else:
            rows = list(_pack_sequences_iter(arrays, seq_len, split_long))
        stats.chunks += 1
        stats.rows += len(rows)
        stats.slots += len(rows) * seq_len
        pad = sum(int(np.count_nonzero(r["segment_ids"] == 0)) for r in rows)
        stats.pad_slots += pad
        stats.tokens_placed += len(rows) * seq_len - pad
        if rows:
            stats.boundary_pad_slots += int(np.count_nonzero(rows[-1]["segment_ids"] == 0))
        yield from rows

    buf: list = []
    for doc in docs:
        buf.append(doc)
        if len(buf) == chunk_docs:
            yield from pack_chunk(buf)
            buf = []
    if buf:
        yield from pack_chunk(buf)


def _pack_ffd_iter(docs: Iterator, seq_len: int, window_docs: int, split_long: bool, stats: PackStats) -> Iterator[dict]:
    """Window-based first-fit-decreasing packing (``pack_stream(...,
    pack_window=N)``).

    Documents buffer in windows of ``window_docs``; each window is sorted
    longest-first (stable — equal lengths keep arrival order) and first-fit
    placed into open rows ("bins"). Unlike the chunked greedy packer, bins
    are NOT flushed at window boundaries: a partially-filled row stays open
    for the next window's documents, so the chunk-boundary tail waste
    disappears entirely — the only padding left is (a) slivers no remaining
    document fits and (b) the end-of-stream flush, which is the only place
    this packer adds to ``boundary_pad_slots``.

    Rows are emitted the moment they fill (or when the open-bin cap — ``max
    (window_docs, 16)`` — evicts the fullest, oldest-first bin to bound
    memory), so downstream stages stream. Everything is pure sequential
    bookkeeping over the input order: the emitted row sequence is
    bit-deterministic given (input stream, ``seq_len``, ``window_docs``).
    """
    max_open = max(int(window_docs), 16)
    bins: list[list] = []  # [fill, parts]; list order == creation order == first-fit order

    def emit(parts: list, fill: int, boundary: bool = False) -> dict:
        tokens = np.zeros(seq_len, np.int32)
        segs = np.zeros(seq_len, np.int32)
        at = 0
        for seg, p in enumerate(parts, 1):
            tokens[at : at + p.size] = p
            segs[at : at + p.size] = seg
            at += p.size
        stats.rows += 1
        stats.slots += seq_len
        stats.pad_slots += seq_len - fill
        stats.tokens_placed += fill
        if boundary:
            stats.boundary_pad_slots += seq_len - fill
        return {"tokens": tokens, "segment_ids": segs}

    def place(part: np.ndarray) -> dict | None:
        for b in bins:
            if b[0] + part.size <= seq_len:
                b[1].append(part)
                b[0] += part.size
                if b[0] == seq_len:
                    bins.remove(b)
                    return emit(b[1], b[0])
                return None
        bins.append([int(part.size), [part]])
        if len(bins) > max_open:
            # bound memory: close the fullest bin (ties -> oldest); its
            # padding is ordinary waste, not boundary waste
            full = max(bins, key=lambda b: b[0])
            bins.remove(full)
            return emit(full[1], full[0])
        return None

    def run_window(buf: list) -> Iterator[dict]:
        arrays = [np.asarray(d, np.int32).ravel() for d in buf]
        stats.docs += len(arrays)
        arrays = [a for a in arrays if a.size]
        if not arrays:
            return
        stats.tokens_in += sum(int(a.size) for a in arrays)
        stats.chunks += 1
        parts: list[np.ndarray] = []
        for a in arrays:
            if a.size > seq_len:
                if split_long:
                    # whole seq_len pieces are born full rows; the tail
                    # joins the window's FFD pool like any short document
                    off = 0
                    while a.size - off >= seq_len:
                        yield emit([a[off : off + seq_len]], seq_len)
                        off += seq_len
                    if off < a.size:
                        parts.append(a[off:])
                else:
                    yield emit([a[:seq_len]], seq_len)
            else:
                parts.append(a)
        parts.sort(key=lambda p: p.size, reverse=True)  # stable: ties keep arrival order
        for p in parts:
            row = place(p)
            if row is not None:
                yield row

    buf: list = []
    for doc in docs:
        buf.append(doc)
        if len(buf) == window_docs:
            yield from run_window(buf)
            buf = []
    if buf:
        yield from run_window(buf)
    for fill, parts in bins:  # end-of-stream flush: the only boundary waste
        yield emit(parts, fill, boundary=True)


# ---------------------------------------------------------------------------
# deterministic weighted multi-source mixing
# ---------------------------------------------------------------------------

_M64 = (1 << 64) - 1


def _mix_u64(seed: int, step: int) -> int:
    """splitmix64-style counter hash: a uniform u64 that is a pure function
    of ``(seed, step)`` — no RNG object, no hidden state, so the draw
    sequence can be re-entered at any step (elastic resume) and is
    identical on every rank and platform."""
    x = (int(seed) * 0x9E3779B97F4A7C15 + (int(step) + 1) * 0xD1B54A32D192ED03) & _M64
    x ^= x >> 30
    x = (x * 0xBF58476D1CE4E5B9) & _M64
    x ^= x >> 27
    x = (x * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


def _mix_choice(seed: int, step: int, weights: Sequence[float], alive: Sequence[bool]) -> int:
    """Source index for draw ``step``: the u64 mapped onto the cumulative
    weights of the still-alive sources (exhausted sources renormalize away
    by carrying zero mass)."""
    total = sum(w for w, a in zip(weights, alive) if a)
    u = (_mix_u64(seed, step) / float(1 << 64)) * total
    acc = 0.0
    last = 0
    for i, (w, a) in enumerate(zip(weights, alive)):
        if not a:
            continue
        acc += w
        last = i
        if u < acc:
            return i
    return last  # float roundoff on the final boundary


class MixPipeline(DataPipeline):
    """Deterministic weighted mixing over child pipelines
    (``DataPipeline.mix``).

    The choice sequence is a pure function of ``(seed, draw index)``
    (counter-based splitmix64 — no RNG object), so the mix is reproducible
    run-to-run and resumable mid-stream: ``state_dict`` captures the draw
    cursor plus every child's own PR-7 iterator state, and
    ``load_state_dict`` fast-forwards the children and re-enters the draw
    sequence at the exact next step — 0 replayed and 0 skipped samples,
    including across a world-size change (all cursors are stored as
    world-size-independent global offsets). A source that exhausts
    renormalizes the remaining weights with a logged warning; the mix ends
    when every source is exhausted."""

    def __init__(
        self,
        sources: Sequence[DataPipeline],
        weights: Sequence[float] | None = None,
        seed: int = 0,
    ):
        sources = list(sources)
        if not sources:
            raise ValueError("mix needs at least one source")
        if weights is None:
            weights = [1.0] * len(sources)
        weights = [float(w) for w in weights]
        if len(weights) != len(sources):
            raise ValueError(
                f"mix got {len(sources)} source(s) but {len(weights)} weight(s)"
            )
        if any(not np.isfinite(w) or w <= 0 for w in weights):
            raise ValueError(f"mix weights must be positive and finite, got {weights}")
        self._sources = sources
        self._weights = weights
        self._seed = int(seed)
        #: draws made by the CURRENT pass / carried in from a resume
        self._draws = 0
        self._draws_base = 0
        #: elements the pass resumed past (load_state_dict arms it)
        self._consumed_base = 0
        self._exhausted = [False] * len(sources)
        #: one-shot resume payload applied by the next __iter__
        self._mix_resume: dict | None = None

        def length() -> int:
            return sum(len(s) for s in self._sources)

        super().__init__(self._mix_iter, length)

    # every shuffling stage of every child re-seeds together
    def set_epoch(self, epoch: int) -> None:
        super().set_epoch(epoch)
        for s in self._sources:
            if hasattr(s, "set_epoch"):
                s.set_epoch(epoch)

    def _mix_iter(self, epoch) -> Iterator:
        # epoch folds into the seed (the shuffle() convention): each epoch
        # draws a fresh deterministic choice sequence, and a mid-epoch
        # resume re-derives the same one (state_dict carries the epoch)
        seed = self._seed + (0 if epoch is None else int(epoch))
        resume = self._mix_resume
        self._mix_resume = None
        if resume is None:
            self._draws_base = 0
            self._consumed_base = 0
            alive = [True] * len(self._sources)
        else:
            self._draws_base = resume["draws"]
            self._consumed_base = resume["consumed"]
            alive = [not x for x in resume["exhausted"]]
        self._draws = 0
        self._exhausted = [not a for a in alive]
        its = [iter(s) for s in self._sources]
        while True:
            live = [w for w, a in zip(self._weights, alive) if a]
            if not live:
                return
            i = _mix_choice(seed, self._draws_base + self._draws, self._weights, alive)
            self._draws += 1
            try:
                yield next(its[i])
            except StopIteration:
                alive[i] = False
                self._exhausted[i] = True
                if any(alive):
                    import logging

                    remaining = [w for w, a in zip(self._weights, alive) if a]
                    logging.getLogger("dmlcloud_tpu").warning(
                        "mix: source %d exhausted after %d draw(s); renormalizing "
                        "over the %d remaining source(s) (weights %s)",
                        i, self._draws_base + self._draws, len(remaining), remaining,
                    )
                continue

    # -- resumable iteration state (doc/data.md, doc/elasticity.md) ---------
    def state_dict(self) -> dict:
        """The mix cursor — global element offset AND global draw count
        (draws outnumber yields when a draw hit an exhausted source) — plus
        every child's own iterator state. All counters are global
        (``local x world_size``), so a resume on a different world size
        re-derives its per-rank position exactly like the base class."""
        ws = runtime.world_size()
        return {
            "v": 1,
            "kind": "mix",
            "epoch": self.epoch,
            "global_offset": (self._consumed_base + self._consumed) * ws,
            "global_draws": (self._draws_base + self._draws) * ws,
            "world_size": ws,
            "exhausted": list(self._exhausted),
            "children": [s.state_dict() for s in self._sources],
        }

    def load_state_dict(self, state: dict) -> None:
        """Restore a mix ``state_dict``: children fast-forward through their
        OWN ``load_state_dict`` (no replay through the mix), and the next
        pass re-enters the draw sequence at the saved step. A plain
        (non-mix) v1 state degrades to the base class's replay skip — the
        draws are pure in ``(seed, step)``, so replay reproduces the exact
        same choices."""
        if not (isinstance(state, dict) and state.get("kind") == "mix"):
            super().load_state_dict(state)
            return
        if state.get("v") != 1:
            raise ValueError(f"unrecognised MixPipeline state: {state!r}")
        children = state.get("children") or []
        if len(children) != len(self._sources):
            raise ValueError(
                f"mix state carries {len(children)} child state(s) for "
                f"{len(self._sources)} source(s)"
            )
        for s, cs in zip(self._sources, children):
            s.load_state_dict(cs)
        if state.get("epoch") is not None:
            self.set_epoch(int(state["epoch"]))
        ws = runtime.world_size()
        consumed, rem_c = divmod(int(state["global_offset"]), ws)
        draws, rem_d = divmod(int(state["global_draws"]), ws)
        if rem_c or rem_d:
            import logging

            logging.getLogger("dmlcloud_tpu").warning(
                "mix resume: global cursor (%d elements, %d draws) is not divisible "
                "by the new world size %d; rounding down",
                state["global_offset"], state["global_draws"], ws,
            )
        self._pending_skip = 0  # children fast-forward themselves
        self._mix_resume = {
            "consumed": consumed,
            "draws": draws,
            "exhausted": [bool(x) for x in state.get("exhausted", [])]
            or [False] * len(self._sources),
        }

def _iter_chunks(
    ds, dim, chunk_size, chunk_overlap, even_shards, equal_chunks, shuffle, seed, rank, world_size, load, load_kwargs
) -> Iterator[Any]:
    num_elements = len(ds[dim]) if hasattr(ds, "__getitem__") and not isinstance(ds, np.ndarray) else ds.sizes[dim]
    chunks = chunk_and_shard_indices(
        num_elements, chunk_size, rank, world_size,
        chunk_overlap=chunk_overlap, even_shards=even_shards, equal_chunks=equal_chunks,
        shuffle=shuffle, seed=seed,
    )
    for start, end in chunks:
        chunk = ds.isel({dim: slice(start, end)})
        if load:
            chunk.load(**(load_kwargs or {}))
        yield chunk


def _prefetch_iter(src: Iterator, num_elements: int, name: str = "dml-host-prefetch") -> Iterator:
    """Bounded-queue background reader. Exceptions in the source re-raise in
    the consumer; closing/abandoning the consumer generator signals the
    producer to stop (otherwise it would block forever on a full queue,
    pinning the thread, its queued batches, and the source iterator).
    ``name`` labels the producer thread (``ShardReader`` reuses this
    machinery under ``dml-shard-reader``)."""
    q: _queue.Queue = _queue.Queue(maxsize=max(num_elements, 1))
    stop = threading.Event()
    _END, _ERR = object(), object()

    def put(item: Any) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except _queue.Full:
                continue
        return False

    def produce() -> None:
        try:
            for item in src:
                if not put(item):
                    return
        except BaseException as e:  # noqa: BLE001 - re-raised consumer-side
            put((_ERR, e))
            return
        put(_END)

    # named so shutdown tests (and a forensics dump's thread list) can
    # identify host-prefetch threads; daemon so a full queue can never pin
    # process exit even if the consumer leaks the generator
    thread = threading.Thread(target=produce, daemon=True, name=name)
    thread.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, tuple) and len(item) == 2 and item[0] is _ERR:
                raise item[1]
            yield item
    finally:
        stop.set()
        try:  # free one slot so a put-blocked producer observes stop promptly
            q.get_nowait()
        except _queue.Empty:
            pass


# ---------------------------------------------------------------------------
# batch interleaving (pytree-generic, native-accelerated)
# ---------------------------------------------------------------------------

def _interleave_pytrees(iterable: Iterable[Any], num_batches: int, copy: bool = False) -> Iterator[Any]:
    """Re-slice each window of ``num_batches`` consecutive batches into
    ``num_batches`` mixed batches, per pytree leaf, through preallocated
    buffers. Mixed batch ``i`` is the concatenation of slice ``i`` of every
    window batch — restores within-batch diversity when upstream chunked
    reads (e.g. xarray time chunks) make batches internally correlated.

    Leaves are interleaved by the C++ kernel (native/interleave.cpp) when
    contiguous, else by strided numpy copies. Yielded leaves ALIAS the reused
    buffers: consume or copy before advancing.
    """
    import jax

    if num_batches < 1:
        raise ValueError("num_batches must be greater than 0")
    if num_batches == 1:
        yield from iterable
        return

    try:
        from ..native import interleave as _native

        native_ok = _native.available()
    except Exception:  # pragma: no cover
        _native, native_ok = None, False

    treedef = None
    buffers: list[np.ndarray] = []
    slice_sizes: list[int] = []
    window: list[list[np.ndarray]] = []

    for batch in iterable:
        leaves, this_def = jax.tree_util.tree_flatten(batch)
        leaves = [np.asarray(x) for x in leaves]
        if treedef is None:
            treedef = this_def
            for leaf in leaves:
                if leaf.shape[0] % num_batches:
                    raise ValueError(
                        f"Batch dimension ({leaf.shape[0]}) must be divisible by num_batches={num_batches}"
                    )
                slice_sizes.append(leaf.shape[0] // num_batches)
                buffers.append(np.empty((num_batches, *leaf.shape), dtype=leaf.dtype))

        window.append(leaves)
        if len(window) < num_batches:
            continue

        for li, (buf, s) in enumerate(zip(buffers, slice_sizes)):
            srcs = [w[li] for w in window]
            if native_ok and all(b.flags.c_contiguous for b in srcs):
                _native.interleave_into(buf, srcs, s)
            else:
                for i in range(num_batches):
                    for j in range(num_batches):
                        buf[i, j * s : (j + 1) * s] = srcs[j][i * s : (i + 1) * s]
        window = []
        for i in range(num_batches):
            leaves_out = [buf[i].copy() if copy else buf[i] for buf in buffers]
            yield jax.tree_util.tree_unflatten(treedef, leaves_out)


def interleave_batches(iterable: Iterable[np.ndarray], num_batches: int) -> Iterator[np.ndarray]:
    """Array variant (capability of reference data.py:266-301). Yielded views
    alias a reused buffer — consume or copy immediately."""
    return _interleave_pytrees(iterable, num_batches)


def interleave_dict_batches(
    iterable: Iterable[dict[str, np.ndarray]], num_batches: int
) -> Iterator[dict[str, np.ndarray]]:
    """Dict-of-arrays variant (capability of reference data.py:304-341) —
    same pytree core, same C++ fast path. Yielded dicts alias reused buffers."""
    return _interleave_pytrees(iterable, num_batches)


# ---------------------------------------------------------------------------
# reference-parity shims (class API of dmlcloud.util.data)
# ---------------------------------------------------------------------------

class _ReconstructOnUnpickle:
    """The pipeline core holds closures, which do not pickle; the shims must
    pickle because torch DataLoader workers receive the dataset by pickle.
    Each shim records its constructor arguments and is rebuilt (epoch
    preserved) on the other side."""

    _ctor_args: tuple = ()
    _ctor_kwargs: dict = {}

    def __getstate__(self):
        return {"args": self._ctor_args, "kwargs": self._ctor_kwargs, "epoch": self.epoch}

    def __setstate__(self, state):
        self.__init__(*state["args"], **state["kwargs"])
        self.epoch = state["epoch"]

def sharded_xr_dataset(
    ds: Any,
    dim: str,
    chunk_size: int,
    chunk_overlap: int = 0,
    even_shards: bool = True,
    equal_chunks: bool = True,
    shuffle: bool = False,
    seed: int = 0,
    rank: int | None = None,
    world_size: int | None = None,
    load: bool = False,
    load_kwargs: dict | None = None,
) -> Iterator[Any]:
    """One epoch of per-rank chunks of an ``.isel``-capable dataset
    (reference data.py:70-107)."""
    rank = runtime.rank() if rank is None else rank
    world_size = runtime.world_size() if world_size is None else world_size
    return _iter_chunks(
        ds, dim, chunk_size, chunk_overlap, even_shards, equal_chunks,
        shuffle, seed, rank, world_size, load, load_kwargs,
    )


class ShardedSequenceDataset(_ReconstructOnUnpickle, DataPipeline):
    """Reference-parity shim over ``DataPipeline.from_sequence``
    (reference data.py:110-147)."""

    def __init__(
        self,
        sequence: Sequence,
        shuffle: bool = False,
        even_shards: bool = True,
        seed: int = 0,
        rank: int | None = None,
        world_size: int | None = None,
    ):
        rank = runtime.rank() if rank is None else rank
        world_size = runtime.world_size() if world_size is None else world_size
        self._ctor_args = (sequence, shuffle, even_shards, seed, rank, world_size)
        self._ctor_kwargs = {}
        p = DataPipeline.from_sequence(
            sequence, shuffle=shuffle, even_shards=even_shards, seed=seed, rank=rank, world_size=world_size
        )
        super().__init__(p._make_iter, p._length_fn)
        self.sequence = sequence


class ShardedXrDataset(_ReconstructOnUnpickle, DataPipeline):
    """Reference-parity shim over ``DataPipeline.from_chunked``; the full
    positional parameter order matches reference data.py:150-207 including
    the ``process_group`` slot (meaningless here — JAX has one global
    runtime — but kept so positional callers' ``load``/``load_kwargs``
    don't silently shift)."""

    def __init__(
        self,
        ds: Any,
        dim: str,
        chunk_size: int,
        chunk_overlap: int = 0,
        even_shards: bool = True,
        equal_chunks: bool = True,
        shuffle: bool = False,
        seed: int = 0,
        rank: int | None = None,
        world_size: int | None = None,
        process_group: Any = None,
        load: bool = False,
        load_kwargs: dict | None = None,
    ):
        if process_group is not None:
            raise ValueError(
                "process_group is a torch.distributed concept; the JAX runtime has a "
                "single global process group — pass rank/world_size instead"
            )
        rank = runtime.rank() if rank is None else rank
        world_size = runtime.world_size() if world_size is None else world_size
        self._ctor_args = (ds, dim, chunk_size, chunk_overlap, even_shards, equal_chunks,
                           shuffle, seed, rank, world_size, None, load, load_kwargs)
        self._ctor_kwargs = {}
        p = DataPipeline.from_chunked(
            ds, dim, chunk_size, chunk_overlap=chunk_overlap, even_shards=even_shards,
            equal_chunks=equal_chunks, shuffle=shuffle, seed=seed, rank=rank,
            world_size=world_size, load=load, load_kwargs=load_kwargs,
        )
        super().__init__(p._make_iter, p._length_fn)
        self.ds = ds


class DownstreamDataset(_ReconstructOnUnpickle, DataPipeline):
    """Reference-parity base for wrappers (reference data.py:210-219):
    epoch setting propagates to the wrapped source."""

    def __init__(self, source_ds: Iterable):
        self._ctor_args = (source_ds,)
        self._ctor_kwargs = {}
        p = DataPipeline.from_source(source_ds)
        super().__init__(p._make_iter, p._length_fn)
        self.source_ds = source_ds

    def set_epoch(self, epoch: int) -> None:
        super().set_epoch(epoch)
        if hasattr(self.source_ds, "set_epoch"):
            self.source_ds.set_epoch(epoch)


class PrefetchDataset(DownstreamDataset):
    """Reference-parity shim over ``.prefetch()`` (reference data.py:222-240)."""

    def __init__(self, source_ds: Iterable, num_elements: int):
        super().__init__(source_ds)
        self._ctor_args = (source_ds, num_elements)
        self.num_elements = num_elements
        parent = self._make_iter
        self._make_iter = lambda epoch: _prefetch_iter(parent(epoch), num_elements)


class BatchDataset(DownstreamDataset):
    """Reference-parity shim over ``.batch()`` (reference data.py:243-263)."""

    def __init__(self, source_ds: Iterable, batch_size: int, drop_remainder: bool = False):
        super().__init__(source_ds)
        self._ctor_args = (source_ds, batch_size, drop_remainder)
        self.batch_size = batch_size
        self.drop_remainder = drop_remainder
        batched = DataPipeline(self._make_iter, self._length_fn).batch(batch_size, drop_remainder)
        self._make_iter = batched._make_iter
        self._length_fn = batched._length_fn


def pack_sequences(
    examples: Iterable[Sequence[int] | np.ndarray],
    seq_len: int,
    *,
    split_long: bool = True,
) -> Iterator[dict]:
    """Greedily pack variable-length token sequences into fixed ``seq_len``
    rows, yielding ``{"tokens": [seq_len] int32, "segment_ids": [seq_len]
    int32}`` — the input contract of ``DecoderLM(segment_ids=...)`` /
    ``lm_loss(segment_ids=...)`` (models/transformer.py): segment ids are
    1-based per row, 0 marks padding, attention never crosses a segment
    boundary and positions restart per segment.

    Streaming single-pass fill: an example that fits the remaining row space
    is appended whole; one that fits an EMPTY row starts a fresh row (never
    split — a split would sever intra-example attention and break the
    packed-equals-unpacked equivalence); only examples longer than
    ``seq_len`` itself are split across rows when ``split_long`` (each part
    its own segment — no cross-row attention), else truncated to
    ``seq_len``. The trailing partially-filled row is emitted padded. (The
    reference has no packing; this is TPU-side scope — static shapes
    without burning FLOPs on padding.)
    """
    if seq_len < 1:  # validate eagerly — the generator body runs lazily
        raise ValueError(f"seq_len must be >= 1, got {seq_len}")
    return _pack_sequences_iter(examples, seq_len, split_long)


def _pack_sequences_iter(examples, seq_len, split_long):
    tokens = np.zeros(seq_len, np.int32)
    segs = np.zeros(seq_len, np.int32)
    fill, seg = 0, 0

    def flush():
        nonlocal tokens, segs, fill, seg
        out = {"tokens": tokens, "segment_ids": segs}
        tokens, segs = np.zeros(seq_len, np.int32), np.zeros(seq_len, np.int32)
        fill, seg = 0, 0
        return out

    def place(part):
        nonlocal fill, seg
        seg += 1
        tokens[fill : fill + part.size] = part
        segs[fill : fill + part.size] = seg
        fill += part.size

    for ex in examples:
        ex = np.asarray(ex, np.int32).ravel()
        if ex.size == 0:
            continue
        if ex.size <= seq_len:
            if ex.size > seq_len - fill:
                yield flush()
            place(ex)
            if fill == seq_len:
                yield flush()
        elif split_long:
            offset = 0
            while offset < ex.size:
                if fill == seq_len:
                    yield flush()
                take = min(ex.size - offset, seq_len - fill)
                place(ex[offset : offset + take])
                offset += take
        else:
            if fill:
                yield flush()
            place(ex[:seq_len])
            yield flush()
    if fill:
        yield flush()
