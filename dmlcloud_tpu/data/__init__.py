from .datasets import (
    BatchDataset,
    DataPipeline,
    DownstreamDataset,
    MixPipeline,
    PackStats,
    PrefetchDataset,
    ShardedSequenceDataset,
    ShardedXrDataset,
    interleave_batches,
    interleave_dict_batches,
    pack_sequences,
    sharded_xr_dataset,
)
from .device import device_iterator
from .sharding import chunk_and_shard_indices, shard_indices, shard_sequence
from .store import (
    CorpusBuilder,
    ShardCorruptError,
    ShardFile,
    ShardReader,
    ShardStore,
    build_corpus,
    write_shard,
)
from .synthetic import markov_tokens

__all__ = [
    "BatchDataset",
    "DataPipeline",
    "DownstreamDataset",
    "MixPipeline",
    "PackStats",
    "PrefetchDataset",
    "ShardedSequenceDataset",
    "ShardedXrDataset",
    "interleave_batches",
    "interleave_dict_batches",
    "pack_sequences",
    "sharded_xr_dataset",
    "device_iterator",
    "markov_tokens",
    "chunk_and_shard_indices",
    "shard_indices",
    "shard_sequence",
    "CorpusBuilder",
    "ShardCorruptError",
    "ShardFile",
    "ShardReader",
    "ShardStore",
    "build_corpus",
    "write_shard",
]
