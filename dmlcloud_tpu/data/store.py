"""Disk-native data plane: mmap'd shard files and async shard readers.

The on-disk unit is a ``.dmlshard`` file — a schema-versioned, checksummed
container for variable-length int32 token records, laid out so a reader
never deserialises anything:

=========  =======================  ==============================================
offset     bytes                    contents
=========  =======================  ==============================================
0          8                        magic ``b"DMLSHRD1"``
8          4                        format version (u32 little-endian, currently 1)
12         4                        dtype code (u32; 1 = int32 tokens)
16         8                        record count ``n`` (u64)
24         8                        payload token count ``t`` (u64)
32         4                        CRC32 of the offset index (u32)
36         4                        CRC32 of the token payload (u32)
40         24                       reserved (zero)
64         8 * (n + 1)              offset index: u64 TOKEN offsets, ``off[0] = 0``,
                                    ``off[n] = t`` — record ``i`` spans
                                    ``payload[off[i] : off[i+1]]``
64+8(n+1)  4 * t                    payload: int32 tokens, records back to back
=========  =======================  ==============================================

Every region is naturally aligned (the index starts at 64, the payload at
``64 + 8(n+1)`` — both multiples of 8), so :class:`ShardFile` maps the file
once with ``np.memmap`` and serves each record as a zero-copy int32 view:
``record(i)`` is two u64 loads and a slice, no read syscall, no copy. The
OS page cache is the only buffer layer; checksums are verified on demand
(:meth:`ShardFile.verify` / ``diag --corpus``), not on open, so opening a
corpus is O(header reads) no matter its size.

:class:`ShardStore` is an ordered corpus of shards (sorted filename order
defines the global record order); :class:`ShardReader` is the pipeline
source: a double-buffered background-thread reader (the PR-1
``host_prefetch`` machinery, dedicated ``dml-shard-reader`` thread) with
world-size-aware record assignment and the PR-7/9 elastic cursor — see
doc/data.md ("On-disk shard format") and doc/elasticity.md.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from typing import Iterable, Iterator, Sequence

import numpy as np

from ..parallel import runtime
from .datasets import DataPipeline, _prefetch_iter

MAGIC = b"DMLSHRD1"
FORMAT_VERSION = 1
_DTYPE_INT32 = 1
HEADER_SIZE = 64
_HEADER_STRUCT = struct.Struct("<8sIIQQII")  # magic, version, dtype, n, t, crc_idx, crc_pay
SHARD_SUFFIX = ".dmlshard"
MANIFEST_NAME = "corpus.json"

__all__ = [
    "FORMAT_VERSION",
    "CorpusBuilder",
    "ShardCorruptError",
    "ShardFile",
    "ShardReader",
    "ShardStore",
    "build_corpus",
    "reader_activity",
    "write_shard",
]


class ShardCorruptError(ValueError):
    """A shard failed structural validation (bad magic/version, truncation)
    or checksum verification. The message always names the offending file —
    the one actionable fact when a corpus of hundreds of shards has one bad
    byte."""

    def __init__(self, path: str | os.PathLike, reason: str):
        self.path = str(path)
        self.reason = reason
        super().__init__(f"corrupt shard {self.path}: {reason}")


# ---------------------------------------------------------------------------
# writing
# ---------------------------------------------------------------------------

def write_shard(path: str | os.PathLike, docs: Iterable[Sequence[int] | np.ndarray]) -> dict:
    """Write one ``.dmlshard`` from an iterable of token sequences.

    Records are stored in iteration order as int32. The write goes through
    a same-directory temp file and ``os.replace`` so a crashed builder never
    leaves a half-written shard behind a valid name. Returns a summary dict
    (``{"file", "records", "tokens"}``) for manifests."""
    path = os.fspath(path)
    arrays = [np.ascontiguousarray(np.asarray(d, np.int32).ravel()) for d in docs]
    offsets = np.zeros(len(arrays) + 1, np.uint64)
    np.cumsum([a.size for a in arrays], out=offsets[1:])
    payload = np.concatenate(arrays) if arrays else np.zeros(0, np.int32)
    index_bytes = offsets.tobytes()
    payload_bytes = payload.tobytes()
    header = _HEADER_STRUCT.pack(
        MAGIC, FORMAT_VERSION, _DTYPE_INT32,
        len(arrays), int(payload.size),
        zlib.crc32(index_bytes), zlib.crc32(payload_bytes),
    )
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(header.ljust(HEADER_SIZE, b"\0"))
        f.write(index_bytes)
        f.write(payload_bytes)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return {"file": os.path.basename(path), "records": len(arrays), "tokens": int(payload.size)}


class CorpusBuilder:
    """Incrementally build a sharded corpus directory.

    ``add()`` buffers documents and rolls a new shard whenever the buffered
    payload reaches ``shard_tokens``; ``finalize()`` flushes the tail and
    writes the ``corpus.json`` manifest. Shard files are named
    ``{prefix}-{index:05d}.dmlshard`` so lexicographic order IS write order
    — the global record order every reader agrees on."""

    def __init__(self, directory: str | os.PathLike, shard_tokens: int = 1 << 22, prefix: str = "corpus"):
        if shard_tokens < 1:
            raise ValueError(f"shard_tokens must be >= 1, got {shard_tokens}")
        self.directory = os.fspath(directory)
        self.shard_tokens = int(shard_tokens)
        self.prefix = prefix
        os.makedirs(self.directory, exist_ok=True)
        self._buf: list[np.ndarray] = []
        self._buf_tokens = 0
        self._shards: list[dict] = []
        self._total_records = 0
        self._total_tokens = 0
        self._finalized = False

    def add(self, doc: Sequence[int] | np.ndarray) -> None:
        if self._finalized:
            raise RuntimeError("CorpusBuilder already finalized")
        a = np.asarray(doc, np.int32).ravel()
        self._buf.append(a)
        self._buf_tokens += int(a.size)
        if self._buf_tokens >= self.shard_tokens:
            self._roll()

    def _roll(self) -> None:
        name = f"{self.prefix}-{len(self._shards):05d}{SHARD_SUFFIX}"
        info = write_shard(os.path.join(self.directory, name), self._buf)
        self._shards.append(info)
        self._total_records += info["records"]
        self._total_tokens += info["tokens"]
        self._buf, self._buf_tokens = [], 0

    def finalize(self) -> dict:
        """Flush the buffered tail shard and write the manifest; returns the
        manifest dict."""
        if self._finalized:
            raise RuntimeError("CorpusBuilder already finalized")
        if self._buf:
            self._roll()
        self._finalized = True
        manifest = {
            "format": "dmlshard",
            "version": FORMAT_VERSION,
            "shards": self._shards,
            "total_records": self._total_records,
            "total_tokens": self._total_tokens,
        }
        tmp = os.path.join(self.directory, f"{MANIFEST_NAME}.tmp.{os.getpid()}")
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=2)
            f.write("\n")
        os.replace(tmp, os.path.join(self.directory, MANIFEST_NAME))
        return manifest


def build_corpus(
    directory: str | os.PathLike,
    docs: Iterable[Sequence[int] | np.ndarray],
    shard_tokens: int = 1 << 22,
    prefix: str = "corpus",
) -> dict:
    """One-shot :class:`CorpusBuilder`: write every document of ``docs`` and
    return the manifest."""
    builder = CorpusBuilder(directory, shard_tokens=shard_tokens, prefix=prefix)
    for doc in docs:
        builder.add(doc)
    return builder.finalize()


# ---------------------------------------------------------------------------
# reading
# ---------------------------------------------------------------------------

class ShardFile:
    """One memory-mapped ``.dmlshard``.

    Opening validates structure only (magic, version, dtype, exact file
    size) — O(1) regardless of shard size. ``record(i)`` returns a
    read-only int32 view over the mapping: zero copies, zero syscalls; the
    page cache faults pages in on first touch (the :class:`ShardReader`
    producer thread does that touching off the training thread).
    :meth:`verify` streams both CRC32s for corruption that structural
    checks can't see."""

    def __init__(self, path: str | os.PathLike):
        self.path = os.fspath(path)
        try:
            size = os.path.getsize(self.path)
        except OSError as e:
            raise ShardCorruptError(self.path, f"unreadable ({e})") from e
        if size < HEADER_SIZE:
            raise ShardCorruptError(self.path, f"file is {size} bytes, smaller than the {HEADER_SIZE}-byte header")
        with open(self.path, "rb") as f:
            raw = f.read(_HEADER_STRUCT.size)
        magic, version, dtype_code, n, t, crc_idx, crc_pay = _HEADER_STRUCT.unpack(raw)
        if magic != MAGIC:
            raise ShardCorruptError(self.path, f"bad magic {magic!r} (expected {MAGIC!r})")
        if version != FORMAT_VERSION:
            raise ShardCorruptError(self.path, f"unsupported format version {version} (reader supports {FORMAT_VERSION})")
        if dtype_code != _DTYPE_INT32:
            raise ShardCorruptError(self.path, f"unsupported dtype code {dtype_code}")
        expected = HEADER_SIZE + 8 * (n + 1) + 4 * t
        if size != expected:
            raise ShardCorruptError(
                self.path,
                f"truncated or oversized: {size} bytes on disk, header promises {expected} "
                f"({n} record(s), {t} token(s))",
            )
        self.version = int(version)
        self.num_records = int(n)
        self.num_tokens = int(t)
        self._crc_index = crc_idx
        self._crc_payload = crc_pay
        raw_map = np.memmap(self.path, dtype=np.uint8, mode="r")
        idx_end = HEADER_SIZE + 8 * (n + 1)
        self._offsets = raw_map[HEADER_SIZE:idx_end].view(np.uint64)
        self._payload = raw_map[idx_end:].view(np.int32)

    def record(self, i: int) -> np.ndarray:
        """Zero-copy int32 view of record ``i`` (read-only: it aliases the
        mapping)."""
        if not 0 <= i < self.num_records:
            raise IndexError(f"record {i} out of range for shard with {self.num_records} record(s)")
        return self._payload[int(self._offsets[i]) : int(self._offsets[i + 1])]

    def __len__(self) -> int:
        return self.num_records

    def verify(self) -> None:
        """Recompute both CRC32s over the mapping; raises
        :class:`ShardCorruptError` naming this file on mismatch."""
        if zlib.crc32(self._offsets.tobytes()) != self._crc_index:
            raise ShardCorruptError(self.path, "offset-index checksum mismatch")
        if zlib.crc32(self._payload.tobytes()) != self._crc_payload:
            raise ShardCorruptError(self.path, "payload checksum mismatch")

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"ShardFile({self.path!r}, records={self.num_records}, tokens={self.num_tokens})"


class ShardStore:
    """An ordered corpus of ``.dmlshard`` files in one directory.

    Shards sort by filename — the builder's zero-padded numbering makes
    lexicographic order equal write order — and their concatenation defines
    the **global record order**: record ``g`` of the corpus is record
    ``g - base(s)`` of the shard ``s`` that :meth:`locate` maps it to.
    Every elastic-cursor contract in :class:`ShardReader` is stated in this
    order."""

    def __init__(self, directory: str | os.PathLike, *, verify: bool = False):
        self.directory = os.fspath(directory)
        if not os.path.isdir(self.directory):
            raise FileNotFoundError(f"corpus directory not found: {self.directory}")
        names = sorted(n for n in os.listdir(self.directory) if n.endswith(SHARD_SUFFIX))
        if not names:
            raise FileNotFoundError(f"no *{SHARD_SUFFIX} files in {self.directory}")
        self.shards = [ShardFile(os.path.join(self.directory, n)) for n in names]
        if verify:
            self.verify()
        #: global record index where each shard starts, plus the total
        self._starts = np.zeros(len(self.shards) + 1, np.int64)
        np.cumsum([s.num_records for s in self.shards], out=self._starts[1:])

    @property
    def version(self) -> int:
        return self.shards[0].version

    @property
    def total_records(self) -> int:
        return int(self._starts[-1])

    @property
    def total_tokens(self) -> int:
        return sum(s.num_tokens for s in self.shards)

    def locate(self, g: int) -> tuple[int, int]:
        """Map global record index ``g`` to ``(shard_id, record_offset)``.
        ``g == total_records`` maps to ``(num_shards, 0)`` — the
        one-past-the-end cursor a fully-consumed reader checkpoints."""
        if not 0 <= g <= self.total_records:
            raise IndexError(f"global record {g} out of range for {self.total_records} record(s)")
        if g == self.total_records:
            return len(self.shards), 0
        sid = int(np.searchsorted(self._starts, g, side="right")) - 1
        return sid, int(g - self._starts[sid])

    def record(self, g: int) -> np.ndarray:
        sid, off = self.locate(g)
        if sid == len(self.shards):
            raise IndexError(f"global record {g} out of range for {self.total_records} record(s)")
        return self.shards[sid].record(off)

    def verify(self) -> None:
        """Checksum every shard (raises on the first corrupt file)."""
        for s in self.shards:
            s.verify()

    def info(self) -> dict:
        """Summary block for ``python -m dmlcloud_tpu diag --corpus``."""
        return {
            "directory": self.directory,
            "format_version": self.version,
            "shards": len(self.shards),
            "total_records": self.total_records,
            "total_tokens": self.total_tokens,
        }

    def __len__(self) -> int:
        return self.total_records

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"ShardStore({self.directory!r}, shards={len(self.shards)}, records={self.total_records})"


# ---------------------------------------------------------------------------
# async pipeline source
# ---------------------------------------------------------------------------

#: monotone count of read-ahead blocks the producer threads have fetched —
#: the stage telemetry samples it per epoch to tell the goodput advisor a
#: ShardReader (not a generic iterable) is feeding the run (telemetry/goodput.py)
_ACTIVITY = 0
_ACTIVITY_LOCK = threading.Lock()


def _bump_activity() -> None:
    global _ACTIVITY
    with _ACTIVITY_LOCK:
        _ACTIVITY += 1


def reader_activity() -> int:
    """Total read-ahead blocks fetched by all :class:`ShardReader` threads
    since import (monotone; compare two samples to detect activity)."""
    return _ACTIVITY


class ShardReader(DataPipeline):
    """Async double-buffered pipeline source over a :class:`ShardStore`.

    **Assignment.** Rank ``r`` of world ``w`` owns global records
    ``g ≡ r (mod w)`` in shard order — record-strided, so every rank
    consumes in lockstep and the globally-consumed prefix after each rank
    reads ``c`` records is exactly records ``[0, c*w)``. That makes the
    PR-7 convention (``global_offset = consumed * world_size``) hold
    literally, and a resume on a DIFFERENT world size is pure arithmetic:
    ``divmod(global_offset, new_w)`` — indivisible offsets warn and round
    down exactly like ``MixPipeline``.

    **Read-ahead.** Records are fetched in blocks of ``read_ahead`` on a
    dedicated ``dml-shard-reader`` daemon thread (the PR-1 host-prefetch
    machinery) with ``buffers`` blocks in flight — double-buffered by
    default. The producer touches one int32 per page of every view it
    fetches, so cold-disk page faults land on the reader thread, not the
    training thread; the consumer then hands out the zero-copy views.

    **Cursor.** ``state_dict()`` extends the PR-7 payload with
    ``kind="shards"`` plus the human-auditable ``shard_id`` /
    ``record_offset`` of the first unconsumed global record;
    ``load_state_dict`` restores by SEEKING (two u64 loads via the offset
    index) instead of the base class's replay-and-discard skip — resume
    cost is O(1) regardless of how deep into the corpus the run died."""

    def __init__(
        self,
        store: "ShardStore | str | os.PathLike",
        *,
        rank: int | None = None,
        world_size: int | None = None,
        buffers: int = 2,
        read_ahead: int = 64,
    ):
        if buffers < 1:
            raise ValueError(f"buffers must be >= 1, got {buffers}")
        if read_ahead < 1:
            raise ValueError(f"read_ahead must be >= 1, got {read_ahead}")
        self.store = store if isinstance(store, ShardStore) else ShardStore(store)
        self._rank = rank
        self._world_size = world_size
        self.buffers = int(buffers)
        self.read_ahead = int(read_ahead)
        #: records the CURRENT pass resumed past (set by the iterator from
        #: the one-shot resume payload, mirroring MixPipeline's bases)
        self._consumed_base = 0
        self._shard_resume: int | None = None
        super().__init__(self._shard_iter, self._assigned)

    def _rank_world(self) -> tuple[int, int]:
        # resolved at call time, not construction: an elastic resume changes
        # the world size under the same reader object
        r = runtime.rank() if self._rank is None else self._rank
        w = runtime.world_size() if self._world_size is None else self._world_size
        return r, w

    def _assigned(self) -> int:
        """Records assigned to this rank: |{g < N : g mod w == r}|."""
        r, w = self._rank_world()
        n = self.store.total_records
        return max(0, (n - r + w - 1) // w)

    def _shard_iter(self, epoch) -> Iterator[np.ndarray]:
        resume = self._shard_resume
        self._shard_resume = None
        base = 0 if resume is None else int(resume)
        self._consumed_base = base
        r, w = self._rank_world()
        store = self.store
        n = store.total_records

        def blocks() -> Iterator[list[np.ndarray]]:
            g = r + base * w
            while g < n:
                block = []
                for _ in range(self.read_ahead):
                    if g >= n:
                        break
                    block.append(store.record(g))
                    g += w
                # fault every page of the block on THIS (producer) thread —
                # one int32 per 4 KiB page — so disk latency never reaches
                # the consumer
                for v in block:
                    if v.size:
                        int(v[::1024].sum())
                _bump_activity()
                yield block

        for block in _prefetch_iter(blocks(), self.buffers, name="dml-shard-reader"):
            yield from block

    # -- resumable iteration state (doc/data.md, doc/elasticity.md) ---------
    def state_dict(self) -> dict:
        """The PR-7 cursor plus the disk location it denotes: global record
        offset (world-size-independent), and the ``(shard_id,
        record_offset)`` of the first unconsumed record —
        ``(num_shards, 0)`` once the corpus is fully consumed."""
        ws = self._rank_world()[1]
        consumed = self._consumed_base + self._consumed
        g = min(consumed * ws, self.store.total_records)
        sid, off = self.store.locate(g)
        return {
            "v": 1,
            "kind": "shards",
            "epoch": self.epoch,
            "global_offset": consumed * ws,
            "world_size": ws,
            "shard_id": sid,
            "record_offset": off,
        }

    def load_state_dict(self, state: dict) -> None:
        """Restore a shard cursor by seeking (no replay). A plain (non-
        shard) v1 state degrades to the base class's replay skip. An offset
        not divisible by the new world size warns and rounds down, exactly
        the MixPipeline contract."""
        if not (isinstance(state, dict) and state.get("kind") == "shards"):
            super().load_state_dict(state)
            return
        if state.get("v") != 1:
            raise ValueError(f"unrecognised ShardReader state: {state!r}")
        if state.get("epoch") is not None:
            self.set_epoch(int(state["epoch"]))
        ws = self._rank_world()[1]
        skip, rem = divmod(int(state["global_offset"]), ws)
        if rem:
            import logging

            logging.getLogger("dmlcloud_tpu").warning(
                "ShardReader resume: global offset %d is not divisible by the new "
                "world size %d; rounding down (up to %d record(s) replay)",
                state["global_offset"], ws, ws - 1,
            )
        self._pending_skip = 0  # the iterator seeks; nothing to replay
        self._shard_resume = skip
