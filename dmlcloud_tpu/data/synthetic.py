"""Synthetic corpora with learnable structure, for examples and benches.

The reference ships no data generators (its examples download MNIST —
/root/reference/examples/mnist.py); this module exists because several
in-repo surfaces (examples/train_lm.py, examples/pod_llama_fsdp.py,
bench.py's speculative bench) need a corpus a small model can actually
LEARN — so losses drop, accept rates mean something, and smoke runs
demonstrate optimisation rather than noise — without any network access.
"""

from __future__ import annotations

import numpy as np

__all__ = ["markov_tokens"]


def markov_tokens(
    vocab: int, n: int, s: int, seed: int = 0, noise: float = 0.1,
    table_seed: int | None = None,
) -> np.ndarray:
    """``[n, s]`` int32 token chains: each token follows a fixed random
    successor table with probability ``1 - noise``, else is uniform random.

    At the default ``noise=0.1`` the per-token entropy floor is
    ``0.9*ln(1/0.9) + 0.1*ln(vocab)`` ≈ 0.9 nats at vocab 512 — a trained
    model's loss near that value means the chain was learned, which is the
    learnedness gate bench.py's speculative bench prints.

    ``table_seed`` decouples the successor TABLE from the sequences: ranks
    of one training job (or a train corpus and its eval prompts) must share
    the table — otherwise the union of their data is a mixture of
    incompatible chains with ~ln(n_tables) extra entropy — while drawing
    distinct sequences via per-rank ``seed``. Default (None) derives the
    table from ``seed``, which is only right single-host."""
    table_rng = np.random.RandomState(seed if table_seed is None else table_seed)
    next_tok = table_rng.randint(0, vocab, size=vocab)
    rng = table_rng if table_seed is None else np.random.RandomState(seed)
    toks = np.empty((n, s), np.int32)
    toks[:, 0] = rng.randint(0, vocab, size=n)
    noisy = rng.rand(n, s) < noise
    for t in range(1, s):
        toks[:, t] = np.where(noisy[:, t], rng.randint(0, vocab, size=n), next_tok[toks[:, t - 1]])
    return toks
