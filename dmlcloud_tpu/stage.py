"""Stage: one phase of an experiment; TrainValStage: the opinionated train loop.

Capability parity with /root/reference/dmlcloud/stage.py — the same hook set
(``pre_stage/post_stage/pre_epoch/post_epoch`` :81-105), epoch loop (:132-143),
metric prefix proxying (:59-76), early stop (:78-79), progress table
(:147,188-205), auto-metrics (:305-314), and barrier placement (:156,161) —
with the hot loop re-designed for XLA:

- The reference's per-batch sequence zero_grad -> step -> backward -> clip ->
  optimizer.step (:298-314, with DDP allreduce firing inside backward) becomes
  ONE jitted, donated, sharded function: value_and_grad + global-norm clip +
  optax update. The gradient mean over the ``data``/``fsdp`` axes is inserted
  by XLA as a fused allreduce over ICI — there is no hook machinery.
- State flows through a ``TrainState`` pytree (train_state.py) instead of
  in-place module mutation; the user's ``step(state, batch)`` is a pure
  function traced once.
- Per-step metrics returned by the step stay on device; tracking them never
  forces a host sync (metrics.py) — the dispatch queue stays full.
- Step timing is reported honestly under async dispatch:
  ``misc/step_dispatch_ms`` is host dispatch-to-dispatch time, and
  ``misc/train_step_avg_ms`` is the wall-clock per-step average taken after
  a single ``block_until_ready`` closes the pipeline at epoch end.

The **overlap engine** (doc/performance.md §"Overlap engine") removes the
remaining host-induced stalls, each behind a flag so behavior can be
bisected:

- ``async_checkpoint()`` (default True): Orbax saves commit on a background
  writer; at most one save is in flight (a new save first waits for the
  previous), with hard barriers at stage end, run end, and preemption exit.
- ``prefetch_depth()`` (default 2, the old ``device_prefetch``) +
  ``host_prefetch()``: double-buffered H2D transfer, optionally with host
  batch prep on a background thread (data/device.py).
- ``deferred_metrics()`` (default True): nothing inside the step loop reads
  a device value; host syncs happen only at ``log_every()`` boundaries
  (where the NaN/inf guard piggybacks on a 2-step-trailing loss fetch) and
  at the epoch-end fused exchange. ``deferred_metrics() == False`` restores
  the eager per-step readback for A/B bisection.
- Every host block is accounted: ``misc/host_stall_ms`` is the wall-clock
  the loop spent waiting on the device or on checkpoint commits this epoch.
"""

from __future__ import annotations

import sys
import time
from datetime import datetime
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from .metrics import MetricTracker, Reduction
from .parallel import mesh as mesh_lib
from .parallel import runtime
from .parallel.runtime import is_root
from .telemetry import journal as _journal
from .train_state import TrainState
from .utils.logging import DevNullIO, flush_log_handlers
from .utils.profiling import StallTimer
from .utils.table import ProgressTable

__all__ = ["Stage", "TrainValStage", "DatasetNotFoundError"]


class DatasetNotFoundError(ValueError):
    """A stage asked the pipeline registry for a dataset that was never
    registered. ``val_epoch`` treats exactly this as "validation is optional"
    — a plain ``ValueError`` raised by a user ``val_dataset()`` override is a
    bug and propagates."""


class Stage:
    """One phase of training (pretrain / finetune / eval ...), run sequentially
    by the pipeline. Hook points: ``pre_stage``, ``post_stage``, ``pre_epoch``,
    ``post_epoch``. Parity: reference stage.py:18-220.
    """

    def __init__(self):
        self.pipeline = None  # set by the pipeline
        self.max_epochs = None  # set by the pipeline
        self.name = None  # set by the pipeline

        self.start_time = None
        self.stop_time = None
        self.epoch_start_time = None
        self.epoch_stop_time = None
        self.current_epoch = 1
        self._stop_requested = False
        self._preempt_exit = False

        self.metric_prefix = None
        self.table = None
        self.barrier_timeout = None
        self._stage_span_t0 = 0.0
        self._epoch_span_t0 = 0.0

    # -- conveniences -------------------------------------------------------
    @property
    def tracker(self) -> MetricTracker:
        return self.pipeline.tracker

    @property
    def logger(self):
        return self.pipeline.logger

    @property
    def mesh(self):
        return self.pipeline.mesh

    @property
    def config(self):
        return self.pipeline.config

    # -- metric proxying (reference stage.py:59-76) -------------------------
    def track_reduce(
        self,
        name: str,
        value: Any,
        step: int | None = None,
        reduction: Reduction = Reduction.MEAN,
        dim: list[int] | None = None,
        reduce_globally: bool = True,
        prefixed: bool = True,
    ):
        if prefixed and self.metric_prefix:
            name = f"{self.metric_prefix}/{name}"
        self.pipeline.track_reduce(name, value, step, reduction, dim, reduce_globally)

    def track(self, name: str, value: Any, step: int | None = None, prefixed: bool = True):
        if prefixed and self.metric_prefix:
            name = f"{self.metric_prefix}/{name}"
        self.pipeline.track(name, value, step)

    def stop_stage(self):
        """Request the epoch loop to stop after the current epoch."""
        self._stop_requested = True

    # -- hooks --------------------------------------------------------------
    def pre_stage(self):
        """Executed before the stage starts. Register stage-specific models
        and datasets here."""

    def post_stage(self):
        """Executed after the stage finishes — cleanup, artifact saves."""

    def pre_epoch(self):
        """Executed before each epoch."""

    def post_epoch(self):
        """Executed after each epoch, after metrics have been reduced."""

    def run_epoch(self):
        """Run one epoch. Must be implemented by subclasses."""
        raise NotImplementedError()

    def table_columns(self) -> list[str | dict[str, Any]]:
        """Customise the progress-table columns; same contract as the
        reference (stage.py:113-130): strings, or dicts with 'name' and
        'metric' keys ('metric': None => manually updated)."""
        columns = [
            {"name": "Epoch", "metric": "misc/epoch"},
            {"name": "Time/Epoch", "metric": None},
        ]
        if self.max_epochs is not None:
            columns.append({"name": "ETA", "metric": None})
        return columns

    # -- lifecycle (reference stage.py:132-205) -----------------------------
    def run(self):
        """Run until ``max_epochs`` or ``stop_stage()``. A restored
        ``_stop_requested`` (stage already stopped before the interruption)
        skips the loop entirely."""
        self._pre_stage()
        while not self._stop_requested and (self.max_epochs is None or self.current_epoch <= self.max_epochs):
            self._pre_epoch()
            # the runtime sanitizer's guard window is exactly one epoch:
            # everything inside may not do unaccounted implicit transfers;
            # the epoch-end reduce below (_post_epoch) is outside on purpose
            with self._sanitizer_guard():
                self.run_epoch()
            if getattr(self, "_mid_epoch_exit", False):
                # a step-granular save already persisted the state and a
                # coordinated preemption cut the epoch short: exit WITHOUT
                # _post_epoch — the partial epoch must not reduce metrics
                # or be recorded as complete (resume continues inside it)
                self._preempt_exit = True
                self.logger.info(
                    f"preemption requested; stage {self.name!r} exiting cleanly mid-epoch "
                    f"{self.current_epoch} (state saved at the last step boundary; resumable)"
                )
                break
            # decide BEFORE _post_epoch so its checkpoint save treats this
            # epoch as final even under checkpoint_every() > 1
            self._preempt_exit = self.pipeline._preemption_coordinated()
            self._post_epoch()
            if self._preempt_exit:
                # clean early exit WITHOUT _stop_requested: the epoch's
                # checkpoint is saved and a requeued run resumes here
                self.logger.info(
                    f"preemption requested; stage {self.name!r} exiting cleanly after epoch "
                    f"{self.current_epoch - 1} (resumable)"
                )
                break
        self._post_stage()

    def _sanitizer_guard(self):
        """The pipeline sanitizer's epoch window, or a no-op when off."""
        san = getattr(self.pipeline, "_sanitizer", None)
        if san is not None and san.armed:
            return san.epoch_guard(stage=self.name or type(self).__name__)
        from contextlib import nullcontext

        return nullcontext()

    def _pre_stage(self):
        self.start_time = datetime.now()
        self._stage_span_t0 = _journal.now()
        # NOTE: root-only table — fixes the reference quirk of passing the
        # function `is_root` (always truthy) instead of calling it (stage.py:147).
        self.table = ProgressTable(file=sys.stdout if is_root() else DevNullIO())
        self._setup_table()
        if len(self.pipeline.stages) > 1:
            self.logger.info(f"\n========== STAGE: {self.name} ==========")
        self.pre_stage()
        flush_log_handlers(self.logger)
        self.pipeline.barrier(self.barrier_timeout)

    def _post_stage(self):
        self.table.close()
        self.post_stage()
        self.pipeline.barrier(self.barrier_timeout)
        self.stop_time = datetime.now()
        _journal.emit("stage", self._stage_span_t0, label=self.name, epochs=self.current_epoch - 1)
        if len(self.pipeline.stages) > 1:
            self.logger.info(f"Finished stage in {self.stop_time - self.start_time}")

    def _pre_epoch(self):
        self.epoch_start_time = datetime.now()
        self._epoch_span_t0 = _journal.now()
        self.table["Epoch"] = self.current_epoch
        self.pre_epoch()
        self.pipeline._pre_epoch()

    def _post_epoch(self):
        self.epoch_stop_time = datetime.now()
        _journal.emit("epoch", self._epoch_span_t0, label=self.name, epoch=self.current_epoch)
        self._reduce_metrics()
        self.post_epoch()
        self.pipeline._post_epoch()
        self._update_table()
        self.current_epoch += 1

    def _reduce_metrics(self):
        self.track(name="misc/epoch", value=self.current_epoch, prefixed=False)
        self.track(
            name="misc/epoch_time",
            value=(self.epoch_stop_time - self.epoch_start_time).total_seconds(),
            prefixed=False,
        )
        self.tracker.next_epoch()

    def _setup_table(self):
        for column_dct in self._metrics():
            column_dct = dict(column_dct)
            display_name = column_dct.pop("name")
            column_dct.pop("metric")
            self.table.add_column(display_name, **column_dct)

    def _update_table(self):
        self.table.update("Epoch", self.current_epoch)
        self.table.update("Time/Epoch", str((datetime.now() - self.start_time) / self.current_epoch).split(".")[0])
        if self.max_epochs is not None:
            eta = (datetime.now() - self.start_time) / self.current_epoch * (self.max_epochs - self.current_epoch)
            self.table.update("ETA", str(eta).split(".")[0])
        for column_dct in self._metrics():
            metric_name = column_dct["metric"]
            if metric_name is not None and metric_name in self.tracker:
                history = self.tracker[metric_name]
                if history:
                    self.table.update(column_dct["name"], history[-1])
        self.table.next_row()

    def _metrics(self):
        metrics = []
        for column in self.table_columns():
            if isinstance(column, str):
                metrics.append({"name": column, "metric": column})
            elif isinstance(column, dict):
                if "name" not in column:
                    raise ValueError('Column dict must contain a "name" key')
                if "metric" not in column:
                    raise ValueError('Column dict must contain a "metric" key')
                metrics.append(column)
            else:
                raise ValueError(f"Invalid column: {column}. Must be a string or a dict.")
        return metrics


class TrainValStage(Stage):
    """Opinionated train+val stage around ONE compiled, sharded step.

    Subclasses implement ``step(state, batch) -> loss`` or
    ``-> (loss, metrics_dict)`` as a *pure traced function* (the reference's
    imperative ``step(batch)``, stage.py:263-264, cannot exist under jit).
    The stage owns a ``TrainState`` built from the pipeline's registered
    model/optimizer in ``_pre_stage`` (override ``make_state`` to customise),
    compiles train/val steps once, and tracks the reference's auto-metrics:
    ``{train,val}/loss``, ``misc/total_{train,val}_batches`` (SUM, global),
    ``misc/worker_{train,val}_batches`` (SUM, local), and per-scheduler
    ``misc/lr_{name}``. The reference's ``misc/step_time_ms`` is
    DELIBERATELY renamed: under async dispatch the loop-body time is host
    enqueue cost, so it ships as ``misc/step_dispatch_ms``, with
    ``misc/train_step_avg_ms`` carrying the wall-clock per-step average.

    ``precision="int8"`` switches the compiled train step to quantized
    training (models/quant.py): master fp32 weights stay the params the
    optimizer, EMA shadow and checkpoints see, while INSIDE the step's
    loss closure every matrix kernel is wrapped as a
    :class:`~dmlcloud_tpu.models.quant.QuantTrainTensor` — int8 matmuls on
    the forward and input-gradient paths, full-precision weight grads
    (straight-through), per-channel scales DELAYED one step via the amax
    tree carried in ``state.extras[QUANT_AMAX_KEY]`` and refreshed from
    the post-update params. Validation always runs full precision on the
    master weights.
    """

    def __init__(self, precision: str = "full"):
        super().__init__()
        if precision not in ("full", "int8"):
            raise ValueError(f'precision must be "full" or "int8", got {precision!r}')
        self._precision = str(precision)
        self.is_train = True
        self.state: TrainState | None = None
        self._policy: Any = "replicate"
        self._train_step_fn = None
        self._val_step_fn = None
        #: batches of the CURRENT epoch to skip on a mid-epoch resume
        #: (one-shot, set by _restore_state from a step-save sidecar,
        #: already scaled to THIS run's world size)
        self._resume_skip_steps = 0
        #: the train DataPipeline's saved iterator state, when the sidecar
        #: carries one (one-shot; preferred over the raw batch skip)
        self._resume_data_state = None
        #: wall-clock of the most recent state save — the preemption
        #: verdict's save-on-preempt latency
        self._last_save_latency_s: float | None = None
        #: set when a preemption poll at a step-save point cut the epoch
        #: short: run_epoch skips val and Stage.run exits without treating
        #: the partial epoch as complete
        self._mid_epoch_exit = False
        #: accumulates the wall-clock the host spends blocked on the device
        #: or on checkpoint commits; reset per epoch, published as
        #: ``misc/host_stall_ms``
        self._stall = StallTimer()
        #: cold-start machinery (compile/): signature registries wrapping the
        #: jitted steps when precompile()/buckets() are armed, else None —
        #: the default path keeps the raw jit fns with zero added overhead
        self._train_compiled = None
        self._val_compiled = None
        self._buckets_resolved: tuple[int, ...] | None = None
        #: True exactly while the per-batch body of train_epoch runs — the
        #: window in which NO device readback may happen under
        #: ``deferred_metrics()`` (tests assert against it)
        self._in_step_loop = False
        #: telemetry (flight recorder) accounting: host ns spent blocked in
        #: the feed iterator's next() this epoch (the goodput ledger's
        #: data_wait bucket), and the cached cost-analysis FLOPs fallback
        #: for MFU when step_flops() is not declared
        self._gp_data_wait_ns = 0
        self._cost_flops: float | None = None
        #: padding accounting over this epoch's HOST batches (telemetry
        #: only): slots whose ``segment_ids`` mark padding vs all token
        #: slots — ``misc/pad_fraction``, the signal the goodput advisor
        #: and the data-plane receipts read (doc/data.md)
        self._gp_pad_slots = 0
        self._gp_token_slots = 0

    # -- overridables (parity: reference stage.py:228-257) ------------------
    def train_dataset(self):
        ds = self.pipeline.datasets.get("train")
        if ds is None:
            raise DatasetNotFoundError(
                'No "train" dataset found in pipeline. Use register_dataset("train", ...) to register a dataset.'
            )
        return ds

    def val_dataset(self):
        ds = self.pipeline.datasets.get("val")
        if ds is None:
            raise DatasetNotFoundError(
                'No "val" dataset found in pipeline. Use register_dataset("val", ...) to register a dataset.'
            )
        return ds

    def loss_metric_name(self) -> str:
        return "loss"

    def train_metric_prefix(self) -> str:
        return "train"

    def val_metric_prefix(self) -> str:
        return "val"

    def gradient_clip(self) -> float:
        """Global-norm clip threshold; 0 disables (reference stage.py:256-257)."""
        return 0.0

    def precision(self) -> str:
        """Matmul precision of the compiled TRAIN step: ``"full"`` (the
        model's own dtype) or ``"int8"`` (quantized training — see the
        class docstring and models/quant.py). A knob method like its
        neighbours so subclasses may override instead of passing the
        constructor arg."""
        return self._precision

    def gradient_accumulation(self) -> int:
        """Number of microbatches to accumulate per optimizer step (1
        disables). The registered batch is split along its leading axis and
        scanned with ``lax.scan`` INSIDE the one compiled step — grads and
        metrics accumulate in fp32 on device, the optimizer applies once.
        Losses, grads, AND step metrics are AVERAGED over microbatches, so
        equivalence with the unaccumulated step requires ``step`` to return
        mean-reduced values: a sum-reduced loss would be rescaled by
        1/accum, and a count-style metric (e.g. samples seen) silently
        changes scale by 1/accum — derive counts from the batch size
        outside ``step`` instead.
        This is the TPU shape of large effective batches under a tight HBM
        budget: one trace, one dispatch, no host round trips per microbatch.
        (The reference has no equivalent; its imperative loop would pay
        ``accum`` Python dispatches, stage.py:290-314.)"""
        return 1

    def ema_decay(self) -> float:
        """Per-step decay of an exponential moving average of the params,
        kept as a fp32 shadow tree on the state (same shapes and shardings
        as the params) and updated inside the one compiled train step; 0
        disables, typical values are 0.999-0.9999. Validation runs on the
        averaged params (see ``val_with_ema``), and the shadow rides
        checkpoints and resume like every other state leaf.

        The reference has no equivalent; torch users bolt on
        ``swa_utils.AveragedModel``, which costs a separate full-model pass
        per update on host-dispatched kernels."""
        return 0.0

    def val_with_ema(self) -> bool:
        """Whether validation sees the EMA params instead of the raw ones
        (only meaningful when ``ema_decay() > 0``; default True — evaluating
        the average is the point of keeping it)."""
        return True

    def step_flops(self) -> float:
        """Total FLOPs one optimizer step performs across the WHOLE mesh
        (forward+backward for the global batch; multiply-add counts as 2 —
        the convention hardware peaks use). Return a positive number and the
        stage tracks ``misc/mfu`` each epoch from the measured per-step
        wall clock and the mesh's aggregate chip peak
        (``utils.profiling.peak_flops_for_kind``). 0 (default) disables; on
        backends whose device kind has no entry in the bf16 peak table
        (CPU/GPU dev runs) the metric is skipped rather than computed
        against a made-up peak.

        Rules of thumb: transformer training ≈ ``6 * params * tokens_per_
        batch`` (PaLM convention, embedding lookups excluded); ResNet-50 @
        224² ≈ ``24.6e9 * images_per_batch`` (see bench.py)."""
        return 0.0

    def model_name(self) -> str | None:
        """Which registered model this stage trains (None = the only one)."""
        return None

    def device_prefetch(self) -> int:
        """Batches kept in flight on device ahead of the compiled step (the
        default feeding path runs every dataset through
        ``data.device_iterator``, overlapping host->HBM transfers with
        compute). Return 0 to feed synchronously (one ``make_global_batch``
        per step) — e.g. when batches are huge and HBM is tight."""
        return 2

    def prefetch_depth(self) -> int:
        """The overlap engine's canonical name for the device prefetch depth
        (default: whatever ``device_prefetch()`` says, so existing overrides
        keep working). 2 = double buffering — batch N+1's H2D copy runs
        while the device computes batch N; 0 = synchronous per-step puts."""
        return int(self.device_prefetch())

    def host_prefetch(self) -> int:
        """Host batches prepared ahead on a background thread before the
        device transfer queue (data/device.py). 0 (default) keeps host batch
        prep on the training thread — raise it when prep (augmentation,
        decode, disk reads) is a measurable share of the step budget."""
        return 0

    def precompile(self) -> bool:
        """Whether to AOT-compile the train/val steps at stage start (the
        ``jit(...).lower(...).compile()`` pattern over abstract
        ``ShapeDtypeStruct``\\ s, compile/aot.py): compile cost lands in a
        timed precompile phase BEFORE the data loop (``misc/compile_ms``),
        and sharding/shape mismatches error at stage start instead of
        step 1. The batch signature comes from ``batch_spec()`` or, by
        default, from peeking the first batch's shapes/dtypes (one
        signature per bucket when ``buckets()`` is set). Default: the
        pipeline's ``precompile=`` flag (False)."""
        return bool(getattr(self.pipeline, "_precompile", False))

    def buckets(self):
        """Batch-dim bucket sizes for ragged batches, ascending (e.g.
        ``(8, 32, 128)`` with 128 the full batch size), or None to disable.
        Every host batch is padded up to the smallest fitting bucket before
        the device transfer — mapping batches gain a zero-weight
        ``bucket_mask_key()`` leaf (reduce per-sample losses with
        ``compile.masked_mean`` to keep the math identical) — so the
        compiled-signature count is bounded by ``len(buckets)`` instead of
        growing with the data (``misc/recompiles`` tracks growth events per
        epoch). Default: the pipeline's ``buckets=`` flag (None)."""
        return getattr(self.pipeline, "_buckets", None)

    def bucket_mask_key(self) -> str:
        """Key under which bucketing injects the padding mask into mapping
        batches (1.0 real row / 0.0 padded row)."""
        from .compile.buckets import DEFAULT_MASK_KEY

        return DEFAULT_MASK_KEY

    def batch_spec(self):
        """Declared abstract spec of one HOST train batch (a pytree of
        ``jax.ShapeDtypeStruct`` — or of example arrays — matching what the
        train dataset yields, pre-sharding). None (default) peeks the first
        batch instead; declare it when the dataset is a one-shot iterator or
        when you want stage-start validation against an explicit contract."""
        return None

    def async_checkpoint(self) -> bool:
        """Whether this stage's Orbax scopes commit saves on a background
        writer (non-blocking saves; default True). The loop never has more
        than one save in flight — a new save first waits out the previous —
        and hard barriers at stage end / run end / preemption exit guarantee
        everything is committed before the process goes away, so resume
        semantics are identical to synchronous saves: a checkpoint either
        committed completely or does not exist. False restores fully
        synchronous saves (the bisection baseline)."""
        return True

    def deferred_metrics(self) -> bool:
        """Whether per-step metrics stay on device until a sync point
        (default True): no ``.item()``/``device_get`` runs inside the step
        loop; host syncs happen only every ``log_every()`` steps (a 2-step-
        trailing loss fetch that also feeds the NaN/inf guard and the live
        table) and at the epoch-end fused exchange. False restores the
        eager path — every step's metrics are fetched to host immediately —
        which produces identical epoch-end values, just slower."""
        return True

    def log_every(self) -> int:
        """Steps between host syncs inside the training loop when
        ``deferred_metrics()`` is on: each boundary fetches one trailing
        loss value (already computed — minimal stall), updates the live
        console EMA, and runs the NaN/inf guard. 0 disables the periodic
        sync entirely (the guard then only sees the epoch-end values)."""
        return 50

    def nan_guard(self) -> bool:
        """Whether the periodic ``log_every()`` sync raises
        ``FloatingPointError`` on a non-finite loss (default True). Under
        deferred metrics the check piggybacks on the boundary fetch —
        detection trails the bad step by up to ``log_every()`` steps instead
        of paying a per-step sync; with eager metrics it checks every step."""
        return True

    def checkpoint_every(self) -> int:
        """Epochs between automatic TrainState saves (0 disables). Active
        only when ``pipeline.enable_checkpointing()`` was called. The
        reference leaves tensor state to user hooks (SURVEY.md §3.5); here a
        resumed pipeline continues bit-for-bit: params, optimizer state, rng,
        extras, metric histories, and the epoch counter are all restored."""
        return 1

    def checkpoint_every_steps(self) -> int:
        """Steps between mid-epoch state saves: every N steps the full
        TrainState is saved collectively (separate Orbax scope keyed by the
        global optimizer step, newest-only retention), the preemption flag
        is polled so a preempted run exits within N steps instead of at the
        epoch boundary, and a resume whose step save is fresher than the
        last completed epoch continues MID-epoch by fast-forwarding the
        train dataset past the consumed batches. 0 disables (the default).

        Epoch-boundary checkpointing (``checkpoint_every``) loses the whole
        current epoch on a crash or preemption — unacceptable when one
        "epoch" is hours of LM pretraining. Mid-epoch resume requires
        per-epoch deterministic iteration order (true for every pipeline
        here, which seeds shuffles by epoch), and continues bit-for-bit.

        Metrics caveat: the resumed epoch's tracked metrics cover only the
        post-resume steps (partial reducer buffers are not checkpointed);
        counters like ``misc/total_train_batches`` under-count that epoch."""
        return 0

    def checkpoint_keep(self) -> int:
        """How many checkpoints the stage's Orbax manager retains."""
        return 3

    def checkpoint_best_metric(self) -> str | None:
        """Tracker metric (e.g. ``'val/loss'``) ranking which checkpoints to
        KEEP: retention holds the best ``checkpoint_keep()`` by this metric
        instead of the most recent. None (default) keeps most-recent.
        Orbax additionally always preserves the newest checkpoint, so a
        Slurm-requeue resume continues from the latest epoch either way."""
        return None

    def checkpoint_best_mode(self) -> str:
        """'min' (e.g. losses) or 'max' (e.g. accuracies)."""
        return "min"

    # -- state construction -------------------------------------------------
    def make_state(self) -> TrainState:
        """Build the TrainState from the pipeline registries. Override for
        multi-model setups.

        Registry arrays are COPIED into the state: the compiled step donates
        its input state, and on the first call those buffers would otherwise
        be the registry's own arrays — a later stage (or user code reading
        ``pipeline.models`` after the run) would see deleted buffers. The rng
        is folded per stage so stages draw independent streams."""
        entry = self.pipeline._model_entry(self.model_name())
        tx = self.pipeline._optimizer_for(entry.name)

        def fresh(tree):
            return jax.tree_util.tree_map(
                lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x, tree
            )

        stage_index = self.pipeline.stages.index(self) if self in self.pipeline.stages else 0
        params = fresh(entry.params)
        extras = fresh(entry.extras) if entry.extras is not None else None
        if self.precision() == "int8":
            # seed the delayed-scale state: step 0 quantizes with the
            # INITIAL params' amax (models/quant.py — every later step
            # uses the previous step's post-update statistics)
            from .models.quant import QUANT_AMAX_KEY, amax_tree

            extras = dict(extras or {})
            extras[QUANT_AMAX_KEY] = amax_tree(params)
        return TrainState.create(
            apply_fn=entry.apply_fn,
            params=params,
            tx=tx,
            rng=jax.random.fold_in(self.pipeline.root_key, stage_index),
            extras=extras,
            ema=True if float(self.ema_decay()) > 0.0 else None,
            mesh=self.mesh,
            policy=entry.policy,
        )

    # -- the pure step ------------------------------------------------------
    def step(self, state: TrainState, batch) -> Any:
        """Pure traced step: return ``loss`` or ``(loss, metrics_dict)``.
        Runs under jit — no Python side effects, no host sync."""
        raise NotImplementedError()

    def train_step(self, state, batch):
        return self.step(state, batch)

    def val_step(self, state, batch):
        return self.step(state, batch)

    # -- compiled steps -----------------------------------------------------
    def _build_train_step(self) -> Callable:
        clip = float(self.gradient_clip())
        accum = int(self.gradient_accumulation())
        ema_decay = float(self.ema_decay())
        int8 = self.precision() == "int8"
        if int8:
            from .models.quant import QUANT_AMAX_KEY, amax_tree, wrap_train_tree

        def train_step(state: TrainState, batch):
            rng = jax.random.fold_in(state.rng, state.step)

            def loss_fn(params, extras, rng, mb):
                if int8:
                    # wrap INSIDE the differentiated closure: grads keep
                    # the plain-params structure, the user's step sees
                    # QuantTrainTensor kernels the QuantDense layers
                    # dispatch on (models/quant.py), and the delayed
                    # scales ride in from the previous step's extras
                    params = wrap_train_tree(params, extras[QUANT_AMAX_KEY])
                out = self.train_step(state.replace(params=params, extras=extras, rng=rng), mb)
                # step may return loss | (loss, metrics) | (loss, metrics, new_extras)
                if not isinstance(out, tuple):
                    loss, metrics, new_extras = out, {}, extras
                elif len(out) == 2:
                    (loss, metrics), new_extras = out, extras
                else:
                    loss, metrics, new_extras = out
                return loss, (metrics, new_extras)

            grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
            if accum == 1:
                (loss, (metrics, new_extras)), grads = grad_fn(state.params, state.extras, rng, batch)
            else:
                loss, metrics, new_extras, grads = self._accumulate(grad_fn, state, rng, batch, accum)
            if clip > 0.0:
                gnorm = jax.lax.rsqrt(
                    jnp.maximum(sum(jnp.sum(g.astype(jnp.float32) ** 2) for g in jax.tree_util.tree_leaves(grads)), 1e-12)
                )
                scale = jnp.minimum(1.0, clip * gnorm)
                grads = jax.tree_util.tree_map(lambda g: g * scale.astype(g.dtype), grads)
            new_state = state.apply_gradients(grads).replace(extras=new_extras)
            if int8:
                # delayed scaling: the NEXT step quantizes with THIS
                # step's post-update amax — one fused reduction here, no
                # statistics pass on the forward's critical path
                new_state = new_state.replace(
                    extras={**new_state.extras, QUANT_AMAX_KEY: amax_tree(new_state.params)}
                )
            if ema_decay > 0.0:
                new_state = new_state.update_ema(ema_decay)
            metrics = dict(metrics)
            metrics[self.loss_metric_name()] = loss
            return new_state, metrics

        state_sh = self.state.shardings(self.mesh, self._policy)
        batch_sh = None  # inferred from the (already sharded) batch arrays
        return jax.jit(
            train_step,
            donate_argnums=0,
            in_shardings=(state_sh, batch_sh),
            out_shardings=(state_sh, None),
        )

    @staticmethod
    def _accumulate(grad_fn, state, rng, batch, accum):
        """Traced microbatch accumulation: split ``batch`` [B, ...] into
        ``accum`` slices of B/accum and ``lax.scan`` ``grad_fn`` over them.
        Losses, metrics, and grads accumulate in fp32 (grads cast back to
        the param dtype for the optimizer); auxiliary state (``extras``,
        e.g. BatchNorm stats) threads through the scan so the last
        microbatch's update wins, exactly as sequential steps would."""
        leaves = jax.tree_util.tree_leaves(batch)
        for leaf in leaves:
            if leaf.shape[0] % accum:
                raise ValueError(
                    f"gradient_accumulation()={accum} must divide the batch dimension, got {leaf.shape[0]}"
                )
        micro = jax.tree_util.tree_map(lambda x: x.reshape(accum, x.shape[0] // accum, *x.shape[1:]), batch)

        # One eval_shape reveals the metrics pytree so the fp32 accumulators
        # can be preallocated for the scan carry.
        first = jax.tree_util.tree_map(lambda x: x[0], micro)
        out_shape = jax.eval_shape(grad_fn, state.params, state.extras, rng, first)
        metrics_shape = out_shape[0][1][0]

        def f32_zeros(tree):
            return jax.tree_util.tree_map(lambda s: jnp.zeros(s.shape, jnp.float32), tree)

        init = (
            f32_zeros(state.params),  # grad accumulators
            state.extras,
            jnp.zeros((), jnp.float32),  # loss
            f32_zeros(metrics_shape),
        )

        def body(carry, xs):
            grads_acc, extras, loss_acc, metrics_acc = carry
            i, mb = xs
            (loss, (metrics, new_extras)), grads = grad_fn(state.params, extras, jax.random.fold_in(rng, i), mb)
            grads_acc = jax.tree_util.tree_map(lambda a, g: a + g.astype(jnp.float32), grads_acc, grads)
            metrics_acc = jax.tree_util.tree_map(lambda a, m: a + m.astype(jnp.float32), metrics_acc, metrics)
            return (grads_acc, new_extras, loss_acc + loss.astype(jnp.float32), metrics_acc), None

        (grads_acc, extras, loss_acc, metrics_acc), _ = jax.lax.scan(
            body, init, (jnp.arange(accum), micro)
        )
        grads = jax.tree_util.tree_map(
            lambda g, p: (g / accum).astype(p.dtype), grads_acc, state.params
        )
        metrics = jax.tree_util.tree_map(lambda m: m / accum, metrics_acc)
        return loss_acc / accum, metrics, extras, grads

    def _build_val_step(self) -> Callable:
        use_ema = float(self.ema_decay()) > 0.0 and self.val_with_ema()

        def val_step(state: TrainState, batch):
            if use_ema:
                # evaluate the averaged weights: the user's val_step reads
                # state.params as usual and sees the EMA tree, cast to the
                # params' dtypes (the fp32 shadow must not silently promote
                # a bf16 model's whole forward pass to fp32)
                ema = jax.tree_util.tree_map(
                    lambda e, p: e.astype(p.dtype), state.ema, state.params
                )
                state = state.replace(params=ema)
            out = self.val_step(state, batch)
            # same contract as train: loss | (loss, metrics) | (loss, metrics, extras);
            # extras are discarded in eval (no state update).
            if not isinstance(out, tuple):
                loss, metrics = out, {}
            else:
                loss, metrics = out[0], out[1]
            metrics = dict(metrics)
            metrics[self.loss_metric_name()] = loss
            return metrics

        return jax.jit(val_step)

    # -- lifecycle ----------------------------------------------------------
    def _configure_state_manager(self):
        """Bind this stage's Orbax retention options (keep count, optional
        keep-best ranking) at first manager creation — before any
        save/restore touches the scope."""
        ckpt = self.pipeline.checkpoint_dir
        if ckpt is None:
            return
        asave = bool(self.async_checkpoint())
        # step-save scope first: it must get its newest-only retention even
        # when the user pre-configured the EPOCH scope (early return below)
        # or disabled epoch checkpointing outright
        if int(self.checkpoint_every_steps()) > 0 and not ckpt.has_state_manager(self._steps_scope):
            # crash/preemption insurance only — history lives in epoch saves
            ckpt.state_manager(self._steps_scope, max_to_keep=1, async_save=asave)
        if int(self.checkpoint_every()) <= 0:
            return
        if ckpt.has_state_manager(self.name):
            return  # the user configured this scope in pre_stage; their options win
        opts = {}
        metric = self.checkpoint_best_metric()
        if metric is not None:
            mode = self.checkpoint_best_mode()
            if mode not in ("min", "max"):
                raise ValueError(f"checkpoint_best_mode() must be 'min' or 'max', got {mode!r}")
            # via the compat layer: new orbax passes the policy through, old
            # orbax (no checkpoint_managers module) gets host-side retention
            from .utils import orbax_compat as ocm

            # best-N by the metric PLUS always the newest (deterministic
            # requeue-resume freshness; best_fn+max_to_keep alone leaves the
            # latest checkpoint's survival to async-gc timing)
            opts = {
                "preservation_policy": ocm.AnyPreservationPolicy(
                    [
                        ocm.LatestN(n=1),
                        ocm.BestN(
                            get_metric_fn=lambda m: m[metric],
                            reverse=(mode == "min"),
                            n=int(self.checkpoint_keep()),
                            # metricless saves must not accumulate forever;
                            # LatestN above still protects the newest one
                            keep_checkpoints_without_metrics=False,
                        ),
                    ]
                )
            }
        keep = None if opts else int(self.checkpoint_keep())  # policy owns retention when set
        ckpt.state_manager(self.name, max_to_keep=keep, async_save=asave, **opts)

    @property
    def _steps_scope(self) -> str:
        """Orbax scope for mid-epoch step saves (separate from the
        epoch-keyed scope so step ids never collide with epoch numbers)."""
        return f"{self.name}.steps"

    def _pre_stage(self):
        super()._pre_stage()
        if self.state is None:
            entry = self.pipeline._model_entry(self.model_name())
            self._policy = entry.policy
            self.state = self.make_state()
        self._configure_state_manager()
        if self.pipeline.resumed and (
            int(self.checkpoint_every()) > 0 or int(self.checkpoint_every_steps()) > 0
        ):
            # manual mode (checkpoint_every()==0) owns its restore layout too
            self._restore_state()
        self._train_step_fn = self._build_train_step()
        self._val_step_fn = self._build_val_step()
        self._setup_compiled_steps()
        san = getattr(self.pipeline, "_sanitizer", None)
        if san is not None and san.armed:
            # the sanitizer's dispatch probe (host-numpy leaves == implicit
            # H2D) interposes OUTSIDE TraceGuard/PrecompiledStep so the
            # default path gains zero overhead when sanitize is off
            self._train_step_fn = san.wrap_dispatch(self._train_step_fn, where=f"{self.name}.train_step")
            self._val_step_fn = san.wrap_dispatch(self._val_step_fn, where=f"{self.name}.val_step")

    # -- cold-start machinery (compile/; doc/performance.md §4) -------------
    def _setup_compiled_steps(self):
        """Arm the signature registries and (optionally) the AOT precompile
        phase. Inactive (raw jit fns, zero added per-step cost) unless
        ``precompile()`` or ``buckets()`` says otherwise."""
        raw_buckets = self.buckets()
        if raw_buckets:
            from .compile.buckets import resolve_buckets

            self._buckets_resolved = resolve_buckets(raw_buckets)
        else:
            self._buckets_resolved = None
        if not self.precompile() and self._buckets_resolved is None:
            return
        from .compile.aot import PrecompiledStep
        from .lint import TraceGuard

        self._train_compiled = PrecompiledStep(self._train_step_fn, name=f"{self.name}.train_step")
        self._val_compiled = PrecompiledStep(self._val_step_fn, name=f"{self.name}.val_step")
        if self.precompile():
            self._run_precompile_phase()
        # the runtime retrace guard reads the registry's _cache_size(): any
        # signature beyond the expected bucket set is a mid-run compile stall
        expected = len(self._buckets_resolved) if self._buckets_resolved else 1
        self._train_step_fn = TraceGuard(
            self._train_compiled, max_traces=expected, action="warn", name=f"{self.name}.train_step"
        )
        self._val_step_fn = self._val_compiled

    def _host_batch_spec(self, dataset_fn) -> Any:
        """The abstract HOST batch for precompilation: ``batch_spec()`` if
        declared (train only), else the peeked first batch; None when the
        dataset is absent."""
        if dataset_fn == self.train_dataset:
            declared = self.batch_spec()
            if declared is not None:
                from .compile.aot import abstract_spec

                return abstract_spec(declared)
        try:
            ds = dataset_fn()
        except DatasetNotFoundError:
            return None
        if iter(ds) is ds:
            raise ValueError(
                f"precompile() needs the first batch's shapes, but stage {self.name!r} "
                "feeds from a one-shot iterator that peeking would consume — declare "
                "batch_spec() or register a re-iterable dataset"
            )
        from .data.device import peek_spec

        spec, _ = peek_spec(ds)
        return spec

    def _run_precompile_phase(self):
        """The timed precompile phase: lower+compile every expected train/val
        signature against abstract specs BEFORE the data loop, so compile
        cost is measured (``misc/compile_ms``), cache hits are counted, and
        sharding/shape mismatches fail here — at stage start."""
        from .compile import aot
        from .compile import cache as compile_cache
        from .compile.buckets import bucket_spec

        t0 = time.perf_counter()
        stats0 = compile_cache.cache_stats()
        state_spec = aot.abstract_spec(self.state)

        def global_specs(host_spec):
            if host_spec is None:
                return []
            if self._buckets_resolved:
                host_variants = [
                    bucket_spec(host_spec, b, mask_key=self.bucket_mask_key())
                    for b in self._buckets_resolved
                ]
            else:
                host_variants = [host_spec]
            out = []
            for hs in host_variants:
                gs = aot.global_batch_spec(hs, self.mesh)
                aot.validate_global_batch_spec(gs, self.mesh)
                out.append(gs)
            return out

        n_train = 0
        verify_args: list[tuple] = []
        for gs in global_specs(self._host_batch_spec(self.train_dataset)):
            self._train_compiled.precompile(state_spec, gs)
            verify_args.append(("train_step", self._train_compiled, (state_spec, gs), (0,)))
            n_train += 1
        # val is best-effort: a stage may have no val dataset, or one whose
        # first-batch peek is impossible — the val step then compiles lazily
        n_val = 0
        try:
            for gs in global_specs(self._host_batch_spec(self.val_dataset)):
                self._val_compiled.precompile(state_spec, gs)
                verify_args.append(("val_step", self._val_compiled, (state_spec, gs), ()))
                n_val += 1
        except ValueError as e:
            self.logger.warning(f"val-step precompile skipped: {e}")

        elapsed_ms = (time.perf_counter() - t0) * 1e3
        if not ("misc/compile_ms" in self.tracker and self.tracker.has_value("misc/compile_ms")):
            self.track("misc/compile_ms", round(elapsed_ms, 3), prefixed=False)
        stats1 = compile_cache.cache_stats()
        if n_train or n_val:
            self.logger.info(
                f"precompile: {n_train} train + {n_val} val signature(s) in {elapsed_ms:.0f} ms "
                f"(compile cache: {stats1['aot_hits'] - stats0['aot_hits']} hit(s), "
                f"{stats1['aot_misses'] - stats0['aot_misses']} miss(es))"
            )
        else:
            self.logger.warning(
                f"precompile() on stage {self.name!r} found no batch spec to compile "
                "against; the first step pays the compile as usual"
            )
        self._verify_precompiled(verify_args)

    def _verify_precompiled(self, verify_args: list[tuple]) -> None:
        """The ``TrainingPipeline(verify=...)`` arm: audit every executable
        the precompile phase just built with the IR verifier (doc/lint.md
        DML6xx) BEFORE the data loop. Re-uses the compiled artifacts — the
        preflight adds jaxpr traces (cheap, no XLA) but zero compiles."""
        mode = getattr(self.pipeline, "_verify_mode", None)
        if not mode or not verify_args:
            return
        from .compile import aot
        from .lint import LintError
        from .lint import ir as ir_mod

        budget = getattr(self.pipeline, "_hbm_budget", None)
        specs = []
        for step_name, reg, args, donate in verify_args:
            specs.append(
                ir_mod.ProgramSpec(
                    name=f"{self.name}.{step_name}[{len(specs)}]",
                    fn=reg._fn,
                    args=args,
                    donate_argnums=donate,
                    mesh=self.mesh,
                    hbm_budget_bytes=budget,
                    kind="train",
                    compiled=reg._compiled.get(aot.signature_of(args)),
                )
            )
        t0 = time.perf_counter()
        findings = ir_mod.verify_programs(specs)
        elapsed_ms = (time.perf_counter() - t0) * 1e3
        self.pipeline.verify_findings = list(findings)
        self.logger.info(
            f"verify: {len(findings)} finding(s) over {len(specs)} precompiled "
            f"program(s) in {elapsed_ms:.0f} ms"
        )
        if not findings:
            return
        report = "\n".join(f.format() for f in findings)
        if mode == "error":
            raise LintError(
                f"IR verifier found {len(findings)} problem(s) in the precompiled "
                f"step programs (doc/lint.md DML6xx; suppress with "
                f"'# dmllint: disable=ID'):\n{report}",
                findings=findings,
            )
        self.logger.warning("IR verifier findings in precompiled step programs:\n%s", report)

    def _pre_epoch(self):
        self._stall.reset()  # misc/host_stall_ms is a per-epoch total
        self._gp_data_wait_ns = 0
        self._gp_pad_slots = 0
        self._gp_token_slots = 0
        from .data import store as _shard_store

        self._gp_reader_mark = _shard_store.reader_activity()
        super()._pre_epoch()

    @property
    def _telemetry_armed(self) -> bool:
        return bool(getattr(self.pipeline, "telemetry_armed", False))

    def _reduce_metrics(self):
        # everything the host spent blocked this epoch (value fetches, the
        # epoch-end block_until_ready, waits on async checkpoint commits)
        self.track("misc/host_stall_ms", round(self._stall.ms, 3), prefixed=False)
        if self._telemetry_armed and self.epoch_stop_time is not None:
            # the goodput ledger's per-epoch buckets (telemetry/goodput.py):
            # disjoint by construction — data_wait is timed OUTSIDE the stall
            # timer, ckpt is the stall timer's 'checkpoint' share, and
            # productive is the remainder. MEAN-reduced across hosts on the
            # packed epoch-end collective like any other scalar metric.
            epoch_s = (self.epoch_stop_time - self.epoch_start_time).total_seconds()
            data_wait_ms = self._gp_data_wait_ns / 1e6
            ckpt_ms = self._stall.label_ms("checkpoint")
            stall_ms = self._stall.ms  # includes the checkpoint share
            productive_s = max(epoch_s - (data_wait_ms + stall_ms) / 1e3, 0.0)
            self.track_reduce(
                "misc/data_wait_ms", round(data_wait_ms, 3), reduction=Reduction.MEAN, prefixed=False
            )
            self.track_reduce(
                "misc/ckpt_ms", round(ckpt_ms, 3), reduction=Reduction.MEAN, prefixed=False
            )
            self.track_reduce(
                "misc/goodput",
                round(productive_s / epoch_s, 6) if epoch_s > 0 else 0.0,
                reduction=Reduction.MEAN,
                prefixed=False,
            )
            if self._gp_token_slots:
                self.track_reduce(
                    "misc/pad_fraction",
                    round(self._gp_pad_slots / self._gp_token_slots, 6),
                    reduction=Reduction.MEAN,
                    prefixed=False,
                )
            from .data import store as _shard_store

            if _shard_store.reader_activity() > getattr(self, "_gp_reader_mark", 0):
                # a ShardReader fetched blocks this epoch — the goodput
                # advisor points at reader knobs instead of generic prefetch
                self.track_reduce(
                    "misc/shard_reader", 1.0, reduction=Reduction.MAX, prefixed=False
                )
        if self._train_compiled is not None:
            # signatures that showed up this epoch WITHOUT a precompiled
            # executable — each one was a mid-run XLA compile (0 is the goal;
            # the TraceGuard wrapper has already warned per growth event)
            self.tracker.bump(
                "misc/recompiles",
                self._train_compiled.pop_recompiles() + self._val_compiled.pop_recompiles(),
            )
        super()._reduce_metrics()

    def _post_epoch(self):
        super()._post_epoch()
        self._maybe_save_state()

    def _post_stage(self):
        # sync point: every async save this stage dispatched must be
        # committed before the stage is considered finished — a following
        # stage's restore, the run-end teardown, and a preemption exit
        # (mid-epoch or epoch-boundary, both route through here) all rely
        # on the newest checkpoint being durable at this line
        if self.pipeline.checkpoint_dir is not None:
            self.pipeline.checkpoint_dir.wait_until_finished(scope=self.name)
            self.pipeline.checkpoint_dir.wait_until_finished(scope=self._steps_scope)
        # publish trained params back to the registry so a following stage
        # continues from them (the reference's in-place nn.Module semantics)
        if self.state is not None:
            entry = self.pipeline._model_entry(self.model_name())
            entry.params = self.state.params
            entry.extras = self.state.extras
            # the averaged weights are what the val metrics (and any
            # best-checkpoint ranking) were computed on — hand them onward too
            entry.ema = self.state.ema
        super()._post_stage()

    # -- automatic state checkpointing (closes reference gap, SURVEY.md §3.5) --
    def _state_pytree(self) -> dict:
        tree = {
            "step": self.state.step,
            "params": self.state.params,
            "opt_state": self.state.opt_state,
            "rng": self.state.rng,
        }
        if self.state.extras is not None:
            tree["extras"] = self.state.extras
        if self.state.ema is not None:
            tree["ema"] = self.state.ema
        return tree

    def _maybe_save_state(self):
        ckpt = self.pipeline.checkpoint_dir
        every = int(self.checkpoint_every())
        if ckpt is None or every <= 0 or self.state is None:
            return
        completed = self.current_epoch - 1  # super()._post_epoch incremented
        final = completed == self.max_epochs or self._stop_requested or self._preempt_exit
        if completed % every != 0 and not final:
            return
        save_kwargs = {}
        best_metric = self.checkpoint_best_metric()
        if best_metric is not None:
            hist = self.tracker[best_metric] if best_metric in self.tracker else []
            val = hist[-1] if hist else None
            if val is None:
                self.logger.warning(
                    f"checkpoint_best_metric {best_metric!r} has no value for epoch "
                    f"{completed}; this save is unranked (retained only while it is the newest)"
                )
            else:
                save_kwargs["metrics"] = {best_metric: float(val)}
        # single-flight: an async save still committing from a previous epoch
        # is waited out (timed as stall) before the new one dispatches. The
        # save call itself is timed too — async it costs one D2H snapshot,
        # sync (async_checkpoint() False) it blocks for the full commit.
        t0 = time.perf_counter()
        with self._stall.measure(label="checkpoint"):
            ckpt.wait_until_finished(scope=self.name)
            ckpt.save_state(completed, self._state_pytree(), scope=self.name, **save_kwargs)
        self._last_save_latency_s = time.perf_counter() - t0
        if is_root():
            from .utils.serialization import to_jsonable

            try:
                tracker_state = to_jsonable(self.tracker.state_dict())
            except TypeError as e:
                # a non-numeric tracked value must not kill the run at save
                # time (worse: only root would die, the other hosts would hang
                # in the next collective) — save epoch/stop without history
                self.logger.warning(
                    f"Metric tracker state is not JSON-encodable ({e}); saving resume "
                    "metadata without metric history"
                )
                tracker_state = None
            self._write_resume_sidecar(
                self.name,
                completed,
                {"epoch": completed, "stopped": self._stop_requested, "tracker": tracker_state},
            )

    def _write_resume_sidecar(self, scope: str, key: int, payload: dict) -> None:
        """Root-side sidecar write + retention cleanup, shared by the epoch
        and step save paths.

        Atomic write: a preemption mid-write must not leave a truncated
        sidecar that breaks the very resume it exists for. Cleanup keeps
        sidecars in lockstep with Orbax's COMMITTED saves (``all_steps``):
        with async saves the previous checkpoint stays the latest committed
        one until the new save lands, so its sidecar must survive until
        then — deleting by 'newest only' would strand the only restorable
        save without resume metadata after a crash mid-commit. ``*.pkl``
        covers sidecars from the pre-JSON format."""
        import json

        from .checkpoint import atomic_write_text

        ckpt = self.pipeline.checkpoint_dir
        meta_dir = ckpt.path / "meta" / scope
        meta_dir.mkdir(parents=True, exist_ok=True)
        atomic_write_text(meta_dir / f"{key}.json", json.dumps(payload))
        kept = set(ckpt.state_manager(scope).all_steps()) | {key}
        for f in list(meta_dir.glob("*.json")) + list(meta_dir.glob("*.pkl")):
            if f.stem.isdigit() and int(f.stem) not in kept:
                f.unlink(missing_ok=True)

    def _save_step_state(self, epoch_step: int) -> None:
        """Collective mid-epoch save keyed by the GLOBAL optimizer step, with
        a root-written sidecar recording where inside which epoch it landed
        (what a resume needs to fast-forward the data), under which world
        size (so a resume on a DIFFERENT process count re-derives its
        per-rank position), and — when the train dataset is resumable — its
        iterator state."""
        ckpt = self.pipeline.checkpoint_dir
        t0 = time.perf_counter()
        with self._stall.measure(label="checkpoint"):
            # at most one save in flight; the step-counter fetch blocks on
            # the dispatched steps, so both waits count as host stall — as
            # does the save call itself (one D2H snapshot when async, the
            # full blocking commit when async_checkpoint() is off)
            ckpt.wait_until_finished(scope=self._steps_scope)
            gstep = int(jax.device_get(self.state.step))
            ckpt.save_state(gstep, self._state_pytree(), scope=self._steps_scope)
        #: the preemption verdict's save-on-preempt latency (doc/elasticity.md)
        self._last_save_latency_s = time.perf_counter() - t0
        if is_root():
            payload = {
                "epoch": self.current_epoch,
                "step_in_epoch": epoch_step,
                "world_size": runtime.world_size(),
            }
            ds = self.pipeline.datasets.get("train")
            if hasattr(ds, "state_dict"):
                try:
                    payload["data"] = ds.state_dict()
                except Exception:
                    self.logger.warning(
                        "train dataset state_dict() failed; resume will fast-forward "
                        "by batch count instead", exc_info=True,
                    )
            self._write_resume_sidecar(self._steps_scope, gstep, payload)

    def _read_step_resume_meta(self, gstep: int) -> dict | None:
        """Root-only: the step-save sidecar, or None (degrade to epoch resume)."""
        import json

        meta_file = self.pipeline.checkpoint_dir.path / "meta" / self._steps_scope / f"{gstep}.json"
        try:
            raw = json.loads(meta_file.read_text())
            meta = {"epoch": int(raw["epoch"]), "step_in_epoch": int(raw["step_in_epoch"])}
            # optional elastic fields (absent in pre-elastic sidecars)
            meta["world_size"] = int(raw.get("world_size", runtime.world_size()))
            if isinstance(raw.get("data"), dict):
                meta["data"] = raw["data"]
            return meta
        except Exception:
            self.logger.warning(
                f"No usable step-resume metadata at {meta_file}; falling back (last "
                "completed epoch if one exists, else weights-only step restore)"
            )
            return None

    def _read_resume_meta(self, step: int) -> dict | None:
        """Root-only: read + validate the JSON resume sidecar for ``step``.
        Returns None (with a logged warning) on a missing/corrupt/ill-typed
        file — the caller degrades to Orbax-only resume."""
        import json

        from .utils.serialization import from_jsonable

        meta_file = self.pipeline.checkpoint_dir.path / "meta" / self.name / f"{step}.json"
        try:
            raw = json.loads(meta_file.read_text())
            meta = {
                "epoch": int(raw["epoch"]),
                "stopped": bool(raw["stopped"]),
                "tracker": from_jsonable(raw["tracker"]),
            }
            if meta["tracker"] is not None:
                # full validation: load into a throwaway tracker so a
                # structurally incomplete sidecar degrades here (to
                # Orbax-only resume) instead of crashing the real restore
                MetricTracker().load_state_dict(meta["tracker"])
            return meta
        except FileNotFoundError:
            legacy = meta_file.with_suffix(".pkl")
            if legacy.exists():
                self.logger.warning(
                    f"Found legacy pickle resume sidecar {legacy}; it is ignored (pickle "
                    "loading executes arbitrary code). Metric history and early-stop flag "
                    "start fresh; training state itself is fully restored from Orbax."
                )
            else:
                self.logger.warning(
                    f"No resume metadata at {meta_file}; continuing from the Orbax step alone "
                    "(metric history and early-stop flag are lost)"
                )
        except Exception:
            self.logger.warning(
                f"Corrupt resume metadata {meta_file}; continuing from the Orbax step alone "
                "(metric history and early-stop flag are lost)"
            )
        return None

    def _restore_tree(self, scope: str, key: int) -> dict:
        """Restore the state pytree from ``scope``/``key``, tolerating the
        one legitimate structure drift: ``ema_decay()`` toggled since the
        checkpoint was written. Any other mismatch re-raises."""
        ckpt = self.pipeline.checkpoint_dir
        template = self._state_pytree()
        try:
            return ckpt.restore_state(key, template=template, scope=scope)
        except Exception as err:
            alt = {k: v for k, v in template.items() if k != "ema"}
            if "ema" not in template:
                # abstract template leaves: no device allocation for a tree
                # that exists only to satisfy the structure match (its
                # restored arrays are dropped below)
                alt["ema"] = jax.tree_util.tree_map(
                    lambda x: jax.ShapeDtypeStruct(
                        x.shape,
                        jnp.float32 if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x.dtype,
                    ),
                    template["params"],
                )
            try:
                restored = ckpt.restore_state(key, template=alt, scope=scope)
            except Exception:
                raise err from None
            if "ema" in template:
                self.logger.warning(
                    f"Checkpoint {key} for scope '{scope}' has no EMA tree "
                    "(ema_decay() was enabled after it was written); the shadow restarts "
                    "from the restored params"
                )
            else:
                self.logger.warning(
                    f"Checkpoint {key} for scope '{scope}' carries an EMA tree but "
                    "ema_decay() is now 0; the shadow is dropped"
                )
                restored.pop("ema", None)
            return restored

    def _restore_state(self):
        ckpt = self.pipeline.checkpoint_dir
        if ckpt is None or self.state is None:
            return
        # manual epoch checkpointing (checkpoint_every()==0) owns its scope's
        # keys — they need not be epoch numbers, so only step saves are
        # considered for automatic resume in that mode
        latest = ckpt.latest_step(scope=self.name) if int(self.checkpoint_every()) > 0 else None
        # a step-granular save mid-epoch may be fresher than the last
        # completed epoch (its sidecar records the epoch it was inside)
        step_meta = step_latest = None
        if int(self.checkpoint_every_steps()) > 0:
            step_latest = ckpt.latest_step(scope=self._steps_scope)
            if step_latest is not None:
                sm = self._read_step_resume_meta(step_latest) if is_root() else None
                sm = runtime.broadcast_object(sm)
                if sm is not None and sm["epoch"] > (latest or 0):
                    step_meta = sm
        # no epoch save to fall back on but a step save exists (step-only
        # mode, or a crash before the first epoch completed) with unusable
        # position metadata: restore the WEIGHTS rather than silently
        # training from scratch into the same checkpoint dir
        blind_step = latest is None and step_meta is None and step_latest is not None
        if latest is None and step_meta is None and not blind_step:
            return  # e.g. crash before this stage's first save
        if step_meta is not None or blind_step:
            restored = self._restore_tree(self._steps_scope, step_latest)
        else:
            restored = self._restore_tree(self.name, latest)
        self.state = self.state.replace(**restored)
        if self.state.ema is not None and "ema" not in restored:
            # EMA newly enabled on a resumed run: average from the restored
            # params, not the random init the fresh state copied
            from .train_state import ema_like

            self.state = self.state.replace(ema=ema_like(self.state.params))
        # The root alone reads and validates the sidecar, then broadcasts the
        # resolved (epoch, stopped, tracker) — if every process read its own
        # copy, a corrupt/missing file on SOME hosts would leave them with
        # different epoch counters and stop flags, so some hosts enter the
        # epoch loop's collectives while others skip it: divergence, then
        # deadlock. Same root-decides pattern as enable_checkpointing.
        if latest is not None:
            meta = self._read_resume_meta(latest) if is_root() else None
            meta = runtime.broadcast_object(meta)
        else:
            meta = None
        if meta is not None:
            if meta["tracker"] is not None:
                self.tracker.load_state_dict(meta["tracker"])
            self.current_epoch = meta["epoch"] + 1
            # a stage that had already stopped early must not re-train
            self._stop_requested = meta["stopped"]
        elif latest is not None:
            self.current_epoch = latest + 1
        if step_meta is not None:
            self.current_epoch = step_meta["epoch"]
            # elastic world-size scaling: the sidecar's batch count is
            # per-rank UNDER THE SAVED world size; re-derive this run's
            # per-rank skip from the world-size-independent global count
            saved_ws = int(step_meta.get("world_size", runtime.world_size()))
            ws = runtime.world_size()
            global_batches = step_meta["step_in_epoch"] * saved_ws
            skip, rem = divmod(global_batches, ws)
            if rem:
                self.logger.warning(
                    f"mid-epoch resume: {global_batches} globally-consumed batches do "
                    f"not divide the new world size {ws}; rounding down (up to "
                    f"{ws - 1} global batch(es) replay)"
                )
            self._resume_skip_steps = skip
            self._resume_data_state = step_meta.get("data")
            # sparse checkpoint_every (>1): the restored tracker may trail
            # the resumed epoch — pad the gap (None entries) so every later
            # epoch's metrics stay aligned with its epoch number
            self.tracker.fast_forward(self.current_epoch)
            self.logger.info(
                f"Restored stage '{self.name}' from mid-epoch step save (global step "
                f"{step_latest}); continuing epoch {self.current_epoch} at batch "
                f"{self._resume_skip_steps}"
                + (f" (resharded from world size {saved_ws})" if saved_ws != ws else "")
            )
        elif blind_step:
            self.logger.warning(
                f"Restored stage '{self.name}' WEIGHTS from step save {step_latest} but its "
                "position metadata was unusable: the epoch loop restarts at epoch "
                f"{self.current_epoch} on the restored state"
            )
        else:
            self.logger.info(
                f"Restored stage '{self.name}' state from epoch {latest}; continuing at epoch {self.current_epoch}"
            )

    def _cost_analysis_flops(self) -> float:
        """MFU fallback when ``step_flops()`` is not declared: whole-mesh
        FLOPs of one step from the AOT-compiled executable's own XLA cost
        analysis (0.0 when no compiled executable or no counter — the MFU
        metric is then skipped, never invented). Cached: the analysis is
        signature-independent to first order."""
        if self._cost_flops is None:
            val = 0.0
            if self._train_compiled is not None:
                exe = self._train_compiled.any_compiled()
                if exe is not None:
                    from .telemetry.goodput import flops_from_compiled

                    val = flops_from_compiled(exe, n_devices=int(self.mesh.devices.size)) or 0.0
            self._cost_flops = val
        return self._cost_flops

    def run_epoch(self):
        self.train_epoch()
        if self._mid_epoch_exit:
            return  # preempted at a step boundary: no val on a partial epoch
        self.val_epoch()

    def _put(self, batch):
        """Move a host batch onto the mesh with batch sharding; pass through
        anything already device-resident."""
        return mesh_lib.make_global_batch(batch, self.mesh)

    def _feed(self, ds):
        """The device feeding path: mesh-sharded batches with
        ``prefetch_depth()`` transfers in flight ahead of the step — and
        optionally ``host_prefetch()`` host batches prepared on a background
        thread (data/device.py) — or per-step synchronous puts when disabled.
        With ``buckets()`` armed, batches are bucket-padded (+ mask) on host
        BEFORE the transfer, so the device only ever sees bucket shapes."""
        if self._telemetry_armed:
            ds = self._count_padding(ds)
        if self._buckets_resolved:
            from .compile.buckets import bucket_iterator

            ds = bucket_iterator(ds, self._buckets_resolved, mask_key=self.bucket_mask_key())
        prefetch = int(self.prefetch_depth())
        if prefetch > 0:
            from .data.device import device_iterator

            return device_iterator(
                ds, self.mesh, prefetch=prefetch, host_prefetch=int(self.host_prefetch())
            )
        return (self._put(batch) for batch in ds)

    def _count_padding(self, ds):
        """Account padding in HOST batches that carry ``segment_ids`` (the
        packed/pad-masked input contract, doc/data.md): slots with id 0 are
        padding — FLOPs the step burns without learning. Feeds
        ``misc/pad_fraction`` and the goodput advisor's "enable
        pack_stream" suggestion. Telemetry-armed runs only (one numpy
        compare per batch, before any device transfer); non-numpy leaves
        (already-on-device batches) are left untouched — no implicit D2H."""
        for batch in ds:
            if isinstance(batch, dict):
                seg = batch.get("segment_ids")
                if isinstance(seg, np.ndarray) and seg.size:
                    self._gp_pad_slots += int(np.count_nonzero(seg == 0))
                    self._gp_token_slots += int(seg.size)
            yield batch

    def _timed_feed(self, ds):
        """``_feed`` with each ``next()`` timed as the goodput ledger's
        data_wait bucket (+ a journal span per batch). Only interposed when
        telemetry is armed — the default feeding path is untouched."""
        it = iter(self._feed(ds))
        try:
            while True:
                t0 = time.perf_counter()
                try:
                    batch = next(it)
                except StopIteration:
                    return
                t1 = time.perf_counter()
                self._gp_data_wait_ns += int((t1 - t0) * 1e9)
                _journal.emit("data_wait", t0, t1)
                yield batch
        finally:
            # abandonment (preemption drain) must reach the device iterator's
            # own shutdown path promptly, not wait for GC
            close = getattr(it, "close", None)
            if close is not None:
                close()

    def _feed_for_epoch(self, ds):
        return self._timed_feed(ds) if self._telemetry_armed else self._feed(ds)

    def train_epoch(self):
        self.is_train = True
        self.metric_prefix = self.train_metric_prefix()

        train_ds = self.train_dataset()
        if hasattr(train_ds, "set_epoch"):
            train_ds.set_epoch(self.current_epoch)
        elif hasattr(train_ds, "sampler") and hasattr(getattr(train_ds, "sampler"), "set_epoch"):
            train_ds.sampler.set_epoch(self.current_epoch)

        # mid-epoch resume: fast-forward the deterministic per-epoch
        # iteration past the batches the interrupted run already consumed
        # (host-side skip — no device transfers for skipped batches). A
        # resumable dataset (DataPipeline.load_state_dict) fast-forwards
        # itself from the saved iterator state — same elements, but the
        # cursor survives world-size changes and future step saves keep
        # checkpointing coherent offsets.
        skipped = self._resume_skip_steps
        self._resume_skip_steps = 0
        data_state = self._resume_data_state
        self._resume_data_state = None
        if data_state is not None and hasattr(train_ds, "load_state_dict"):
            train_ds.load_state_dict(data_state)
            self.logger.info(
                f"mid-epoch resume: train dataset fast-forwarded from saved iterator "
                f"state {data_state} for epoch {self.current_epoch}"
            )
        elif skipped:
            import itertools

            train_ds = itertools.islice(iter(train_ds), skipped, None)
            self.logger.info(
                f"mid-epoch resume: skipping the first {skipped} batches of epoch {self.current_epoch}"
            )
        every_steps = int(self.checkpoint_every_steps())
        if self.pipeline.checkpoint_dir is None:
            every_steps = 0

        # Deferred-readback plumbing (overlap engine). Deferred (default):
        # losses ride a short rolling window of device arrays whose D2H
        # copies are issued non-blocking at dispatch time; the host touches
        # a value only every log_every() steps — a 2-3-step-TRAILING fetch
        # that is already computed AND already copied, so the sync point
        # costs ~nothing. The NaN/inf guard and the live console EMA both
        # piggyback on that one periodic fetch. Eager (deferred_metrics()
        # False, the bisection baseline): every step's metrics are pulled to
        # host immediately, timed as stall.
        live = self.table.live_target() is not None
        deferred = bool(self.deferred_metrics())
        log_every = int(self.log_every())
        guard = bool(self.nan_guard())
        loss_name = self.loss_metric_name()
        pending_losses: list = []
        loss_ema = None
        steps_done = 0
        epoch_t0 = time.perf_counter()
        last_render = 0.0

        def _guard_loss(v: float, at_step: int) -> None:
            if guard and not np.isfinite(v):
                raise FloatingPointError(
                    f"non-finite loss ({v}) detected at step {at_step} of epoch "
                    f"{self.current_epoch} (stage {self.name!r})"
                )

        last_metrics = None
        self._in_step_loop = True
        feed = self._feed_for_epoch(train_ds)
        try:
            for batch in feed:
                step_start = time.perf_counter_ns()
                self.state, metrics = self._train_step_fn(self.state, batch)
                step_end = time.perf_counter_ns()
                _journal.emit(
                    "step_dispatch", step_start / 1e9, step_end / 1e9, step=steps_done + 1
                )

                if not deferred:
                    with self._stall.measure(label="metric_readback"):  # eager per-step readback
                        metrics = jax.device_get(metrics)
                for mname, mval in metrics.items():
                    self.track_reduce(mname, mval)
                self.track_reduce("misc/total_train_batches", 1, reduction=Reduction.SUM, prefixed=False)
                self.track_reduce(
                    "misc/worker_train_batches", 1, reduction=Reduction.SUM, reduce_globally=False, prefixed=False
                )
                # dispatch-to-dispatch time: how long the host took to enqueue the
                # step. Under async dispatch this is NOT device execution time —
                # see misc/train_step_avg_ms for the wall-clock per-step average.
                self.track_reduce("misc/step_dispatch_ms", (step_end - step_start) / 1e6, prefixed=False)
                last_metrics = metrics

                steps_done += 1
                if every_steps and (skipped + steps_done) % every_steps == 0:
                    self._save_step_state(skipped + steps_done)
                    if self.pipeline._preemption_coordinated():
                        # the save just above is the resume point; cut the epoch
                        # here instead of finishing it (Stage.run handles exit)
                        self._mid_epoch_exit = True
                        break

                loss_val = metrics.get(loss_name)
                if deferred:
                    if loss_val is not None and (live or (guard and log_every > 0)):
                        copy_async = getattr(loss_val, "copy_to_host_async", None)
                        if copy_async is not None:
                            try:
                                copy_async()
                            except Exception:
                                pass
                        pending_losses.append(loss_val)
                        if len(pending_losses) > 3:
                            pending_losses.pop(0)
                    if log_every > 0 and steps_done % log_every == 0 and pending_losses:
                        v = float(self._stall.fetch(pending_losses[0]))
                        loss_ema = v if loss_ema is None else 0.98 * loss_ema + 0.02 * v
                        _guard_loss(v, steps_done)
                elif loss_val is not None:
                    # eager bisection path: the value is already host-side
                    # (fetched under the stall timer in the device_get above)
                    # dmllint: disable-next-line=DML101 -- converts, not syncs
                    v = float(np.asarray(loss_val))
                    loss_ema = v if loss_ema is None else 0.98 * loss_ema + 0.02 * v
                    _guard_loss(v, steps_done)

                if live:
                    now = time.perf_counter()
                    if now - last_render > 0.25:
                        self.table.live(
                            {
                                "Epoch": self.current_epoch,
                                "[Train] Loss": loss_ema,
                                "it/s": steps_done / max(now - epoch_t0, 1e-9),
                            }
                        )
                        last_render = now
        finally:
            self._in_step_loop = False
            # deterministic feed shutdown: a break (mid-epoch preemption
            # drain) must stop the prefetch machinery NOW — its background
            # thread joins within one put timeout — not at GC time
            close = getattr(feed, "close", None)
            if close is not None:
                close()

        # Close the async pipeline BEFORE the epoch wall-clock reading so the
        # per-step average below reflects device execution, then derive the
        # honest number users actually want from "step time". This is THE
        # epoch sync point: past this line every dispatched step has
        # executed and host-side state (tracker buffers, self.state) is
        # guaranteed current.
        if last_metrics is not None:
            self._stall.block(last_metrics)
        if self._mid_epoch_exit:
            # partial epoch: skip epoch-level metrics — the resumed run
            # finishes the epoch and reduces over its remaining steps
            return
        train_elapsed = time.perf_counter() - epoch_t0
        if steps_done:
            self.track("misc/train_step_avg_ms", train_elapsed / steps_done * 1e3, prefixed=False)
            flops = float(self.step_flops())
            if flops <= 0 and self._telemetry_armed:
                flops = self._cost_analysis_flops()
            if flops > 0:
                from .utils.profiling import peak_flops_for_kind

                kind = jax.local_devices()[0].device_kind
                peak = peak_flops_for_kind(kind)
                if peak is None:
                    # no honest denominator for this backend (CPU/GPU dev
                    # runs): skip the metric rather than log a fiction
                    if not getattr(self, "_warned_mfu_peak", False):
                        self._warned_mfu_peak = True
                        self.logger.warning(
                            f"device kind {kind!r} is not in the bf16 peak table; "
                            "misc/mfu will not be tracked on this backend"
                        )
                else:
                    peak_total = peak * int(self.mesh.devices.size)
                    self.track(
                        "misc/mfu", flops * steps_done / train_elapsed / peak_total, prefixed=False
                    )
        self.table["it/s"] = steps_done / max(train_elapsed, 1e-9)

        for name, schedule in self.pipeline.schedulers.items():
            if self.state is not None:
                with self._stall.measure(label="metric_readback"):
                    step_count = int(jax.device_get(self.state.step))
            else:
                step_count = 0
            self.track(f"misc/lr_{name}", float(schedule(step_count)), prefixed=False)

    def val_epoch(self):
        self.is_train = False
        self.metric_prefix = self.val_metric_prefix()

        try:
            val_ds = self.val_dataset()
        except DatasetNotFoundError:
            # val dataset optional in the TPU build. ONLY the sentinel is
            # swallowed — an arbitrary ValueError raised by a user
            # val_dataset() override is a bug and must surface, not silently
            # skip validation forever.
            return

        deferred = bool(self.deferred_metrics())
        last_metrics = None
        for batch in self._feed_for_epoch(val_ds):
            metrics = self._val_step_fn(self.state, batch)
            if not deferred:
                with self._stall.measure(label="metric_readback"):  # eager per-step readback
                    metrics = jax.device_get(metrics)
            for mname, mval in metrics.items():
                self.track_reduce(mname, mval)
            self.track_reduce("misc/total_val_batches", 1, reduction=Reduction.SUM, prefixed=False)
            self.track_reduce(
                "misc/worker_val_batches", 1, reduction=Reduction.SUM, reduce_globally=False, prefixed=False
            )
            last_metrics = metrics
        if last_metrics is not None:
            self._stall.block(last_metrics)

    def table_columns(self):
        columns = super().table_columns()
        columns.insert(1, {"name": "[Train] Loss", "metric": f"{self.train_metric_prefix()}/{self.loss_metric_name()}"})
        columns.insert(2, {"name": "[Val] Loss", "metric": f"{self.val_metric_prefix()}/{self.loss_metric_name()}"})
        columns.insert(3, {"name": "it/s", "metric": None})  # live + epoch average
        return columns
