"""TrainState: the single pytree that flows through the compiled train step.

The reference mutates stateful objects in place — ``nn.Module`` params, torch
optimizer slots (/root/reference/dmlcloud/stage.py:263-288). Under XLA the step
is a pure function traced once, so all mutable state is funneled through one
pytree: params, optimizer state, step counter, PRNG key. ``TrainState.create``
lays the whole tree out on the mesh according to a sharding policy
(parallel/mesh.py), which is the moment the reference would have wrapped with
DDP (pipeline.py:72-74).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .parallel import mesh as mesh_lib


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any
    rng: jax.Array
    #: non-trained mutable collections (e.g. flax ``batch_stats``); the step
    #: returns updated extras as a third output (stage.py). The TPU analog of
    #: the reference's SyncBN buffers (pipeline.py:70-71): with the batch
    #: sharded over ``data``, computing stats inside the jitted step with an
    #: ``axis_name`` psum gives synchronised statistics for free.
    #: Quantized training (``TrainValStage(precision="int8")``) also rides
    #: here: ``extras[models.quant.QUANT_AMAX_KEY]`` carries the delayed
    #: per-channel amax tree the next step's fake-quant scales derive from —
    #: training state, not a parameter, so it shards, donates, checkpoints
    #: and resumes with everything else for free.
    extras: Any = None
    #: optional exponential-moving-average shadow of ``params`` (same tree,
    #: same shapes, same shardings). Maintained by ``update_ema`` inside the
    #: compiled step; rides checkpoints like any other state leaf. The
    #: reference has no equivalent — torch users reach for a sidecar
    #: AveragedModel; here it is one fused tree_map in the step.
    ema: Any = None
    apply_fn: Callable = struct.field(pytree_node=False, default=None)
    tx: optax.GradientTransformation = struct.field(pytree_node=False, default=None)

    @classmethod
    def create(
        cls,
        *,
        apply_fn: Callable,
        params: Any,
        tx: optax.GradientTransformation,
        rng: jax.Array | int = 0,
        extras: Any = None,
        ema: Any = None,
        mesh: Mesh | None = None,
        policy: Any = "replicate",
    ) -> "TrainState":
        """Build and (if ``mesh`` is given) shard the full train state.

        ``policy`` follows ``parallel.mesh.make_param_policy``: 'replicate'
        (DDP semantics), 'fsdp' (ZeRO-3), T5X-style rule list, or a callable.
        Optimizer slots that mirror a param (Adam moments) inherit its
        sharding; scalar slots are replicated.

        ``ema=True`` starts the shadow average as a FLOAT32 copy of
        ``params`` (the standard init — the average is immediately usable;
        fp32 because a low-precision shadow quantises away the ``(1-d)*p``
        increments that make an EMA an EMA); a pytree starts it explicitly
        in its own dtypes.
        """
        if isinstance(rng, int):
            rng = jax.random.PRNGKey(rng)
        if ema is True:
            ema = ema_like(params)
        opt_state = tx.init(params)
        state = cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=opt_state,
            rng=rng,
            extras=extras,
            ema=ema,
            apply_fn=apply_fn,
            tx=tx,
        )
        if mesh is not None:
            state = jax.device_put(state, state.shardings(mesh, policy))
        return state

    def shardings(self, mesh: Mesh, policy: Any = "replicate") -> "TrainState":
        """A TrainState-shaped tree of NamedShardings (for jit in/out_shardings)."""
        param_sh = mesh_lib.sharding_for(self.params, mesh, policy)
        opt_sh = _opt_state_shardings(self.opt_state, self.params, param_sh, mesh)
        rep = NamedSharding(mesh, P())
        extras_sh = (
            mesh_lib.sharding_for(self.extras, mesh, policy) if self.extras is not None else None
        )
        # the EMA tree mirrors params exactly, so it inherits their shardings
        ema_sh = param_sh if self.ema is not None else None
        return self.replace(
            step=rep, params=param_sh, opt_state=opt_sh, rng=rep, extras=extras_sh, ema=ema_sh
        )

    def apply_gradients(self, grads: Any) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(
            step=self.step + 1,
            params=new_params,
            opt_state=new_opt_state,
        )

    def update_ema(self, decay: float) -> "TrainState":
        """Fold the current params into the EMA: ``ema = d*ema + (1-d)*p``.

        Traced (runs inside the compiled step — one fused tree_map, no extra
        HBM round trips). The blend always accumulates in float32, then casts
        back to the EMA leaf's dtype: in bf16, decay >= 0.996 rounds to
        exactly 1.0 and the whole update would silently vanish. (A bf16
        SHADOW still quantises each store — keep the shadow fp32, as
        ``create(ema=True)`` does, when params are low-precision.)
        No-op when no EMA tree is attached."""
        if self.ema is None:
            return self
        d = jnp.float32(decay)

        def blend(e, p):
            # non-float leaves can't average (an int blend through fp32
            # truncates back to its old value forever) — they track params
            if not jnp.issubdtype(e.dtype, jnp.floating):
                return p.astype(e.dtype)
            return (d * e.astype(jnp.float32) + (1.0 - d) * p.astype(jnp.float32)).astype(e.dtype)

        new_ema = jax.tree_util.tree_map(blend, self.ema, self.params)
        return self.replace(ema=new_ema)


def ema_like(params: Any) -> Any:
    """A fresh fp32 EMA tree initialised from ``params``.

    Float leaves are upcast to float32 (a low-precision shadow quantises
    away the ``(1-d)*p`` increments); others copy as-is. Always COPIES —
    an EMA that aliases a param buffer breaks the train step's donation."""
    return jax.tree_util.tree_map(
        lambda x: (
            jnp.array(x, jnp.float32, copy=True)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
            else jnp.copy(x)
        ),
        params,
    )


def _opt_state_shardings(opt_state: Any, params: Any, param_shardings: Any, mesh: Mesh) -> Any:
    """Sharding tree for optimizer state.

    Optimizer slots that mirror the params (Adam mu/nu, momentum) are matched
    STRUCTURALLY: optax lays them out as subtrees with exactly the params'
    tree structure, so any such subtree inherits the param shardings
    one-for-one. This is exact even when two same-shaped params carry
    different specs (a (shape, dtype) heuristic would silently give both the
    first-seen layout).

    Leaves that are not part of a param-shaped subtree (step counts, scalar
    hyperparams, ``optax.masked`` remnants) are replicated — except that a
    non-scalar stray leaf whose (shape, dtype) maps to exactly ONE param spec
    still inherits it (unambiguous fallback, e.g. moments inside a masked
    wrapper whose MaskedNode placeholders break the structure match)."""
    rep = NamedSharding(mesh, P())
    tu = jax.tree_util
    params_def = tu.tree_structure(params)
    param_leaves = tu.tree_leaves(params)
    shard_leaves = tu.tree_leaves(param_shardings)
    param_shapes = [getattr(p, "shape", ()) for p in param_leaves]

    # (shape, dtype) -> spec, but only where unambiguous across all params
    _AMBIG = object()
    shape_map: dict[tuple, Any] = {}
    for p, s in zip(param_leaves, shard_leaves):
        key = (getattr(p, "shape", ()), getattr(p, "dtype", None))
        if shape_map.get(key, s) != s:
            shape_map[key] = _AMBIG
        else:
            shape_map.setdefault(key, s)

    def is_param_shaped(node: Any) -> bool:
        if tu.tree_structure(node) != params_def:
            return False
        leaves = tu.tree_leaves(node)
        return all(getattr(x, "shape", ()) == shp for x, shp in zip(leaves, param_shapes))

    def assign(node: Any) -> Any:
        if is_param_shaped(node):
            return tu.tree_unflatten(params_def, shard_leaves)
        # one-level decomposition: children of this node, or the node itself
        # when it is already a leaf
        children, treedef = tu.tree_flatten(node, is_leaf=lambda x: x is not node)
        if len(children) == 1 and children[0] is node:
            shape = getattr(node, "shape", ())
            if shape:  # non-scalar stray leaf: unambiguous shape fallback
                spec = shape_map.get((shape, getattr(node, "dtype", None)), rep)
                return rep if spec is _AMBIG else spec
            return rep
        return tu.tree_unflatten(treedef, [assign(c) for c in children])

    return assign(opt_state)
