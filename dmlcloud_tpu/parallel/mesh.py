"""Mesh & sharding core — the TPU-native replacement for DDP.

The reference's only parallelism is data-parallel DDP wrapping
(/root/reference/dmlcloud/pipeline.py:72-74) with NCCL bucketed allreduce.
Here the first-class object is a ``jax.sharding.Mesh`` over the device grid:
the batch is sharded over the ``data`` (and ``fsdp``) axes, parameters are
placed by a sharding *policy* (replicated == DDP; ``fsdp`` == ZeRO-3; explicit
rules == tensor parallelism), and the gradient allreduce is emitted by XLA as
a fused psum over ICI inside the compiled step — no hook machinery.

Axes are named, and every higher layer speaks these names:

- ``data``  — pure data parallelism (batch sharding)
- ``fsdp``  — parameter-sharded data parallelism (batch + params sharded)
- ``model`` — tensor parallelism (attention heads / mlp hidden)
- ``seq``   — sequence/context parallelism (ring attention, ops/ring_attention.py)
- ``expert``— expert parallelism for MoE layers
- ``pipe``  — pipeline parallelism stages

A single-axis ``data`` mesh over all devices reproduces the reference's DDP
semantics exactly (replicated params, batch split, mean-reduced grads).
"""

from __future__ import annotations

import logging
import math
import re
from typing import Any, Callable, Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_logger = logging.getLogger("dmlcloud_tpu")

DATA, FSDP, MODEL, SEQ, EXPERT, PIPE = "data", "fsdp", "model", "seq", "expert", "pipe"

#: rule list: (regex over '/'-joined param path, PartitionSpec)
PartitionRules = Sequence[tuple[str, P]]


def parse_mesh_axes(spec: str) -> dict[str, int]:
    """Parse a CLI mesh spec like ``'data=2,fsdp=4'`` into an axes dict for
    :func:`create_mesh` / ``TrainingPipeline.set_mesh`` (``-1`` absorbs the
    remaining devices). One shared parser so every example/CLI rejects a
    malformed spec with the same actionable error."""
    axes: dict[str, int] = {}
    for part in spec.split(","):
        name, eq, size = part.partition("=")
        name = name.strip()
        try:
            if not (name and eq):
                raise ValueError
            parsed = int(size)
        except ValueError:
            raise ValueError(
                f"malformed mesh spec {spec!r}: expected comma-separated name=int "
                f"pairs like 'data=2,fsdp=4' (bad part: {part!r})"
            ) from None
        if name in axes:
            # a duplicate would silently drop the first size (dict overwrite)
            # — e.g. 'data=2,data=4' becoming {'data': 4}
            raise ValueError(f"malformed mesh spec {spec!r}: axis {name!r} given more than once")
        axes[name] = parsed
    return axes


def create_mesh(
    axes: Mapping[str, int] | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a named device mesh.

    ``axes`` maps axis name -> size; one axis may be ``-1`` to absorb all
    remaining devices. Default: ``{'data': -1}`` — the DDP-equivalent mesh.
    Uses ``mesh_utils.create_device_mesh`` when the shape matches the full
    device count so the ICI topology is respected.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if axes is None:
        axes = {DATA: -1}
    names = list(axes.keys())
    sizes = list(axes.values())
    n = len(devices)
    if sizes.count(-1) > 1:
        raise ValueError("at most one mesh axis may be -1")
    if -1 in sizes:
        known = math.prod(s for s in sizes if s != -1)
        if n % known != 0:
            raise ValueError(f"{n} devices not divisible by fixed axes product {known}")
        sizes[sizes.index(-1)] = n // known
    if math.prod(sizes) != n:
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {math.prod(sizes)} devices, have {n}")
    try:
        from jax.experimental import mesh_utils

        grid = mesh_utils.create_device_mesh(tuple(sizes), devices=devices)
    except Exception:
        grid = np.array(devices).reshape(tuple(sizes))
    return Mesh(grid, tuple(names))


def auto_mesh(
    n_devices: int | None = None,
    axis_names: Sequence[str] = (DATA, FSDP, MODEL),
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Factorize ``n_devices`` over ``axis_names`` (greedy powers of two,
    leading axes get the larger factors) — used by dry-runs and quick starts."""
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    sizes = [1] * len(axis_names)
    rem, i = n, 0
    # round-robin factor assignment: split off smallest prime factors one at a time
    while rem > 1:
        for p in (2, 3, 5, 7, 11, 13):
            if rem % p == 0:
                sizes[i % len(sizes)] *= p
                rem //= p
                break
        else:
            sizes[i % len(sizes)] *= rem
            rem = 1
        i += 1
    return create_mesh(dict(zip(axis_names, sizes)), devices=devices)


def data_axes(mesh: Mesh) -> tuple[str, ...]:
    """The axes the batch dimension is sharded over: ``data`` plus ``fsdp``
    when present (standard FSDP batch layout)."""
    return tuple(a for a in (DATA, FSDP) if a in mesh.axis_names)


def batch_pspec(mesh: Mesh) -> P:
    ax = data_axes(mesh)
    return P(ax if len(ax) > 1 else (ax[0] if ax else None))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, batch_pspec(mesh))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_parallel_size(mesh: Mesh) -> int:
    return int(math.prod(mesh.shape[a] for a in data_axes(mesh)) or 1)


def shard_map_compat(fn, *, mesh: Mesh, in_specs, out_specs):
    """``jax.shard_map`` across jax versions: newer releases expose it at the
    top level (replication check flag ``check_vma``); 0.4.x ships it as
    ``jax.experimental.shard_map.shard_map`` with the flag named
    ``check_rep``. Both callers here disable the check (their collectives
    intentionally produce per-shard values)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
    )


def respec_for_mesh(spec: P | Sequence, shape: Sequence[int], mesh: Mesh) -> P:
    """Re-target a PartitionSpec recorded on ONE mesh onto ``mesh`` — the
    elastic-resume primitive: a checkpoint saved on an N-device mesh carries
    each leaf's spec, and the resumed run rebuilds shardings for whatever
    mesh it actually got. Axes the new mesh lacks are dropped (replicated);
    axes that no longer divide their dim (the axis grew, e.g. fsdp 2 -> 8 on
    a dim of 4) are relocated to another divisible dim when one exists, else
    dropped with a warning. Always returns a spec valid on ``mesh``."""
    entries = list(spec) if spec is not None else []
    shape = tuple(shape)
    cleaned: list = [None] * len(shape)
    displaced: list = []
    for i, a in enumerate(entries[: len(shape)]):
        axes = (a,) if isinstance(a, str) else (a or ())
        if a is None or not axes or not all(x in mesh.axis_names for x in axes):
            continue
        n = math.prod(mesh.shape[x] for x in axes)
        if shape[i] % n == 0:
            cleaned[i] = a
        else:
            displaced.append((a, n))
    for a, n in displaced:
        for i in sorted(range(len(shape)), key=lambda i: -shape[i]):
            if cleaned[i] is None and shape[i] % n == 0 and shape[i] >= 2 * n:
                cleaned[i] = a
                break
        else:
            _logger.warning(
                "restore respec: no dim of shape %s divisible by saved axis %r "
                "(size %d on the new mesh); restoring that axis replicated",
                shape, a, n,
            )
    return P(*cleaned)


def spec_to_jsonable(spec: P | None) -> list:
    """A PartitionSpec as a JSON-serialisable list (None | str | [str, ...]
    per dim) — the sharding-sidecar wire format (checkpoint.py)."""
    out: list = []
    for a in (spec or ()):
        if a is None or isinstance(a, str):
            out.append(a)
        else:
            out.append(list(a))
    return out


def spec_from_jsonable(entries: Sequence) -> P:
    """Inverse of :func:`spec_to_jsonable`."""
    return P(*[tuple(a) if isinstance(a, list) else a for a in (entries or ())])


# ---------------------------------------------------------------------------
# parameter sharding policies
# ---------------------------------------------------------------------------

def path_str(path) -> str:
    """'/'-joined pytree key path (dict keys, attr names, sequence indices)
    — the string that sharding rules, LoRA matchers, and quantization
    matchers all run their regexes against."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _fsdp_spec(x: Any, mesh: Mesh, axis: str = FSDP, min_size: int = 2**14) -> P:
    """Shard the largest divisible dim of ``x`` over the fsdp axis; tiny or
    indivisible params stay replicated (they cost nothing)."""
    shape = getattr(x, "shape", ())
    size = int(np.prod(shape)) if shape else 0
    n = mesh.shape.get(axis, 1)
    if n <= 1 or size < min_size:
        return P()
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in order:
        if shape[i] % n == 0:
            spec = [None] * len(shape)
            spec[i] = axis
            return P(*spec)
    return P()


def make_param_policy(policy: str | PartitionRules | Callable[[str, Any], P]) -> Callable[[str, Any, Mesh], P]:
    """Normalise a sharding policy to ``(path, leaf, mesh) -> PartitionSpec``.

    - ``'replicate'``: every param replicated (DDP semantics).
    - ``'fsdp'``: largest divisible dim sharded over the ``fsdp`` axis (ZeRO-3).
    - rule list ``[(regex, PartitionSpec), ...]``: first match wins, falling
      back to fsdp-or-replicate for unmatched params (T5X-style rules — this
      is how tensor parallelism is expressed).
    - callable ``(path, leaf) -> PartitionSpec``.
    """
    if callable(policy):
        return lambda path, leaf, mesh: policy(path, leaf)
    if policy == "replicate":
        return lambda path, leaf, mesh: P()
    if policy == "fsdp":
        return lambda path, leaf, mesh: _fsdp_spec(leaf, mesh)
    if isinstance(policy, (list, tuple)):
        rules = [(re.compile(pat), spec) for pat, spec in policy]

        def apply_rules(path: str, leaf: Any, mesh: Mesh) -> P:
            for pat, spec in rules:
                if pat.search(path):
                    # Drop axes the mesh doesn't have (lets one rule set serve
                    # many meshes). Axes that don't divide their param dim get
                    # relocated to another divisible dim if one exists (e.g. a
                    # 30522-row word table on fsdp=4 moves the fsdp shards to
                    # the hidden dim), else dropped with a warning — the rule
                    # must also cover e.g. a 2-row type table without crashing.
                    shape = getattr(leaf, "shape", ())
                    cleaned: list = []
                    displaced: list = []
                    for i, a in enumerate(spec):
                        axes = (a,) if isinstance(a, str) else a
                        if a is None or not all(x in mesh.axis_names for x in axes):
                            cleaned.append(None)
                            continue
                        n = math.prod(mesh.shape[x] for x in axes)
                        if i < len(shape) and shape[i] % n == 0:
                            cleaned.append(a)
                        else:
                            cleaned.append(None)
                            displaced.append((a, n))
                    if displaced:
                        cleaned += [None] * (len(shape) - len(cleaned))
                    for a, n in displaced:
                        for i in sorted(range(len(shape)), key=lambda i: -shape[i]):
                            if cleaned[i] is None and shape[i] % n == 0 and shape[i] >= 2 * n:
                                cleaned[i] = a
                                _logger.info(
                                    "param %s: axis %r (size %d) does not divide its rule dim; "
                                    "relocated to dim %d of shape %s",
                                    path, a, n, i, tuple(shape),
                                )
                                break
                        else:
                            _logger.warning(
                                "param %s: no dim of shape %s divisible by axis %r "
                                "(size %d); leaving that axis unsharded (replicated)",
                                path, tuple(shape), a, n,
                            )
                    return P(*cleaned)
            return _fsdp_spec(leaf, mesh) if FSDP in mesh.axis_names else P()

        return apply_rules
    raise ValueError(f"unknown sharding policy: {policy!r}")


def sharding_for(tree: Any, mesh: Mesh, policy: str | PartitionRules | Callable = "replicate") -> Any:
    """A pytree of NamedShardings matching ``tree`` under ``policy`` — feed to
    ``jax.jit(in_shardings=...)`` or ``jax.device_put``."""
    fn = make_param_policy(policy)
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, fn(path_str(path), leaf, mesh)), tree
    )


def shard_pytree(tree: Any, mesh: Mesh, policy: str | PartitionRules | Callable = "replicate") -> Any:
    """Place ``tree`` on the mesh under ``policy`` (the moment the reference
    wraps with DDP, pipeline.py:72-74, we instead lay params out on the mesh)."""
    return jax.device_put(tree, sharding_for(tree, mesh, policy))


def make_global_batch(batch: Any, mesh: Mesh, pspec: P | None = None) -> Any:
    """Form a globally-sharded jax.Array from per-process host data.

    Single-process: a plain sharded ``device_put``. Multi-process:
    ``jax.make_array_from_process_local_data`` stitches each host's shard into
    one global array — the moment the reference relied on DistributedSampler
    to keep per-rank batches disjoint, we instead declare the global batch.
    """
    if pspec is None:
        pspec = batch_pspec(mesh)
    sharding = NamedSharding(mesh, pspec)

    def put(x):
        if isinstance(x, jax.Array):
            if x.sharding == sharding:
                return x  # already laid out — pass through
            if not x.is_fully_addressable:
                return x  # already a global array (e.g. from device_iterator)
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(sharding, np.asarray(x))
        return jax.device_put(x, sharding)

    return jax.tree_util.tree_map(put, batch)
