"""L1 distributed runtime: one-call cluster bootstrap + control-plane collectives.

Capability parity with /root/reference/dmlcloud/util/distributed.py (the
``init_process_group_*`` ladder at :142-244, rank accessors :84-101, root
helpers :43-70, object collectives :121-139, deinit :247-259) — re-designed for
JAX's multi-controller runtime:

- ``torch.distributed`` process groups -> one ``jax.distributed.initialize()``
  control plane (gRPC coordination service over DCN) plus XLA collectives over
  ICI for tensor traffic.
- c10d TCPStore/HashStore rendezvous -> the jax.distributed coordinator; the
  Slurm / MPI / env-var / single-process detection ladder is preserved in
  spirit (the reference's four init paths map 1:1 onto the four ``init_*``
  functions below).
- gloo object collectives -> the coordination-service key-value store
  (rendezvous-grade small payloads, never touching device memory or ICI).
- ``monitored_barrier`` -> ``wait_at_barrier`` on the coordination client,
  which has real timeout semantics and names the barrier that timed out.

Single-process use (the reference's ``init_process_group_dummy``,
util/distributed.py:142-159) requires no initialization at all — every
accessor and collective degenerates correctly — but ``init_single()`` exists
so user code can call ``init_auto()`` unconditionally.
"""

from __future__ import annotations

import base64
import functools
import logging
import os
import pickle
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Callable

import jax
import numpy as np

from ..utils import slurm as _slurm
from ..utils.tcp import find_free_port, get_local_ips

logger = logging.getLogger("dmlcloud_tpu")

#: Default coordinator port; analog of the reference's DEFAULT_PORT=41312
#: (util/distributed.py:10), overridable via env.
DEFAULT_PORT = int(os.environ.get("DMLCLOUD_TPU_PORT", 41313))

_DEFAULT_TIMEOUT = 600.0  # seconds; matches the reference's 10-min barriers (pipeline.py:244)


@dataclass
class _WorkerInfo:
    """Cached process-level topology, set once at init (reference: the
    ``_WorkerInfo`` global at util/distributed.py:13-19)."""

    rank: int = 0
    world_size: int = 1
    local_rank: int = 0
    local_world_size: int = 1
    node: int = 0
    initialized: bool = False
    backend: str = "single"


_info = _WorkerInfo()


# ---------------------------------------------------------------------------
# predicates
# ---------------------------------------------------------------------------

def is_initialized() -> bool:
    """True once any ``init_*`` path has run."""
    return _info.initialized


def has_slurm() -> bool:
    """True inside a Slurm step (reference util/distributed.py:22-23)."""
    return _slurm.slurm_available()


def has_mpi() -> bool:
    """True if mpi4py is importable (reference util/distributed.py:30-36)."""
    try:
        import mpi4py  # noqa: F401

        return True
    except ImportError:
        return False


def has_environment() -> bool:
    """True if an explicit coordinator address is provided via env — the analog
    of the reference's MASTER_PORT probe (util/distributed.py:26-27)."""
    return "DMLCLOUD_TPU_COORDINATOR" in os.environ or "JAX_COORDINATOR_ADDRESS" in os.environ


def has_tpu_pod_env() -> bool:
    """True on a multi-host Cloud TPU pod slice, where libtpu metadata gives
    jax.distributed everything it needs with zero arguments."""
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return len([h for h in hosts.split(",") if h.strip()]) > 1


# ---------------------------------------------------------------------------
# rank accessors (reference util/distributed.py:84-101)
# ---------------------------------------------------------------------------

def rank() -> int:
    """Process rank (multi-controller index). NOTE: in JAX each process owns
    several devices; use ``device_rank``/``device_count`` for per-chip ids."""
    return _info.rank if _info.initialized else jax.process_index()


def world_size() -> int:
    """Number of controller processes."""
    return _info.world_size if _info.initialized else jax.process_count()


def local_rank() -> int:
    return _info.local_rank


def local_world_size() -> int:
    return _info.local_world_size


def local_node() -> int:
    return _info.node


def device_count() -> int:
    """Global number of accelerator devices (chips), across all processes."""
    return jax.device_count()


def local_device_count() -> int:
    return jax.local_device_count()


def is_root() -> bool:
    return rank() == 0


# ---------------------------------------------------------------------------
# root helpers (reference util/distributed.py:43-70)
# ---------------------------------------------------------------------------

def root_only(fn: Callable) -> Callable:
    """Decorator: run only on the root process; other ranks return None."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if is_root():
            return fn(*args, **kwargs)
        return None

    return wrapper


@contextmanager
def root_first():
    """Context manager: the root process executes the body first, then all
    other ranks enter after a barrier (reference util/distributed.py:55-70).
    Canonical use: dataset download."""
    if is_root():
        try:
            yield
        finally:
            barrier("root_first")
    else:
        barrier("root_first")
        yield


def print_root(*args, **kwargs) -> None:
    if is_root():
        print(*args, **kwargs)


def print_worker(*args, flush: bool = True, barrier_first: bool = False, **kwargs) -> None:
    """Print prefixed with the worker rank (reference util/distributed.py:104-112)."""
    if barrier_first:
        barrier("print_worker")
    print(f"Worker {rank()} ({local_node()}.{local_rank()}):", *args, flush=flush, **kwargs)


# ---------------------------------------------------------------------------
# init ladder (reference util/distributed.py:142-244)
# ---------------------------------------------------------------------------

def _cpu_safety_flags() -> None:
    """Disable async dispatch on the CPU backend (no effect on TPU).

    XLA:CPU shares one small thread pool across all (virtual) devices; with
    async dispatch, many in-flight programs containing collectives starve the
    40s collective rendezvous and hard-abort the process on few-core machines
    (the CI/emulation environment this backend exists for). Must run before
    the CPU client is instantiated — which is why every ``init_*`` path calls
    it first.
    """
    try:
        jax.config.update("jax_cpu_enable_async_dispatch", False)
    except AttributeError:  # pragma: no cover - flag renamed/removed upstream
        pass


def init_single() -> None:
    """Single-process fallback — the analog of ``init_process_group_dummy``
    (reference util/distributed.py:142-159). No coordination service is
    started; all collectives degenerate to identity."""
    _cpu_safety_flags()
    _info.rank = 0
    _info.world_size = 1
    _info.local_rank = 0
    _info.local_world_size = 1
    _info.node = 0
    _info.backend = "single"
    _info.initialized = True


def init_from_env(**kwargs) -> None:
    """Init from an explicit coordinator address in the environment — the
    analog of the ``env://`` torchrun path (reference util/distributed.py:237-238).

    Env contract: ``DMLCLOUD_TPU_COORDINATOR=host:port`` (or JAX's own
    ``JAX_COORDINATOR_ADDRESS``), ``DMLCLOUD_TPU_NUM_PROCESSES``,
    ``DMLCLOUD_TPU_PROCESS_ID`` (fall back to JAX's env vars, then to 1/0).
    """
    _cpu_safety_flags()
    coordinator = os.environ.get("DMLCLOUD_TPU_COORDINATOR") or os.environ.get("JAX_COORDINATOR_ADDRESS")
    nproc = int(os.environ.get("DMLCLOUD_TPU_NUM_PROCESSES") or os.environ.get("JAX_NUM_PROCESSES") or 1)
    pid = int(os.environ.get("DMLCLOUD_TPU_PROCESS_ID") or os.environ.get("JAX_PROCESS_ID") or 0)
    jax.distributed.initialize(
        coordinator_address=coordinator, num_processes=nproc, process_id=pid, **kwargs
    )
    _fill_info(pid, nproc, backend="env")


def init_tpu_pod(**kwargs) -> None:
    """Init on a Cloud TPU pod slice: libtpu metadata supplies coordinator,
    process count and id, so ``jax.distributed.initialize()`` is argument-free."""
    _cpu_safety_flags()
    jax.distributed.initialize(**kwargs)
    _fill_info(jax.process_index(), jax.process_count(), backend="tpu_pod")


def init_slurm(port: int = DEFAULT_PORT, **kwargs) -> None:
    """Init from Slurm env vars — analog of ``init_process_group_slurm``
    (reference util/distributed.py:162-177): rank/world from
    SLURM_{PROCID,NTASKS,...}, coordinator = first node of the allocation."""
    _cpu_safety_flags()
    rank_ = _slurm.slurm_rank()
    world = _slurm.slurm_world_size()
    head = _slurm.slurm_head_node()
    if rank_ is None or world is None or head is None:
        raise RuntimeError("Slurm environment incomplete (need SLURM_PROCID/SLURM_NTASKS/nodelist)")
    jax.distributed.initialize(
        coordinator_address=f"{head}:{port}", num_processes=world, process_id=rank_, **kwargs
    )
    _fill_info(
        rank_,
        world,
        local_rank=_slurm.slurm_local_rank() or 0,
        local_world=_slurm.slurm_tasks_per_node() or 1,
        node=_slurm.slurm_node_id() or 0,
        backend="slurm",
    )


def init_mpi(**kwargs) -> None:
    """Init via MPI address exchange — analog of ``init_process_group_MPI``
    (reference util/distributed.py:180-224): MPI gives rank/size; the root
    picks a free port + routable IP and broadcasts them; jax.distributed then
    rendezvouses on that address. MPI is used ONLY for the address exchange."""
    _cpu_safety_flags()
    from mpi4py import MPI

    comm = MPI.COMM_WORLD
    rank_, world = comm.Get_rank(), comm.Get_size()
    local_comm = comm.Split_type(MPI.COMM_TYPE_SHARED)
    ip, port = None, None
    if rank_ == 0:
        port = find_free_port()
        ip = get_local_ips()[0]
    ip = comm.bcast(ip, root=0)
    port = comm.bcast(port, root=0)
    comm.Barrier()
    jax.distributed.initialize(
        coordinator_address=f"{ip}:{port}", num_processes=world, process_id=rank_, **kwargs
    )
    _fill_info(
        rank_,
        world,
        local_rank=local_comm.Get_rank(),
        local_world=local_comm.Get_size(),
        node=rank_ // max(local_comm.Get_size(), 1),
        backend="mpi",
    )


def init_auto(verbose: bool = False, **kwargs) -> str:
    """Detect the launch environment and initialize the right way — the analog
    of ``init_process_group_auto`` (reference util/distributed.py:227-244).

    Ladder: explicit env coordinator -> Cloud TPU pod metadata -> Slurm ->
    MPI -> single process. Returns the chosen backend name.
    """
    if _info.initialized:
        return _info.backend
    if has_environment():
        init_from_env(**kwargs)
    elif has_tpu_pod_env():
        init_tpu_pod(**kwargs)
    elif has_slurm():
        init_slurm(**kwargs)
    elif has_mpi():
        init_mpi(**kwargs)
    else:
        init_single()
    if verbose:
        logger.info(f"initialized distributed runtime via '{_info.backend}' "
                    f"(rank {rank()}/{world_size()}, {local_device_count()} local devices)")
    return _info.backend


def _fill_info(rank_: int, world: int, local_rank: int = 0, local_world: int = 1,
               node: int = 0, backend: str = "env") -> None:
    _info.rank = rank_
    _info.world_size = world
    _info.local_rank = local_rank
    _info.local_world_size = local_world
    _info.node = node
    _info.backend = backend
    _info.initialized = True


def deinitialize() -> None:
    """Tear the runtime down (reference ``deinitialize_torch_distributed``,
    util/distributed.py:247-259)."""
    global _info
    if _info.initialized and _info.backend not in ("single",):
        try:
            jax.distributed.shutdown()
        except Exception:
            pass
    _info = _WorkerInfo()


# ---------------------------------------------------------------------------
# control-plane collectives: KV-store object exchange + monitored barrier
# (reference util/distributed.py:121-139, pipeline.py:191-196)
# ---------------------------------------------------------------------------

def _client():
    """The jax.distributed coordination client, or None single-process."""
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client
    except Exception:
        return None


_seq = {"barrier": 0, "obj": 0}

#: Barrier ids whose arrival keys are safe to garbage-collect (the barrier
#: completed on this rank). Swept by the ROOT rank at the NEXT successful
#: barrier — see the retention note inside ``barrier()``.
_gc_barrier_ids: list = []

#: Snapshot of this rank's most recent barrier: tag, status
#: ("waiting"/"completed"/"timeout"), entry wall-clock, and — after a
#: timeout — the straggler ranks that never arrived. The telemetry flight
#: recorder (telemetry/watchdog.py) embeds this in its forensics dump so a
#: hang post-mortem names the rank everyone else was waiting on.
_barrier_state: dict = {}


def barrier_state() -> dict:
    """Copy of this rank's most recent barrier record (see ``_barrier_state``);
    empty before the first barrier."""
    return dict(_barrier_state)


class BarrierTimeout(RuntimeError):
    """A barrier timed out; ``stragglers`` lists the ranks that never arrived
    (parity with the reference's ``monitored_barrier(wait_all_ranks=True)``,
    pipeline.py:191-196, which names late ranks)."""

    def __init__(self, tag: str, timeout: float, stragglers: list[int]):
        self.tag = tag
        self.timeout = timeout
        self.stragglers = stragglers
        super().__init__(
            f"barrier '{tag}' timed out after {timeout:.0f}s; "
            f"straggler ranks (never arrived): {stragglers or 'unknown'}"
        )


def _find_stragglers(client, barrier_id: str, probe_timeout_ms: int = 200) -> list[int]:
    """Ranks whose arrival key for ``barrier_id`` is absent — probed
    concurrently with short blocking gets."""
    from concurrent.futures import ThreadPoolExecutor

    def probe(src: int) -> int | None:
        try:
            client.blocking_key_value_get(f"{barrier_id}/arrived/{src}", probe_timeout_ms)
            return None
        except Exception:
            return src

    with ThreadPoolExecutor(max_workers=min(world_size(), 32)) as ex:
        return [r for r in ex.map(probe, range(world_size())) if r is not None]


def barrier(tag: str = "", timeout: float = _DEFAULT_TIMEOUT) -> None:
    """All-process barrier with real timeout semantics that NAMES stragglers.

    The reference uses gloo ``monitored_barrier(wait_all_ranks=True)``
    (pipeline.py:191-196), whose timeout error lists the late ranks. Here
    every process drops a per-rank arrival key into the coordination-service
    KV store before waiting; on timeout the error reports exactly which ranks
    never arrived (``BarrierTimeout.stragglers``). Control-plane only: no
    device traffic.
    """
    if world_size() <= 1:
        return
    from ..telemetry import journal as _journal  # stdlib-only; no import cycle

    client = _client()
    _seq["barrier"] += 1
    barrier_id = f"dmlcloud_tpu:{tag}:{_seq['barrier']}"
    _barrier_state.clear()
    _barrier_state.update(
        {
            "tag": tag,
            "id": barrier_id,
            "rank": rank(),
            "status": "waiting",
            "entered_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "timeout_s": timeout,
        }
    )
    _t0 = _journal.now()
    if client is not None:
        # Arrival-key retention: keys are NOT deleted when their own barrier
        # completes — a rank whose timer expired in the same instant the
        # barrier completed could then misreport arrived ranks as
        # stragglers. Instead the root sweeps them ONE completed barrier
        # later (below): by the time a subsequent barrier succeeds, every
        # rank has provably left the earlier one, so its keys can no longer
        # feed any straggler probe. Bounds the coordinator's KV-store RAM to
        # O(world) keys instead of O(world x barriers) on month-long jobs.
        client.key_value_set(f"{barrier_id}/arrived/{rank()}", "1")
        try:
            client.wait_at_barrier(barrier_id, timeout_in_ms=int(timeout * 1000))
        except Exception as e:
            msg = str(e).lower()
            if "deadline" in msg or "timeout" in msg or "timed out" in msg:
                stragglers = _find_stragglers(client, barrier_id)
                # feed the flight recorder BEFORE raising: the forensics dump
                # this timeout usually precipitates must name the late ranks
                _barrier_state.update({"status": "timeout", "stragglers": stragglers})
                _journal.emit("barrier", _t0, label=tag, status="timeout", stragglers=stragglers)
                raise BarrierTimeout(tag, timeout, stragglers) from e
            _barrier_state["status"] = "error"
            raise  # not a timeout (e.g. coordinator connection lost) — do not misdiagnose
        _barrier_state["status"] = "completed"
        _journal.emit("barrier", _t0, label=tag, status="completed")
        if is_root():
            for done_id in _gc_barrier_ids:
                for src in range(world_size()):
                    try:
                        client.key_value_delete(f"{done_id}/arrived/{src}")
                    except Exception:  # best effort — a missing delete is only RAM
                        pass
        _gc_barrier_ids.clear()
        _gc_barrier_ids.append(barrier_id)
    else:  # pragma: no cover - multiprocess without coordination service
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(barrier_id)
        _barrier_state["status"] = "completed"
        _journal.emit("barrier", _t0, label=tag, status="completed")


def _kv_key(name: str, seq: int, src: int) -> str:
    return f"dmlcloud_tpu/obj/{name}/{seq}/{src}"


class CollectiveMismatchError(RuntimeError):
    """Two processes paired up collectives issued from DIFFERENT call sites.

    The object collectives match messages by a per-process sequence counter,
    which assumes every process issues the identical sequence of collective
    calls. A rank-conditional extra (or skipped) call would silently pair
    call N on one rank with a different call N on another and deliver the
    wrong object; the call-site tag carried inside every payload turns that
    into this loud error whenever the misaligned pair spans two different
    call sites. (A misalignment that realigns the SAME line with itself —
    e.g. one rank running an extra loop iteration of one collective — pairs
    identical tags and is not detectable from the tag alone.)"""

    def __init__(self, kind: str, seq: int, local_tag: str, remote_tag: str, src: int):
        self.local_tag, self.remote_tag = local_tag, remote_tag
        super().__init__(
            f"control-plane {kind} #{seq}: this process called from {local_tag} but "
            f"rank {src} published from {remote_tag} — the ranks' collective call "
            "sequences have diverged (a rank-conditional collective call?). If the "
            "differing call sites are intentional, pass the same explicit tag= on "
            "both sides."
        )


def _call_site_tag() -> str:
    """``dir/file.py:lineno`` of the first frame outside this module — the
    user call site, fingerprinting WHICH collective call this is. The last
    TWO path components are kept: a bare basename collides across packages
    (every project has a ``train.py``/``utils.py``), which would pair two
    genuinely different call sites as "matching" and let a diverged
    collective sequence deliver the wrong object undiagnosed."""
    import sys

    f = sys._getframe(1)
    while f is not None and f.f_code.co_filename == __file__:
        f = f.f_back
    if f is None:  # pragma: no cover - interpreter entry
        return "?"
    parts = f.f_code.co_filename.replace(os.sep, "/").rsplit("/", 2)
    return f"{'/'.join(parts[-2:])}:{f.f_lineno}"


def _put_obj(key: str, obj: Any, tag: str) -> None:
    payload = base64.b64encode(pickle.dumps((tag, obj))).decode("ascii")
    _client().key_value_set(key, payload)


def _get_obj(key: str, timeout: float, *, expect_tag: str, kind: str, seq: int, src: int) -> Any:
    payload = _client().blocking_key_value_get(key, int(timeout * 1000))
    remote_tag, obj = pickle.loads(base64.b64decode(payload))
    if remote_tag != expect_tag:
        raise CollectiveMismatchError(kind, seq, expect_tag, remote_tag, src)
    return obj


def broadcast_object(
    obj: Any = None, root: int = 0, timeout: float = _DEFAULT_TIMEOUT, tag: str | None = None
) -> Any:
    """Broadcast a picklable object from ``root`` to all processes
    (reference ``broadcast_object``, util/distributed.py:136-139). Rides the
    coordination-service KV store — small payloads, no device memory.

    Every payload carries a call-site tag (default: the caller's file:line)
    that receivers verify, so rank-divergent call sequences fail with
    :class:`CollectiveMismatchError` instead of silently delivering the wrong
    object. Pass an explicit shared ``tag`` when matching calls legitimately
    come from different lines (e.g. an if/else on ``is_root()``)."""
    if world_size() <= 1:
        return obj
    tag = tag or _call_site_tag()
    _seq["obj"] += 1
    seq = _seq["obj"]
    key = _kv_key("bcast", seq, root)
    if rank() == root:
        _put_obj(key, obj, tag)
        return obj
    return _get_obj(key, timeout, expect_tag=tag, kind="broadcast_object", seq=seq, src=root)


def _get_objs(name: str, seq: int, timeout: float, expect_tag: str) -> list[Any]:
    """Fetch every rank's KV entry CONCURRENTLY — ``blocking_key_value_get``
    releases the GIL during its gRPC wait, so a thread pool turns O(world)
    serial round trips into ~one."""
    from concurrent.futures import ThreadPoolExecutor

    n = world_size()

    def fetch(src: int) -> Any:
        return _get_obj(
            _kv_key(name, seq, src), timeout, expect_tag=expect_tag, kind=name, seq=seq, src=src
        )

    with ThreadPoolExecutor(max_workers=min(n, 32)) as ex:
        return list(ex.map(fetch, range(n)))


def all_gather_object(
    obj: Any, timeout: float = _DEFAULT_TIMEOUT, tag: str | None = None
) -> list[Any]:
    """Gather one picklable object from every process, returned to all ranks
    ordered by rank (reference ``all_gather_object``, util/distributed.py:121-128).
    Call-site-tag verified — see :func:`broadcast_object`."""
    if world_size() <= 1:
        return [obj]
    tag = tag or _call_site_tag()
    _seq["obj"] += 1
    seq = _seq["obj"]
    _put_obj(_kv_key("agather", seq, rank()), obj, tag)
    return _get_objs("agather", seq, timeout, tag)


def gather_object(
    obj: Any, root: int = 0, timeout: float = _DEFAULT_TIMEOUT, tag: str | None = None
) -> list[Any] | None:
    """Gather objects to ``root`` only; other ranks get None (reference
    ``gather_object``, util/distributed.py:131-133).
    Call-site-tag verified — see :func:`broadcast_object`."""
    if world_size() <= 1:
        return [obj]
    tag = tag or _call_site_tag()
    _seq["obj"] += 1
    seq = _seq["obj"]
    _put_obj(_kv_key("gather", seq, rank()), obj, tag)
    barrier("gather_object", timeout)
    if rank() != root:
        return None
    return _get_objs("gather", seq, timeout, tag)


# ---------------------------------------------------------------------------
# preemption guard (elastic resume; doc/elasticity.md)
# ---------------------------------------------------------------------------

class PreemptionGuard:
    """Signal-driven drain flag for preemption-tolerant training.

    The scheduler's eviction warning (Cloud TPU: SIGTERM; Slurm:
    ``--signal=USR1@60`` -> SIGUSR1; an operator's Ctrl-C: SIGINT) lands on
    SOME rank as an async signal. The guard turns that into a clean,
    coordinated drain: the handler only flips :attr:`triggered` (never logs
    or raises — the signal may interrupt a buffered stream), and the step
    loop polls :meth:`coordinated` at save boundaries so every rank agrees
    to stop at the SAME step — a one-sided exit would strand the survivors
    in the next collective.

    ``install()`` resolves every signal name BEFORE touching any handler (a
    typo'd name must not leave a half-installed set) and remembers the
    original dispositions for :meth:`uninstall`. ``armed`` is separate from
    installation so tests (and driver code that learns about preemption out
    of band) can flip :attr:`triggered` directly.
    """

    #: default signal set: scheduler eviction + operator interrupt, plus the
    #: Slurm warning signal when running inside a Slurm step
    DEFAULT_SIGNALS = ("SIGTERM", "SIGINT")

    def __init__(self, signals: tuple[str, ...] | None = None):
        if signals is None:
            signals = self.DEFAULT_SIGNALS
            if _slurm.slurm_available():
                signals = signals + ("SIGUSR1",)
        self.signals = tuple(signals)
        #: set (async) by the signal handler; cleared by install()
        self.triggered = False
        #: the signal name that tripped the guard, for the requeue verdict
        self.signal_name: str | None = None
        #: monotonic (perf_counter) instant the guard tripped — drain
        #: budgets (e.g. the serve engine's) are measured from here
        self.triggered_at: float | None = None
        #: whether coordinated() participates in the cross-rank gather
        self.armed = False
        self._prev: dict = {}

    def install(self) -> "PreemptionGuard":
        import signal as _signal

        sigs = [getattr(_signal, name) for name in self.signals]
        for sig in sigs:
            prev = _signal.signal(sig, self._handler)
            # re-install on the same signal keeps the ORIGINAL disposition
            self._prev.setdefault(sig, prev)
        self.triggered = False
        self.signal_name = None
        self.triggered_at = None
        self.armed = True
        return self

    def _handler(self, signum, frame):
        # flag only — the normal control path reports the drain
        import time as _time

        self.triggered = True
        self.triggered_at = _time.perf_counter()
        try:
            import signal as _signal

            self.signal_name = _signal.Signals(signum).name
        except Exception:  # pragma: no cover - exotic signum
            self.signal_name = str(signum)

    def uninstall(self) -> None:
        """Restore the original process-wide dispositions (a stale handler
        would make post-run SIGTERM a silent no-op)."""
        if self._prev:
            import signal as _signal

            for sig, prev in self._prev.items():
                _signal.signal(sig, prev)
            self._prev = {}
        self.armed = False

    def coordinated(self) -> bool:
        """Whether ANY rank caught a preemption signal — ranks must agree on
        stopping or the survivors deadlock in the next collective."""
        if not self.armed:
            return False
        if world_size() <= 1:
            return self.triggered
        return any(all_gather_object(self.triggered, tag="preemption-drain"))


def all_gather_array(x) -> np.ndarray:
    """Gather one same-shape numeric array from every process as
    ``[world, *x.shape]`` via ONE XLA collective over ICI/DCN — the fast path
    for the fused epoch-end metric exchange (metrics.py), replacing the
    per-object KV-store hops entirely. All processes must call this with the
    same shape/dtype (SPMD); a mismatch fails loudly in the collective."""
    if world_size() <= 1:
        return np.asarray(x)[None]
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(np.asarray(x), tiled=False))
