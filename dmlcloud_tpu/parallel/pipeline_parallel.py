"""Pipeline parallelism: GPipe microbatch scheduling over a ``pipe`` mesh axis.

The reference has no pipeline parallelism (its ``TrainingPipeline`` stages run
sequentially — /root/reference/dmlcloud/pipeline.py:198-206; SURVEY.md §2.2).
This module is the TPU build's ``pipe`` axis implementation, designed for XLA
rather than as a scheduler translation:

- Every pipeline stage runs the SAME traced computation (``stage_fn``) on its
  own slice of the stacked stage parameters — SPMD, so one program serves all
  stages and the MXU sees identical shapes everywhere.
- Microbatches advance through the pipeline with ``lax.ppermute`` neighbour
  exchanges over ICI (stage i -> i+1), inside one ``lax.scan`` over
  ``n_micro + n_stages - 1`` ticks. There is no host-side scheduler: the
  whole GPipe schedule, bubbles and all, is a single compiled XLA program.
- Everything is differentiable (scan/ppermute/psum have transposes), so
  ``jax.grad`` through ``pipeline_apply`` yields the standard GPipe backward
  schedule automatically — no hand-written backward pipeline.
- Composes with the other axes: activations may be batch-sharded over
  ``data``/``fsdp`` and the per-stage computation may itself be tensor- or
  sequence-parallel (``model``/``seq`` axes) since those axes are untouched by
  the shard_map specs used here.

Bubble math is the classic GPipe one: efficiency = n_micro / (n_micro +
n_stages - 1); pick ``n_micro >= 4 * n_stages`` to keep the bubble under ~20%.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as mesh_lib

__all__ = ["pipeline_apply", "stack_pytrees", "microbatch", "unmicrobatch", "stage_sharding"]


def stack_pytrees(trees: list[Any]) -> Any:
    """Stack per-stage parameter pytrees into one pytree whose leaves gain a
    leading ``n_stages`` dim — the dim sharded over the ``pipe`` axis."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def microbatch(batch: jnp.ndarray, n_micro: int) -> jnp.ndarray:
    """[B, ...] -> [n_micro, B/n_micro, ...] (B must divide evenly)."""
    b = batch.shape[0]
    if b % n_micro:
        raise ValueError(f"batch size {b} not divisible into {n_micro} microbatches")
    return batch.reshape(n_micro, b // n_micro, *batch.shape[1:])


def unmicrobatch(x: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`microbatch`."""
    return x.reshape(x.shape[0] * x.shape[1], *x.shape[2:])


def stage_sharding(mesh: Mesh, axis: str = mesh_lib.PIPE) -> NamedSharding:
    """Sharding for stacked stage params: leading (stage) dim over ``axis``."""
    return NamedSharding(mesh, P(axis))


def _squeeze_leading(tree: Any) -> Any:
    return jax.tree_util.tree_map(lambda x: x[0], tree)


def pipeline_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stacked_params: Any,
    x: jnp.ndarray,
    mesh: Mesh,
    axis: str = mesh_lib.PIPE,
) -> jnp.ndarray:
    """Run ``x`` through ``n_stages`` pipeline stages with GPipe microbatching.

    Args:
      stage_fn: ``(params_slice, act) -> act`` — one stage's computation; the
        activation shape must be preserved (homogeneous pipeline). Traced once;
        runs on every stage with that stage's params.
      stacked_params: pytree whose leaves have leading dim ``n_stages``
        (:func:`stack_pytrees`), laid out with :func:`stage_sharding`.
      x: ``[n_micro, micro_b, ...]`` microbatched activations
        (:func:`microbatch`). May be sharded over ``data``/``fsdp`` on the
        micro-batch dim.
      mesh: mesh containing ``axis``; other axes pass through untouched.
      axis: the pipeline mesh axis name.

    Returns ``[n_micro, micro_b, ...]`` outputs of the last stage, replicated
    over ``axis``.
    """
    n_stages = mesh.shape[axis]
    n_micro = x.shape[0]
    for path, leaf in jax.tree_util.tree_leaves_with_path(stacked_params):
        if leaf.shape[:1] != (n_stages,):
            raise ValueError(
                f"stacked_params leaf {jax.tree_util.keystr(path)} has leading dim "
                f"{leaf.shape[:1]}, expected ({n_stages},) == mesh.shape[{axis!r}] "
                "(a mismatch would silently drop stages)"
            )
    batch_axes = mesh_lib.data_axes(mesh) or None
    act_spec = P(None, batch_axes)  # [n_micro, micro_b, ...]

    fn = partial(_pipeline_local, stage_fn, n_stages=n_stages, n_micro=n_micro, axis=axis)
    return mesh_lib.shard_map_compat(
        fn,
        mesh=mesh,
        in_specs=(P(axis), act_spec),
        out_specs=act_spec,
    )(stacked_params, x)


def _pipeline_local(stage_fn, stacked_params, x, *, n_stages: int, n_micro: int, axis: str):
    """Per-device body: run the GPipe tick loop for this stage."""
    params = _squeeze_leading(stacked_params)  # this stage's slice
    stage = jax.lax.axis_index(axis)
    is_first = stage == 0
    is_last = stage == n_stages - 1
    micro_shape = x.shape[1:]

    # stage i -> i+1; stage 0 receives zeros (no cyclic wrap)
    shift_perm = [(i, i + 1) for i in range(n_stages - 1)]

    def tick(carry, t):
        recv, y = carry
        # stage 0 injects microbatch t (zeros once the batch is exhausted —
        # those ticks only drain the pipeline and their outputs are masked)
        x_t = jax.lax.dynamic_index_in_dim(x, jnp.minimum(t, n_micro - 1), keepdims=False)
        feed = jnp.where(t < n_micro, x_t, jnp.zeros_like(x_t))
        act = jnp.where(is_first, feed, recv)

        out = stage_fn(params, act)

        # the last stage commits finished microbatch t-(n_stages-1)
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        prev = jax.lax.dynamic_index_in_dim(y, out_idx, keepdims=False)
        write = jnp.logical_and(is_last, t >= n_stages - 1)
        y = jax.lax.dynamic_update_index_in_dim(y, jnp.where(write, out, prev), out_idx, 0)

        recv = jax.lax.ppermute(out, axis, shift_perm)
        return (recv, y), None

    y0 = jnp.zeros((n_micro, *micro_shape), x.dtype)
    recv0 = jnp.zeros(micro_shape, x.dtype)
    (_, y), _ = jax.lax.scan(tick, (recv0, y0), jnp.arange(n_micro + n_stages - 1))

    # replicate the last stage's outputs to every pipe rank (all other stages
    # contribute zeros) so downstream specs see a pipe-invariant value
    return jax.lax.psum(jnp.where(is_last, y, jnp.zeros_like(y)), axis)
