"""Mixture-of-Experts with expert parallelism over the ``expert`` mesh axis.

The reference has no MoE (its models are user-supplied torch modules,
/root/reference/dmlcloud/pipeline.py:55-75); this is the TPU build's ``expert``
axis implementation, designed the XLA way:

- Switch/Mixtral-style top-k routing with a fixed per-expert capacity —
  static shapes, so the whole layer jits and the MXU sees dense matmuls.
- Dispatch and combine are einsums against a one-hot dispatch mask (the
  Shazeer formulation). When the expert dim of the expert weights is sharded
  over the ``expert`` mesh axis (see :func:`moe_partition_rules`), XLA lowers
  the dispatch/combine einsums to all-to-alls over ICI automatically — there
  is no hand-written a2a, and the same code runs unsharded on one chip.
- Load-balancing auxiliary loss (Switch Transformer eq. 4) and router z-loss
  are returned via flax's ``self.sow`` under the ``'losses'`` collection, so
  any training loop can fold them into the objective without plumbing.

Capacity math: ``capacity = ceil(tokens/experts * capacity_factor)`` rounded
up to a multiple of 8 (TPU lane alignment). Overflowed tokens are dropped by
the mask (their combine weight is zero) — standard Switch behaviour.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def moe_partition_rules() -> list[tuple[str, P]]:
    """Sharding rules for MoE layers: expert dim over ``expert``, per-expert
    matrices over ``fsdp``/``model`` like their dense counterparts. Compose
    with the base model's rules (earlier rules win)."""
    return [
        ("moe/(gate|up)_proj", P("expert", "fsdp", "model")),
        ("moe/down_proj", P("expert", "model", "fsdp")),
        ("moe/router/kernel", P()),
    ]


@dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    hidden_dim: int = 512
    mlp_dim: int = 1408
    dtype: Any = jnp.bfloat16
    router_z_coef: float = 1e-3
    balance_coef: float = 1e-2


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


class MoEMLP(nn.Module):
    """Expert-parallel SwiGLU MLP block: ``[B, T, D] -> [B, T, D]``.

    Sows ``losses/moe_aux`` (balance + z loss, already coefficient-weighted);
    collect with ``mutable=['losses']`` or via ``total_aux_loss``.
    """

    cfg: MoEConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        b, t, d = x.shape
        if d != cfg.hidden_dim:
            raise ValueError(f"MoEMLP input dim {d} != cfg.hidden_dim {cfg.hidden_dim}")
        n_tok = b * t
        e = cfg.num_experts
        capacity = _round_up(max(int(n_tok / e * cfg.capacity_factor), 1), 8)
        capacity = min(capacity, n_tok)

        top_k = min(cfg.top_k, e)  # degenerate single-expert configs stay valid
        tokens = x.reshape(n_tok, d)

        # -- routing (fp32 for a stable softmax) ----------------------------
        from .quant import QuantDense

        logits = QuantDense(e, use_bias=False, dtype=jnp.float32, param_dtype=jnp.float32, name="router")(
            tokens.astype(jnp.float32)
        )  # [N, E]
        probs = jax.nn.softmax(logits, axis=-1)

        # -- top-k expert choice with per-expert capacity positions ---------
        gate_weights, expert_idx = jax.lax.top_k(probs, top_k)  # [N, k]
        # renormalise the kept gates (Mixtral convention)
        gate_weights = gate_weights / jnp.maximum(jnp.sum(gate_weights, -1, keepdims=True), 1e-9)

        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.int32)  # [N, k, E]
        # position of each (token, choice) in its expert's buffer, in token order;
        # k choices count sequentially so a token's kth pick queues behind its first
        flat = onehot.reshape(n_tok * top_k, e)
        pos = jnp.cumsum(flat, axis=0) - 1  # [N*k, E]
        pos = jnp.sum(pos * flat, axis=-1).reshape(n_tok, top_k)  # [N, k]
        in_capacity = pos < capacity

        # dispatch mask [N, E, C]: one-hot over (expert, slot) for kept choices
        slot_onehot = jax.nn.one_hot(pos, capacity, dtype=x.dtype) * in_capacity[..., None].astype(x.dtype)
        dispatch = jnp.einsum("nke,nkc->nec", onehot.astype(x.dtype), slot_onehot)  # [N, E, C]
        combine = jnp.einsum(
            "nke,nkc,nk->nec",
            onehot.astype(jnp.float32),
            slot_onehot.astype(jnp.float32),
            gate_weights,
        ).astype(x.dtype)

        # -- expert computation (dense, batched over E; a2a via sharding) ---
        expert_in = jnp.einsum("nec,nd->ecd", dispatch, tokens)  # [E, C, D]

        wi_init = nn.initializers.variance_scaling(1.0, "fan_in", "truncated_normal")
        gate_w = self.param("moe/gate_proj", wi_init, (e, d, cfg.mlp_dim), jnp.float32)
        up_w = self.param("moe/up_proj", wi_init, (e, d, cfg.mlp_dim), jnp.float32)
        down_w = self.param("moe/down_proj", wi_init, (e, cfg.mlp_dim, d), jnp.float32)

        h = expert_in.astype(cfg.dtype)
        gate = jnp.einsum("ecd,edm->ecm", h, gate_w.astype(cfg.dtype))
        up = jnp.einsum("ecd,edm->ecm", h, up_w.astype(cfg.dtype))
        expert_out = jnp.einsum("ecm,emd->ecd", nn.silu(gate) * up, down_w.astype(cfg.dtype))

        out = jnp.einsum("nec,ecd->nd", combine, expert_out)  # [N, D]

        # -- aux losses -----------------------------------------------------
        # Switch balance loss: E * sum_e (fraction routed to e) * (mean prob of e)
        token_frac = jnp.mean(jnp.sum(onehot, axis=1).astype(jnp.float32), axis=0)  # [E]
        prob_frac = jnp.mean(probs, axis=0)  # [E]
        balance = e * jnp.sum(token_frac * prob_frac) / top_k
        z_loss = jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)
        self.sow(
            "losses",
            "moe_aux",
            cfg.balance_coef * balance + cfg.router_z_coef * z_loss,
            init_fn=lambda: jnp.zeros(()),
            reduce_fn=lambda a, b: a + b,
        )

        return out.reshape(b, t, d).astype(x.dtype)


def total_aux_loss(variables: Any) -> jnp.ndarray:
    """Sum every sown ``losses`` entry of a ``mutable=['losses']`` apply."""
    losses = variables.get("losses", {}) if isinstance(variables, dict) else {}
    leaves = jax.tree_util.tree_leaves(losses)
    if not leaves:
        return jnp.zeros(())
    return sum(jnp.sum(l) for l in leaves)
