"""Speculative decoding: exact greedy OR exact sampled generation, fewer
target passes.

A small draft model proposes ``k`` tokens autoregressively; the target
model verifies all of them in ONE forward pass (k+1 positions). At
temperature 0 it accepts the longest matching prefix plus its own
correction token; at temperature > 0 it runs the rejection-sampling
acceptance rule (accept with ``min(1, p_t/p_d)``, resample rejections
from the residual), which preserves the target's sampling distribution
exactly. Either way the draft changes the cost, never the result: greedy
output matches ``generate(target, ...)`` token for token, sampled output
is statistically indistinguishable from target-only sampling (both
asserted in tests). One caveat: the verify pass batches k+1 positions
where plain decode runs one, so a bf16 near-tie between two logits can
reduce in a different order and flip an argmax; exact-arithmetic (fp32)
configs are bitwise-identical. Decode cost per accepted token drops from one full
weight-stream of the target to ``~1/(n_accept+1)`` of one, plus k+1 cheap
draft passes; with a well-matched draft this is a 2-3x wall-clock win on
the weight-bandwidth-bound decode path. (The reference has no inference
path at all; this composes with the int8 weight-only quantization in
``models/quant.py`` — pass quantized trees for either model.)

TPU-first mechanics (everything static-shape, one compiled program):

- One ``lax.while_loop`` over verification rounds, with the whole
  accept/rollback decision ON DEVICE — no host round-trips anywhere in
  the loop. Each round runs exactly ``k`` draft passes (unrolled — ``k``
  is static) and one (k+1)-token target pass at a DYNAMIC cache offset
  (the transformer's decode path already supports traced offsets).
- The FIRST draft pass of a round processes two tokens
  ``[y[pos-2], y[pos-1]]`` at offset ``pos-2``: when the previous round
  accepted all ``k`` proposals, the draft cache has a one-slot gap at the
  bonus token's position — the 2-token pass fills it, which is what lets
  the round run ``k`` draft passes instead of the k+1 the pre-PR-6 loop
  paid (the old (k+1)-th pass existed only to write that slot every
  round). In every other case the extra slot is an identical rewrite.
- Rejected proposals leave stale K/V in both caches, but every round
  writes the contiguous range starting at its own offset, and the next
  round's offset never exceeds the previous offset + accepted + 1 — so
  stale slots are always overwritten (in-pass, before attention reads
  them) before the causal mask can expose them. ``return_cache=True``
  additionally applies :func:`~dmlcloud_tpu.models.generate.rewind_cache`
  ONCE after the loop — one masked select discarding the whole stale
  tail, instead of per-slot re-dispatches — so the returned caches are
  bit-identical to a non-speculative decode of the same accepted prefix.
- Batching: the B=1 routine is ``vmap``-ed over rows (per-row dynamic
  offsets come for free); under vmap the while_loop keeps running until
  every row finishes. Only the CHEAP carry leaves (pos/y/done/counters)
  are done-masked: a finished row's cache writes keep landing at its
  frozen ``pos`` with frozen inputs — idempotent, never read back into
  ``y`` — so the loop avoids two whole-cache selects per round.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from .transformer import DecoderLM

__all__ = ["speculative_generate", "verify_proposals", "init_medusa_heads", "medusa_head_logits"]


def _greedy(logits):
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def verify_proposals(tlogits, dlogits, proposals, rng, temperature, top_k, top_p, eos_id):
    """The batched accept rule — one verification round for ``B`` rows
    with PER-ROW sampling params (the serving engine's spec-decode step;
    the single-row loop above is the same math specialised to B=1 and one
    static greedy/sampled switch).

    ``tlogits`` is the target's ``[B, k+1, V]`` verification logits over
    ``[y_last, d_1..d_k]``; ``dlogits`` is ``[B, k, V]`` — row ``i`` is
    the TRUNCATED, SCALED draft distribution ``d_{i+1}`` was sampled from
    (``generate._truncate_scaled`` output; for greedy rows the values are
    never read); ``proposals`` is ``[B, k]``; ``temperature``/``top_k``/
    ``top_p``/``eos_id`` are ``[B]`` traced arrays. Rows with
    ``temperature == 0`` take the greedy rule (longest matching prefix +
    the target's correction token — committed tokens are exactly what
    greedy ``generate`` would emit); rows with ``temperature > 0`` run
    rejection sampling against their OWN truncated distributions, which
    preserves each row's truncated target sampling distribution exactly.

    Returns ``(new_tokens [B, k+1], n_new [B], n_accept [B])`` int32:
    tokens to commit (positions ``>= n_new`` are meaningless), how many
    to commit this round (``>= 1``; truncated at a row's own eos), and
    the exact count of verifier-accepted proposals (the accept-rate
    numerator; drafted is always ``k``)."""
    from .generate import _truncate_scaled

    b, kp1, _ = tlogits.shape
    k = kp1 - 1
    temperature = jnp.asarray(temperature, jnp.float32)
    ar = jnp.arange(k + 1)[None, :]  # [1, k+1]
    no = jnp.zeros((b, 1), bool)

    # --- greedy rule: longest matching prefix + correction ---
    greedy_tok = _greedy(tlogits)  # [B, k+1]
    match = proposals == greedy_tok[:, :k]
    n_acc_g = jnp.argmin(jnp.concatenate([match, no], axis=1), axis=1)
    new_g = jnp.where(ar <= n_acc_g[:, None], greedy_tok, 0)

    # --- rejection sampling (Leviathan et al. 2023), per-row params ---
    tlp = jax.nn.log_softmax(
        _truncate_scaled(tlogits.astype(jnp.float32), temperature, top_k, top_p), axis=-1
    )  # [B, k+1, V]
    # (k+1)-th draft row is an indexing placeholder — selected only when
    # every proposal was accepted, where probs comes from p_t alone
    dlp = jax.nn.log_softmax(
        jnp.concatenate(
            [dlogits.astype(jnp.float32), jnp.zeros_like(dlogits[:, :1])], axis=1
        ),
        axis=-1,
    )
    lp_t = jnp.take_along_axis(tlp[:, :k], proposals[..., None], axis=-1)[..., 0]
    lp_d = jnp.take_along_axis(dlp[:, :k], proposals[..., None], axis=-1)[..., 0]
    u = jax.random.uniform(rng, (b, k))
    accept = jnp.log(u) < jnp.minimum(lp_t - lp_d, 0.0)
    n_acc_s = jnp.argmin(jnp.concatenate([accept, no], axis=1), axis=1)
    p_t = jnp.exp(jnp.take_along_axis(tlp, n_acc_s[:, None, None], axis=1)[:, 0])  # [B, V]
    p_d = jnp.exp(jnp.take_along_axis(dlp, n_acc_s[:, None, None], axis=1)[:, 0])
    residual = jnp.maximum(p_t - p_d, 0.0)
    probs = jnp.where((n_acc_s == k)[:, None], p_t, residual)
    probs = probs / jnp.maximum(probs.sum(-1, keepdims=True), 1e-30)
    final_tok = jax.random.categorical(
        jax.random.fold_in(rng, 1), jnp.log(probs + 1e-30), axis=-1
    ).astype(jnp.int32)
    prop_pad = jnp.concatenate([proposals, jnp.zeros((b, 1), jnp.int32)], axis=1)
    new_s = jnp.where(
        ar < n_acc_s[:, None], prop_pad,
        jnp.where(ar == n_acc_s[:, None], final_tok[:, None], 0),
    )

    sampled = temperature > 0
    n_accept = jnp.where(sampled, n_acc_s, n_acc_g).astype(jnp.int32)
    new_tokens = jnp.where(sampled[:, None], new_s, new_g).astype(jnp.int32)

    # a row's own eos truncates its round: tokens strictly after the first
    # eos never commit, and the advance stops at the eos inclusive
    is_eos = new_tokens == eos_id[:, None]
    seen_eos = jnp.cumsum(is_eos, axis=1) - is_eos.astype(jnp.int32) > 0
    hit_eos = jnp.any(is_eos & ~seen_eos & (ar <= n_accept[:, None]), axis=1)
    n_new = jnp.minimum(
        n_accept + 1,
        jnp.where(hit_eos, jnp.argmax(is_eos & ~seen_eos, axis=1) + 1, k + 1),
    ).astype(jnp.int32)
    return new_tokens, n_new, n_accept


def init_medusa_heads(cfg, k: int, rng: jax.Array, lm_head_kernel=None):
    """Parameters for ``k - 1`` Medusa decode heads (Cai et al., "Medusa:
    Simple LLM Inference Acceleration Framework with Multiple Decoding
    Heads"): head ``h`` predicts the token ``h + 2`` positions ahead of the
    round's anchor from the SAME final hidden state the base ``lm_head``
    reads — the ``k - 1`` heads cover a ``medusa_k = k`` round's lookahead
    (the round's first position is always the last committed token), so a
    Medusa round needs no heads at all when ``k == 1``.

    Each head is one Medusa-1 residual block over the hidden state::

        logits_h = (hidden + silu(hidden @ w1[h] + b1[h])) @ w2[h]

    stacked across heads: ``w1 [k-1, D, D]``, ``b1 [k-1, D]``,
    ``w2 [k-1, D, V]`` (fp32 — the proposal distributions feed the exact
    rejection-sampling verify). ``w1``/``b1`` start at ZERO, so a fresh
    head's block is the identity over the hidden state; with
    ``lm_head_kernel`` ([D, V], the base model's unembedding) every head
    then starts as an exact copy of the base next-token head — the
    standard warm start for head distillation. Without it ``w2`` draws
    small normals. ``k == 1`` returns empty (0-head) stacks, which
    ``medusa_head_logits`` maps to an empty ``[B, 0, V]``."""
    if k < 1:
        raise ValueError(f"k (proposals per Medusa round) must be >= 1, got {k}")
    d, v, h = cfg.hidden_dim, cfg.vocab_size, k - 1
    if lm_head_kernel is not None:
        w2 = jnp.broadcast_to(jnp.asarray(lm_head_kernel, jnp.float32)[None], (h, d, v))
    else:
        w2 = 0.02 * jax.random.normal(rng, (h, d, v), jnp.float32)
    return {
        "w1": jnp.zeros((h, d, d), jnp.float32),
        "b1": jnp.zeros((h, d), jnp.float32),
        "w2": jnp.asarray(w2, jnp.float32),
    }


def medusa_head_logits(heads, hidden):
    """Apply every Medusa head to one batch of final hidden states:
    ``hidden [B, D]`` -> ``[B, k-1, V]`` fp32, row ``h`` the block-``h``
    head's logits (``init_medusa_heads``' residual form). All heads run as
    two stacked einsums — one fused matmul pair per round, not a Python
    loop over heads."""
    hidden = hidden.astype(jnp.float32)
    pre = jnp.einsum("bd,hde->bhe", hidden, heads["w1"]) + heads["b1"][None]
    res = hidden[:, None, :] + jax.nn.silu(pre)
    return jnp.einsum("bhd,hdv->bhv", res, heads["w2"])


def _row_spec_decode(
    target: DecoderLM,
    draft: DecoderLM,
    target_params,
    draft_params,
    prompt,  # [T] int32, one row
    rng,  # per-row PRNG key (unused at temperature 0)
    pad_len,  # [1] int32 — this row's LEFT-pad count (0 when not ragged)
    max_new_tokens: int,
    k: int,
    eos_id: int,
    pad_id: int,
    temperature,  # traced scalar — a new value must not recompile
    sampled: bool,  # static: selects the greedy or rejection-sampling body
    ragged: bool,  # static: False keeps the pad_len=None fast path compiled
    return_stats: bool = False,  # static: also return (rounds, advanced, accepted)
    return_cache: bool = False,  # static: also return the rewound KV caches
):
    from .generate import decode_step, init_cache, rewind_cache
    from .quant import dequant_tree, widen_quant_tree

    # int8 kernels stay quantized for the fused QuantDense path; only
    # exotic non-kernel quantized leaves rehydrate, and off-TPU the operand
    # widen is hoisted out of the verification loop (see generate.py)
    keep_kernel = lambda p: p.endswith("kernel")
    target_params = dequant_tree(target_params, target.cfg.dtype, keep=keep_kernel)
    draft_params = dequant_tree(draft_params, draft.cfg.dtype, keep=keep_kernel)
    if jax.default_backend() != "tpu":
        target_params = widen_quant_tree(target_params)
        draft_params = widen_quant_tree(draft_params)

    t = prompt.shape[0]
    # vmap hands a scalar; apply wants [B]=[1]. Unpadded calls pass None so
    # the transformer keeps its cheaper non-ragged decode program
    pad_len = jnp.reshape(pad_len, (1,)) if ragged else None
    # slack: the last round may propose past the buffer end; clamp-free
    # writes land in the slack and are sliced off at the end
    cache_len = t + max_new_tokens + k + 1
    tcache = init_cache(target.cfg, 1, cache_len, dtype=target.cfg.dtype)
    dcache = init_cache(draft.cfg, 1, cache_len, dtype=draft.cfg.dtype)
    row = prompt[None]  # [1, T]

    # Prefill both models over the prompt. attend_len=None: these are
    # one-time full passes, the fill-proportional chunking that matters in
    # plain decode buys little across a single prefill.
    tlogits, tcache = decode_step(
        target, target_params, row, tcache, offset=0, pad_len=pad_len, attend_len=t
    )
    _, dcache = decode_step(
        draft, draft_params, row, dcache, offset=0, pad_len=pad_len, attend_len=t
    )

    def _pick(logits, key):
        """Next token from target logits: argmax, or a temperature sample."""
        if not sampled:
            return _greedy(logits)
        return jax.random.categorical(key, logits.astype(jnp.float32) / temperature)

    # y holds the full sequence: prompt + generated (+ slack)
    y = jnp.zeros((cache_len,), jnp.int32)
    y = jax.lax.dynamic_update_slice(y, prompt, (0,))
    rng, first_key = jax.random.split(rng)
    # the first new token needs no speculation: it comes straight from the
    # target's prefill logits (exact greedy / exact target sample)
    first_tok = _pick(tlogits[0, -1], first_key).astype(jnp.int32)
    y = y.at[t].set(first_tok)
    # pos = next position to fill; rounds start at pos = t+1
    state = {
        "pos": jnp.asarray(t + 1, jnp.int32),
        "y": y,
        "rng": rng,
        "tcache": tcache,
        "dcache": dcache,
        "done": first_tok == eos_id,
        # verification rounds run (one target pass each) and draft
        # proposals the verifier accepted — together the EXACT accept-rate
        # observable: accept_rate = accepted / (rounds * k)
        "rounds": jnp.asarray(0, jnp.int32),
        "accepted": jnp.asarray(0, jnp.int32),
    }

    def cond(s):
        return (s["pos"] < t + max_new_tokens) & ~s["done"]

    def round_body(s):
        pos = s["pos"]
        y = s["y"]
        round_key = jax.random.fold_in(s["rng"], pos) if sampled else None

        def pick_draft(row, i):
            if sampled:
                return jax.random.categorical(
                    jax.random.fold_in(round_key, i), row.astype(jnp.float32) / temperature
                ).astype(jnp.int32)
            return _greedy(row)

        # --- draft proposes k tokens in k passes (unrolled: k is static).
        # Pass 0 feeds [y[pos-2], y[pos-1]] at offset pos-2 — the extra
        # leading token closes the draft cache's one-slot gap after a
        # fully-accepted round (see module docstring) and is an identical
        # rewrite otherwise; its last-position logits propose d_1.
        first2 = jax.lax.dynamic_slice(y, (pos - 2,), (2,))[None]  # [1, 2]
        logits, dcache = decode_step(
            draft, draft_params, first2, s["dcache"],
            offset=pos - 2, pad_len=pad_len, attend_len=cache_len,
        )
        nxt = pick_draft(logits[0, -1], 0)
        props, drows = [nxt], [logits[0, -1]]
        for i in range(1, k):  # k-1 single-token passes
            logits, dcache = decode_step(
                draft, draft_params, nxt[None, None], dcache,
                offset=pos - 1 + i, pad_len=pad_len, attend_len=cache_len,
            )
            nxt = pick_draft(logits[0, 0], i)
            props.append(nxt)
            drows.append(logits[0, 0])
        proposals = jnp.stack(props)  # [k]
        # row i is the draft distribution d_{i+1} was sampled from; the
        # rejection-sampling residual needs a (k+1)-th row only as an
        # indexing placeholder (never selected — see below)
        dlogits = jnp.concatenate([jnp.stack(drows), jnp.zeros((1,) + drows[0].shape, drows[0].dtype)])

        # --- target verifies y[pos-1], d_1..d_k in one pass ---
        x = jnp.concatenate([s["y"][pos - 1][None], proposals])[None]  # [1, k+1]
        tlogits, tcache = decode_step(
            target, target_params, x, s["tcache"],
            offset=pos - 1, pad_len=pad_len, attend_len=cache_len,
        )

        if not sampled:
            greedy = _greedy(tlogits[0])  # [k+1]: target tokens for pos..pos+k
            # longest matching prefix, then the target's correction token.
            # Wherever a proposal matched, proposal == greedy, so greedy[i]
            # IS the accepted token for every i <= n_accept (correction
            # included).
            match = proposals == greedy[:k]
            n_accept = jnp.argmin(jnp.concatenate([match, jnp.asarray([False])]))  # first miss
            new_tokens = jnp.where(jnp.arange(k + 1) <= n_accept, greedy, pad_id)
        else:
            # Rejection sampling (Leviathan et al. 2023): accept proposal
            # d_i with prob min(1, p_t(d_i)/p_d(d_i)); at the first
            # rejection, resample from the residual max(p_t - p_d, 0); if
            # all k accepted, sample the bonus token from the target's
            # (k+1)-th distribution. Preserves the target sampling
            # distribution EXACTLY (asserted statistically in tests).
            tlp = jax.nn.log_softmax(tlogits[0].astype(jnp.float32) / temperature)  # [k+1, V]
            dlp = jax.nn.log_softmax(dlogits.astype(jnp.float32) / temperature)  # [k+1, V]
            idx = jnp.arange(k)
            lp_t = tlp[idx, proposals]  # log p_t(d_i) at each proposal
            lp_d = dlp[idx, proposals]
            u = jax.random.uniform(jax.random.fold_in(round_key, k + 1), (k,))
            accept = jnp.log(u) < jnp.minimum(lp_t - lp_d, 0.0)
            n_accept = jnp.argmin(jnp.concatenate([accept, jnp.asarray([False])]))
            # the position-n_accept token: residual resample on rejection,
            # plain target sample when every proposal was accepted (the
            # dlp row there is the zero placeholder — never selected)
            p_t = jnp.exp(tlp[n_accept])
            residual = jnp.maximum(p_t - jnp.exp(dlp[n_accept]), 0.0)
            probs = jnp.where(n_accept == k, p_t, residual)
            probs = probs / jnp.maximum(probs.sum(), 1e-30)
            final_tok = jax.random.categorical(
                jax.random.fold_in(round_key, k + 2), jnp.log(probs + 1e-30)
            ).astype(jnp.int32)
            prop_pad = jnp.concatenate([proposals, jnp.asarray([pad_id], jnp.int32)])
            ar = jnp.arange(k + 1)
            new_tokens = jnp.where(
                ar < n_accept, prop_pad, jnp.where(ar == n_accept, final_tok, pad_id)
            )
        # tokens past the first eos inside the round must not count
        is_eos = new_tokens == eos_id
        seen_eos = jnp.cumsum(is_eos) - is_eos.astype(jnp.int32) > 0  # strictly after an eos
        new_tokens = jnp.where(seen_eos, pad_id, new_tokens)
        hit_eos = jnp.any(is_eos & ~seen_eos & (jnp.arange(k + 1) <= n_accept))
        # number of sequence positions actually advanced this round
        n_new = jnp.minimum(
            n_accept + 1,
            jnp.where(hit_eos, jnp.argmax(is_eos & ~seen_eos) + 1, k + 1),
        ).astype(jnp.int32)

        y_new = jax.lax.dynamic_update_slice(y, new_tokens, (pos,))
        done_row = s["done"]
        # caches are deliberately NOT done-masked (two whole-tree selects
        # per round): a done row's pos/y freeze below, so its repeated
        # writes are idempotent and never reach the output
        new_state = {
            "pos": jnp.where(done_row, pos, pos + n_new),
            "y": jnp.where(done_row, y, y_new),
            "rng": s["rng"],
            "tcache": tcache,
            "dcache": dcache,
            "done": done_row | hit_eos,
            "rounds": jnp.where(done_row, s["rounds"], s["rounds"] + 1),
            "accepted": jnp.where(done_row, s["accepted"], s["accepted"] + n_accept),
        }
        return new_state

    state = jax.lax.while_loop(cond, round_body, state)
    out = jax.lax.dynamic_slice(state["y"], (t,), (max_new_tokens,))
    # positions past the fill (loop exited with pos < t+max_new on eos)
    fill = state["pos"] - t
    out = jnp.where(jnp.arange(max_new_tokens) < fill, out, pad_id)
    extras = []
    if return_stats:
        # `fill` is the UNCLAMPED advance: the final round may overshoot
        # max_new_tokens by up to k (the surplus is masked out of `out`
        # above). `accepted` is the exact verifier acceptance count, so
        # accept_rate = accepted / (rounds * k) holds even under eos
        # truncation (where the advance-based algebra breaks).
        extras.append((state["rounds"], fill, state["accepted"]))
    if return_cache:
        # ONE rewind primitive discards both caches' stale speculative
        # tails. Rewind to pos - 1, NOT pos: slot pos-1 is the one slot the
        # loop's overwrite invariant does not reach — after a rejection it
        # holds the REJECTED draft's K/V (the correction token was emitted
        # but its slot is only rewritten by the next round's pass), and
        # after a fully-accepted round the bonus token's slot was never
        # written at all. The decode convention self-heals (the pass that
        # consumes y[p] writes slot p before attending), so zeroing it is
        # free for consumers and makes every KEPT slot provably correct.
        extras.append(
            (
                rewind_cache(state["tcache"], state["pos"] - 1),
                rewind_cache(state["dcache"], state["pos"] - 1),
            )
        )
    if extras:
        return (out, *extras)
    return out


@functools.partial(
    jax.jit,
    static_argnames=(
        "target", "draft", "max_new_tokens", "k", "eos_id", "pad_id", "sampled", "ragged",
        "return_stats", "return_cache",
    ),
)
def _spec_compiled(
    target, draft, target_params, draft_params, prompt, rng, pad_len, temperature,
    max_new_tokens, k, eos_id, pad_id, sampled, ragged, return_stats=False, return_cache=False,
):
    row_fn = functools.partial(
        _row_spec_decode, target, draft,
        max_new_tokens=max_new_tokens, k=k, eos_id=eos_id, pad_id=pad_id,
        temperature=temperature, sampled=sampled, ragged=ragged, return_stats=return_stats,
        return_cache=return_cache,
    )
    row_keys = jax.random.split(rng, prompt.shape[0])
    return jax.vmap(
        lambda p, key, pl: row_fn(target_params, draft_params, p, key, pl)
    )(prompt, row_keys, pad_len)


def speculative_generate(
    target: DecoderLM,
    target_params: Any,
    draft: DecoderLM,
    draft_params: Any,
    prompt,
    max_new_tokens: int = 32,
    *,
    k: int = 4,
    temperature: float = 0.0,
    rng: jax.Array | None = None,
    prompt_mask: jnp.ndarray | None = None,
    eos_id: int = -1,
    pad_id: int = 0,
    return_stats: bool = False,
    return_cache: bool = False,
):
    """Decode ``max_new_tokens`` continuations of ``prompt`` [B, T] using
    ``draft`` to propose ``k`` tokens per target verification pass: at
    ``temperature == 0`` (default) the output is token-identical to greedy
    ``generate(target, ...)``; at ``temperature > 0`` it is speculative
    SAMPLING via rejection (Leviathan et al. 2023) — accept each proposal
    with probability ``min(1, p_target/p_draft)``, resample rejections
    from the residual — distributed exactly as target-only sampling at
    that temperature (``rng`` seeds it). Speculation changes cost, never
    results.

    Both models must share the tokenizer/vocab; either params tree may be
    int8 weight-only quantized (models/quant.py). Ragged prompts work like
    ``generate``: LEFT-pad and pass ``prompt_mask`` ([B, T] {0,1}, zeros
    first). The temperature value is traced (sweeping it does not
    recompile); only the greedy-vs-sampled switch is compiled in.

    ``return_stats=True`` additionally returns ``(rounds, advanced,
    accepted)`` int32 arrays [B]: verification rounds run (= target decode
    passes), positions the decode loop advanced per row — ``advanced`` can
    exceed ``max_new_tokens`` by up to ``k`` when the final round
    overshoots (the surplus tokens are masked out of the returned
    sequence) — and the EXACT count of verifier-accepted draft proposals,
    so the per-row accept rate is ``accepted / (rounds * k)`` (exact even
    when an in-round eos truncates the advance; absent eos it equals the
    older ``(advanced - 1 - rounds) / (rounds * k)`` algebra).

    ``return_cache=True`` additionally returns ``(target_cache,
    draft_cache)`` with each row's cache REWOUND (one
    ``generate.rewind_cache`` masked select, not k re-dispatches) to
    ``advanced - 1`` valid positions: every kept slot is bit-identical to a
    non-speculative decode of the same tokens, and the speculative tail —
    including the final token's slot, which the loop's overwrite invariant
    never certifies — is zeroed. (The decode convention writes slot ``p``
    in the pass that consumes token ``p``, so a consumer resuming from the
    final token re-fills the zeroed slot before anything reads it.) Leaves
    are [B, S, KH, D], ``init_cache``'s layout (the vmap row axis replaces
    the per-row singleton batch axis)."""
    prompt = jnp.asarray(prompt, jnp.int32)
    _, t = prompt.shape
    if k < 1:
        raise ValueError(f"k (draft proposals per round) must be >= 1, got {k}")
    if temperature < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    for m, name in ((target, "target"), (draft, "draft")):
        if t + max_new_tokens + k + 1 > m.cfg.max_seq_len:
            raise ValueError(
                f"prompt ({t}) + max_new_tokens ({max_new_tokens}) + k+1 ({k + 1}) exceeds the "
                f"{name} model's max_seq_len ({m.cfg.max_seq_len})"
            )
    if rng is None:
        rng = jax.random.PRNGKey(0)
    from .generate import _pad_len_from_mask

    pad_len = _pad_len_from_mask(prompt_mask, prompt.shape[0], t)
    ragged = pad_len is not None
    if not ragged:  # dummy zeros ride the vmap; the static flag drops them
        pad_len = jnp.zeros((prompt.shape[0],), jnp.int32)
    # greedy-vs-sampled is the only static switch; the temperature VALUE is
    # a traced operand so sweeping it never recompiles (generate()'s
    # convention). The 1e-6 clamp keeps the unused division safe at t == 0.
    out = _spec_compiled(
        target, draft, target_params, draft_params, prompt, rng, pad_len,
        jnp.float32(max(float(temperature), 1e-6)),
        int(max_new_tokens), int(k), int(eos_id), int(pad_id), float(temperature) > 0.0, ragged,
        return_stats=bool(return_stats), return_cache=bool(return_cache),
    )
    if return_cache:
        # vmap left each row's singleton batch axis inside: [B, 1, S, KH, D]
        # -> init_cache's [B, S, KH, D]
        *rest, caches = out
        caches = jax.tree_util.tree_map(lambda x: x.squeeze(1), caches)
        return (*rest, caches)
    return out
