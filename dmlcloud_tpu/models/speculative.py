"""Speculative decoding: exact greedy OR exact sampled generation, fewer
target passes.

A small draft model proposes ``k`` tokens autoregressively; the target
model verifies all of them in ONE forward pass (k+1 positions). At
temperature 0 it accepts the longest matching prefix plus its own
correction token; at temperature > 0 it runs the rejection-sampling
acceptance rule (accept with ``min(1, p_t/p_d)``, resample rejections
from the residual), which preserves the target's sampling distribution
exactly. Either way the draft changes the cost, never the result: greedy
output matches ``generate(target, ...)`` token for token, sampled output
is statistically indistinguishable from target-only sampling (both
asserted in tests). One caveat: the verify pass batches k+1 positions
where plain decode runs one, so a bf16 near-tie between two logits can
reduce in a different order and flip an argmax; exact-arithmetic (fp32)
configs are bitwise-identical. Decode cost per accepted token drops from one full
weight-stream of the target to ``~1/(n_accept+1)`` of one, plus k+1 cheap
draft passes; with a well-matched draft this is a 2-3x wall-clock win on
the weight-bandwidth-bound decode path. (The reference has no inference
path at all; this composes with the int8 weight-only quantization in
``models/quant.py`` — pass quantized trees for either model.)

TPU-first mechanics (everything static-shape, one compiled program):

- One ``lax.while_loop`` over verification rounds; each round does k+1
  single-token draft passes (a ``lax.scan``) and one (k+1)-token target
  pass at a DYNAMIC cache offset (the transformer's decode path already
  supports traced offsets).
- Rejected proposals leave stale K/V in both caches, but every round
  writes the contiguous range starting at its own offset, and the next
  round's offset never exceeds the previous offset + accepted + 1 — so
  stale slots are always overwritten before the causal mask can expose
  them (round r+1 writes [o', o'+k+1) which covers the stale tail of
  round r's [o, o+k+1) because o' >= o+1).
- Batching: the B=1 routine is ``vmap``-ed over rows (per-row dynamic
  offsets come for free); under vmap the while_loop keeps running until
  every row finishes, so all carry updates are masked by a per-row
  ``done`` flag.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from .transformer import DecoderLM

__all__ = ["speculative_generate"]


def _greedy(logits):
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def _row_spec_decode(
    target: DecoderLM,
    draft: DecoderLM,
    target_params,
    draft_params,
    prompt,  # [T] int32, one row
    rng,  # per-row PRNG key (unused at temperature 0)
    pad_len,  # [1] int32 — this row's LEFT-pad count (0 when not ragged)
    max_new_tokens: int,
    k: int,
    eos_id: int,
    pad_id: int,
    temperature,  # traced scalar — a new value must not recompile
    sampled: bool,  # static: selects the greedy or rejection-sampling body
    ragged: bool,  # static: False keeps the pad_len=None fast path compiled
    return_stats: bool = False,  # static: also return (rounds, generated)
):
    from .generate import init_cache
    from .quant import dequant_tree

    target_params = dequant_tree(target_params, target.cfg.dtype)
    draft_params = dequant_tree(draft_params, draft.cfg.dtype)

    t = prompt.shape[0]
    # vmap hands a scalar; apply wants [B]=[1]. Unpadded calls pass None so
    # the transformer keeps its cheaper non-ragged decode program
    pad_len = jnp.reshape(pad_len, (1,)) if ragged else None
    # slack: the last round may propose past the buffer end; clamp-free
    # writes land in the slack and are sliced off at the end
    cache_len = t + max_new_tokens + k + 1
    tcache = init_cache(target.cfg, 1, cache_len, dtype=target.cfg.dtype)
    dcache = init_cache(draft.cfg, 1, cache_len, dtype=draft.cfg.dtype)
    row = prompt[None]  # [1, T]

    # Prefill both models over the prompt. attend_len=None: these are
    # one-time full passes, the fill-proportional chunking that matters in
    # plain decode buys little across a single prefill.
    tlogits, tcache = target.apply(
        {"params": target_params}, row, cache=tcache, offset=0, pad_len=pad_len, attend_len=t
    )
    _, dcache = draft.apply(
        {"params": draft_params}, row, cache=dcache, offset=0, pad_len=pad_len, attend_len=t
    )

    def _pick(logits, key):
        """Next token from target logits: argmax, or a temperature sample."""
        if not sampled:
            return _greedy(logits)
        return jax.random.categorical(key, logits.astype(jnp.float32) / temperature)

    # y holds the full sequence: prompt + generated (+ slack)
    y = jnp.zeros((cache_len,), jnp.int32)
    y = jax.lax.dynamic_update_slice(y, prompt, (0,))
    rng, first_key = jax.random.split(rng)
    # the first new token needs no speculation: it comes straight from the
    # target's prefill logits (exact greedy / exact target sample)
    first_tok = _pick(tlogits[0, -1], first_key).astype(jnp.int32)
    y = y.at[t].set(first_tok)
    # pos = next position to fill; rounds start at pos = t+1
    state = {
        "pos": jnp.asarray(t + 1, jnp.int32),
        "y": y,
        "rng": rng,
        "tcache": tcache,
        "dcache": dcache,
        "done": first_tok == eos_id,
        # verification rounds run (one target pass each) — the accept-rate
        # observable: generated = 1 + sum(n_accept_r + 1) over rounds
        "rounds": jnp.asarray(0, jnp.int32),
    }

    def cond(s):
        return (s["pos"] < t + max_new_tokens) & ~s["done"]

    def round_body(s):
        pos = s["pos"]
        round_key = jax.random.fold_in(s["rng"], pos) if sampled else None

        # --- draft proposes k tokens (k+1 passes: the last one exists only
        # to write d_k's K/V so the draft cache has no gap after a full
        # acceptance) ---
        def draft_step(carry, i):
            dcache, prev = carry
            logits, dcache = draft.apply(
                {"params": draft_params},
                prev[None, None],
                cache=dcache,
                offset=pos - 1 + i,
                pad_len=pad_len,
                attend_len=cache_len,
            )
            row = logits[0, 0]
            if sampled:
                nxt = jax.random.categorical(
                    jax.random.fold_in(round_key, i), row.astype(jnp.float32) / temperature
                ).astype(jnp.int32)
            else:
                nxt = _greedy(row)
            return (dcache, nxt), (nxt, row)

        (dcache, _), (proposals, dlogits) = jax.lax.scan(
            draft_step, (s["dcache"], s["y"][pos - 1]), jnp.arange(k + 1)
        )
        proposals = proposals[:k]  # [k] — the (k+1)-th output is discarded

        # --- target verifies y[pos-1], d_1..d_k in one pass ---
        x = jnp.concatenate([s["y"][pos - 1][None], proposals])[None]  # [1, k+1]
        tlogits, tcache = target.apply(
            {"params": target_params},
            x,
            cache=s["tcache"],
            offset=pos - 1,
            pad_len=pad_len,
            attend_len=cache_len,
        )

        if not sampled:
            greedy = _greedy(tlogits[0])  # [k+1]: target tokens for pos..pos+k
            # longest matching prefix, then the target's correction token.
            # Wherever a proposal matched, proposal == greedy, so greedy[i]
            # IS the accepted token for every i <= n_accept (correction
            # included).
            match = proposals == greedy[:k]
            n_accept = jnp.argmin(jnp.concatenate([match, jnp.asarray([False])]))  # first miss
            new_tokens = jnp.where(jnp.arange(k + 1) <= n_accept, greedy, pad_id)
        else:
            # Rejection sampling (Leviathan et al. 2023): accept proposal
            # d_i with prob min(1, p_t(d_i)/p_d(d_i)); at the first
            # rejection, resample from the residual max(p_t - p_d, 0); if
            # all k accepted, sample the bonus token from the target's
            # (k+1)-th distribution. Preserves the target sampling
            # distribution EXACTLY (asserted statistically in tests).
            tlp = jax.nn.log_softmax(tlogits[0].astype(jnp.float32) / temperature)  # [k+1, V]
            dlp = jax.nn.log_softmax(dlogits.astype(jnp.float32) / temperature)  # [k+1, V]
            idx = jnp.arange(k)
            lp_t = tlp[idx, proposals]  # log p_t(d_i) at each proposal
            lp_d = dlp[idx, proposals]
            u = jax.random.uniform(jax.random.fold_in(round_key, k + 1), (k,))
            accept = jnp.log(u) < jnp.minimum(lp_t - lp_d, 0.0)
            n_accept = jnp.argmin(jnp.concatenate([accept, jnp.asarray([False])]))
            # the position-n_accept token: residual resample on rejection,
            # plain target sample when every proposal was accepted (the
            # dlp row there is the discarded (k+1)-th draft pass — unused)
            p_t = jnp.exp(tlp[n_accept])
            residual = jnp.maximum(p_t - jnp.exp(dlp[n_accept]), 0.0)
            probs = jnp.where(n_accept == k, p_t, residual)
            probs = probs / jnp.maximum(probs.sum(), 1e-30)
            final_tok = jax.random.categorical(
                jax.random.fold_in(round_key, k + 2), jnp.log(probs + 1e-30)
            ).astype(jnp.int32)
            prop_pad = jnp.concatenate([proposals, jnp.asarray([pad_id], jnp.int32)])
            ar = jnp.arange(k + 1)
            new_tokens = jnp.where(
                ar < n_accept, prop_pad, jnp.where(ar == n_accept, final_tok, pad_id)
            )
        # tokens past the first eos inside the round must not count
        is_eos = new_tokens == eos_id
        seen_eos = jnp.cumsum(is_eos) - is_eos.astype(jnp.int32) > 0  # strictly after an eos
        new_tokens = jnp.where(seen_eos, pad_id, new_tokens)
        hit_eos = jnp.any(is_eos & ~seen_eos & (jnp.arange(k + 1) <= n_accept))
        # number of sequence positions actually advanced this round
        n_new = jnp.minimum(
            n_accept + 1,
            jnp.where(hit_eos, jnp.argmax(is_eos & ~seen_eos) + 1, k + 1),
        ).astype(jnp.int32)

        y_new = jax.lax.dynamic_update_slice(s["y"], new_tokens, (pos,))
        done_row = s["done"]
        new_state = {
            "pos": jnp.where(done_row, pos, pos + n_new),
            "y": jnp.where(done_row, s["y"], y_new),
            "rng": s["rng"],
            "tcache": jax.tree_util.tree_map(lambda old, new: jnp.where(done_row, old, new), s["tcache"], tcache),
            "dcache": jax.tree_util.tree_map(lambda old, new: jnp.where(done_row, old, new), s["dcache"], dcache),
            "done": done_row | hit_eos,
            "rounds": jnp.where(done_row, s["rounds"], s["rounds"] + 1),
        }
        return new_state

    state = jax.lax.while_loop(cond, round_body, state)
    out = jax.lax.dynamic_slice(state["y"], (t,), (max_new_tokens,))
    # positions past the fill (loop exited with pos < t+max_new on eos)
    fill = state["pos"] - t
    out = jnp.where(jnp.arange(max_new_tokens) < fill, out, pad_id)
    if return_stats:
        # UNCLAMPED advance: the final round may overshoot max_new_tokens by
        # up to k (the surplus is masked out of `out` above). Returning the
        # true advance keeps the accept-rate algebra exact:
        # advanced - 1 == sum over rounds of (n_accept_r + 1).
        return out, (state["rounds"], fill)
    return out


@functools.partial(
    jax.jit,
    static_argnames=(
        "target", "draft", "max_new_tokens", "k", "eos_id", "pad_id", "sampled", "ragged",
        "return_stats",
    ),
)
def _spec_compiled(
    target, draft, target_params, draft_params, prompt, rng, pad_len, temperature,
    max_new_tokens, k, eos_id, pad_id, sampled, ragged, return_stats=False,
):
    row_fn = functools.partial(
        _row_spec_decode, target, draft,
        max_new_tokens=max_new_tokens, k=k, eos_id=eos_id, pad_id=pad_id,
        temperature=temperature, sampled=sampled, ragged=ragged, return_stats=return_stats,
    )
    row_keys = jax.random.split(rng, prompt.shape[0])
    return jax.vmap(
        lambda p, key, pl: row_fn(target_params, draft_params, p, key, pl)
    )(prompt, row_keys, pad_len)


def speculative_generate(
    target: DecoderLM,
    target_params: Any,
    draft: DecoderLM,
    draft_params: Any,
    prompt,
    max_new_tokens: int = 32,
    *,
    k: int = 4,
    temperature: float = 0.0,
    rng: jax.Array | None = None,
    prompt_mask: jnp.ndarray | None = None,
    eos_id: int = -1,
    pad_id: int = 0,
    return_stats: bool = False,
):
    """Decode ``max_new_tokens`` continuations of ``prompt`` [B, T] using
    ``draft`` to propose ``k`` tokens per target verification pass: at
    ``temperature == 0`` (default) the output is token-identical to greedy
    ``generate(target, ...)``; at ``temperature > 0`` it is speculative
    SAMPLING via rejection (Leviathan et al. 2023) — accept each proposal
    with probability ``min(1, p_target/p_draft)``, resample rejections
    from the residual — distributed exactly as target-only sampling at
    that temperature (``rng`` seeds it). Speculation changes cost, never
    results.

    Both models must share the tokenizer/vocab; either params tree may be
    int8 weight-only quantized (models/quant.py). Ragged prompts work like
    ``generate``: LEFT-pad and pass ``prompt_mask`` ([B, T] {0,1}, zeros
    first). The temperature value is traced (sweeping it does not
    recompile); only the greedy-vs-sampled switch is compiled in.

    ``return_stats=True`` additionally returns ``(rounds, advanced)`` int32
    arrays [B]: verification rounds run (= target decode passes) and
    positions the decode loop advanced per row — ``advanced`` can exceed
    ``max_new_tokens`` by up to ``k`` when the final round overshoots (the
    surplus tokens are masked out of the returned sequence). Each round
    accepts ``n_accept`` draft proposals plus one target token (and the
    first token costs no round), so absent eos the per-row draft accept
    rate is exactly ``(advanced - 1 - rounds) / (rounds * k)``."""
    prompt = jnp.asarray(prompt, jnp.int32)
    _, t = prompt.shape
    if k < 1:
        raise ValueError(f"k (draft proposals per round) must be >= 1, got {k}")
    if temperature < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    for m, name in ((target, "target"), (draft, "draft")):
        if t + max_new_tokens + k + 1 > m.cfg.max_seq_len:
            raise ValueError(
                f"prompt ({t}) + max_new_tokens ({max_new_tokens}) + k+1 ({k + 1}) exceeds the "
                f"{name} model's max_seq_len ({m.cfg.max_seq_len})"
            )
    if rng is None:
        rng = jax.random.PRNGKey(0)
    from .generate import _pad_len_from_mask

    pad_len = _pad_len_from_mask(prompt_mask, prompt.shape[0], t)
    ragged = pad_len is not None
    if not ragged:  # dummy zeros ride the vmap; the static flag drops them
        pad_len = jnp.zeros((prompt.shape[0],), jnp.int32)
    # greedy-vs-sampled is the only static switch; the temperature VALUE is
    # a traced operand so sweeping it never recompiles (generate()'s
    # convention). The 1e-6 clamp keeps the unused division safe at t == 0.
    return _spec_compiled(
        target, draft, target_params, draft_params, prompt, rng, pad_len,
        jnp.float32(max(float(temperature), 1e-6)),
        int(max_new_tokens), int(k), int(eos_id), int(pad_id), float(temperature) > 0.0, ragged,
        return_stats=bool(return_stats),
    )
