"""Weight-only int8 quantization for inference, TPU-first.

Decode is HBM-bandwidth-bound: every generated token streams the full
weight set from HBM once, so halving the bytes (bf16 -> int8 + per-channel
fp32 scales) is a direct throughput lever on the MEASURED bottleneck
(bench.py's decode path runs at ~60% of the HBM roofline in bf16). The
reference has no inference path at all, let alone a quantized one.

Design:

- ``QuantizedTensor`` is a pytree node carrying ``q`` (int8) + ``scale``
  (fp32, per-output-channel). It flows through jit like any array leaf,
  so quantized param trees drop into the existing ``generate`` /
  ``beam_search`` entry points unchanged.
- The dequant is FUSED into each consuming matmul (:class:`QuantDense` /
  :class:`QuantDenseGeneral`, :func:`_fused_quant_dot`): the int8 tensor
  feeds ``lax.dot_general`` directly and the per-channel scales multiply
  the fp32 accumulator — no dequantized weight copy is ever materialised,
  so the weight stream stays 1 byte/element end to end. (The pre-PR-6
  design dequantized the whole tree at program entry; XLA hoisted the
  copies and the bandwidth saving never showed up — 1.02x in the r05
  receipts, vs >= 1.2x fused.)
- Symmetric per-channel quantization: ``w ~= q * scale`` with the amax
  reduced over the kernel's leading input axes, so every trailing output
  coordinate keeps its own scale (see :func:`quantize`).
- Weight-only: activations stay in the model's compute dtype. This is the
  bandwidth-bound inference tradeoff — prefill (compute-bound) keeps full
  precision.

**Quantized training** (PR 16): the same fused-dot discipline applied to
the train step. :class:`QuantTrainTensor` pairs a MASTER fp32 weight with
a DELAYED per-channel scale (computed from the previous step's post-update
amax, carried in ``TrainState.extras[QUANT_AMAX_KEY]`` — no per-step amax
reduction on the forward's critical path, the fp8-recipe trick applied to
int8). :func:`quant_train_dot` is a ``custom_vjp`` whose forward AND
input-gradient matmuls consume the freshly-quantized int8 kernel through
the same ``lax.dot_general`` operand convention as
:func:`_fused_quant_dot`, while the WEIGHT gradient stays a full-precision
``x^T @ g`` into the fp32 master (straight-through estimator: the
round/clip's zero-a.e. derivative is replaced by identity). The optimizer,
EMA shadow and checkpoint layout never see any of this — they hold plain
fp32 params; ``TrainValStage(precision="int8")`` wraps kernels inside the
compiled step's loss closure (:func:`wrap_train_tree`) and refreshes the
amax tree from the post-update params (:func:`amax_tree`).
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
from flax import struct

__all__ = [
    "QuantizedTensor",
    "QuantTrainTensor",
    "QuantDense",
    "QuantDenseGeneral",
    "quantize",
    "quantize_tree",
    "dequant_tree",
    "widen_quant_tree",
    "prepare_decode_params",
    "quantized_size",
    "quant_train_dot",
    "amax_tree",
    "wrap_train_tree",
    "QUANT_AMAX_KEY",
]

#: extras key under which TrainValStage(precision="int8") carries the
#: delayed per-channel amax tree (see amax_tree / wrap_train_tree)
QUANT_AMAX_KEY = "quant_amax"


class QuantizedTensor(struct.PyTreeNode):
    """``w ~= q * scale`` with int8 ``q`` and broadcast-ready fp32 ``scale``."""

    q: jax.Array
    scale: jax.Array

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    def dequant(self, dtype=jnp.bfloat16) -> jax.Array:
        # int8 -> f32 multiply keeps the scale exact; the cast to the
        # compute dtype happens last. Under jit this is one fused
        # elementwise chain feeding the consumer matmul.
        return (self.q.astype(jnp.float32) * self.scale).astype(dtype)


def _fused_quant_dot(x: jax.Array, qt: QuantizedTensor, dtype) -> jax.Array:
    """``x @ dequant(qt)`` WITHOUT a materialised dequantized weight copy:
    the int8 tensor feeds ``lax.dot_general`` directly (the int8->compute
    convert fuses into the matmul's operand read, so HBM streams 1 byte per
    weight instead of 2-4) and the per-output-channel scales multiply the
    fp32 ACCUMULATOR — O(out) work on the result instead of O(in*out) on
    the weight. int8 values are exact in bf16 (8 mantissa bits cover ±127),
    so this equals ``x @ (q * scale)`` up to the usual accumulation order.

    Contracts ``x``'s last axis with ``q``'s first (the nn.Dense /
    nn.DenseGeneral(axis=-1) convention); requires the quantization's
    reduced axis to be that same first axis (``scale.shape[0] == 1``)."""
    q = qt.q
    # Operand precision is a per-backend choice (static at trace time):
    # int8 is EXACT in both bf16 (8 mantissa bits cover ±127) and fp32, so
    # either is a faithful dequant. On TPU the operands stay in the compute
    # dtype — the narrow-operand MXU path is the fast one. Everywhere else
    # they promote to the fp32 accumulator's precision: XLA:CPU emulates
    # bf16 GEMMs (widen + fp32 GEMM + round EVERY step), so the quantized
    # decode runs the native fp32 GEMM directly. The widen itself is hoisted
    # out of the decode loop by :func:`widen_quant_tree` (q arrives here
    # already fp32 and the astype below is a no-op); the bf16 baseline
    # cannot hoist its emulation widen, and skipping that per-step tax is
    # where the measured CPU decode win comes from.
    if not jnp.issubdtype(q.dtype, jnp.integer):
        op_dtype = q.dtype  # pre-widened by widen_quant_tree — use as-is
    else:
        op_dtype = dtype if jax.default_backend() == "tpu" else jnp.promote_types(jnp.float32, dtype)
    acc = jax.lax.dot_general(
        x.astype(op_dtype),
        q.astype(op_dtype),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [..., *out] fp32
    scale = qt.scale.reshape(q.shape[1:])  # drop the keepdims reduced axis
    return (acc * scale).astype(dtype)


class QuantTrainTensor(struct.PyTreeNode):
    """Quantized-TRAINING leaf: master fp32 weight ``w`` plus the DELAYED
    per-output-channel ``scale`` (previous step's post-update amax / 127,
    keepdims layout, exactly :class:`QuantizedTensor`'s). The wrapped leaf
    lives only INSIDE the compiled train step's loss closure
    (:func:`wrap_train_tree`); params, grads, optimizer state and
    checkpoints stay plain fp32 trees."""

    w: jax.Array
    scale: jax.Array


def _train_op_dtype(dtype):
    # the same per-backend operand choice _fused_quant_dot makes: int8 is
    # exact in bf16 and fp32, TPU MXUs eat narrow operands natively,
    # XLA:CPU widens to the fp32 accumulator dtype (skipping the bf16
    # GEMM-emulation tax — the measured CPU training win)
    return dtype if jax.default_backend() == "tpu" else jnp.promote_types(jnp.float32, dtype)


@jax.custom_vjp
def quant_train_dot(x, w, scale):
    """``x @ fake_quant(w)`` with int8 matmuls on BOTH the forward and the
    input-gradient path, and a straight-through fp32 weight gradient.

    - forward: ``q = clip(round(w / scale))`` int8 feeds ``lax.dot_general``
      directly (the :func:`_fused_quant_dot` fusion — no dequantized copy),
      per-channel ``scale`` multiplies the fp32 accumulator.
    - ``dx = (g * scale) @ q^T``: the SAME int8 kernel re-feeds the
      transposed dot, so the backward's activation-gradient GEMM is
      quantized too (the residual holds ``q`` at 1 byte/element, not a
      second fp32 weight copy).
    - ``dw = x^T @ g`` in fp32 into the MASTER weight (straight-through:
      the quantizer's round/clip differentiates as identity) and
      ``dscale = 0`` — the scale is training STATE (delayed amax), never
      a trained parameter.

    Contracts ``x``'s last axis with ``w``'s first (the nn.Dense /
    DenseGeneral(axis=-1) convention, kernels ``[in, *out]``)."""
    y, _ = _quant_train_fwd(x, w, scale)
    return y


def _quant_train_fwd(x, w, scale):
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127).astype(jnp.int8)
    op = _train_op_dtype(x.dtype)
    acc = jax.lax.dot_general(
        x.astype(op), q.astype(op),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    y = (acc * scale.reshape(q.shape[1:])).astype(x.dtype)
    # the residual carries q int8 (1 byte/element), x, and a 0-size dtype
    # token so dw lands in the master weight's own dtype
    return y, (x, q, scale, jnp.zeros((0,), w.dtype))


def _quant_train_bwd(res, g):
    x, q, scale, wtok = res
    op = _train_op_dtype(x.dtype)
    n_out = q.ndim - 1
    gs = g.astype(jnp.float32) * scale.reshape(q.shape[1:])
    g_axes = tuple(range(g.ndim - n_out, g.ndim))
    dx = jax.lax.dot_general(
        gs.astype(op), q.astype(op),
        ((g_axes, tuple(range(1, q.ndim))), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)
    dw = jax.lax.dot_general(
        x.astype(jnp.float32), g.astype(jnp.float32),
        ((tuple(range(x.ndim - 1)), tuple(range(g.ndim - n_out))), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(wtok.dtype)
    return dx, dw, jnp.zeros_like(scale)


quant_train_dot.defvjp(_quant_train_fwd, _quant_train_bwd)


def amax_tree(params: Any, match: Callable[[str, Any], bool] | None = None) -> Any:
    """Per-output-channel ``max|w|`` of every matched kernel — the delayed-
    scale state ``TrainValStage(precision="int8")`` carries in
    ``extras[QUANT_AMAX_KEY]`` and refreshes from the POST-update params
    each step (so step N's forward quantizes with step N-1's statistics;
    step 0 seeds from the initial params in ``make_state``). Unmatched
    leaves hold a 0-d zero placeholder, keeping the tree structure
    identical to ``params`` for jit/donation/checkpointing. Default match:
    ``lora.default_match`` (matrix-shaped kernels)."""
    from .lora import _paths, default_match

    matcher = match or default_match

    def leaf_amax(path, leaf):
        if not matcher(path, leaf):
            return jnp.zeros((), jnp.float32)
        w = jnp.asarray(leaf)
        reduce_axes = tuple(range(min(1, w.ndim - 1)))
        return jnp.max(jnp.abs(w.astype(jnp.float32)), axis=reduce_axes, keepdims=True)

    return jax.tree_util.tree_map(leaf_amax, _paths(params), params)


def wrap_train_tree(
    params: Any, amax: Any, match: Callable[[str, Any], bool] | None = None
) -> Any:
    """Wrap every matched kernel as :class:`QuantTrainTensor` with the
    delayed scale ``amax / 127`` (1.0 for all-zero channels, mirroring
    :func:`quantize`). Called INSIDE the loss closure on the
    differentiated params, so grads keep the plain-params structure: the
    wrapper's ``w`` cotangent flows straight back to the leaf and the
    stop-gradient'd scale contributes nothing."""
    from .lora import _paths, default_match

    matcher = match or default_match

    def wrap(path, leaf, a):
        if not matcher(path, leaf):
            return leaf
        scale = jnp.where(a > 0, a / 127.0, 1.0)
        return QuantTrainTensor(w=leaf, scale=jax.lax.stop_gradient(scale))

    return jax.tree_util.tree_map(wrap, _paths(params), params, amax)


def _fusible(qt: QuantizedTensor) -> bool:
    """Whether the fused path applies: per-output-channel scales reduced
    over exactly the first (contracted) axis."""
    import math

    return qt.scale.shape[0] == 1 and qt.scale.size == math.prod(qt.q.shape[1:])


def widen_quant_tree(params: Any, dtype=jnp.float32) -> Any:
    """Hoist the int8 -> GEMM-operand widen OUT of a decode loop (CPU/GPU
    only; a no-op tree on TPU callers' side — don't call it there).

    On backends whose GEMMs cannot consume int8 operands, every
    ``_fused_quant_dot`` call widens ``q`` to fp32 — and when that call
    sits inside a ``scan``/``while_loop`` decode body, XLA:CPU re-runs the
    widen (write + read of a 4-byte copy) EVERY step, exactly the
    emulation tax the bf16 baseline pays. Calling this once before the
    loop (inside jit) converts each fusible kernel's ``q`` a single time;
    the ``optimization_barrier`` pins the widened buffers so XLA cannot
    sink the converts back into the loop body. Scales stay separate and
    still multiply the accumulator in :func:`_fused_quant_dot` —
    ``q * scale`` is never materialised, and the arithmetic is bit-for-bit
    the per-step path (int8 -> fp32 is exact)."""
    is_qt = lambda x: isinstance(x, QuantizedTensor)
    widened = jax.tree_util.tree_map(
        lambda x: x.replace(q=x.q.astype(dtype)) if is_qt(x) and _fusible(x) else x,
        params,
        is_leaf=is_qt,
    )
    return jax.lax.optimization_barrier(widened)


def prepare_decode_params(params: Any, dtype=jnp.bfloat16) -> Any:
    """ONE-TIME host-side preparation of a (possibly int8-quantized) tree
    for repeated decode calls: non-kernel quantized leaves rehydrate to
    ``dtype`` and, off-TPU, fusible int8 kernels pre-widen to the GEMM
    operand dtype so no per-call widen remains inside the compiled decode
    program (the in-program :func:`widen_quant_tree` then no-ops). On TPU
    kernels stay int8 — the MXU consumes them directly and pre-widening
    would only inflate HBM. Serving loops that decode from the same
    weights many times should call this once at model-load time; passing
    the raw quantized tree to :func:`~dmlcloud_tpu.models.generate.generate`
    stays correct and merely re-pays the widen each call."""
    params = dequant_tree(params, dtype, keep=lambda p: p.endswith("kernel"))
    if jax.default_backend() == "tpu":
        return params
    is_qt = lambda x: isinstance(x, QuantizedTensor)
    return jax.tree_util.tree_map(
        lambda x: x.replace(q=x.q.astype(jnp.float32)) if is_qt(x) and _fusible(x) else x,
        params,
        is_leaf=is_qt,
    )


class QuantDense(nn.Dense):
    """``nn.Dense`` that natively consumes an int8 :class:`QuantizedTensor`
    kernel via :func:`_fused_quant_dot` — decode-path layers use this so
    quantized param trees run without any dequantized weight copy. With an
    ordinary array kernel (including at init) it IS ``nn.Dense``."""

    @nn.compact
    def __call__(self, inputs):
        kernel = (
            self.get_variable("params", "kernel") if self.has_variable("params", "kernel") else None
        )
        if isinstance(kernel, QuantTrainTensor):  # quantized TRAINING path
            y = quant_train_dot(inputs.astype(self.dtype), kernel.w, kernel.scale)
            if self.use_bias:
                y = y + self.get_variable("params", "bias").astype(self.dtype)
            return y
        if not isinstance(kernel, QuantizedTensor):
            return super().__call__(inputs)
        if not _fusible(kernel):  # exotic scale layout: correctness over speed
            y = inputs.astype(self.dtype) @ kernel.dequant(self.dtype)
        else:
            y = _fused_quant_dot(inputs, kernel, self.dtype)
        if self.use_bias:
            y = y + self.get_variable("params", "bias").astype(self.dtype)
        return y


class QuantDenseGeneral(nn.DenseGeneral):
    """``nn.DenseGeneral`` twin of :class:`QuantDense` (supports the
    ``axis=-1`` single-contraction form the transformer uses; other axis
    configurations fall back to a dequantized matmul)."""

    @nn.compact
    def __call__(self, inputs):
        kernel = (
            self.get_variable("params", "kernel") if self.has_variable("params", "kernel") else None
        )
        if isinstance(kernel, QuantTrainTensor):
            if self.axis != -1 or self.batch_dims:
                raise NotImplementedError(
                    "quantized training supports the axis=-1 DenseGeneral form only"
                )
            y = quant_train_dot(inputs.astype(self.dtype), kernel.w, kernel.scale)
            if self.use_bias:
                y = y + self.get_variable("params", "bias").astype(self.dtype)
            return y
        if not isinstance(kernel, QuantizedTensor) or self.axis != -1 or self.batch_dims:
            if isinstance(kernel, QuantizedTensor):  # unsupported layout: dequantize locally
                kernel = kernel.dequant(self.dtype)
                contract = (((inputs.ndim - 1,), (0,)), ((), ()))
                return jax.lax.dot_general(inputs.astype(self.dtype), kernel, contract)
            return super().__call__(inputs)
        if not _fusible(kernel):
            y = jax.lax.dot_general(
                inputs.astype(self.dtype),
                kernel.dequant(self.dtype),
                (((inputs.ndim - 1,), (0,)), ((), ())),
            )
        else:
            y = _fused_quant_dot(inputs, kernel, self.dtype)
        if self.use_bias:
            y = y + self.get_variable("params", "bias").astype(self.dtype)
        return y


def quantize(w: jax.Array, *, num_input_axes: int = 1) -> QuantizedTensor:
    """Symmetric per-output-channel int8 quantization of ``w``.

    The amax is reduced over the leading ``num_input_axes`` axes (the dims a
    matmul collapses), so every trailing output coordinate keeps its own
    scale. For 2D ``[in, out]`` kernels that is the classic per-output-column
    scale; for DenseGeneral-style ``[in, heads, head_dim]`` kernels each
    (head, head_dim) output channel gets its own scale rather than sharing
    one across heads. Finer-than-per-channel scales (e.g. an out-projection
    ``[heads, head_dim, out]`` with the default ``num_input_axes=1``) are
    still exact elementwise and only cost a slightly larger scale tensor.
    """
    w = jnp.asarray(w)
    reduce_axes = tuple(range(min(num_input_axes, w.ndim - 1)))
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=reduce_axes, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127).astype(jnp.int8)
    return QuantizedTensor(q=q, scale=scale)


def quantize_tree(params: Any, match: Callable[[str, Any], bool] | None = None) -> Any:
    """Quantize every matched leaf of a param tree; the result drops into
    ``generate`` / ``beam_search`` directly (they dequantize in-program).
    Default match: matrix-shaped kernels (lora.default_match — embeddings,
    biases, and norm scales stay full precision)."""
    from .lora import _paths, default_match

    matcher = match or default_match
    return jax.tree_util.tree_map(
        lambda path, leaf: quantize(leaf) if matcher(path, leaf) else leaf, _paths(params), params
    )


def dequant_tree(params: Any, dtype=jnp.bfloat16, keep: Callable[[str], bool] | None = None) -> Any:
    """Rehydrate a (possibly partially) quantized tree to ``dtype`` arrays.
    Pure and cheap to call inside jit — a no-op tree_map when nothing is
    quantized.

    ``keep`` (path -> bool) leaves matching quantized leaves AS
    QuantizedTensor: the decode paths pass ``keep=lambda p:
    p.endswith("kernel")`` so matmul kernels stay int8 for the fused
    :class:`QuantDense` layers (no materialised weight copy) while any
    exotically-quantized leaf a custom matcher produced (an embedding, a
    bias) still rehydrates for its quant-unaware consumer."""
    is_qt = lambda x: isinstance(x, QuantizedTensor)
    if keep is None:
        return jax.tree_util.tree_map(
            lambda x: x.dequant(dtype) if is_qt(x) else x, params, is_leaf=is_qt
        )
    from .lora import _paths

    return jax.tree_util.tree_map(
        lambda path, x: x.dequant(dtype) if is_qt(x) and not keep(path) else x,
        _paths(params, is_leaf=is_qt),
        params,
        is_leaf=is_qt,
    )


def quantized_size(params: Any) -> tuple[int, int]:
    """(bytes_quantized, bytes_unquantized) for a bf16-deployed model — the
    per-token HBM weight-traffic ratio decode actually pays. Unquantized
    float leaves count as bf16 (2 bytes) on BOTH sides: they would stream
    at the compute dtype either way, whatever dtype the tree stores."""
    q_bytes = full_bytes = 0
    for leaf in jax.tree_util.tree_leaves(
        params, is_leaf=lambda x: isinstance(x, QuantizedTensor)
    ):
        if isinstance(leaf, QuantizedTensor):
            q_bytes += leaf.q.size + leaf.scale.size * 4
            full_bytes += leaf.q.size * 2
        else:
            n = 2 if jnp.issubdtype(leaf.dtype, jnp.floating) else leaf.dtype.itemsize
            q_bytes += leaf.size * n
            full_bytes += leaf.size * n
    return q_bytes, full_bytes
