"""Weight-only int8 quantization for inference, TPU-first.

Decode is HBM-bandwidth-bound: every generated token streams the full
weight set from HBM once, so halving the bytes (bf16 -> int8 + per-channel
fp32 scales) is a direct throughput lever on the MEASURED bottleneck
(bench.py's decode path runs at ~60% of the HBM roofline in bf16). The
reference has no inference path at all, let alone a quantized one.

Design:

- ``QuantizedTensor`` is a pytree node carrying ``q`` (int8) + ``scale``
  (fp32, per-output-channel). It flows through jit like any array leaf,
  so quantized param trees drop into the existing ``generate`` /
  ``beam_search`` entry points unchanged — they dequantize INSIDE the
  compiled program, which keeps the HBM-resident buffers int8 and lets
  XLA fuse the dequant (convert + multiply) into each consumer.
- Symmetric per-channel quantization: ``w ~= q * scale`` with the amax
  reduced over the kernel's leading input axes, so every trailing output
  coordinate keeps its own scale (see :func:`quantize`).
- Weight-only: activations stay in the model's compute dtype. This is the
  bandwidth-bound inference tradeoff — training and prefill (compute-
  bound) keep full precision.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from flax import struct

__all__ = ["QuantizedTensor", "quantize", "quantize_tree", "dequant_tree", "quantized_size"]


class QuantizedTensor(struct.PyTreeNode):
    """``w ~= q * scale`` with int8 ``q`` and broadcast-ready fp32 ``scale``."""

    q: jax.Array
    scale: jax.Array

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    def dequant(self, dtype=jnp.bfloat16) -> jax.Array:
        # int8 -> f32 multiply keeps the scale exact; the cast to the
        # compute dtype happens last. Under jit this is one fused
        # elementwise chain feeding the consumer matmul.
        return (self.q.astype(jnp.float32) * self.scale).astype(dtype)


def quantize(w: jax.Array, *, num_input_axes: int = 1) -> QuantizedTensor:
    """Symmetric per-output-channel int8 quantization of ``w``.

    The amax is reduced over the leading ``num_input_axes`` axes (the dims a
    matmul collapses), so every trailing output coordinate keeps its own
    scale. For 2D ``[in, out]`` kernels that is the classic per-output-column
    scale; for DenseGeneral-style ``[in, heads, head_dim]`` kernels each
    (head, head_dim) output channel gets its own scale rather than sharing
    one across heads. Finer-than-per-channel scales (e.g. an out-projection
    ``[heads, head_dim, out]`` with the default ``num_input_axes=1``) are
    still exact elementwise and only cost a slightly larger scale tensor.
    """
    w = jnp.asarray(w)
    reduce_axes = tuple(range(min(num_input_axes, w.ndim - 1)))
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=reduce_axes, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127).astype(jnp.int8)
    return QuantizedTensor(q=q, scale=scale)


def quantize_tree(params: Any, match: Callable[[str, Any], bool] | None = None) -> Any:
    """Quantize every matched leaf of a param tree; the result drops into
    ``generate`` / ``beam_search`` directly (they dequantize in-program).
    Default match: matrix-shaped kernels (lora.default_match — embeddings,
    biases, and norm scales stay full precision)."""
    from .lora import _paths, default_match

    matcher = match or default_match
    return jax.tree_util.tree_map(
        lambda path, leaf: quantize(leaf) if matcher(path, leaf) else leaf, _paths(params), params
    )


def dequant_tree(params: Any, dtype=jnp.bfloat16) -> Any:
    """Rehydrate a (possibly partially) quantized tree to ``dtype`` arrays.
    Pure and cheap to call inside jit — a no-op tree_map when nothing is
    quantized."""
    return jax.tree_util.tree_map(
        lambda x: x.dequant(dtype) if isinstance(x, QuantizedTensor) else x,
        params,
        is_leaf=lambda x: isinstance(x, QuantizedTensor),
    )


def quantized_size(params: Any) -> tuple[int, int]:
    """(bytes_quantized, bytes_unquantized) for a bf16-deployed model — the
    per-token HBM weight-traffic ratio decode actually pays. Unquantized
    float leaves count as bf16 (2 bytes) on BOTH sides: they would stream
    at the compute dtype either way, whatever dtype the tree stores."""
    q_bytes = full_bytes = 0
    for leaf in jax.tree_util.tree_leaves(
        params, is_leaf=lambda x: isinstance(x, QuantizedTensor)
    ):
        if isinstance(leaf, QuantizedTensor):
            q_bytes += leaf.q.size + leaf.scale.size * 4
            full_bytes += leaf.q.size * 2
        else:
            n = 2 if jnp.issubdtype(leaf.dtype, jnp.floating) else leaf.dtype.itemsize
            q_bytes += leaf.size * n
            full_bytes += leaf.size * n
    return q_bytes, full_bytes
