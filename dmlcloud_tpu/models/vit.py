"""Vision Transformer (ViT) — the BASELINE.json "ViT-L multi-epoch vision
run" config family, TPU-first:

- Patch embedding is one strided Conv (patch×patch, stride patch) — a single
  big MXU matmul per image, NHWC, no im2col.
- bf16 activations / fp32 params and LayerNorms (models/encoder.py).
- 'cls' (prepended class token) or 'gap' (global average pool) pooling;
  ``num_classes=0`` returns pooled features (the CLIP image tower).
- Sharding via encoder_partition_rules(): heads/MLP over ``model``, large
  sibling axes over ``fsdp`` — same mesh machinery as the decoder LM.

The reference has no model zoo (models are user nn.Modules,
/root/reference/dmlcloud/pipeline.py:55-75); this covers its users' vision
configs natively.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from .encoder import AddLearnedPositions, EncoderConfig, TransformerEncoder


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    hidden_dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    num_classes: int = 1000  # 0 => return pooled features (no head)
    pooling: str = "cls"  # 'cls' | 'gap'
    dropout_rate: float = 0.0
    dtype: Any = jnp.bfloat16

    @property
    def encoder(self) -> EncoderConfig:
        return EncoderConfig(
            hidden_dim=self.hidden_dim,
            num_layers=self.num_layers,
            num_heads=self.num_heads,
            mlp_dim=self.mlp_dim,
            dtype=self.dtype,
            causal=False,
            dropout_rate=self.dropout_rate,
        )


class ViT(nn.Module):
    """images [B, H, W, C] -> logits [B, num_classes] fp32 (or features)."""

    cfg: ViTConfig

    @nn.compact
    def __call__(self, images, train: bool = False):
        cfg = self.cfg
        b = images.shape[0]
        x = nn.Conv(
            cfg.hidden_dim,
            kernel_size=(cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size),
            padding="VALID",
            dtype=cfg.dtype,
            param_dtype=jnp.float32,
            name="patch_embed",
        )(images.astype(cfg.dtype))
        x = x.reshape(b, -1, cfg.hidden_dim)  # [B, P, D]

        if cfg.pooling == "cls":
            cls = self.param("cls_token", nn.initializers.zeros_init(), (1, 1, cfg.hidden_dim), jnp.float32)
            x = jnp.concatenate([jnp.tile(cls.astype(cfg.dtype), (b, 1, 1)), x], axis=1)
        x = AddLearnedPositions(x.shape[1], name="pos_embed")(x)

        x = TransformerEncoder(cfg.encoder, name="encoder")(x, train=train)

        if cfg.pooling == "cls":
            pooled = x[:, 0]
        elif cfg.pooling == "gap":
            pooled = jnp.mean(x, axis=1)
        else:
            raise ValueError(f"unknown pooling {cfg.pooling!r}")

        if cfg.num_classes == 0:
            return pooled
        return nn.Dense(
            cfg.num_classes, dtype=jnp.float32, param_dtype=jnp.float32, name="head"
        )(pooled.astype(jnp.float32))


def ViT_S16(**kw) -> ViT:
    return ViT(ViTConfig(patch_size=16, hidden_dim=384, num_layers=12, num_heads=6, mlp_dim=1536, **kw))


def ViT_B16(**kw) -> ViT:
    return ViT(ViTConfig(patch_size=16, hidden_dim=768, num_layers=12, num_heads=12, mlp_dim=3072, **kw))


def ViT_L16(**kw) -> ViT:
    return ViT(ViTConfig(patch_size=16, hidden_dim=1024, num_layers=24, num_heads=16, mlp_dim=4096, **kw))
