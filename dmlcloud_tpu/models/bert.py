"""BERT-style bidirectional encoder — the BASELINE.json "BERT fine-tune with
sharded data" config family, TPU-first:

- Word + learned-position + segment embeddings, pre-LN encoder core
  (models/encoder.py), bf16 matmuls / fp32 norms.
- Padding handled with static shapes (one compiled program for all mask
  patterns): an additive softmax bias on the dot path, kernel segment ids
  on the flash path.
- MLM head tied to the word embedding (one [D, V] matmul on the MXU);
  ``ignore_index=-100`` label convention in :func:`mlm_loss`.
- Sequence classification via a tanh pooler over the [CLS] position.

The reference has no model zoo (/root/reference/dmlcloud/pipeline.py:55-75);
this covers the encoder configs its users bring, sharded by
encoder_partition_rules().
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax.numpy as jnp
import optax

from .encoder import AddLearnedPositions, EncoderConfig, TransformerEncoder

IGNORE_INDEX = -100


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    max_seq_len: int = 512
    type_vocab_size: int = 2
    hidden_dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    dropout_rate: float = 0.0
    dtype: Any = jnp.bfloat16
    attn_impl: str = "dot"  # 'dot' | 'flash' (padding masks ride both paths)

    @property
    def encoder(self) -> EncoderConfig:
        return EncoderConfig(
            hidden_dim=self.hidden_dim,
            num_layers=self.num_layers,
            num_heads=self.num_heads,
            mlp_dim=self.mlp_dim,
            dtype=self.dtype,
            causal=False,
            dropout_rate=self.dropout_rate,
            attn_impl=self.attn_impl,
        )


class BertEmbeddings(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, tokens, token_type_ids=None):
        cfg = self.cfg
        embed = nn.Embed(
            cfg.vocab_size, cfg.hidden_dim, dtype=cfg.dtype, param_dtype=jnp.float32, name="word"
        )
        x = embed(tokens)
        x = AddLearnedPositions(cfg.max_seq_len, name="pos_embed")(x)
        if cfg.type_vocab_size:
            if token_type_ids is None:
                token_type_ids = jnp.zeros_like(tokens)
            x = x + nn.Embed(
                cfg.type_vocab_size, cfg.hidden_dim, dtype=cfg.dtype, param_dtype=jnp.float32, name="type"
            )(token_type_ids)
        x = nn.LayerNorm(dtype=jnp.float32, param_dtype=jnp.float32, name="norm")(x)
        return x.astype(cfg.dtype)


class BertEncoder(nn.Module):
    """tokens [B, T] -> hidden states [B, T, D]."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, tokens, attention_mask=None, token_type_ids=None, train: bool = False):
        x = BertEmbeddings(self.cfg, name="embeddings")(tokens, token_type_ids)
        # raw keep-mask: the flash path turns it into kernel segment ids,
        # the dot path into the additive bias (padding_mask_bias)
        return TransformerEncoder(self.cfg.encoder, name="encoder")(
            x, train=train, keep_mask=attention_mask
        )


class BertForMaskedLM(nn.Module):
    """tokens [B, T] -> MLM logits [B, T, V] fp32, decoder tied to word embed."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, tokens, attention_mask=None, token_type_ids=None, train: bool = False):
        cfg = self.cfg
        h = BertEncoder(cfg, name="bert")(tokens, attention_mask, token_type_ids, train=train)
        h = nn.Dense(cfg.hidden_dim, dtype=cfg.dtype, param_dtype=jnp.float32, name="mlm_transform")(h)
        h = nn.gelu(h)
        h = nn.LayerNorm(dtype=jnp.float32, param_dtype=jnp.float32, name="mlm_norm")(h)
        embedding = self.variables["params"]["bert"]["embeddings"]["word"]["embedding"]
        # bf16 operands on the MXU, fp32 accumulation — the [B*T,D]x[D,V]
        # matmul is the model's largest and must not run in fp32
        logits = jnp.einsum(
            "btd,vd->btv",
            h.astype(cfg.dtype),
            embedding.astype(cfg.dtype),
            preferred_element_type=jnp.float32,
        )
        bias = self.param("mlm_bias", nn.initializers.zeros_init(), (cfg.vocab_size,), jnp.float32)
        return logits + bias


class BertForSequenceClassification(nn.Module):
    """tokens [B, T] -> class logits [B, num_classes] fp32 (tanh CLS pooler)."""

    cfg: BertConfig
    num_classes: int = 2

    @nn.compact
    def __call__(self, tokens, attention_mask=None, token_type_ids=None, train: bool = False):
        h = BertEncoder(self.cfg, name="bert")(tokens, attention_mask, token_type_ids, train=train)
        pooled = nn.tanh(
            nn.Dense(self.cfg.hidden_dim, dtype=jnp.float32, param_dtype=jnp.float32, name="pooler")(
                h[:, 0].astype(jnp.float32)
            )
        )
        return nn.Dense(self.num_classes, dtype=jnp.float32, param_dtype=jnp.float32, name="classifier")(
            pooled
        )


def mlm_loss(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Masked cross entropy: positions with ``labels == IGNORE_INDEX`` are
    skipped; the mean is over masked positions only (static shapes — the mask
    is a weight, not a gather)."""
    keep = (labels != IGNORE_INDEX).astype(jnp.float32)
    safe_labels = jnp.where(labels == IGNORE_INDEX, 0, labels)
    per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, safe_labels)
    return jnp.sum(per_tok * keep) / jnp.maximum(jnp.sum(keep), 1.0)
