"""Model zoo: TPU-friendly flax implementations for the BASELINE.json ladder
(MNIST CNN, ResNet-50, BERT-style encoder, ViT, CLIP dual encoder,
Llama-style decoder LM with optional MoE), plus the train/deploy toolkit
around them: ``hf`` (checkpoint import/export), ``generate`` (KV-cache
sampling + beam search), ``speculative`` (draft-verified greedy/sampled decode),
``quant`` (weight-only int8 decode), and ``lora`` (adapter finetuning)."""
