"""Model zoo: TPU-friendly flax implementations for the BASELINE.json ladder
(MNIST CNN, ResNet-50, BERT-style encoder, ViT, Llama-style decoder LM)."""
