"""Model zoo: TPU-friendly flax implementations for the BASELINE.json ladder
(MNIST CNN, ResNet-50, BERT-style encoder, ViT, CLIP dual encoder,
Llama-style decoder LM with optional MoE)."""
