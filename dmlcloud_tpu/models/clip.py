"""CLIP-style contrastive dual encoder — the BASELINE.json "ViT-L/CLIP"
rung, TPU-first:

- Image tower: ViT with ``num_classes=0`` (pooled features); text tower: a
  causal TransformerEncoder whose sequence feature is read at the EOT
  position (highest token id, the CLIP convention).
- Both towers project into a shared ``embed_dim`` and are L2-normalised in
  fp32; a learnable ``logit_scale`` (stored as log, clamped at 100) scales
  the similarity.
- **Global-batch contrastive loss under data parallelism**:
  :func:`clip_loss` takes an optional ``axis_name`` — inside a shard_mapped /
  pmapped step it ``all_gather``s both embedding sets over the data axis so
  every device contrasts its local examples against the GLOBAL batch, with
  label offsets computed from ``axis_index``. The gather rides ICI; XLA
  overlaps it with the tower matmuls.

The reference has no model zoo (/root/reference/dmlcloud/pipeline.py:55-75).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from .encoder import AddLearnedPositions, EncoderConfig, TransformerEncoder
from .vit import ViT, ViTConfig


@dataclass(frozen=True)
class CLIPTextConfig:
    vocab_size: int = 49408
    max_seq_len: int = 77
    hidden_dim: int = 512
    num_layers: int = 12
    num_heads: int = 8
    mlp_dim: int = 2048
    dtype: Any = jnp.bfloat16

    @property
    def encoder(self) -> EncoderConfig:
        return EncoderConfig(
            hidden_dim=self.hidden_dim,
            num_layers=self.num_layers,
            num_heads=self.num_heads,
            mlp_dim=self.mlp_dim,
            dtype=self.dtype,
            causal=True,
        )


@dataclass(frozen=True)
class CLIPConfig:
    embed_dim: int = 512
    vision: ViTConfig = field(default_factory=lambda: ViTConfig(num_classes=0))
    text: CLIPTextConfig = field(default_factory=CLIPTextConfig)


class CLIPTextTower(nn.Module):
    cfg: CLIPTextConfig
    embed_dim: int

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        cfg = self.cfg
        x = nn.Embed(
            cfg.vocab_size, cfg.hidden_dim, dtype=cfg.dtype, param_dtype=jnp.float32, name="token_embed"
        )(tokens)
        x = AddLearnedPositions(cfg.max_seq_len, stddev=0.01, name="pos_embed")(x)
        x = TransformerEncoder(cfg.encoder, name="encoder")(x, train=train)
        # EOT = highest token id in each sequence (CLIP tokenizer convention)
        eot = jnp.argmax(tokens, axis=-1)
        feats = jnp.take_along_axis(x, eot[:, None, None], axis=1)[:, 0]
        return nn.Dense(
            self.embed_dim, use_bias=False, dtype=jnp.float32, param_dtype=jnp.float32, name="proj"
        )(feats.astype(jnp.float32))


class CLIP(nn.Module):
    """(images, tokens) -> (image_emb, text_emb, logit_scale); embeddings are
    L2-normalised fp32 [B, embed_dim]."""

    cfg: CLIPConfig

    def setup(self):
        self.visual = ViT(self.cfg.vision)
        self.vision_proj = nn.Dense(
            self.cfg.embed_dim, use_bias=False, dtype=jnp.float32, param_dtype=jnp.float32
        )
        self.text = CLIPTextTower(self.cfg.text, self.cfg.embed_dim)
        self.log_logit_scale = self.param(
            "log_logit_scale", nn.initializers.constant(jnp.log(1 / 0.07)), (), jnp.float32
        )

    def encode_image(self, images, train: bool = False):
        feats = self.visual(images, train=train)
        return _l2_normalize(self.vision_proj(feats.astype(jnp.float32)))

    def encode_text(self, tokens, train: bool = False):
        return _l2_normalize(self.text(tokens, train=train))

    def __call__(self, images, tokens, train: bool = False):
        img = self.encode_image(images, train=train)
        txt = self.encode_text(tokens, train=train)
        scale = jnp.minimum(jnp.exp(self.log_logit_scale), 100.0)
        return img, txt, scale


def _l2_normalize(x: jnp.ndarray, eps: float = 1e-8) -> jnp.ndarray:
    return x / jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True), eps)


def clip_loss(
    image_emb: jnp.ndarray,
    text_emb: jnp.ndarray,
    logit_scale: jnp.ndarray,
    axis_name: str | None = None,
) -> jnp.ndarray:
    """Symmetric InfoNCE. With ``axis_name`` (inside shard_map/pmap over the
    data axis), both embedding sets are all-gathered so each local example
    contrasts against the GLOBAL batch; labels are offset by
    ``axis_index * local_batch``."""
    local = image_emb.shape[0]
    if axis_name is None:
        all_img, all_txt, offset = image_emb, text_emb, 0
    else:
        all_img = jax.lax.all_gather(image_emb, axis_name, tiled=True)
        all_txt = jax.lax.all_gather(text_emb, axis_name, tiled=True)
        offset = jax.lax.axis_index(axis_name) * local

    labels = jnp.arange(local) + offset
    logits_i = logit_scale * image_emb @ all_txt.T  # [local, global]
    logits_t = logit_scale * text_emb @ all_img.T
    loss_i = optax.softmax_cross_entropy_with_integer_labels(logits_i, labels).mean()
    loss_t = optax.softmax_cross_entropy_with_integer_labels(logits_t, labels).mean()
    return 0.5 * (loss_i + loss_t)
