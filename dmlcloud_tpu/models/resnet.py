"""ResNet v1.5 family (18/34/50/101/152) — the BASELINE.json "ResNet-50 /
ImageNet DDP Stage" config, built TPU-first rather than ported from
torchvision:

- NHWC layout throughout (XLA:TPU native; no transposes).
- bf16 compute / fp32 params & batch stats: convs and the dense head run on
  the MXU in bf16; batch-norm statistics accumulate in fp32.
- 3x3 stride-2 downsampling in the bottleneck's middle conv (the "v1.5"
  variant — same as torchvision's default used by the reference examples).
- BatchNorm is flax's, with ``axis_name`` plumbed so a sharded step can use
  cross-device SyncBN (the reference exposes this as ``sync_bn``,
  pipeline.py:70-71); pass ``axis_name=None`` for per-device stats.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)

        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)

        return self.act(residual + y)


class BasicBlock(nn.Module):
    filters: int
    strides: tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)

        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)

        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: jnp.dtype = jnp.bfloat16
    axis_name: str | None = None  # set to the data axis name for SyncBN

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            axis_name=self.axis_name,
        )

        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))

        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=nn.relu,
                )(x)

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3], block_cls=BottleneckBlock)
