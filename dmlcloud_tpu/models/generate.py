"""Autoregressive text generation for DecoderLM — the inference half the
training stack feeds into (the reference ships no inference path at all;
this is TPU-side scope).

TPU-first shape of the problem:

- The KV cache is a static-shape pytree ([B, max_len, KH, D] per layer,
  bf16); every decode step writes one slot with ``dynamic_update_slice``.
  Attention reads only a STATIC prefix of the buffer (``attend_len``),
  grown chunk-by-chunk as the cache fills, so per-token attention cost
  scales with the filled length instead of max_len — while every shape
  stays static.
- Generation is ONE jitted program: prefill over the (padded) prompt, then
  a short chain of ``lax.scan`` segments (one per attend-length chunk,
  at most ``_DECODE_CHUNKS``). No per-token Python dispatch; the only
  host transfer is the final token matrix.
- Sampling is functional: greedy at ``temperature=0``, otherwise
  temperature softmax with optional top-k and nucleus (top-p) truncation,
  PRNG folded per step.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from .transformer import DecoderLM, TransformerConfig


#: Max number of scan segments in a chunked decode: bounds trace/compile
#: size (each segment is one scan body) while the growing attend_len keeps
#: attention work proportional to fill.
_DECODE_CHUNKS = 8


def init_cache(cfg: TransformerConfig, batch_size: int, max_len: int | None = None, dtype=jnp.bfloat16):
    """Zeroed KV cache pytree: ``{layer_i: {k, v: [B, S, KH, D]}}``."""
    s = max_len or cfg.max_seq_len
    shape = (batch_size, s, cfg.kv_heads, cfg.head_dim)
    return {
        f"layer_{i}": {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
        for i in range(cfg.num_layers)
    }


def rewind_cache(cache, fill_len):
    """Rewind a KV cache to ``fill_len`` valid positions: slots at
    ``position >= fill_len`` are zeroed in ONE masked select over the tree —
    the single rewind primitive speculative decoding needs to discard a
    rejected draft tail (instead of k per-slot re-dispatches), and the only
    way to make a cache that speculated past ``fill_len`` bit-identical to
    one that never did. ``fill_len`` may be traced ([B] per-row or scalar);
    the masked positions never influence attention (the causal/attend_len
    masks already exclude them), so rewinding is semantically free — it
    matters when caches are compared, checkpointed, or handed to a consumer
    that trusts the whole buffer."""
    fill_len = jnp.asarray(fill_len, jnp.int32)

    def mask_leaf(x):  # x: [B, S, KH, D]
        pos = jnp.arange(x.shape[1], dtype=jnp.int32)
        keep = pos[None, :] < jnp.reshape(fill_len, (-1, 1))  # [B or 1, S]
        return jnp.where(keep[:, :, None, None], x, jnp.zeros((), x.dtype))

    return jax.tree_util.tree_map(mask_leaf, cache)


def _chunked_scan(step, carry, first_step, n_total, attend_len_for_end):
    """Run ``step(carry, i, attend_len=...)`` over steps
    [first_step, first_step + n_total) as at most ``_DECODE_CHUNKS``
    ``lax.scan`` segments; segment covering steps < end gets the static
    ``attend_len_for_end(end)``. The single source of truth for decode
    chunking — greedy/sampling and beam search share it (their index bases
    differ by one, hence the callback). Returns (carry, per-segment ys)."""
    chunk = -(-n_total // _DECODE_CHUNKS) if n_total else 1
    ys = []
    for start in range(first_step, first_step + n_total, chunk):
        end = min(start + chunk, first_step + n_total)
        seg_step = functools.partial(step, attend_len=attend_len_for_end(end))
        carry, y = jax.lax.scan(seg_step, carry, jnp.arange(start, end))
        ys.append(y)
    return carry, ys


def decode_step(
    model: DecoderLM, params, tokens, cache, *, offset=0, pad_len=None, attend_len=None,
    pages=None, adapters=None, return_hidden=False,
):
    """THE cache-step primitive: one model application that writes
    ``tokens``' K/V into ``cache`` and returns ``(logits, new_cache)``.

    Every decode path — :func:`generate`, :func:`beam_search`,
    ``speculative_generate`` and the continuous-batching serving engine
    (``dmlcloud_tpu.serve``) — funnels its cache-carrying model calls
    through this one function, so the cache write/attend convention (write
    the slot BEFORE attention reads it, causal mask over the filled
    prefix) cannot drift between them: a numerics change lands in all four
    at once or not at all.

    ``cache`` is either the dense ``init_cache`` tree stepped at the
    scalar ``offset`` (with optional ``pad_len`` ragged-prompt positions
    and ``attend_len`` bounded reads), or the serving engine's pool pages
    stepped via ``pages=(block_tables, fill)``; ``adapters`` threads
    per-row LoRA deltas for multi-tenant serving (``serve.AdapterSet``).
    ``return_hidden=True`` returns ``((logits, hidden), new_cache)`` — the
    Medusa serving path reads the final hidden states for its extra decode
    heads out of the SAME forward that produced the base logits."""
    return model.apply(
        {"params": params}, tokens, cache=cache, offset=offset, pad_len=pad_len,
        attend_len=attend_len, pages=pages, adapters=adapters, return_hidden=return_hidden,
    )


def sample_logits(logits, rng, temperature: float, top_k: int, top_p: float):
    """logits: [B, V] fp32 -> tokens [B] int32."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        # nucleus: keep the smallest prefix of the sorted distribution whose
        # mass reaches top_p (the first token always stays)
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        csum = jnp.cumsum(jax.nn.softmax(sorted_logits, axis=-1), axis=-1)
        # first index reaching p; clamped so a cumsum that never reaches
        # top_p (rounding near 1.0) keeps everything EXPLICITLY instead of
        # via take_along_axis's implicit clip-at-bounds indexing
        cutoff_idx = jnp.minimum(
            jnp.sum(csum < top_p, axis=-1, keepdims=True), logits.shape[-1] - 1
        )
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def _truncate_scaled(logits, temperature, top_k, top_p):
    """Per-row temperature/top-k/nucleus truncation with TRACED params.

    ``logits`` is ``[B, V]`` or ``[B, T, V]``; ``temperature``/``top_k``/
    ``top_p`` are ``[B]`` arrays (one value per row — the serving engine's
    mixed-tenant case). Returns logits scaled and masked so their softmax
    IS each row's sampling distribution, applying the SAME ops in the SAME
    order as :func:`sample_logits` (scale, then top-k mask, then nucleus
    mask) so a batch whose rows share one parameter set truncates
    bit-identically to the scalar path. Rows with ``temperature == 0`` are
    left at scale 1 (their caller takes the argmax; the division must
    merely stay finite), ``top_k <= 0`` / ``top_p >= 1`` disable the
    respective mask per row — every knob is data, nothing recompiles."""
    v = logits.shape[-1]
    extra = logits.ndim - 2  # 0 for [B, V], 1 for [B, T, V]
    bshape = (-1,) + (1,) * (extra + 1)
    temperature = jnp.reshape(temperature, bshape)
    top_k = jnp.reshape(top_k, bshape)
    top_p = jnp.reshape(top_p, bshape)
    x = logits / jnp.where(temperature > 0, temperature, 1.0)
    # top-k: the row's k-th largest value is the cut (k clamped into [1, V]
    # so the disabled rows still index validly; their mask is dropped)
    sorted_desc = jnp.flip(jnp.sort(x, axis=-1), axis=-1)
    kth = jnp.take_along_axis(
        sorted_desc, jnp.clip(top_k, 1, v) - 1, axis=-1
    )
    x = jnp.where((top_k > 0) & (x < kth), -jnp.inf, x)
    # nucleus: smallest prefix of the sorted distribution reaching top_p
    # (sample_logits' clamp semantics — the first token always survives)
    sx = jnp.flip(jnp.sort(x, axis=-1), axis=-1)
    csum = jnp.cumsum(jax.nn.softmax(sx, axis=-1), axis=-1)
    cutoff_idx = jnp.minimum(
        jnp.sum(csum < top_p, axis=-1, keepdims=True), v - 1
    )
    cutoff = jnp.take_along_axis(sx, cutoff_idx, axis=-1)
    return jnp.where((top_p < 1.0) & (x < cutoff), -jnp.inf, x)


def sample_logits_batched(logits, rng, temperature, top_k, top_p):
    """Per-row traced twin of :func:`sample_logits`: ``logits`` is
    ``[B, V]`` fp32, the sampling params are ``[B]`` arrays so ONE
    compiled program serves mixed greedy/sampled tenants (the serving
    engine's batched-sampling contract). Rows with ``temperature == 0``
    return the exact argmax — bit-identical to the scalar greedy path —
    and a batch whose rows all carry one parameter set samples the same
    tokens as ``sample_logits`` with those scalars (same rng, same masked
    logits, same categorical)."""
    temperature = jnp.asarray(temperature, jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    x = _truncate_scaled(logits, temperature, top_k, top_p)
    sampled = jax.random.categorical(rng, x, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)


@functools.partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "temperature", "top_k", "top_p", "eos_id", "pad_id"),
)
def _generate_compiled(
    model: DecoderLM,
    params,
    prompt: jnp.ndarray,
    pad_len: jnp.ndarray | None,
    rng: jax.Array,
    max_new_tokens: int,
    temperature: float,
    top_k: int,
    top_p: float,
    eos_id: int,
    pad_id: int,
):
    b, t = prompt.shape
    # int8 weight-only kernels (models/quant.py) stay quantized END TO END:
    # the quant-aware dense layers feed them to the matmul with the
    # per-channel scales applied to the fp32 accumulator — q * scale is
    # never materialised. Only exotically-quantized non-kernel leaves
    # rehydrate here. Off-TPU, the int8 -> fp32 GEMM-operand widen is
    # hoisted out of the decode loop (once per call, not once per step —
    # see widen_quant_tree); on TPU q stays int8 into the MXU.
    from .quant import dequant_tree, widen_quant_tree

    params = dequant_tree(params, model.cfg.dtype, keep=lambda p: p.endswith("kernel"))
    if jax.default_backend() != "tpu":
        params = widen_quant_tree(params)
    # cache in the model's compute dtype so fp32 configs stay exact
    cache = init_cache(model.cfg, b, t + max_new_tokens, dtype=model.cfg.dtype)

    # Prefill: one pass over the whole prompt fills cache slots [0, t).
    # Left padding means every row's LAST slot is real, so sampling reads
    # logits[:, -1] and decode write offsets stay uniform across rows.
    # attend_len=t: the empty generation tail is never read.
    logits, cache = decode_step(
        model, params, prompt, cache, offset=0, pad_len=pad_len, attend_len=t
    )
    last = logits[:, -1]  # [B, V]

    def sample_next(prev_logits, rng, done):
        tok = sample_logits(prev_logits, rng, temperature, top_k, top_p)
        tok = jnp.where(done, pad_id, tok)
        return tok, done | (tok == eos_id)

    def step(carry, i, attend_len):
        cache, prev_logits, rng, done = carry
        rng, sub = jax.random.split(rng)
        tok, done = sample_next(prev_logits, sub, done)
        logits, cache = decode_step(
            model, params, tok[:, None], cache, offset=t + i, pad_len=pad_len,
            attend_len=attend_len,
        )
        return (cache, logits[:, 0], rng, done), tok

    # N-1 decode steps as a chain of scans (the Nth token needs only a
    # sample, not another forward pass): each scan segment attends over a
    # statically-bounded prefix that grows with the fill, so attention work
    # totals O(N * (t + N/2)) instead of O(N * (t + N)).
    # step i writes slot t + i, so the segment ending at `end` needs t + end.
    carry = (cache, last, rng, jnp.zeros((b,), bool))
    carry, chunks = _chunked_scan(step, carry, 0, max_new_tokens - 1, lambda end: t + end)
    cache, last, rng, done = carry
    final_tok, _ = sample_next(last, jax.random.split(rng)[1], done)
    tokens = jnp.concatenate(chunks + [final_tok[None]], axis=0)
    return tokens.T  # [B, max_new_tokens]


def _pad_len_from_mask(prompt_mask, b: int, t: int):
    """[B, T] {0,1} LEFT-pad keep-mask -> per-row pad counts [B] int32
    (None passthrough). Concrete masks are validated eagerly — a
    right-padded mask would silently generate garbage."""
    if prompt_mask is None:
        return None
    import numpy as np

    if jnp.shape(prompt_mask) != (b, t):
        raise ValueError(f"prompt_mask must be [B, T] == {(b, t)}, got {jnp.shape(prompt_mask)}")
    if not isinstance(prompt_mask, jax.core.Tracer):
        host = np.asarray(prompt_mask).astype(np.int32)
        if not (np.diff(host, axis=1) >= 0).all():
            raise ValueError("prompt_mask must be LEFT padding: zeros then ones per row")
    prompt_mask = jnp.asarray(prompt_mask, jnp.int32)
    return (t - jnp.sum(prompt_mask, axis=1)).astype(jnp.int32)


def _check_len(model: DecoderLM, t: int, max_new_tokens: int) -> None:
    if t + max_new_tokens > model.cfg.max_seq_len:
        raise ValueError(
            f"prompt ({t}) + max_new_tokens ({max_new_tokens}) exceeds max_seq_len ({model.cfg.max_seq_len})"
        )


def generate(
    model: DecoderLM,
    params: Any,
    prompt: jnp.ndarray,
    max_new_tokens: int = 32,
    *,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    rng: jax.Array | None = None,
    eos_id: int = -1,
    pad_id: int = 0,
    prompt_mask: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Generate ``max_new_tokens`` continuations of ``prompt`` [B, T] int32.
    Greedy when ``temperature == 0``; otherwise temperature sampling with
    optional ``top_k`` / nucleus ``top_p`` truncation. Rows that emit
    ``eos_id`` keep emitting ``pad_id``. Returns [B, max_new_tokens] int32.

    Ragged prompts: LEFT-pad them to a common length and pass
    ``prompt_mask`` ([B, T] {0,1}, zeros first) — pad slots are masked out
    of attention and rotary positions count from each row's first real
    token, so every row decodes exactly as it would unpadded.

    The whole generation — prefill + scan over decode steps — is one
    compiled program; recompiles happen only when shapes or the static
    knobs change.
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    b, t = prompt.shape
    _check_len(model, t, max_new_tokens)
    pad_len = _pad_len_from_mask(prompt_mask, b, t)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    return _generate_compiled(
        model, params, prompt, pad_len, rng,
        int(max_new_tokens), float(temperature), int(top_k), float(top_p), int(eos_id), int(pad_id),
    )


@functools.partial(
    jax.jit, static_argnames=("model", "max_new_tokens", "num_beams", "eos_id", "pad_id")
)
def _beam_search_compiled(
    model: DecoderLM,
    params,
    prompt: jnp.ndarray,
    pad_len: jnp.ndarray | None,
    length_penalty: jnp.ndarray,
    max_new_tokens: int,
    num_beams: int,
    eos_id: int,
    pad_id: int,
):
    b, t = prompt.shape
    k = num_beams
    v = model.cfg.vocab_size
    neg = jnp.float32(-1e30)

    # int8 weight-only kernels stay quantized in-program, with the off-TPU
    # operand widen hoisted out of the beam loop (see _generate_compiled)
    from .quant import dequant_tree, widen_quant_tree

    params = dequant_tree(params, model.cfg.dtype, keep=lambda p: p.endswith("kernel"))
    if jax.default_backend() != "tpu":
        params = widen_quant_tree(params)
    # Prefill once per batch row, then tile the cache across beams.
    cache = init_cache(model.cfg, b, t + max_new_tokens, dtype=model.cfg.dtype)
    logits, cache = decode_step(
        model, params, prompt, cache, offset=0, pad_len=pad_len, attend_len=t
    )
    cache = jax.tree_util.tree_map(lambda x: jnp.repeat(x, k, axis=0), cache)  # [B*K, ...]
    pad_len_k = None if pad_len is None else jnp.repeat(pad_len, k, axis=0)  # beam-tiled
    first_lp = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32))  # [B, V]

    # Step 0: the K best first tokens seed the beams.
    scores, tok = jax.lax.top_k(first_lp, k)  # [B, K]
    finished = tok == eos_id
    tokens = jnp.full((b, k, max_new_tokens), pad_id, jnp.int32)
    tokens = tokens.at[:, :, 0].set(tok)
    lengths = jnp.ones((b, k), jnp.int32)  # emitted tokens incl. eos

    def step(carry, i, attend_len):
        cache, tokens, scores, lengths, finished, last_tok = carry
        # last_tok was emitted at position t + i - 1; its K/V lands there
        logits, cache = decode_step(
            model, params, last_tok.reshape(b * k, 1), cache, offset=t + i - 1,
            pad_len=pad_len_k, attend_len=attend_len,
        )
        lp = jax.nn.log_softmax(logits[:, 0].astype(jnp.float32)).reshape(b, k, v)
        # finished beams may only extend with pad at no cost; everything else
        # is impossible, so a finished beam's score freezes
        pad_only = jnp.full((v,), neg).at[pad_id].set(0.0)
        lp = jnp.where(finished[..., None], pad_only[None, None], lp)

        cand = scores[..., None] + lp  # [B, K, V]
        scores, flat_idx = jax.lax.top_k(cand.reshape(b, k * v), k)  # [B, K]
        beam_idx = flat_idx // v  # which parent beam
        tok = (flat_idx % v).astype(jnp.int32)

        # reorder per-beam state to follow the winning parents. Only the
        # FILLED cache prefix needs the gather — unwritten tail slots are
        # zeros on every beam, so reordering them would move identical data
        def reorder_prefix(x):
            pre = jax.lax.slice_in_dim(x, 0, attend_len, axis=1)
            pre = jnp.take_along_axis(
                pre.reshape(b, k, *pre.shape[1:]),
                beam_idx.reshape(b, k, *([1] * (x.ndim - 1))),
                axis=1,
            ).reshape(b * k, *pre.shape[1:])
            return jax.lax.dynamic_update_slice_in_dim(x, pre, 0, axis=1)

        take = lambda x: jnp.take_along_axis(x, beam_idx, axis=1)
        tokens = jnp.take_along_axis(tokens, beam_idx[..., None], axis=1)
        lengths, finished = take(lengths), take(finished)
        cache = jax.tree_util.tree_map(reorder_prefix, cache)

        tokens = tokens.at[:, :, i].set(tok)
        lengths = jnp.where(finished, lengths, lengths + 1)
        finished = finished | (tok == eos_id)
        return (cache, tokens, scores, lengths, finished, tok), None

    # chunked like generate(): each scan segment attends over (and gathers)
    # a statically-bounded prefix that grows with the fill. Beam step i
    # writes slot t + i - 1, so the segment ending at `end` needs t + end - 1.
    carry = (cache, tokens, scores, lengths, finished, tok)
    carry, _ = _chunked_scan(step, carry, 1, max_new_tokens - 1, lambda end: t + end - 1)
    (cache, tokens, scores, lengths, finished, _) = carry

    # pick each row's best beam under GNMT-style length normalisation
    norm = scores / (lengths.astype(jnp.float32) ** length_penalty)
    best = jnp.argmax(norm, axis=1)  # [B]
    best_tokens = jnp.take_along_axis(tokens, best[:, None, None], axis=1)[:, 0]
    best_scores = jnp.take_along_axis(norm, best[:, None], axis=1)[:, 0]
    return best_tokens, best_scores


def beam_search(
    model: DecoderLM,
    params: Any,
    prompt: jnp.ndarray,
    max_new_tokens: int = 32,
    *,
    num_beams: int = 4,
    length_penalty: float = 1.0,
    eos_id: int = -1,
    pad_id: int = 0,
    prompt_mask: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Beam-search decoding: returns ``(tokens [B, max_new_tokens],
    scores [B])`` where scores are length-normalised sequence log-probs
    (``sum logp / len**length_penalty``). Beams that emit ``eos_id`` freeze
    and pad. Like :func:`generate`, the whole search — prefill, scan, beam
    reordering (cache gathered along the beam axis) — is ONE compiled
    program. Ragged prompts work like :func:`generate`: LEFT-pad and pass
    ``prompt_mask``."""
    prompt = jnp.asarray(prompt, jnp.int32)
    b, t = prompt.shape
    _check_len(model, t, max_new_tokens)
    if num_beams < 1:
        raise ValueError(f"num_beams must be >= 1, got {num_beams}")
    if num_beams > model.cfg.vocab_size:
        raise ValueError("num_beams cannot exceed vocab_size")
    if not 0 <= pad_id < model.cfg.vocab_size:
        # pad_id is a scatter index into the finished-beam cost vector; an
        # out-of-range value would silently corrupt eos handling under jit
        raise ValueError(f"pad_id must be in [0, vocab_size), got {pad_id}")
    pad_len = _pad_len_from_mask(prompt_mask, b, t)
    # length_penalty rides as a traced operand: sweeping it must not
    # recompile the whole search
    return _beam_search_compiled(
        model, params, prompt, pad_len, jnp.float32(length_penalty), int(max_new_tokens),
        int(num_beams), int(eos_id), int(pad_id),
    )
