"""Small convnet for the MNIST example pair — the model the reference's
examples/mnist.py:28-41 builds with torch.nn, re-expressed in flax.

TPU notes: NHWC layout (XLA:TPU's native conv layout), bf16-friendly compute
with fp32 params, matmul-heavy head so the MXU does the work.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MnistCNN(nn.Module):
    """conv(32) -> conv(64) -> maxpool -> dense(128) -> dense(10)."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        # x: [B, 28, 28, 1] (NHWC)
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(64, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x
