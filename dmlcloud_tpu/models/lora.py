"""LoRA: low-rank adapter finetuning (arXiv:2106.09685), TPU-first.

The reference framework has no parameter-efficient finetuning story; torch
users reach for peft. Here LoRA is three pure functions over param pytrees,
shaped for how this framework already trains:

- ``lora_init`` builds an adapter tree for every matched kernel
  (``b`` zero-initialised, so the merged model starts EXACTLY at the base).
- ``lora_merge`` folds ``base + (a @ b) * alpha/rank`` inside the traced
  step: the base rides ``TrainState.extras`` (carried through the donated
  compiled step, checkpointed, NOT differentiated) while the adapters are
  ``state.params`` — so autodiff reaches only the adapters and the
  optimizer state is rank-sized, which is the actual memory win of LoRA
  (Adam moments for a 7B model are 56 GB fp32; for rank-16 adapters they
  are tens of MB).
- ``lora_merge`` again at the end exports a standalone finetuned model
  (e.g. back to a HF state dict via ``models.hf``).

Canonical stage::

    class LoraStage(dml.TrainValStage):
        def pre_stage(self):
            adapters = lora_init(jax.random.PRNGKey(0), base, rank=16)
            self.pipeline.register_model(
                "lm", apply_fn=model.apply,
                params={"params": adapters, "lora_base": base})
            self.pipeline.register_optimizer("adamw", optax.adamw(1e-4))

        def step(self, state, batch):
            merged = lora_merge(state.extras["lora_base"], state.params)
            return lm_loss(state.apply_fn({"params": merged}, batch), batch)

Kernels of any rank >= 2 are supported: leading axes collapse into the
LoRA "in" dimension and the last axis is "out" (covers this repo's
``[hidden, heads, head_dim]`` attention kernels and conv ``[h, w, in, out]``
filters alike).
"""

from __future__ import annotations

import re
from typing import Any, Callable

import jax
import jax.numpy as jnp
from flax import struct

__all__ = [
    "LoraPair",
    "lora_init",
    "lora_merge",
    "lora_size",
    "default_match",
    "batched_lora_delta",
]


class LoraPair(struct.PyTreeNode):
    """One adapted kernel's factor pair: ``delta = (a @ b) * alpha/rank``.

    A distinct pytree node (not a bare dict) so ``lora_merge`` can identify
    adapter leaves unambiguously — a model with a submodule literally named
    ``a`` must not be mistaken for one."""

    a: jax.Array
    b: jax.Array


def default_match(path: str, leaf: Any) -> bool:
    """Adapt every matrix-shaped ``kernel`` (dense/attention/conv); biases,
    norms, and embeddings stay frozen-only."""
    return path.endswith("kernel") and getattr(leaf, "ndim", 0) >= 2


def _paths(tree: Any, is_leaf=None) -> Any:
    """Tree of '/'-joined key paths, same structure as ``tree``."""
    from ..parallel.mesh import path_str

    return jax.tree_util.tree_map_with_path(lambda kp, _: path_str(kp), tree, is_leaf=is_leaf)


def _as_matcher(match: Any) -> Callable[[str, Any], bool]:
    if match is None:
        return default_match
    if isinstance(match, str):
        pattern = re.compile(match)
        return lambda path, leaf: pattern.search(path) is not None and getattr(leaf, "ndim", 0) >= 2
    return match


def lora_init(
    rng: jax.Array,
    params: Any,
    rank: int = 8,
    match: str | Callable[[str, Any], bool] | None = None,
    in_axes: int | None = None,
) -> Any:
    """Adapter tree for ``params``: matched leaves become ``LoraPair``
    factor pairs, everything else becomes None (so the tree stays
    params-shaped for sharding rules and optax alike — wrap the optimizer
    only if your optax version rejects None leaves; stock optax treats
    them as empty subtrees).

    ``a`` is ``[in, rank]`` Gaussian (1/sqrt(in) scale, the LoRA paper's
    init), ``b`` is ``[rank, out]`` zeros — the merged model starts exactly
    at the base. ``match`` is the ``default_match`` kernel predicate, a
    regex over '/'-joined param paths, or an explicit ``(path, leaf) ->
    bool`` callable.

    ``in_axes`` picks how a rank-``n`` kernel's axes split between the LoRA
    "in" and "out" dims: the leading ``in_axes`` axes collapse into "in",
    the rest into "out". The default (``None``) keeps the historical
    all-but-last split. Batched multi-tenant serving
    (:class:`dmlcloud_tpu.serve.AdapterSet`) requires ``in_axes=1`` — the
    factored per-request application ``(x @ a) @ b`` only works when ``a``
    contracts against the layer INPUT, i.e. the kernel's first axis.
    ``lora_merge`` accepts either split (the delta reshape is
    factorization-agnostic)."""
    matcher = _as_matcher(match)
    paths = _paths(params)
    counter = [0]

    def init_leaf(path, leaf):
        if not matcher(path, leaf):
            return None
        n_in = leaf.ndim - 1 if in_axes is None else int(in_axes)
        if not 1 <= n_in < leaf.ndim:
            raise ValueError(
                f"in_axes must be in [1, ndim) for {path!r} (ndim {leaf.ndim}), got {n_in}"
            )
        d_in = d_out = 1
        for s in leaf.shape[:n_in]:
            d_in *= int(s)
        for s in leaf.shape[n_in:]:
            d_out *= int(s)
        counter[0] += 1
        key = jax.random.fold_in(rng, counter[0])
        a = jax.random.normal(key, (d_in, rank), jnp.float32) / jnp.sqrt(d_in)
        return LoraPair(a=a, b=jnp.zeros((rank, d_out), jnp.float32))

    return jax.tree_util.tree_map(init_leaf, paths, params)


def lora_merge(base: Any, adapters: Any, alpha: float = 16.0) -> Any:
    """``base + (a @ b) * alpha/rank`` on every adapted leaf, pure and
    traced (call it INSIDE your step; under jit the delta fuses into the
    consumer and grads flow only to ``a``/``b``). Non-adapted leaves pass
    through untouched. The delta computes in fp32 and casts to the base
    leaf's dtype."""

    def merge_leaf(ad, p):
        if ad is None:
            return p
        rank = ad.a.shape[-1]
        delta = (ad.a @ ad.b) * (alpha / rank)
        return (p.astype(jnp.float32) + delta.reshape(p.shape)).astype(p.dtype)

    # adapters is the outer tree: its None leaves mark non-adapted params
    return jax.tree_util.tree_map(
        merge_leaf, adapters, base, is_leaf=lambda x: x is None or isinstance(x, LoraPair)
    )


def batched_lora_delta(x: jax.Array, a: jax.Array, b: jax.Array, scale: float = 1.0) -> jax.Array:
    """Per-row LoRA delta for multi-tenant batched serving: each batch row
    applies ITS OWN adapter, gathered by request id before the call.

    ``x`` is the layer input ``[B, T, d_in]``; ``a``/``b`` are the
    already-gathered per-row factors ``[B, d_in, r]`` / ``[B, r, d_out]``
    (``AdapterSet`` stacks every tenant's pair and indexes by adapter id).
    Returns the fp32 delta ``[B, T, d_out]`` = ``(x @ a_row) @ b_row *
    scale`` — the ``lora_merge``-free application order: rank-r work per
    token instead of materialising any per-row ``d_in x d_out`` weight."""
    h = jnp.einsum("btd,bdr->btr", x.astype(jnp.float32), a.astype(jnp.float32))
    return jnp.einsum("btr,bro->bto", h, b.astype(jnp.float32)) * scale


def lora_size(adapters: Any) -> int:
    """Trainable adapter parameter count (what the optimizer actually sees)."""
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(adapters))


def lora_partition_rules(base_rules: list) -> list:
    """Sharding rules for a LoRA setup: replicate the adapter factors, keep
    ``base_rules`` for everything else (the frozen base in ``extras`` still
    shards over fsdp/model axes — the point of LoRA on big models).

    Needed because T5X-style rules match with ``re.search``: a base rule for
    ``attn/q_proj/kernel`` also matches the adapter path
    ``attn/q_proj/kernel/a``, which would pointlessly shard the rank-R
    factor (R is rarely divisible by a mesh axis, and rank-dim tensor
    parallelism buys collectives for no FLOPs). First-match-wins ordering
    puts the adapter rule in front."""
    from jax.sharding import PartitionSpec

    return [(r"kernel/(a|b)$", PartitionSpec()), *base_rules]
