"""Decoder-only transformer LM (Llama-style) — the BASELINE.json
"Llama-3-8B pretrain (FSDP -> pjit named-sharding)" config family, built
TPU-first:

- RMSNorm (fp32 accumulation), rotary position embeddings, grouped-query
  attention, SwiGLU MLP — the modern decoder recipe.
- bf16 activations / fp32 params; every matmul shaped for the MXU.
- Tensor parallelism is expressed as data, not code: ``partition_rules()``
  returns T5X-style (regex -> PartitionSpec) rules that shard attention heads
  and MLP hidden over the ``model`` axis and everything else over ``fsdp``.
  XLA inserts the all-reduces; no Megatron-style manual f/g collectives.
- Attention pluggability: ``attn_impl`` picks 'dot' (reference einsum path),
  'flash' (Pallas TPU kernel, ops/flash_attention.py), or 'ring'
  (sequence-parallel ring attention over the ``seq`` axis,
  ops/ring_attention.py) — the long-context path.
"""

from __future__ import annotations

import math

from dataclasses import dataclass, field
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    num_layers: int = 8
    num_heads: int = 8
    num_kv_heads: int | None = None  # None => MHA; < num_heads => GQA
    head_dim: int = 64
    hidden_dim: int = 512
    mlp_dim: int = 1408  # ~8/3 * hidden, SwiGLU convention
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    # RoPE context-extension scaling, as a HASHABLE tuple (the config is a
    # jit-static aux of the model): ("linear", factor) or
    # ("llama3", factor, low_freq_factor, high_freq_factor, original_len).
    rope_scaling: tuple | None = None
    dtype: Any = jnp.bfloat16
    tie_embeddings: bool = False
    attn_impl: str = "dot"  # 'dot' | 'flash' | 'ring'
    # Sliding-window attention (Mistral convention): each token attends to
    # itself + the previous W-1. Supported by every impl: 'dot'/'flash'
    # (stale K/V blocks skipped — O(T*W) compute), 'ring' (the ring visits
    # only 1 + ceil((W-1)/Tl) blocks — O(W) communication), and the decode
    # cache.
    sliding_window: int | None = None
    # MoE: replace the dense MLP with an expert-parallel MoEMLP (models/moe.py)
    # in every ``moe_every``-th block (0 = dense everywhere). Experts shard
    # over the ``expert`` mesh axis via moe_partition_rules().
    num_experts: int = 0
    moe_every: int = 2
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    seq_axis: str = "seq"  # mesh axis used when attn_impl == 'ring'
    # Mesh for attn_impl='ring' under plain jit (ring_attention_sharded wraps
    # itself in shard_map); leave None when the step is already shard_mapped.
    mesh: Any = None
    # Residual-stream sharding constraint ([B, T, D] activations), applied
    # after the embedding and every block. Pin this (e.g. a NamedSharding of
    # P(('data','fsdp'))) on multi-axis meshes so XLA's sharding propagation
    # keeps one layout instead of involuntarily rematerialising between
    # conflicting choices. None = let XLA decide (fine on 1-axis meshes).
    act_sharding: Any = None
    # Gradient rematerialisation: recompute each block in the backward pass
    # instead of saving its activations — trades ~1/3 more FLOPs for O(1)
    # blocks of live activation memory, the standard lever for long-context
    # training (composes with flash/ring attention, which already avoid the
    # [T, S] score matrix).
    remat: bool = False

    def __post_init__(self):
        if self.attn_impl not in ("dot", "flash", "ring"):
            # a typo here would otherwise silently run the unfused path
            raise ValueError(f"attn_impl must be 'dot', 'flash' or 'ring', got {self.attn_impl!r}")
        if self.sliding_window is not None and self.sliding_window < 1:
            raise ValueError(f"sliding_window must be >= 1, got {self.sliding_window}")

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads


def llama_partition_rules() -> list[tuple[str, P]]:
    """T5X-style sharding rules for this model family: embeddings and heads
    over ``model`` (tensor parallel), with ``fsdp`` sharding the other large
    axis. Axes missing from the active mesh are dropped automatically
    (parallel/mesh.py make_param_policy). Includes the MoE rules so
    expert-parallel configs shard out of the box."""
    from .moe import moe_partition_rules

    return list(moe_partition_rules()) + [
        # vocab over fsdp, features over model: the token gather then never
        # crosses the model axis (each TP shard gathers its feature slice)
        ("embed/embedding", P("fsdp", "model")),
        ("attn/(q|k|v)_proj/kernel", P("fsdp", "model")),
        ("attn/o_proj/kernel", P("model", "fsdp")),
        ("mlp/(gate|up)_proj/kernel", P("fsdp", "model")),
        ("mlp/down_proj/kernel", P("model", "fsdp")),
        ("lm_head/kernel", P("fsdp", "model")),
        ("norm", P()),
        (".*", P()),
    ]


class RMSNorm(nn.Module):
    eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones_init(), (x.shape[-1],), jnp.float32)
        x32 = x.astype(jnp.float32)
        normed = x32 * jax.lax.rsqrt(jnp.mean(x32**2, axis=-1, keepdims=True) + self.eps)
        return (normed * scale).astype(x.dtype)


def rope_frequencies(
    head_dim: int, max_len: int, theta: float, scaling: tuple | None = None
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Rotary cos/sin tables; ``scaling`` applies a context-extension
    transform to the base frequencies:

    - ``("linear", factor)`` — positions interpolated by 1/factor;
    - ``("llama3", factor, low_freq_factor, high_freq_factor, orig_len)`` —
      Llama-3's wavelength-banded scheme: high-frequency components kept,
      low-frequency ones divided by ``factor``, a smooth ramp between.
    """
    freqs = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    if scaling is not None:
        kind = scaling[0]
        if kind == "linear":
            freqs = freqs / float(scaling[1])
        elif kind == "llama3":
            _, factor, low_ff, high_ff, orig_len = scaling
            wavelen = 2.0 * math.pi / freqs
            low_wl = orig_len / float(low_ff)
            high_wl = orig_len / float(high_ff)
            smooth = (orig_len / wavelen - low_ff) / (high_ff - low_ff)
            scaled = jnp.where(
                wavelen > low_wl,
                freqs / factor,  # long wavelengths: fully interpolated
                jnp.where(
                    wavelen < high_wl,
                    freqs,  # short wavelengths: untouched
                    (1 - smooth) * freqs / factor + smooth * freqs,
                ),
            )
            freqs = scaled
        else:
            raise ValueError(f"unsupported rope scaling kind {kind!r}")
    t = jnp.arange(max_len, dtype=jnp.float32)
    angles = jnp.outer(t, freqs)  # [T, head_dim/2]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(
    x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray, offset: int = 0, positions: jnp.ndarray | None = None
) -> jnp.ndarray:
    """x: [B, T, H, D]. Rotates pairs (even, odd) of the head dim.
    ``positions`` [B, T] overrides the contiguous ``offset`` window —
    packed rows use it to restart positions at each segment boundary."""
    if positions is not None:
        cos = cos[positions][:, :, None, :]  # [B, T, 1, D/2]
        sin = sin[positions][:, :, None, :]
    else:
        seq_len = x.shape[1]
        cos = jax.lax.dynamic_slice_in_dim(cos, offset, seq_len)[None, :, None, :]
        sin = jax.lax.dynamic_slice_in_dim(sin, offset, seq_len)[None, :, None, :]
    x1, x2 = x[..., ::2], x[..., 1::2]
    rotated = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rotated.reshape(x.shape).astype(x.dtype)


def _window_keep(q_pos, k_pos, window: int) -> jnp.ndarray:
    """The sliding-window predicate, defined ONCE (Mistral convention:
    attend to self + the previous window-1 → ``q_pos - k_pos < window``).
    Broadcasts over whatever position shapes the caller derived."""
    return (q_pos - k_pos) < window


def _dot_attention(q, k, v, causal: bool = True, mask: jnp.ndarray | None = None):
    """Reference attention: fp32 softmax, bf16 matmuls. q:[B,T,H,D] k/v:[B,S,K,D].
    ``mask`` ([T, S] or [B, T, S] bool, True = attend) REPLACES the causal
    triangle entirely — callers must bake causality into it (the decode path
    does for unwritten KV-cache slots, packed training for segment
    isolation)."""
    b, t, h, d = q.shape
    s, kh = k.shape[1], k.shape[2]
    group = h // kh
    q = q.reshape(b, t, kh, group, d)
    scores = jnp.einsum("btkgd,bskd->bkgts", q, k).astype(jnp.float32) / jnp.sqrt(d)
    if mask is None and causal:
        mask = jnp.tril(jnp.ones((t, s), dtype=bool), k=s - t)
    if mask is not None:
        if mask.ndim == 2:
            mask = mask[None]  # [B(1), T, S]
        scores = jnp.where(mask[:, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, v)
    return out.reshape(b, t, h, d)


def _adapter_add(y, inp, name, adapters):
    """Add the per-row LoRA delta for dense ``name`` when ``adapters``
    carries a stacked pair for it (multi-tenant serving; see
    ``serve.AdapterSet``). ``adapters`` is ``(subtree, ids)`` — the
    lora-init-shaped subtree for the enclosing module and the per-row
    adapter ids — or None. Rows gather their own factors by id; the delta
    is the merge-free ``(x @ a) @ b`` order (``lora.batched_lora_delta``,
    ``b`` pre-scaled by alpha/rank at stacking time)."""
    if adapters is None:
        return y
    from .lora import LoraPair, batched_lora_delta

    sub, ids = adapters
    pair = (sub or {}).get(name)
    if isinstance(pair, dict):
        pair = pair.get("kernel")
    if not isinstance(pair, LoraPair):
        return y
    delta = batched_lora_delta(inp, pair.a[ids], pair.b[ids])
    return y + delta.reshape(y.shape).astype(y.dtype)


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(
        self, x, cos, sin, cache=None, offset=0, seg_info=None, decode_pad=None, attend_len=None,
        paged=None, adapters=None,
    ):
        from .quant import QuantDenseGeneral

        cfg = self.cfg
        # quant-aware: int8 weight-only trees (models/quant.py) feed the
        # matmuls directly, scales applied to the fp32 accumulator
        dense = lambda feats, name: QuantDenseGeneral(
            feats, axis=-1, use_bias=False, dtype=cfg.dtype, param_dtype=jnp.float32, name=name
        )
        b, t, _ = x.shape
        q = _adapter_add(dense((cfg.num_heads, cfg.head_dim), "q_proj")(x), x, "q_proj", adapters)
        k = _adapter_add(dense((cfg.kv_heads, cfg.head_dim), "k_proj")(x), x, "k_proj", adapters)
        v = _adapter_add(dense((cfg.kv_heads, cfg.head_dim), "v_proj")(x), x, "v_proj", adapters)

        if seg_info is None and decode_pad is None and paged is None:
            q = apply_rope(q, cos, sin, offset=offset)
            k = apply_rope(k, cos, sin, offset=offset)
        elif paged is not None:
            # paged decode: every row sits at its own absolute position
            # (fill + step offset) — precomputed once in DecoderLM
            _, _, positions = paged
            q = apply_rope(q, cos, sin, positions=positions)
            k = apply_rope(k, cos, sin, positions=positions)
        elif decode_pad is not None:
            # left-padded ragged prompts: per-row positions (real tokens
            # count from 0 at each row's first real slot)
            _, positions = decode_pad
            q = apply_rope(q, cos, sin, positions=positions)
            k = apply_rope(k, cos, sin, positions=positions)

        new_cache = None
        if seg_info is not None:
            # Packed sequences (precomputed once in DecoderLM): rotary
            # positions restart at each segment's first token and attention
            # is causal AND same-segment (the flash kernel takes the raw ids,
            # the dot path the precomputed mask).
            positions, mask, seg_ids = seg_info
            q = apply_rope(q, cos, sin, positions=positions)
            k = apply_rope(k, cos, sin, positions=positions)
            if cfg.attn_impl == "flash":
                from ..ops.flash_attention import flash_attention

                out = flash_attention(
                    q, k, v, causal=True, window=cfg.sliding_window, segment_ids=seg_ids
                )
            else:
                out = _dot_attention(q, k, v, mask=mask)
        elif paged is not None:
            # Paged decode (the serving engine's path): the cache leaves
            # are the POOL pages [num_blocks, block_size, KH, D]. Write the
            # new K/V into the pages each row's block table names, then
            # gather the table back into a contiguous [B, NB*bs, KH, D]
            # view and run the SAME masked attention as the dense path —
            # identical math, memory owned by the pool. Sentinel table
            # entries drop the writes of padded rows and clip the gathers
            # into masked positions (ops/paged_attention.py).
            from ..ops.paged_attention import gather_pages, scatter_tokens

            tables, fill, positions = paged
            k_pool = scatter_tokens(cache["k"], tables, positions, k)
            v_pool = scatter_tokens(cache["v"], tables, positions, v)
            new_cache = {"k": k_pool, "v": v_pool}
            gk = gather_pages(k_pool, tables)
            gv = gather_pages(v_pool, tables)
            kv_pos = jnp.arange(gk.shape[1])[None, None, :]  # [1, 1, L]
            q_pos = positions[:, :, None]  # [B, t, 1] absolute positions
            mask = kv_pos <= q_pos  # causal AND only this row's filled slots
            if cfg.sliding_window is not None:
                mask = mask & _window_keep(q_pos, kv_pos, cfg.sliding_window)
            out = _dot_attention(q, gk, gv, mask=mask)
        elif cache is not None:
            # Autoregressive decode: write this call's K/V into the static-
            # shape cache at ``offset`` and attend over the FILLED prefix
            # with the unwritten tail masked out. ``attend_len`` (STATIC,
            # chunk-rounded by the caller — generate.py grows it as the
            # cache fills) bounds the slots actually read, so per-token
            # attention cost scales with fill instead of max_len while
            # every shape stays static for XLA.
            k = jax.lax.dynamic_update_slice(cache["k"], k.astype(cache["k"].dtype), (0, offset, 0, 0))
            v = jax.lax.dynamic_update_slice(cache["v"], v.astype(cache["v"].dtype), (0, offset, 0, 0))
            new_cache = {"k": k, "v": v}
            s = k.shape[1]
            if attend_len is not None and attend_len < s:
                s = int(attend_len)
                k = jax.lax.slice_in_dim(k, 0, s, axis=1)
                v = jax.lax.slice_in_dim(v, 0, s, axis=1)
            q_pos = offset + jnp.arange(t)[:, None]  # [t, 1]
            kv_pos = jnp.arange(s)[None, :]  # [1, s]
            mask = kv_pos <= q_pos  # causal AND only written slots
            if cfg.sliding_window is not None:
                mask = mask & _window_keep(q_pos, kv_pos, cfg.sliding_window)
            if decode_pad is not None:
                # left-pad slots hold garbage K/V — mask them per row
                pad_len, _ = decode_pad
                mask = mask[None] & (kv_pos[None] >= pad_len[:, None, None])
            out = _dot_attention(q, k, v, mask=mask)
        elif cfg.attn_impl == "flash":
            from ..ops.flash_attention import flash_attention

            out = flash_attention(q, k, v, causal=True, window=cfg.sliding_window)
        elif cfg.attn_impl == "ring":
            if cfg.mesh is not None:
                from ..ops.ring_attention import ring_attention_sharded

                out = ring_attention_sharded(
                    q, k, v, cfg.mesh, axis_name=cfg.seq_axis, causal=True, window=cfg.sliding_window
                )
            else:
                from ..ops.ring_attention import ring_attention

                out = ring_attention(
                    q, k, v, axis_name=cfg.seq_axis, causal=True, window=cfg.sliding_window
                )
        elif cfg.sliding_window is not None:
            pos = jnp.arange(t)
            q_pos, k_pos = pos[:, None], pos[None, :]
            out = _dot_attention(
                q, k, v, mask=(q_pos >= k_pos) & _window_keep(q_pos, k_pos, cfg.sliding_window)
            )
        else:
            out = _dot_attention(q, k, v, causal=True)

        out = out.reshape(b, t, cfg.num_heads * cfg.head_dim)
        from .quant import QuantDenseGeneral

        proj = QuantDenseGeneral(
            cfg.hidden_dim, use_bias=False, dtype=cfg.dtype, param_dtype=jnp.float32, name="o_proj"
        )(out)
        proj = _adapter_add(proj, out, "o_proj", adapters)
        return proj if new_cache is None else (proj, new_cache)


class MLP(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, adapters=None):
        from .quant import QuantDense

        cfg = self.cfg
        dense = lambda feats, name: QuantDense(
            feats, use_bias=False, dtype=cfg.dtype, param_dtype=jnp.float32, name=name
        )
        gate = _adapter_add(dense(cfg.mlp_dim, "gate_proj")(x), x, "gate_proj", adapters)
        up = _adapter_add(dense(cfg.mlp_dim, "up_proj")(x), x, "up_proj", adapters)
        h = nn.silu(gate) * up
        return _adapter_add(dense(cfg.hidden_dim, "down_proj")(h), h, "down_proj", adapters)


class DecoderBlock(nn.Module):
    cfg: TransformerConfig
    use_moe: bool = False

    @nn.compact
    def __call__(
        self, x, cos, sin, cache=None, offset=0, seg_info=None, decode_pad=None, attend_len=None,
        paged=None, adapters=None,
    ):
        cfg = self.cfg
        # split the lora-init-shaped adapter subtree for this layer into the
        # attn/mlp halves its submodules consume (ids ride along unchanged)
        attn_ad = mlp_ad = None
        if adapters is not None:
            sub, ids = adapters
            attn_ad = ((sub or {}).get("attn"), ids)
            mlp_ad = ((sub or {}).get("mlp"), ids)
        new_cache = None
        if cache is not None:
            attn_out, new_cache = Attention(cfg, name="attn")(
                RMSNorm(name="attn_norm")(x), cos, sin, cache=cache, offset=offset,
                decode_pad=decode_pad, attend_len=attend_len, paged=paged, adapters=attn_ad,
            )
            x = x + attn_out
        else:
            x = x + Attention(cfg, name="attn")(
                RMSNorm(name="attn_norm")(x), cos, sin, seg_info=seg_info, adapters=attn_ad
            )
        if self.use_moe:
            from .moe import MoEConfig, MoEMLP

            moe_cfg = MoEConfig(
                num_experts=cfg.num_experts,
                top_k=cfg.moe_top_k,
                capacity_factor=cfg.moe_capacity_factor,
                hidden_dim=cfg.hidden_dim,
                mlp_dim=cfg.mlp_dim,
                dtype=cfg.dtype,
            )
            # MoE blocks carry no per-request adapters (expert routing and
            # LoRA-per-tenant compose poorly; dense layers cover serving)
            x = x + MoEMLP(moe_cfg, name="moe")(RMSNorm(name="mlp_norm")(x))
        else:
            x = x + MLP(cfg, name="mlp")(RMSNorm(name="mlp_norm")(x), adapters=mlp_ad)
        return x if new_cache is None else (x, new_cache)


class DecoderLM(nn.Module):
    """Causal LM: tokens [B, T] int32 -> logits [B, T, vocab] fp32.

    With ``cache``/``offset`` (see ``models/generate.py``) runs in
    autoregressive-decode mode and returns ``(logits, new_cache)``. With
    ``cache`` holding pool pages and ``pages=(block_tables, fill)`` the
    decode is PAGED (the serving engine's path, ``dmlcloud_tpu/serve/``):
    each row reads/writes the pool blocks its table names at its own
    absolute position. With ``segment_ids`` [B, T] int32, rows hold
    multiple packed examples and attention never crosses segment
    boundaries (pair with ``lm_loss(..., segment_ids=...)``).
    ``adapters=(stacked_tree, ids)`` applies per-row LoRA deltas gathered
    by adapter id inside every dense layer (multi-tenant serving; see
    ``serve.AdapterSet``).

    ``return_hidden=True`` without a cache returns the final hidden states
    instead of logits (the chunked-vocab loss path); WITH a cache it
    returns ``((logits, hidden), new_cache)`` — one decode forward feeding
    both the base distribution and any extra decode heads (Medusa)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(
        self, tokens, cache=None, offset=0, segment_ids=None, pad_len=None, attend_len=None,
        return_hidden=False, pages=None, adapters=None,
    ):
        cfg = self.cfg
        if pad_len is not None and cache is None:
            raise ValueError("pad_len (left-padded ragged prompts) is a decode-mode feature")
        if attend_len is not None and cache is None:
            raise ValueError("attend_len (bounded cache reads) is a decode-mode feature")
        paged = None
        if pages is not None:
            # Paged decode (serving engine): ``cache`` holds the POOL pages
            # per layer and ``pages = (block_tables [B, NB], fill [B])``
            # says where each row's tokens live and how many are filled.
            # Rows sit at their own absolute positions (no left-padding —
            # ragged prompts need no pad path here), so positions derive
            # from fill, not from a batch-wide offset.
            if cache is None:
                raise ValueError("pages (paged KV decode) requires the pool cache")
            if pad_len is not None or attend_len is not None:
                raise ValueError("pages replaces pad_len/attend_len: positions come from fill")
            tables, fill = pages
            positions = fill[:, None] + jnp.arange(tokens.shape[1])[None, :]
            paged = (tables, fill, positions)
        decode_pad = None
        if pad_len is not None:
            positions = jnp.maximum(jnp.arange(tokens.shape[1])[None, :] + offset - pad_len[:, None], 0)
            decode_pad = (pad_len, positions)
        seg_info = None
        if segment_ids is not None:
            if cache is not None:
                raise ValueError("segment_ids are a packed-training feature; unsupported in decode mode")
            if cfg.attn_impl == "ring":
                raise ValueError("segment_ids are not supported with attn_impl='ring'")
            # computed ONCE here, shared by every layer: per-segment rotary
            # positions (restart at each segment's first token) and the
            # causal-AND-same-segment attention mask
            t = tokens.shape[1]
            same = segment_ids[:, :, None] == segment_ids[:, None, :]  # [B, T, S]
            seg_start = jnp.argmax(same, axis=-1)  # first index of own segment
            positions = jnp.arange(t)[None, :] - seg_start
            if cfg.attn_impl == "flash":
                mask = None  # the flash kernels mask from the raw ids
            else:
                mask = jnp.tril(jnp.ones((t, t), dtype=bool))[None] & same
                if cfg.sliding_window is not None:
                    pos = jnp.arange(t)
                    mask = mask & _window_keep(pos[:, None], pos[None, :], cfg.sliding_window)[None]
            seg_info = (positions, mask, segment_ids)
        x = nn.Embed(
            cfg.vocab_size, cfg.hidden_dim, dtype=cfg.dtype, param_dtype=jnp.float32, name="embed"
        )(tokens)
        cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta, cfg.rope_scaling)

        def constrain(x):
            if cfg.act_sharding is None:
                return x
            if hasattr(cfg.act_sharding, "shard_shape"):
                try:  # skip when the (static) shape isn't divisible, e.g. module.init on a size-1 batch
                    cfg.act_sharding.shard_shape(x.shape)
                except (ValueError, ZeroDivisionError):
                    return x
            return jax.lax.with_sharding_constraint(x, cfg.act_sharding)

        x = constrain(x)
        block_cls = nn.remat(DecoderBlock, prevent_cse=True) if cfg.remat else DecoderBlock
        new_cache = {} if cache is not None else None
        adapter_tree, adapter_ids = adapters if adapters is not None else (None, None)
        for i in range(cfg.num_layers):
            use_moe = cfg.num_experts > 0 and cfg.moe_every > 0 and (i % cfg.moe_every == cfg.moe_every - 1)
            name = f"layer_{i}"
            layer_ad = None
            if adapter_tree is not None and adapter_tree.get(name) is not None:
                layer_ad = (adapter_tree[name], adapter_ids)
            if cache is not None:
                x, new_cache[name] = DecoderBlock(cfg, use_moe=use_moe, name=name)(
                    x, cos, sin, cache=cache[name], offset=offset, decode_pad=decode_pad,
                    attend_len=attend_len, paged=paged, adapters=layer_ad,
                )
                x = constrain(x)
            else:
                x = constrain(
                    block_cls(cfg, use_moe=use_moe, name=name)(
                        x, cos, sin, seg_info=seg_info, adapters=layer_ad
                    )
                )

        x = RMSNorm(name="final_norm")(x)
        if return_hidden and new_cache is None:
            # the chunked-vocab loss path (chunked_lm_loss) consumes the
            # final hidden states directly and never materializes logits
            return x
        if cfg.tie_embeddings:
            embed = self.variables["params"]["embed"]["embedding"]
            logits = jnp.einsum("btd,vd->btv", x.astype(jnp.float32), embed.astype(jnp.float32))
        else:
            from .quant import QuantDense

            logits = QuantDense(
                cfg.vocab_size, use_bias=False, dtype=jnp.float32, param_dtype=jnp.float32, name="lm_head"
            )(x)
            if adapter_tree is not None:
                logits = _adapter_add(logits, x, "lm_head", (adapter_tree, adapter_ids))
        if new_cache is None:
            return logits
        if return_hidden:
            # cache-stepping callers (Medusa decode heads) need the final
            # hidden states NEXT TO the base logits — one forward feeds the
            # base distribution and every extra head
            return (logits, x), new_cache
        return logits, new_cache


def chunked_lm_loss(
    hidden: jnp.ndarray,
    kernel: jnp.ndarray,
    tokens: jnp.ndarray,
    *,
    vocab_chunk: int = 8192,
    segment_ids: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """``lm_loss`` without ever materializing the ``[B, T, vocab]`` logits.

    At vocab 32k+, the logits of a training step can dominate activation
    memory (8k tokens x 32k vocab x 4B = 1 GB fp32 — more than the rest of
    a small model's activations combined). This computes the identical
    next-token cross entropy by streaming the vocab in chunks of
    ``vocab_chunk``: per chunk, ``hidden @ kernel[:, c]`` feeds an ONLINE
    logsumexp (the flash-attention trick applied to the loss) and a gather
    of the target logit; the ``lax.scan`` body is ``jax.checkpoint``-ed so
    the backward recomputes each chunk's logits instead of storing them.
    Peak extra memory is O(B*T*vocab_chunk) regardless of vocab size.

    ``hidden`` is the final-norm output (``DecoderLM(..., return_hidden=
    True)``), ``kernel`` the ``[hidden_dim, vocab]`` projection —
    ``params["lm_head"]["kernel"]``, or ``embed.T`` for tied embeddings.
    The kernel is consumed chunk by chunk (full chunks via a scanned
    dynamic slice, a non-divisible tail as one static epilogue), so no
    padded or re-typed copy of it is ever built. Matches ``lm_loss(logits,
    tokens, segment_ids)`` to float32 accuracy (asserted fwd AND grad in
    tests/test_models.py)."""
    h = hidden[:, :-1].astype(jnp.float32)
    targets = tokens[:, 1:]
    d, v = kernel.shape
    neg = jnp.float32(-1e30)  # finite sentinel: -inf would NaN the rescale

    def online_update(carry, logits, base):
        """Fold one chunk's logits [B, T-1, width] starting at vocab index
        ``base`` into (running max, running sum(exp(logit - m)), target
        logit)."""
        m, s, tl = carry
        new_m = jnp.maximum(m, logits.max(-1))
        s = s * jnp.exp(m - new_m) + jnp.exp(logits - new_m[..., None]).sum(-1)
        width = logits.shape[-1]
        in_chunk = (targets >= base) & (targets < base + width)
        local = jnp.clip(targets - base, 0, width - 1)
        picked = jnp.take_along_axis(logits, local[..., None], axis=-1)[..., 0]
        return new_m, s, jnp.where(in_chunk, picked, tl)

    @jax.checkpoint
    def body(carry, c):
        w = jax.lax.dynamic_slice(kernel, (0, c * vocab_chunk), (d, vocab_chunk))
        # [B, T-1, chunk] — the only logits ever live; the astype fuses
        # into the matmul's operand read
        return online_update(carry, h @ w.astype(jnp.float32), c * vocab_chunk), None

    carry = (
        jnp.full(h.shape[:-1], neg, jnp.float32),
        jnp.zeros(h.shape[:-1], jnp.float32),
        jnp.zeros(h.shape[:-1], jnp.float32),
    )
    n_full = v // vocab_chunk
    if n_full:
        carry, _ = jax.lax.scan(body, carry, jnp.arange(n_full))
    if v % vocab_chunk:  # static epilogue for the non-divisible tail
        tail = kernel[:, n_full * vocab_chunk :]
        carry = jax.checkpoint(
            lambda c: online_update(c, h @ tail.astype(jnp.float32), n_full * vocab_chunk)
        )(carry)
    m, s, tl = carry
    losses = (m + jnp.log(s)) - tl  # logsumexp - target logit
    return _packed_mean(losses, segment_ids)


def lm_loss(
    logits: jnp.ndarray, tokens: jnp.ndarray, segment_ids: jnp.ndarray | None = None
) -> jnp.ndarray:
    """Next-token cross entropy over shifted targets.

    With ``segment_ids`` (packed rows), a position only contributes when its
    target is in the SAME segment (no predicting across a packing boundary)
    and the segment is not padding (id 0 marks pad tokens)."""
    import optax

    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    losses = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    return _packed_mean(losses, segment_ids)


def _packed_mean(losses: jnp.ndarray, segment_ids: jnp.ndarray | None) -> jnp.ndarray:
    """Mean of per-position losses; with packed ``segment_ids``, a position
    only counts when its target is in the SAME non-pad segment. Shared by
    both loss paths so the packing convention cannot diverge."""
    if segment_ids is None:
        return losses.mean()
    w = (segment_ids[:, 1:] == segment_ids[:, :-1]) & (segment_ids[:, 1:] != 0)
    w = w.astype(losses.dtype)
    return (losses * w).sum() / jnp.maximum(w.sum(), 1)
