"""Bidirectional transformer encoder core shared by the ViT / BERT / CLIP
families.

The reference ships no model code at all — models are user-supplied
``nn.Module``s (/root/reference/dmlcloud/pipeline.py:55-75). This zoo exists
to cover the BASELINE.json config ladder (ResNet-50 → BERT fine-tune →
ViT-L/CLIP → Llama) with TPU-first implementations:

- Pre-LN blocks, GELU MLP; LayerNorm accumulates in fp32, matmuls run bf16
  on the MXU.
- Attention masks are additive fp32 biases ``[B, 1, T, S]`` (already in
  log-space), so padding masks fuse into the softmax instead of branching.
- ``causal=True`` adds a triangular bias — used by the CLIP text tower.
- Sharding is data, not code: :func:`encoder_partition_rules` shards heads
  and the MLP hidden over the ``model`` mesh axis and the other large axis
  over ``fsdp``, mirroring the decoder family (transformer.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


@dataclass(frozen=True)
class EncoderConfig:
    hidden_dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    dtype: Any = jnp.bfloat16
    causal: bool = False
    dropout_rate: float = 0.0
    layer_norm_eps: float = 1e-6
    # recompute each block in the backward pass (gradient rematerialisation):
    # O(1) blocks of live activation memory for ~1/3 more FLOPs
    remat: bool = False
    # 'dot' (einsum softmax) | 'flash' (fused Pallas kernel; {0,1} padding
    # keep-masks ride it as kernel segment ids, arbitrary additive biases
    # fall back to 'dot' per call). Sequence length must be a multiple of 64
    # for 'flash' (ViT-B/L's 197 tokens is not; pad or keep 'dot' there).
    attn_impl: str = "dot"

    def __post_init__(self):
        if self.attn_impl not in ("dot", "flash"):
            # a typo here would otherwise silently run the unfused path
            raise ValueError(f"attn_impl must be 'dot' or 'flash', got {self.attn_impl!r}")

    @property
    def head_dim(self) -> int:
        assert self.hidden_dim % self.num_heads == 0
        return self.hidden_dim // self.num_heads


def encoder_partition_rules() -> list[tuple[str, P]]:
    """T5X-style rules for the encoder family (ViT / BERT / CLIP towers)."""
    return [
        ("attn/(q|k|v)_proj/kernel", P("fsdp", "model")),
        ("attn/o_proj/kernel", P("model", None, "fsdp")),
        ("mlp/fc_in/kernel", P("fsdp", "model")),
        ("mlp/fc_out/kernel", P("model", "fsdp")),
        ("(^|/)embedding$", P("fsdp", "model")),  # nn.Embed tables only, not pos_embedding
        (".*", P()),
    ]


class AddLearnedPositions(nn.Module):
    """``x + pos[:, :T]`` with a learned fp32 table ``[1, max_len, D]``.

    The one copy of the positional-embedding pattern shared by the ViT, BERT
    and CLIP towers; rejects sequences longer than ``max_len`` at trace time
    instead of failing deep inside a broadcast.
    """

    max_len: int
    stddev: float = 0.02

    @nn.compact
    def __call__(self, x):
        t = x.shape[1]
        if t > self.max_len:
            raise ValueError(f"sequence length {t} exceeds max_len {self.max_len}")
        pos = self.param(
            "pos_embedding",
            nn.initializers.normal(stddev=self.stddev),
            (1, self.max_len, x.shape[-1]),
            jnp.float32,
        )
        return x + pos[:, :t].astype(x.dtype)


def padding_mask_bias(attention_mask: jnp.ndarray) -> jnp.ndarray:
    """[B, S] {0,1} keep-mask -> additive fp32 bias [B, 1, 1, S]."""
    return jnp.where(attention_mask[:, None, None, :].astype(bool), 0.0, NEG_INF).astype(jnp.float32)


class EncoderAttention(nn.Module):
    cfg: EncoderConfig

    @nn.compact
    def __call__(self, x, mask_bias=None, keep_mask=None):
        cfg = self.cfg
        b, t, _ = x.shape
        dense = lambda name: nn.DenseGeneral(
            (cfg.num_heads, cfg.head_dim),
            axis=-1,
            dtype=cfg.dtype,
            param_dtype=jnp.float32,
            name=name,
        )
        q = dense("q_proj")(x)
        k = dense("k_proj")(x)
        v = dense("v_proj")(x)

        if mask_bias is not None and keep_mask is not None:
            # a silent ignore would let pad keys leak into a custom-bias call
            raise ValueError("pass either mask_bias or keep_mask, not both")
        if cfg.attn_impl == "flash" and mask_bias is None:
            # fused Pallas path (ops/flash_attention.py). A {0,1} keep-mask
            # rides as kernel segment ids: real tokens attend real tokens
            # only. (Pad positions attend pads instead of everything — their
            # outputs differ from the bias path but are masked downstream by
            # pooling/loss anyway.) Arbitrary additive biases still fall back.
            from ..ops.flash_attention import flash_attention

            seg = keep_mask.astype(jnp.int32) if keep_mask is not None else None
            out = flash_attention(q, k, v, causal=cfg.causal, segment_ids=seg)
        else:
            if mask_bias is None and keep_mask is not None:
                mask_bias = padding_mask_bias(keep_mask)
            scores = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32)
            scores = scores / jnp.sqrt(cfg.head_dim)
            if cfg.causal:
                causal = jnp.tril(jnp.ones((t, t), dtype=bool))
                scores = jnp.where(causal[None, None], scores, NEG_INF)
            if mask_bias is not None:
                scores = scores + mask_bias
            probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
            out = jnp.einsum("bhts,bshd->bthd", probs, v)
        return nn.DenseGeneral(
            cfg.hidden_dim,
            axis=(-2, -1),
            dtype=cfg.dtype,
            param_dtype=jnp.float32,
            name="o_proj",
        )(out)


class EncoderMLP(nn.Module):
    cfg: EncoderConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = nn.Dense(cfg.mlp_dim, dtype=cfg.dtype, param_dtype=jnp.float32, name="fc_in")(x)
        h = nn.gelu(h)
        return nn.Dense(cfg.hidden_dim, dtype=cfg.dtype, param_dtype=jnp.float32, name="fc_out")(h)


class EncoderBlock(nn.Module):
    cfg: EncoderConfig

    @nn.compact
    def __call__(self, x, mask_bias=None, train: bool = False, keep_mask=None):
        cfg = self.cfg
        norm = lambda name: nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=jnp.float32, param_dtype=jnp.float32, name=name
        )
        drop = lambda y: nn.Dropout(cfg.dropout_rate)(y, deterministic=not train)
        x = x + drop(
            EncoderAttention(cfg, name="attn")(norm("attn_norm")(x).astype(cfg.dtype), mask_bias, keep_mask)
        )
        x = x + drop(EncoderMLP(cfg, name="mlp")(norm("mlp_norm")(x).astype(cfg.dtype)))
        return x


class TransformerEncoder(nn.Module):
    """Stack of pre-LN encoder blocks + final LayerNorm.

    ``x``: [B, T, D] embeddings; returns [B, T, D] in ``cfg.dtype``.
    """

    cfg: EncoderConfig

    @nn.compact
    def __call__(self, x, mask_bias=None, train: bool = False, keep_mask=None):
        cfg = self.cfg
        block_cls = EncoderBlock
        if cfg.remat:
            # train is a static arg (index 3 counting self): it selects the
            # dropout branch, so it must not be traced through remat
            block_cls = nn.remat(EncoderBlock, prevent_cse=True, static_argnums=(3,))
        for i in range(cfg.num_layers):
            x = block_cls(cfg, name=f"layer_{i}")(x, mask_bias, train, keep_mask)
        x = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=jnp.float32, param_dtype=jnp.float32, name="final_norm"
        )(x)
        return x.astype(cfg.dtype)
