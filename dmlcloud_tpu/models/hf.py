"""HuggingFace Llama checkpoint import for :class:`DecoderLM`.

The reference framework trains only user-supplied modules; this gives the
TPU build a real-world on-ramp: load any HF Llama-family checkpoint
(``LlamaForCausalLM`` state dict) into the jax model and get bit-equal
logits (pinned by ``tests/test_hf_import.py`` against a live HF forward).

Two conversions happen beyond plain transposes:

- flax kernels are ``[in, out]`` while torch ``nn.Linear`` stores
  ``[out, in]``;
- HF stores rotary q/k projections in the half-split layout
  (``[r_0..r_{D/2-1}, i_0..i_{D/2-1}]`` per head) while this model rotates
  interleaved pairs (``[r_0, i_0, r_1, i_1, ...]``, the Meta convention) —
  the q/k output rows are permuted accordingly, which is exactly how the
  two RoPE conventions are made to agree.
"""

from __future__ import annotations

from typing import Any, Mapping

import jax.numpy as jnp
import numpy as np

from .transformer import TransformerConfig


def _np(t: Any) -> np.ndarray:
    """torch tensor / numpy array -> float32 numpy."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t, np.float32)


def _interleave_rope_rows(w: np.ndarray) -> np.ndarray:
    """[..., D] half-split rotary layout -> interleaved pairs."""
    d = w.shape[-1]
    out = np.empty_like(w)
    out[..., 0::2] = w[..., : d // 2]
    out[..., 1::2] = w[..., d // 2 :]
    return out


def transformer_config_from_hf(hf_config: Any, **overrides) -> TransformerConfig:
    """Build a :class:`TransformerConfig` from a HF ``LlamaConfig`` /
    ``MistralConfig`` (same architecture; Mistral's ``sliding_window``
    carries over into the model's windowed attention paths)."""
    base = dict(
        vocab_size=hf_config.vocab_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=getattr(hf_config, "num_key_value_heads", None),
        # some Mistral-family configs decouple head_dim from hidden/heads
        head_dim=getattr(hf_config, "head_dim", None)
        or hf_config.hidden_size // hf_config.num_attention_heads,
        hidden_dim=hf_config.hidden_size,
        mlp_dim=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        rope_theta=getattr(hf_config, "rope_theta", 10000.0),
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings", False)),
        sliding_window=getattr(hf_config, "sliding_window", None),
        rope_scaling=_rope_scaling_from_hf(getattr(hf_config, "rope_scaling", None)),
    )
    base.update(overrides)
    return TransformerConfig(**base)


def _rope_scaling_from_hf(rs: Any) -> tuple | None:
    """HF ``rope_scaling`` dict -> the config's hashable tuple. Unsupported
    schemes raise — a silently-dropped scaling would import a Llama-3
    checkpoint with wrong positional geometry."""
    if rs is None:
        return None
    kind = rs.get("rope_type", rs.get("type"))
    if kind is None:
        # a scaling dict with no recognizable type key must not silently
        # import as plain RoPE
        raise ValueError(f"rope_scaling dict has no 'rope_type'/'type' key: {rs!r}")
    if kind == "default":
        return None
    if kind == "linear":
        return ("linear", float(rs["factor"]))
    if kind == "llama3":
        return (
            "llama3",
            float(rs["factor"]),
            float(rs["low_freq_factor"]),
            float(rs["high_freq_factor"]),
            int(rs["original_max_position_embeddings"]),
        )
    raise ValueError(f"unsupported HF rope_scaling type {kind!r} (supported: linear, llama3)")


def llama_params_from_hf(state_dict: Mapping[str, Any], cfg: TransformerConfig, dtype=jnp.float32):
    """Convert a ``LlamaForCausalLM`` state dict into this model's params.

    ``state_dict`` values may be torch tensors or numpy arrays. Returns the
    flax params pytree for ``DecoderLM(cfg)``.
    """
    sd = {k: v for k, v in state_dict.items()}
    h, kh, d, hid = cfg.num_heads, cfg.kv_heads, cfg.head_dim, cfg.hidden_dim

    def take(key: str) -> np.ndarray:
        if key not in sd:
            raise KeyError(f"HF state dict is missing {key!r}")
        return _np(sd.pop(key))

    def qkv_kernel(key: str, heads: int, rope: bool) -> np.ndarray:
        w = take(key)  # [heads*d, hid]
        w = w.reshape(heads, d, hid)
        if rope:
            w = _interleave_rope_rows(w.transpose(0, 2, 1)).transpose(0, 2, 1)
        return np.ascontiguousarray(w.transpose(2, 0, 1))  # [hid, heads, d]

    params: dict[str, Any] = {
        "embed": {"embedding": take("model.embed_tokens.weight")},
        "final_norm": {"scale": take("model.norm.weight")},
    }
    if not cfg.tie_embeddings:
        lm_head = sd.pop("lm_head.weight", None)
        if lm_head is None:  # tied checkpoint loaded into an untied config
            lm_head = np.array(params["embed"]["embedding"])
        params["lm_head"] = {"kernel": _np(lm_head).T}
    else:
        sd.pop("lm_head.weight", None)

    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        params[f"layer_{i}"] = {
            "attn_norm": {"scale": take(p + "input_layernorm.weight")},
            "mlp_norm": {"scale": take(p + "post_attention_layernorm.weight")},
            "attn": {
                "q_proj": {"kernel": qkv_kernel(p + "self_attn.q_proj.weight", h, rope=True)},
                "k_proj": {"kernel": qkv_kernel(p + "self_attn.k_proj.weight", kh, rope=True)},
                "v_proj": {"kernel": qkv_kernel(p + "self_attn.v_proj.weight", kh, rope=False)},
                # o_proj consumes the flattened [H*D] heads: [hid, H*D] -> flax [H*D, hid]
                "o_proj": {"kernel": take(p + "self_attn.o_proj.weight").T},
            },
            "mlp": {
                "gate_proj": {"kernel": take(p + "mlp.gate_proj.weight").T},
                "up_proj": {"kernel": take(p + "mlp.up_proj.weight").T},
                "down_proj": {"kernel": take(p + "mlp.down_proj.weight").T},
            },
        }

    leftovers = [k for k in sd if "rotary_emb" not in k]
    if leftovers:
        raise ValueError(f"unconverted HF weights: {leftovers[:8]}{'...' if len(leftovers) > 8 else ''}")

    import jax

    return jax.tree_util.tree_map(lambda x: jnp.asarray(x, dtype), params)


def _split_rope_rows(w: np.ndarray) -> np.ndarray:
    """[..., D] interleaved rotary layout -> half-split (inverse of
    :func:`_interleave_rope_rows`)."""
    d = w.shape[-1]
    out = np.empty_like(w)
    out[..., : d // 2] = w[..., 0::2]
    out[..., d // 2 :] = w[..., 1::2]
    return out


def hf_state_dict_from_params(params: Any, cfg: TransformerConfig) -> dict:
    """The inverse of :func:`llama_params_from_hf`: export this model's
    params as a ``LlamaForCausalLM``/``MistralForCausalLM`` state dict of
    float32 numpy arrays (wrap in ``torch.from_numpy`` to ``load_state_dict``
    into a HF model) — train on TPU, serve anywhere HF runs."""
    h, kh, d = cfg.num_heads, cfg.kv_heads, cfg.head_dim

    def qkv_weight(kernel, heads: int, rope: bool) -> np.ndarray:
        w = _np(kernel).transpose(1, 2, 0)  # [heads, d, hid]
        if rope:
            w = _split_rope_rows(w.transpose(0, 2, 1)).transpose(0, 2, 1)
        return np.ascontiguousarray(w.reshape(heads * d, -1))

    sd: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": _np(params["embed"]["embedding"]),
        "model.norm.weight": _np(params["final_norm"]["scale"]),
    }
    if cfg.tie_embeddings:
        # HF tied models still materialise the tied key in their state dict,
        # and a strict load_state_dict requires it
        sd["lm_head.weight"] = sd["model.embed_tokens.weight"]
    else:
        sd["lm_head.weight"] = np.ascontiguousarray(_np(params["lm_head"]["kernel"]).T)
    for i in range(cfg.num_layers):
        layer = params[f"layer_{i}"]
        p = f"model.layers.{i}."
        sd[p + "input_layernorm.weight"] = _np(layer["attn_norm"]["scale"])
        sd[p + "post_attention_layernorm.weight"] = _np(layer["mlp_norm"]["scale"])
        attn, mlp = layer["attn"], layer["mlp"]
        sd[p + "self_attn.q_proj.weight"] = qkv_weight(attn["q_proj"]["kernel"], h, rope=True)
        sd[p + "self_attn.k_proj.weight"] = qkv_weight(attn["k_proj"]["kernel"], kh, rope=True)
        sd[p + "self_attn.v_proj.weight"] = qkv_weight(attn["v_proj"]["kernel"], kh, rope=False)
        sd[p + "self_attn.o_proj.weight"] = np.ascontiguousarray(_np(attn["o_proj"]["kernel"]).T)
        sd[p + "mlp.gate_proj.weight"] = np.ascontiguousarray(_np(mlp["gate_proj"]["kernel"]).T)
        sd[p + "mlp.up_proj.weight"] = np.ascontiguousarray(_np(mlp["up_proj"]["kernel"]).T)
        sd[p + "mlp.down_proj.weight"] = np.ascontiguousarray(_np(mlp["down_proj"]["kernel"]).T)
    return sd
