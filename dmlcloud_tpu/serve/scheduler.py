"""The continuous-batching scheduler: FIFO admission, no drain barrier.

The scheduling contract, in order of importance:

1. **No starvation.** Admission is STRICT FIFO with full reservation: the
   head of the waiting queue is admitted the moment a decode slot opens
   AND the pool can cover its worst case (``ceil((prompt + max_new +
   lookahead) / block_size)`` blocks, where ``lookahead`` is the engine's
   speculative overshoot — ``k`` proposals a verification round may write
   past the committed fill, 0 for plain decode); nobody behind it may
   jump the queue even if they would fit. Head-of-line blocking costs a
   little utilisation, but it makes progress provable — every admitted
   request holds all the blocks it can ever need (it cannot deadlock
   mid-decode), every finished request frees a slot and blocks, so the
   head always eventually admits. Property-tested over randomized traces
   (including spec-decode partial accepts) in tests/test_serve.py.
2. **No drain barrier.** A sequence that emits EOS (or hits its token
   budget) releases its slot and blocks immediately; the next waiting
   request joins the running batch at the next step. Dense static
   batching — where finished rows burn slots until the whole batch
   drains — is exactly what this module exists to delete.
3. **Prefill never stalls decode.** A newly admitted request's prompt is
   processed in ``prefill_chunk``-token chunks, at most one chunk per
   engine step, interleaved with the decode batch of the already-running
   streams — a 100k-token prompt delays running streams by one chunk's
   latency per step, never by its whole prefill.

Speculative serving adds a second pool: the draft model's pages. The
scheduler allocates from BOTH pools atomically at admission (a request
holds its worst case in each, checked before either allocation so a
failed admit leaks nothing) and frees both at finish — the ``free + live
== capacity`` invariant holds per pool, always.

Prefix sharing (``prefix_cache=``, serve/prefix_cache.py) changes the
ACCOUNTING but not the contract: admission first locks the head's longest
cached prefix (``match`` + ``lock`` — lock re-validates against races and
pins each shared block with a retain, so the eviction below can never
reclaim them), then needs only ``reservation - shared`` NEW blocks —
shared blocks are discounted because the head already holds a reference
to them. An exact full-block match rolls prefill back one token for its
logits, which guarantees one copy-on-write fork, so ONE spare block is
added back to the reservation in that case — full reservation stays
exact and the starvation-freedom proof survives: every admitted request
holds (a reference to) every block it can ever need, pinned shared
prefixes become evictable the moment their holders finish, and the head
admits as soon as ``free + evictable`` covers its discounted need. A
failed admit releases the locked prefix before breaking, so strict FIFO
never leaks a reference. The draft pool has NO tree: spec requests still
reserve their full worst case there (draft prefill skips via the
target's match length, leaving the skipped draft pages unwritten — the
verifier guarantees token identity regardless).

Overload control (PR 13) adds the failure half without touching the
proof above:

- **Terminal statuses.** Every request ends in exactly one of
  :data:`TERMINAL_STATUSES` — ``ok`` (EOS or token budget), ``cancelled``
  (explicit :meth:`~dmlcloud_tpu.serve.engine.ServeEngine.cancel`),
  ``deadline_exceeded`` (its ``deadline_s`` elapsed), ``shed`` (evicted
  by overload control or drain), or ``error`` (a step failed underneath
  it). :meth:`Scheduler.terminate` is the ONE exit path: it removes the
  sequence from whichever queue holds it and releases every resource it
  owns — target blocks (including locked prefix references and unused
  COW spares, which live in ``seq.blocks``), draft blocks — so
  ``free + unique-live == capacity`` holds per pool after ANY exit, at
  ANY phase. ``finish`` is ``terminate(..., "ok")``.
- **Bounded admission queue.** ``max_waiting`` caps the waiting queue;
  an arrival beyond it sheds a victim chosen by ``shed_policy`` —
  ``"reject"`` sheds the arrival itself, ``"oldest-deadline"`` sheds the
  lowest-``priority`` request with the earliest deadline (no deadline
  sorts last; ties shed the arrival — it is cheapest, holding nothing).
  ``priority`` affects ONLY shed-victim selection, never admission
  order, so the FIFO starvation-freedom property is untouched.
- **Per-tenant fairness** (``fairness="tenant"``): deficit round-robin
  over per-tenant FIFO queues, the classic DRR of Shreedhar & Varghese.
  Each tenant in the ring accrues ``drr_quantum`` block-credits per
  visit; the head of the ring serves while its deficit covers the head
  request's full reservation, then rotates. A head that fits its
  tenant's deficit but NOT the pool is STICKY — the scheduler stops
  admitting rather than rotating past it, which is exactly the strict
  FIFO head-of-line rule applied per ring position, so the
  starvation-freedom argument survives: every tenant is visited
  infinitely often, deficits grow unboundedly until served, and the
  selected head admits as soon as the pool covers it. Within a tenant,
  order stays strict FIFO.

The scheduler is pure host-side bookkeeping (deques of :class:`_Sequence`
records); the engine owns every device interaction.
"""

from __future__ import annotations

import collections
import itertools
import math
from dataclasses import dataclass, field
from typing import Any, Iterator

import numpy as np

from .kv_pool import KVBlockPool

__all__ = ["Request", "Scheduler", "TERMINAL_STATUSES"]

#: Every request ends in exactly one of these (engine ``status(rid)``).
TERMINAL_STATUSES = ("ok", "cancelled", "deadline_exceeded", "shed", "error")


@dataclass(eq=False)  # identity comparison: prompt arrays don't define ==
class Request:
    """One generation request. ``prompt`` is a 1-D int32 token array;
    ``adapter`` names a tenant adapter in the engine's ``AdapterSet``
    (None = base model). The sampling knobs (``temperature``/``top_k``/
    ``top_p``/``eos_id``) are PER REQUEST — they ride the decode step as
    traced per-row arrays, so one compiled engine serves mixed
    greedy/sampled tenants in a single batch; None inherits the engine's
    default. ``deadline_s`` is a relative budget from arrival (None =
    none); ``priority`` orders SHED-VICTIM selection only (lower sheds
    first); ``tenant`` keys the fairness scheduler (None = the adapter
    name, or the shared default tenant)."""

    prompt: Any
    max_new_tokens: int = 32
    adapter: str | None = None
    temperature: float | None = None
    top_k: int | None = None
    top_p: float | None = None
    eos_id: int | None = None
    deadline_s: float | None = None
    priority: int = 0
    tenant: str | None = None
    id: int = -1  # assigned by the engine at submit


@dataclass(eq=False)  # identity comparison (deque/list membership tests)
class _Sequence:
    """Runtime state of one admitted request (engine-internal)."""

    req: Request
    arrival: float
    blocks: list[int] = field(default_factory=list)
    draft_blocks: list[int] = field(default_factory=list)  # spec mode only
    fill: int = 0  # cache slots written (prefill progress, then decode)
    out: list[int] = field(default_factory=list)  # emitted tokens
    last_token: int = 0  # next decode step's input
    prev_token: int = 0  # the token before it (spec rounds feed two)
    admitted: float | None = None
    first_token: float | None = None
    finished: float | None = None
    adapter_id: int = 0
    # lifecycle: absolute deadline (arrival + deadline_s), fairness tenant,
    # shed priority, and the terminal status (None while live)
    deadline: float | None = None
    tenant: str = ""
    priority: int = 0
    status: str | None = None
    # caller-supplied idempotency token (engine dedups on it — a router
    # retry after an ambiguous failure can never double-admit)
    token: str | None = None
    # trace id stamped on every span this request touches; the router
    # mints one per logical request and REUSES it across failover retries
    # so the whole causal chain links into a single trace
    trace: str | None = None
    # prefix-cache state: leading table entries mapped READ-ONLY from the
    # radix tree (refcount > 1 is the ground truth; this count is the
    # observable), matched tokens, and spare blocks reserved for COW forks
    shared: int = 0
    cached_tokens: int = 0
    cow_spare: int = 0
    # resolved per-row sampling params (request value or engine default)
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_id: int = -1

    @property
    def prompt_len(self) -> int:
        return int(np.shape(self.req.prompt)[0])

    @property
    def prefilled(self) -> bool:
        return self.fill >= self.prompt_len

    def needed_blocks(self, block_size: int, lookahead: int = 0) -> int:
        """Blocks covering the next step's reads AND writes: position
        ``fill`` for plain decode, through ``fill + lookahead`` when a
        speculative round writes ``lookahead`` proposals past the pending
        token — the live prefix plus this round's worst case, which is
        what the decode batch actually gathers (the full reservation is
        admission's concern)."""
        return -(-(self.fill + 1 + int(lookahead)) // block_size)


class Scheduler:
    """FIFO continuous-batching admission over one :class:`KVBlockPool`
    (plus the draft model's pool in speculative mode). ``lookahead`` is
    the per-round speculative overshoot reserved per request (``spec_k``
    for a spec engine, 0 otherwise); ``prefix_cache`` is the engine's
    :class:`~dmlcloud_tpu.serve.prefix_cache.PrefixCache` (None = no
    sharing — the exact PR-8 accounting). ``max_waiting`` bounds the
    admission queue (None = unbounded), ``shed_policy`` picks the victim
    on overflow, ``fairness="tenant"`` switches admission to deficit
    round-robin over per-tenant FIFO queues with ``drr_quantum``
    block-credits per ring visit."""

    def __init__(
        self,
        pool: KVBlockPool,
        max_slots: int,
        prefill_chunk: int,
        *,
        draft_pool: KVBlockPool | None = None,
        lookahead: int = 0,
        prefix_cache: "PrefixCache | None" = None,
        max_waiting: int | None = None,
        shed_policy: str = "reject",
        fairness: str = "fifo",
        drr_quantum: int | None = None,
    ):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got {prefill_chunk}")
        if lookahead < 0:
            raise ValueError(f"lookahead must be >= 0, got {lookahead}")
        if max_waiting is not None and max_waiting < 1:
            raise ValueError(f"max_waiting must be >= 1, got {max_waiting}")
        if shed_policy not in ("reject", "oldest-deadline"):
            raise ValueError(f"unknown shed_policy {shed_policy!r}")
        if fairness not in ("fifo", "tenant"):
            raise ValueError(f"unknown fairness {fairness!r}")
        self.pool = pool
        self.draft_pool = draft_pool
        self.prefix = prefix_cache
        self.lookahead = int(lookahead)
        self.max_slots = int(max_slots)
        self.prefill_chunk = int(prefill_chunk)
        self.max_waiting = None if max_waiting is None else int(max_waiting)
        self.shed_policy = shed_policy
        self.fairness = fairness
        self.drr_quantum = int(
            drr_quantum
            if drr_quantum is not None
            else max(1, pool.blocks_for(prefill_chunk))
        )
        if self.drr_quantum < 1:
            raise ValueError(f"drr_quantum must be >= 1, got {drr_quantum}")
        self.waiting: collections.deque[_Sequence] = collections.deque()
        self.prefilling: collections.deque[_Sequence] = collections.deque()
        self.running: list[_Sequence] = []
        # tenant-fairness state: per-tenant FIFO queues, the DRR ring of
        # tenants with queued work, and their block-credit deficits
        self._queues: dict[str, collections.deque[_Sequence]] = {}
        self._ring: collections.deque[str] = collections.deque()
        self._deficit: dict[str, float] = {}

    # -- queue state ---------------------------------------------------------
    @property
    def active(self) -> int:
        """Admitted-but-unfinished sequences (holding a decode slot)."""
        return len(self.prefilling) + len(self.running)

    @property
    def num_waiting(self) -> int:
        """Requests queued for admission, across every tenant queue."""
        if self.fairness == "fifo":
            return len(self.waiting)
        return sum(len(q) for q in self._queues.values())

    @property
    def idle(self) -> bool:
        return not (self.num_waiting or self.prefilling or self.running)

    def depth(self) -> int:
        """Requests waiting for admission (the queue-depth observable)."""
        return self.num_waiting

    def iter_waiting(self) -> Iterator[_Sequence]:
        """Every waiting sequence (ring order across tenant queues)."""
        if self.fairness == "fifo":
            return iter(self.waiting)
        return itertools.chain.from_iterable(
            self._queues[t] for t in self._ring if t in self._queues
        )

    # -- lifecycle -----------------------------------------------------------
    def reservation(self, seq: _Sequence) -> int:
        """The full worst-case block reservation of one request: every
        slot its committed tokens can occupy PLUS the ``lookahead``
        speculative positions the final round may write past them."""
        return self.pool.blocks_for(
            seq.prompt_len + seq.req.max_new_tokens + self.lookahead
        )

    def submit(self, seq: _Sequence) -> list[_Sequence]:
        """Queue a request. Rejects one that could NEVER be admitted —
        a worst case larger than the whole pool would starve the queue
        behind it forever under strict FIFO.

        Returns the sequences SHED by overload control: empty when the
        queue has room, else the victim ``shed_policy`` chose — possibly
        ``seq`` itself, which is then never enqueued. The caller owns
        stamping each victim terminal (:meth:`terminate`)."""
        need = self.reservation(seq)
        pools = [self.pool] + ([self.draft_pool] if self.draft_pool else [])
        for pool in pools:
            if need > pool.num_blocks:
                raise ValueError(
                    f"request needs {need} blocks worst-case but the pool only has "
                    f"{pool.num_blocks}; raise num_blocks or lower max_new_tokens"
                )
        if seq.req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        shed: list[_Sequence] = []
        if self.max_waiting is not None and self.num_waiting >= self.max_waiting:
            shed.append(self._shed_victim(seq))
        if seq not in shed:
            self._enqueue(seq)
        return shed

    def _shed_victim(self, incoming: _Sequence) -> _Sequence:
        """Pick the overflow victim. ``reject``: the arrival. ``oldest-
        deadline``: lowest priority first, then earliest deadline (no
        deadline = latest); the arrival breaks ties — it holds nothing."""
        if self.shed_policy == "reject":
            return incoming
        return min(
            [*self.iter_waiting(), incoming],
            key=lambda s: (
                s.priority,
                s.deadline if s.deadline is not None else math.inf,
                0 if s is incoming else 1,
            ),
        )

    def _enqueue(self, seq: _Sequence) -> None:
        if self.fairness == "fifo":
            self.waiting.append(seq)
            return
        q = self._queues.get(seq.tenant)
        if q is None:
            q = self._queues[seq.tenant] = collections.deque()
        if not q and seq.tenant not in self._ring:
            self._ring.append(seq.tenant)
            self._deficit.setdefault(seq.tenant, 0.0)
        q.append(seq)

    def _discard_waiting(self, seq: _Sequence) -> None:
        """Forgiving removal from the waiting structures (no-op when the
        sequence is not queued — e.g. a rejected arrival)."""
        if self.fairness == "fifo":
            if seq in self.waiting:
                self.waiting.remove(seq)
            return
        q = self._queues.get(seq.tenant)
        if q is not None and seq in q:
            q.remove(seq)
            if not q:
                self._retire_tenant(seq.tenant)

    def _retire_tenant(self, tenant: str) -> None:
        """Drop an emptied tenant queue from the ring; its deficit resets
        (classic DRR: credit does not accumulate while idle)."""
        self._queues.pop(tenant, None)
        self._deficit.pop(tenant, None)
        if tenant in self._ring:
            self._ring.remove(tenant)

    def _select_head(self) -> _Sequence | None:
        """The ONE request admission may consider this step. FIFO: the
        queue head. Tenant mode: deficit round-robin — visit the ring
        head; serve it while its deficit covers its head request's full
        reservation, else grant a quantum and rotate. Terminates because
        every full ring pass grows every deficit by a quantum."""
        if self.fairness == "fifo":
            return self.waiting[0] if self.waiting else None
        while self._ring:
            tenant = self._ring[0]
            q = self._queues.get(tenant)
            if not q:
                self._retire_tenant(tenant)
                continue
            head = q[0]
            if self._deficit[tenant] >= self.reservation(head):
                return head
            self._deficit[tenant] += self.drr_quantum
            self._ring.rotate(-1)
        return None

    def _pop_admitted(self, head: _Sequence) -> None:
        """Dequeue an admitted head and charge its tenant's deficit."""
        if self.fairness == "fifo":
            self.waiting.popleft()
            return
        q = self._queues[head.tenant]
        q.popleft()
        self._deficit[head.tenant] -= self.reservation(head)
        if not q:
            self._retire_tenant(head.tenant)

    def admit(self, now: float) -> list[_Sequence]:
        """Admit from the head of the waiting queue while a slot AND the
        head's full reservation fit — in EVERY pool, checked before
        either allocation so a partial admit can never leak blocks.
        Returns the newly admitted sequences (blocks already allocated,
        prefill pending).

        With a prefix cache: the head's cached prefix is matched and
        LOCKED first (lock pins the shared blocks, so the eviction that
        follows can never reclaim what the head is about to map — the
        match→admit race the property tests exercise), shared blocks are
        discounted from the reservation, and an exact full-block match
        adds one COW spare (divergence rolls back one token, so the final
        shared block WILL be forked). When the discounted need still
        exceeds the free list, LRU leaves are evicted; if that is not
        enough, the locked prefix is released and the head waits — strict
        FIFO (sticky DRR head in tenant mode), no leaked references."""
        admitted = []
        while self.active < self.max_slots:
            head = self._select_head()
            if head is None:
                break
            need = self.reservation(head)
            shared_blocks: list[int] = []
            cached = 0
            if self.prefix is not None:
                shared_blocks, cached = self.prefix.lock(
                    self.prefix.match(head.req.prompt, adapter=head.adapter_id),
                    )
            spare = 1 if cached >= head.prompt_len else 0  # guaranteed COW fork
            need_new = need - len(shared_blocks) + spare
            if self.prefix is not None and need_new > self.pool.num_free:
                self.prefix.evict(need_new)  # leaf-first LRU; pinned blocks safe
            short = need_new > self.pool.num_free or (
                self.draft_pool is not None and need > self.draft_pool.num_free
            )
            if short:
                if shared_blocks:
                    self.pool.release(shared_blocks)  # unlock: no leaked refs
                break  # strict FIFO: nobody may overtake the head
            self._pop_admitted(head)
            head.blocks = shared_blocks + self.pool.alloc(need_new)
            head.shared = len(shared_blocks)
            head.cached_tokens = cached
            head.cow_spare = spare
            # chunked prefill starts at the divergence point; at least the
            # final prompt token must run for its logits (first token)
            head.fill = min(cached, head.prompt_len - 1)
            if self.draft_pool is not None:
                head.draft_blocks = self.draft_pool.alloc(need)
            head.admitted = now
            self.prefilling.append(head)
            admitted.append(head)
        return admitted

    def next_prefill(self) -> _Sequence | None:
        """The sequence owed the next prefill chunk (oldest first)."""
        return self.prefilling[0] if self.prefilling else None

    def prefill_done(self, seq: _Sequence) -> None:
        """Move a fully-prefilled sequence into the decode batch."""
        self.prefilling.remove(seq)
        self.running.append(seq)

    def terminate(self, seq: _Sequence, now: float, status: str) -> bool:
        """The ONE exit path: remove ``seq`` from whichever queue holds
        it and release EVERY resource it owns — target blocks (shared
        prefix references and unused COW spares live in ``seq.blocks``,
        so one release covers them) and draft blocks — then stamp the
        terminal ``status``. Idempotent: a second terminate is a no-op
        returning False, so a cancel racing a deadline (or a fault
        racing either) can never double-free."""
        if status not in TERMINAL_STATUSES:
            raise ValueError(f"unknown terminal status {status!r}")
        if seq.status is not None:
            return False
        if seq in self.running:
            self.running.remove(seq)
        elif seq in self.prefilling:
            self.prefilling.remove(seq)
        else:
            self._discard_waiting(seq)
        if seq.blocks:
            self.pool.free(seq.blocks)
        seq.blocks = []
        seq.shared = 0
        seq.cow_spare = 0
        if self.draft_pool is not None and seq.draft_blocks:
            self.draft_pool.free(seq.draft_blocks)
        seq.draft_blocks = []
        seq.finished = now
        seq.status = status
        return True

    def expire(self, now: float) -> list[_Sequence]:
        """Terminate every request whose deadline has passed — at ANY
        phase (queued, mid-prefill, mid-decode); returns the casualties
        so the engine can record them."""
        expired = [
            s
            for s in [*self.iter_waiting(), *self.prefilling, *self.running]
            if s.deadline is not None and now >= s.deadline
        ]
        for s in expired:
            self.terminate(s, now, "deadline_exceeded")
        return expired

    def finish(self, seq: _Sequence, now: float) -> None:
        """Release a finished sequence's slot and blocks IMMEDIATELY —
        the no-drain-barrier property lives here (both pools in spec
        mode: the draft pages recycle with the target's)."""
        self.terminate(seq, now, "ok")

    def decode_batch(self) -> list[_Sequence]:
        """The sequences decoding this step (stable submission order)."""
        return list(self.running)
