"""The continuous-batching scheduler: FIFO admission, no drain barrier.

The scheduling contract, in order of importance:

1. **No starvation.** Admission is STRICT FIFO with full reservation: the
   head of the waiting queue is admitted the moment a decode slot opens
   AND the pool can cover its worst case (``ceil((prompt + max_new) /
   block_size)`` blocks); nobody behind it may jump the queue even if they
   would fit. Head-of-line blocking costs a little utilisation, but it
   makes progress provable — every admitted request holds all the blocks
   it can ever need (it cannot deadlock mid-decode), every finished
   request frees a slot and blocks, so the head always eventually admits.
   Property-tested over randomized traces in tests/test_serve.py.
2. **No drain barrier.** A sequence that emits EOS (or hits its token
   budget) releases its slot and blocks immediately; the next waiting
   request joins the running batch at the next step. Dense static
   batching — where finished rows burn slots until the whole batch
   drains — is exactly what this module exists to delete.
3. **Prefill never stalls decode.** A newly admitted request's prompt is
   processed in ``prefill_chunk``-token chunks, at most one chunk per
   engine step, interleaved with the decode batch of the already-running
   streams — a 100k-token prompt delays running streams by one chunk's
   latency per step, never by its whole prefill.

The scheduler is pure host-side bookkeeping (deques of :class:`_Sequence`
records); the engine owns every device interaction.
"""

from __future__ import annotations

import collections
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from .kv_pool import KVBlockPool

__all__ = ["Request", "Scheduler"]


@dataclass(eq=False)  # identity comparison: prompt arrays don't define ==
class Request:
    """One generation request. ``prompt`` is a 1-D int32 token array;
    ``adapter`` names a tenant adapter in the engine's ``AdapterSet``
    (None = base model)."""

    prompt: Any
    max_new_tokens: int = 32
    adapter: str | None = None
    id: int = -1  # assigned by the engine at submit


@dataclass(eq=False)  # identity comparison (deque/list membership tests)
class _Sequence:
    """Runtime state of one admitted request (engine-internal)."""

    req: Request
    arrival: float
    blocks: list[int] = field(default_factory=list)
    fill: int = 0  # cache slots written (prefill progress, then decode)
    out: list[int] = field(default_factory=list)  # emitted tokens
    last_token: int = 0  # next decode step's input
    admitted: float | None = None
    first_token: float | None = None
    finished: float | None = None
    adapter_id: int = 0

    @property
    def prompt_len(self) -> int:
        return int(np.shape(self.req.prompt)[0])

    @property
    def prefilled(self) -> bool:
        return self.fill >= self.prompt_len

    def needed_blocks(self, block_size: int) -> int:
        """Blocks covering the next step's reads AND write (position
        ``fill``), i.e. the live prefix only — what the decode batch
        actually gathers, not the full reservation."""
        return -(-(self.fill + 1) // block_size)


class Scheduler:
    """FIFO continuous-batching admission over a :class:`KVBlockPool`."""

    def __init__(self, pool: KVBlockPool, max_slots: int, prefill_chunk: int):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got {prefill_chunk}")
        self.pool = pool
        self.max_slots = int(max_slots)
        self.prefill_chunk = int(prefill_chunk)
        self.waiting: collections.deque[_Sequence] = collections.deque()
        self.prefilling: collections.deque[_Sequence] = collections.deque()
        self.running: list[_Sequence] = []

    # -- queue state ---------------------------------------------------------
    @property
    def active(self) -> int:
        """Admitted-but-unfinished sequences (holding a decode slot)."""
        return len(self.prefilling) + len(self.running)

    @property
    def idle(self) -> bool:
        return not (self.waiting or self.prefilling or self.running)

    def depth(self) -> int:
        """Requests waiting for admission (the queue-depth observable)."""
        return len(self.waiting)

    # -- lifecycle -----------------------------------------------------------
    def submit(self, seq: _Sequence) -> None:
        """Queue a request. Rejects one that could NEVER be admitted —
        a worst case larger than the whole pool would starve the queue
        behind it forever under strict FIFO."""
        need = self.pool.blocks_for(seq.prompt_len + seq.req.max_new_tokens)
        if need > self.pool.num_blocks:
            raise ValueError(
                f"request needs {need} blocks worst-case but the pool only has "
                f"{self.pool.num_blocks}; raise num_blocks or lower max_new_tokens"
            )
        if seq.req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.waiting.append(seq)

    def admit(self, now: float) -> list[_Sequence]:
        """Admit from the head of the waiting queue while a slot AND the
        head's full reservation fit. Returns the newly admitted sequences
        (blocks already allocated, prefill pending)."""
        admitted = []
        while self.waiting and self.active < self.max_slots:
            head = self.waiting[0]
            need = self.pool.blocks_for(head.prompt_len + head.req.max_new_tokens)
            if need > self.pool.num_free:
                break  # strict FIFO: nobody may overtake the head
            self.waiting.popleft()
            head.blocks = self.pool.alloc(need)
            head.admitted = now
            self.prefilling.append(head)
            admitted.append(head)
        return admitted

    def next_prefill(self) -> _Sequence | None:
        """The sequence owed the next prefill chunk (oldest first)."""
        return self.prefilling[0] if self.prefilling else None

    def prefill_done(self, seq: _Sequence) -> None:
        """Move a fully-prefilled sequence into the decode batch."""
        self.prefilling.remove(seq)
        self.running.append(seq)

    def finish(self, seq: _Sequence, now: float) -> None:
        """Release a finished sequence's slot and blocks IMMEDIATELY —
        the no-drain-barrier property lives here."""
        if seq in self.running:
            self.running.remove(seq)
        elif seq in self.prefilling:
            self.prefilling.remove(seq)
        self.pool.free(seq.blocks)
        seq.blocks = []
        seq.finished = now

    def decode_batch(self) -> list[_Sequence]:
        """The sequences decoding this step (stable submission order)."""
        return list(self.running)
