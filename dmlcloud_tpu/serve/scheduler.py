"""The continuous-batching scheduler: FIFO admission, no drain barrier.

The scheduling contract, in order of importance:

1. **No starvation.** Admission is STRICT FIFO with full reservation: the
   head of the waiting queue is admitted the moment a decode slot opens
   AND the pool can cover its worst case (``ceil((prompt + max_new +
   lookahead) / block_size)`` blocks, where ``lookahead`` is the engine's
   speculative overshoot — ``k`` proposals a verification round may write
   past the committed fill, 0 for plain decode); nobody behind it may
   jump the queue even if they would fit. Head-of-line blocking costs a
   little utilisation, but it makes progress provable — every admitted
   request holds all the blocks it can ever need (it cannot deadlock
   mid-decode), every finished request frees a slot and blocks, so the
   head always eventually admits. Property-tested over randomized traces
   (including spec-decode partial accepts) in tests/test_serve.py.
2. **No drain barrier.** A sequence that emits EOS (or hits its token
   budget) releases its slot and blocks immediately; the next waiting
   request joins the running batch at the next step. Dense static
   batching — where finished rows burn slots until the whole batch
   drains — is exactly what this module exists to delete.
3. **Prefill never stalls decode.** A newly admitted request's prompt is
   processed in ``prefill_chunk``-token chunks, at most one chunk per
   engine step, interleaved with the decode batch of the already-running
   streams — a 100k-token prompt delays running streams by one chunk's
   latency per step, never by its whole prefill.

Speculative serving adds a second pool: the draft model's pages. The
scheduler allocates from BOTH pools atomically at admission (a request
holds its worst case in each, checked before either allocation so a
failed admit leaks nothing) and frees both at finish — the ``free + live
== capacity`` invariant holds per pool, always.

The scheduler is pure host-side bookkeeping (deques of :class:`_Sequence`
records); the engine owns every device interaction.
"""

from __future__ import annotations

import collections
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from .kv_pool import KVBlockPool

__all__ = ["Request", "Scheduler"]


@dataclass(eq=False)  # identity comparison: prompt arrays don't define ==
class Request:
    """One generation request. ``prompt`` is a 1-D int32 token array;
    ``adapter`` names a tenant adapter in the engine's ``AdapterSet``
    (None = base model). The sampling knobs (``temperature``/``top_k``/
    ``top_p``/``eos_id``) are PER REQUEST — they ride the decode step as
    traced per-row arrays, so one compiled engine serves mixed
    greedy/sampled tenants in a single batch; None inherits the engine's
    default."""

    prompt: Any
    max_new_tokens: int = 32
    adapter: str | None = None
    temperature: float | None = None
    top_k: int | None = None
    top_p: float | None = None
    eos_id: int | None = None
    id: int = -1  # assigned by the engine at submit


@dataclass(eq=False)  # identity comparison (deque/list membership tests)
class _Sequence:
    """Runtime state of one admitted request (engine-internal)."""

    req: Request
    arrival: float
    blocks: list[int] = field(default_factory=list)
    draft_blocks: list[int] = field(default_factory=list)  # spec mode only
    fill: int = 0  # cache slots written (prefill progress, then decode)
    out: list[int] = field(default_factory=list)  # emitted tokens
    last_token: int = 0  # next decode step's input
    prev_token: int = 0  # the token before it (spec rounds feed two)
    admitted: float | None = None
    first_token: float | None = None
    finished: float | None = None
    adapter_id: int = 0
    # resolved per-row sampling params (request value or engine default)
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_id: int = -1

    @property
    def prompt_len(self) -> int:
        return int(np.shape(self.req.prompt)[0])

    @property
    def prefilled(self) -> bool:
        return self.fill >= self.prompt_len

    def needed_blocks(self, block_size: int, lookahead: int = 0) -> int:
        """Blocks covering the next step's reads AND writes: position
        ``fill`` for plain decode, through ``fill + lookahead`` when a
        speculative round writes ``lookahead`` proposals past the pending
        token — the live prefix plus this round's worst case, which is
        what the decode batch actually gathers (the full reservation is
        admission's concern)."""
        return -(-(self.fill + 1 + int(lookahead)) // block_size)


class Scheduler:
    """FIFO continuous-batching admission over one :class:`KVBlockPool`
    (plus the draft model's pool in speculative mode). ``lookahead`` is
    the per-round speculative overshoot reserved per request (``spec_k``
    for a spec engine, 0 otherwise)."""

    def __init__(
        self,
        pool: KVBlockPool,
        max_slots: int,
        prefill_chunk: int,
        *,
        draft_pool: KVBlockPool | None = None,
        lookahead: int = 0,
    ):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got {prefill_chunk}")
        if lookahead < 0:
            raise ValueError(f"lookahead must be >= 0, got {lookahead}")
        self.pool = pool
        self.draft_pool = draft_pool
        self.lookahead = int(lookahead)
        self.max_slots = int(max_slots)
        self.prefill_chunk = int(prefill_chunk)
        self.waiting: collections.deque[_Sequence] = collections.deque()
        self.prefilling: collections.deque[_Sequence] = collections.deque()
        self.running: list[_Sequence] = []

    # -- queue state ---------------------------------------------------------
    @property
    def active(self) -> int:
        """Admitted-but-unfinished sequences (holding a decode slot)."""
        return len(self.prefilling) + len(self.running)

    @property
    def idle(self) -> bool:
        return not (self.waiting or self.prefilling or self.running)

    def depth(self) -> int:
        """Requests waiting for admission (the queue-depth observable)."""
        return len(self.waiting)

    # -- lifecycle -----------------------------------------------------------
    def reservation(self, seq: _Sequence) -> int:
        """The full worst-case block reservation of one request: every
        slot its committed tokens can occupy PLUS the ``lookahead``
        speculative positions the final round may write past them."""
        return self.pool.blocks_for(
            seq.prompt_len + seq.req.max_new_tokens + self.lookahead
        )

    def submit(self, seq: _Sequence) -> None:
        """Queue a request. Rejects one that could NEVER be admitted —
        a worst case larger than the whole pool would starve the queue
        behind it forever under strict FIFO."""
        need = self.reservation(seq)
        pools = [self.pool] + ([self.draft_pool] if self.draft_pool else [])
        for pool in pools:
            if need > pool.num_blocks:
                raise ValueError(
                    f"request needs {need} blocks worst-case but the pool only has "
                    f"{pool.num_blocks}; raise num_blocks or lower max_new_tokens"
                )
        if seq.req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.waiting.append(seq)

    def admit(self, now: float) -> list[_Sequence]:
        """Admit from the head of the waiting queue while a slot AND the
        head's full reservation fit — in EVERY pool, checked before
        either allocation so a partial admit can never leak blocks.
        Returns the newly admitted sequences (blocks already allocated,
        prefill pending)."""
        admitted = []
        while self.waiting and self.active < self.max_slots:
            head = self.waiting[0]
            need = self.reservation(head)
            if need > self.pool.num_free:
                break  # strict FIFO: nobody may overtake the head
            if self.draft_pool is not None and need > self.draft_pool.num_free:
                break
            self.waiting.popleft()
            head.blocks = self.pool.alloc(need)
            if self.draft_pool is not None:
                head.draft_blocks = self.draft_pool.alloc(need)
            head.admitted = now
            self.prefilling.append(head)
            admitted.append(head)
        return admitted

    def next_prefill(self) -> _Sequence | None:
        """The sequence owed the next prefill chunk (oldest first)."""
        return self.prefilling[0] if self.prefilling else None

    def prefill_done(self, seq: _Sequence) -> None:
        """Move a fully-prefilled sequence into the decode batch."""
        self.prefilling.remove(seq)
        self.running.append(seq)

    def finish(self, seq: _Sequence, now: float) -> None:
        """Release a finished sequence's slot and blocks IMMEDIATELY —
        the no-drain-barrier property lives here (both pools in spec
        mode: the draft pages recycle with the target's)."""
        if seq in self.running:
            self.running.remove(seq)
        elif seq in self.prefilling:
            self.prefilling.remove(seq)
        self.pool.free(seq.blocks)
        seq.blocks = []
        if self.draft_pool is not None and seq.draft_blocks:
            self.draft_pool.free(seq.draft_blocks)
        seq.draft_blocks = []
        seq.finished = now

    def decode_batch(self) -> list[_Sequence]:
        """The sequences decoding this step (stable submission order)."""
        return list(self.running)
