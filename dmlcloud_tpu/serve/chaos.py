"""Deterministic fault injection for the serving engine — the chaos half
of the robustness contract.

A server's failure paths are the least-executed code it ships; this
module exists so they run in every test cycle instead of the first bad
night in production. :class:`ChaosMonkey` attaches to a live
:class:`~dmlcloud_tpu.serve.engine.ServeEngine` and, from ONE seeded RNG,
injects the four failures the engine promises to survive:

- **step-function exceptions** — a :class:`ChaosError` raised at the
  device-phase hook points (``prefill`` / ``decode`` / ``draft`` /
  ``verify``) just before the jitted call. The engine must isolate the
  blast radius: affected request(s) end ``status="error"`` with every
  block released; a DRAFT fault degrades the round to plain decode
  instead (the draft is an optimization, not a dependency).
- **pool exhaustion** — the monkey allocates ("squats") free blocks for
  a few steps, exactly as a burst of admissions would. Admission stalls
  (by design, never an error) and any COW fork that needs a fresh block
  sees :class:`~dmlcloud_tpu.serve.kv_pool.PoolExhausted` — which must
  fail only that request. Squatted blocks go through the pool's normal
  ``alloc``/``release``, so the ``free + unique-live == capacity``
  invariant keeps holding DURING the outage, not just after.
- **slow-clock stalls** — the engine's injectable clock jumps forward,
  firing deadline expiries exactly as a GC pause / preempted host would.
- **random cancels** — ``cancel(rid)`` against a random live request at
  a random phase (queued, mid-prefill, mid-decode, mid-spec-round).

Everything draws from ``numpy.random.RandomState(seed)`` in a fixed
per-step order, so a drill is REPLAYABLE: the same seed over the same
trace injects the same faults at the same points. The drill's acceptance
bar (tests/test_serve.py, ``BENCH_serve_chaos_*``): every request ends
terminal, ``free + unique-live == capacity`` in every pool (checked with
``assert_consistent`` after every step, squat included), zero prefix
lock leaks, and greedy SURVIVORS are token-identical to a fault-free run
— the engine's rng folds a per-call counter, and argmax ignores it, so
identity is provable under greedy sampling.

Usage::

    monkey = ChaosMonkey(seed=7, p_fault=0.05, p_exhaust=0.1, p_cancel=0.02)
    monkey.attach(engine)
    engine.run()
    monkey.detach()         # releases any squatted blocks
    assert engine.leaked_blocks() == 0
"""

from __future__ import annotations

import numpy as np

from .kv_pool import PoolExhausted

__all__ = ["ChaosError", "ChaosMonkey"]


class ChaosError(RuntimeError):
    """An injected step failure (distinguishable from real bugs in logs)."""


class ChaosMonkey:
    """Seeded fault injector over one engine (module docstring).

    Probabilities are per opportunity: ``p_fault`` per device-phase call
    (limited to ``fault_points``), ``p_exhaust`` / ``p_stall`` /
    ``p_cancel`` per engine step. ``max_faults`` caps injected
    exceptions so a drill can guarantee survivors exist. ``verify_pools``
    audits every pool's host accounting each step (cheap at test scale,
    and exactly the audit that would catch a corrupted free list the
    moment the fault lands)."""

    def __init__(
        self,
        seed: int = 0,
        *,
        p_fault: float = 0.0,
        fault_points: tuple[str, ...] = ("prefill", "decode", "draft", "verify"),
        max_faults: int | None = None,
        p_exhaust: float = 0.0,
        exhaust_blocks: int = 4,
        exhaust_steps: int = 3,
        p_stall: float = 0.0,
        stall_s: float = 0.25,
        p_cancel: float = 0.0,
        verify_pools: bool = True,
    ):
        self._rng = np.random.RandomState(int(seed))
        self.p_fault = float(p_fault)
        self.fault_points = tuple(fault_points)
        self.max_faults = max_faults
        self.p_exhaust = float(p_exhaust)
        self.exhaust_blocks = int(exhaust_blocks)
        self.exhaust_steps = int(exhaust_steps)
        self.p_stall = float(p_stall)
        self.stall_s = float(stall_s)
        self.p_cancel = float(p_cancel)
        self.verify_pools = bool(verify_pools)
        self.engine = None
        self.faults = 0
        self.steps = 0
        #: replayable event log: (step, kind, detail) — the drill's record
        self.log: list[tuple[int, str, str]] = []
        self._squat: list[int] = []
        self._squat_left = 0
        self._offset = 0.0
        self._base_clock = None

    # -- wiring --------------------------------------------------------------
    def attach(self, engine) -> "ChaosMonkey":
        """Install on ``engine``: becomes its ``fault_injector`` and wraps
        its clock (stall injection). One engine per monkey."""
        if self.engine is not None:
            raise RuntimeError("monkey already attached")
        self.engine = engine
        engine.fault_injector = self
        self._base_clock = engine.clock
        engine.clock = self._clock
        return self

    def detach(self) -> None:
        """Restore the engine and release every squatted block — after
        this the pools owe nothing to the chaos harness."""
        if self.engine is None:
            return
        self._release_squat()
        self.engine.fault_injector = None
        self.engine.clock = self._base_clock
        self.engine = None

    def _clock(self) -> float:
        return self._base_clock() + self._offset

    # -- injection -----------------------------------------------------------
    def __call__(self, point: str, seqs) -> None:
        """The engine's chaos hook. ``step`` acts (never raises); device
        points flip one seeded coin and may raise :class:`ChaosError`."""
        if point == "step":
            self._on_step()
            return
        if (
            self.p_fault
            and point in self.fault_points
            and self._rng.random_sample() < self.p_fault
            and (self.max_faults is None or self.faults < self.max_faults)
        ):
            self.faults += 1
            who = ",".join(str(s.req.id) for s in seqs or [])
            self.log.append((self.steps, "fault", f"{point}:{who}"))
            raise ChaosError(f"injected {point} fault #{self.faults}")

    def _on_step(self) -> None:
        self.steps += 1
        eng = self.engine
        if self._squat:
            self._squat_left -= 1
            if self._squat_left <= 0:
                self._release_squat()
        elif self.p_exhaust and self._rng.random_sample() < self.p_exhaust:
            self._grab_squat()
        if self.p_stall and self._rng.random_sample() < self.p_stall:
            self._offset += self.stall_s
            self.log.append((self.steps, "stall", f"+{self.stall_s}s"))
        if self.p_cancel and self._rng.random_sample() < self.p_cancel:
            live = [rid for rid, s in eng._all.items() if s.status is None]
            if live:
                rid = live[int(self._rng.randint(len(live)))]
                if eng.cancel(rid):
                    self.log.append((self.steps, "cancel", str(rid)))
        if self.verify_pools:
            eng.pool.assert_consistent()
            if eng.draft_pool is not None:
                eng.draft_pool.assert_consistent()

    def _grab_squat(self) -> None:
        """Steal free blocks through the pool's own alloc — a legitimate
        (accounted) allocation, so exhaustion looks to the engine exactly
        like a competing admission burst."""
        pool = self.engine.pool
        n = min(self.exhaust_blocks, pool.num_free)
        if n < 1:
            return
        try:
            self._squat = pool.alloc(n)
        except PoolExhausted:  # raced our own num_free read: inject nothing
            return
        self._squat_left = self.exhaust_steps
        self.log.append((self.steps, "exhaust", f"{n} blocks"))

    def _release_squat(self) -> None:
        if self._squat:
            self.engine.pool.release(self._squat)
            self._squat = []
        self._squat_left = 0
