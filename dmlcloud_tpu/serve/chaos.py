"""Deterministic fault injection for the serving engine — the chaos half
of the robustness contract.

A server's failure paths are the least-executed code it ships; this
module exists so they run in every test cycle instead of the first bad
night in production. :class:`ChaosMonkey` attaches to a live
:class:`~dmlcloud_tpu.serve.engine.ServeEngine` and, from ONE seeded RNG,
injects the four failures the engine promises to survive:

- **step-function exceptions** — a :class:`ChaosError` raised at the
  device-phase hook points (``prefill`` / ``decode`` / ``draft`` /
  ``verify``) just before the jitted call. The engine must isolate the
  blast radius: affected request(s) end ``status="error"`` with every
  block released; a DRAFT fault degrades the round to plain decode
  instead (the draft is an optimization, not a dependency).
- **pool exhaustion** — the monkey allocates ("squats") free blocks for
  a few steps, exactly as a burst of admissions would. Admission stalls
  (by design, never an error) and any COW fork that needs a fresh block
  sees :class:`~dmlcloud_tpu.serve.kv_pool.PoolExhausted` — which must
  fail only that request. Squatted blocks go through the pool's normal
  ``alloc``/``release``, so the ``free + unique-live == capacity``
  invariant keeps holding DURING the outage, not just after.
- **slow-clock stalls** — the engine's injectable clock jumps forward,
  firing deadline expiries exactly as a GC pause / preempted host would.
- **random cancels** — ``cancel(rid)`` against a random live request at
  a random phase (queued, mid-prefill, mid-decode, mid-spec-round).

Attached to a :class:`~dmlcloud_tpu.serve.router.Router` instead
(:meth:`ChaosMonkey.attach_router`), the monkey injects REPLICA-level
events from the same seeded RNG into the same replayable log:

- **replica kills** (``p_replica_kill``) — permanent death of a random
  live replica; the router must fail its requests over and keep every
  contract (always leaves at least one replica standing).
- **replica stalls** (``p_replica_stall``) — a replica misses
  ``replica_stall_steps`` step calls; the router's heartbeat detector
  decides whether that was a blip or a death.

Everything draws from ``numpy.random.RandomState(seed)`` in a fixed
per-step order, so a drill is REPLAYABLE: the same seed over the same
trace injects the same faults at the same points. The drill's acceptance
bar (tests/test_serve.py, ``BENCH_serve_chaos_*``): every request ends
terminal, ``free + unique-live == capacity`` in every pool (checked with
``assert_consistent`` after every step, squat included), zero prefix
lock leaks, and greedy SURVIVORS are token-identical to a fault-free run
— the engine's rng folds a per-call counter, and argmax ignores it, so
identity is provable under greedy sampling.

Usage::

    monkey = ChaosMonkey(seed=7, p_fault=0.05, p_exhaust=0.1, p_cancel=0.02)
    monkey.attach(engine)
    engine.run()
    monkey.detach()         # releases any squatted blocks
    assert engine.leaked_blocks() == 0
"""

from __future__ import annotations

import numpy as np

from .kv_pool import PoolExhausted

__all__ = ["ChaosError", "ChaosMonkey"]


class ChaosError(RuntimeError):
    """An injected step failure (distinguishable from real bugs in logs)."""


class ChaosMonkey:
    """Seeded fault injector over one engine (module docstring).

    Probabilities are per opportunity: ``p_fault`` per device-phase call
    (limited to ``fault_points``), ``p_exhaust`` / ``p_stall`` /
    ``p_cancel`` per engine step. ``max_faults`` caps injected
    exceptions so a drill can guarantee survivors exist. ``verify_pools``
    audits every pool's host accounting each step (cheap at test scale,
    and exactly the audit that would catch a corrupted free list the
    moment the fault lands)."""

    def __init__(
        self,
        seed: int = 0,
        *,
        p_fault: float = 0.0,
        fault_points: tuple[str, ...] = ("prefill", "decode", "draft", "verify"),
        max_faults: int | None = None,
        p_exhaust: float = 0.0,
        exhaust_blocks: int = 4,
        exhaust_steps: int = 3,
        p_stall: float = 0.0,
        stall_s: float = 0.25,
        p_cancel: float = 0.0,
        verify_pools: bool = True,
        p_replica_kill: float = 0.0,
        max_replica_kills: int | None = None,
        p_replica_stall: float = 0.0,
        replica_stall_steps: int = 2,
    ):
        self._rng = np.random.RandomState(int(seed))
        self.p_fault = float(p_fault)
        self.fault_points = tuple(fault_points)
        self.max_faults = max_faults
        self.p_exhaust = float(p_exhaust)
        self.exhaust_blocks = int(exhaust_blocks)
        self.exhaust_steps = int(exhaust_steps)
        self.p_stall = float(p_stall)
        self.stall_s = float(stall_s)
        self.p_cancel = float(p_cancel)
        self.verify_pools = bool(verify_pools)
        self.p_replica_kill = float(p_replica_kill)
        self.max_replica_kills = max_replica_kills
        self.p_replica_stall = float(p_replica_stall)
        self.replica_stall_steps = int(replica_stall_steps)
        self.engine = None
        self.router = None
        self.faults = 0
        self.replica_kills = 0
        self.steps = 0
        #: replayable event log: (step, kind, detail) — the drill's record
        self.log: list[tuple[int, str, str]] = []
        self._squat: list[int] = []
        self._squat_left = 0
        self._offset = 0.0
        self._base_clock = None

    # -- wiring --------------------------------------------------------------
    def attach(self, engine) -> "ChaosMonkey":
        """Install on ``engine``: becomes its ``fault_injector`` and wraps
        its clock (stall injection). One engine per monkey."""
        if self.engine is not None or self.router is not None:
            raise RuntimeError("monkey already attached")
        self.engine = engine
        engine.fault_injector = self
        self._base_clock = engine.clock
        engine.clock = self._clock
        return self

    def detach(self) -> None:
        """Restore the engine and release every squatted block — after
        this the pools owe nothing to the chaos harness."""
        if self.engine is None:
            return
        self._release_squat()
        self.engine.fault_injector = None
        self.engine.clock = self._base_clock
        self.engine = None

    def attach_router(self, router) -> "ChaosMonkey":
        """Install on a :class:`~dmlcloud_tpu.serve.router.Router` for the
        REPLICA-level events (``p_replica_kill`` / ``p_replica_stall``):
        one seeded draw order per router step, logged into the same
        replayable event log as the engine-level faults. One router per
        monkey; a monkey may drive either an engine or a router, not
        both (two injectors sharing one RNG would entangle their draw
        sequences)."""
        if self.router is not None or self.engine is not None:
            raise RuntimeError("monkey already attached")
        self.router = router
        router.fault_injector = self
        return self

    def detach_router(self) -> None:
        if self.router is None:
            return
        self.router.fault_injector = None
        self.router = None

    def _clock(self) -> float:
        return self._base_clock() + self._offset

    # -- injection -----------------------------------------------------------
    def __call__(self, point: str, seqs) -> None:
        """The engine's chaos hook. ``step`` acts (never raises); device
        points flip one seeded coin and may raise :class:`ChaosError`."""
        if point == "step":
            self._on_step()
            return
        if point == "router_step":
            self._on_router_step()
            return
        if (
            self.p_fault
            and point in self.fault_points
            and self._rng.random_sample() < self.p_fault
            and (self.max_faults is None or self.faults < self.max_faults)
        ):
            self.faults += 1
            who = ",".join(str(s.req.id) for s in seqs or [])
            self.log.append((self.steps, "fault", f"{point}:{who}"))
            raise ChaosError(f"injected {point} fault #{self.faults}")

    def _on_step(self) -> None:
        self.steps += 1
        eng = self.engine
        if self._squat:
            self._squat_left -= 1
            if self._squat_left <= 0:
                self._release_squat()
        elif self.p_exhaust and self._rng.random_sample() < self.p_exhaust:
            self._grab_squat()
        if self.p_stall and self._rng.random_sample() < self.p_stall:
            self._offset += self.stall_s
            self.log.append((self.steps, "stall", f"+{self.stall_s}s"))
        if self.p_cancel and self._rng.random_sample() < self.p_cancel:
            live = [rid for rid, s in eng._all.items() if s.status is None]
            if live:
                rid = live[int(self._rng.randint(len(live)))]
                if eng.cancel(rid):
                    self.log.append((self.steps, "cancel", str(rid)))
        if self.verify_pools:
            eng.pool.assert_consistent()
            if eng.draft_pool is not None:
                eng.draft_pool.assert_consistent()

    def _on_router_step(self) -> None:
        """Replica-level events, fixed draw order (kill, then stall) —
        the same determinism contract as :meth:`_on_step`. A kill always
        leaves at least one replica standing (a drill with zero survivors
        proves nothing), and chaos never targets a draining replica (the
        drain path has its own verdict to keep clean)."""
        self.steps += 1
        r = self.router
        candidates = [
            name for name, rep in r.replicas.items()
            if rep.alive and not rep.removed and not rep.draining
        ]
        if (
            self.p_replica_kill
            and self._rng.random_sample() < self.p_replica_kill
            and (self.max_replica_kills is None
                 or self.replica_kills < self.max_replica_kills)
        ):
            if len(candidates) > 1:
                name = candidates[int(self._rng.randint(len(candidates)))]
                self.replica_kills += 1
                self.log.append((self.steps, "replica_kill", name))
                r.kill_replica(name, reason="chaos")
                candidates.remove(name)
        if self.p_replica_stall and self._rng.random_sample() < self.p_replica_stall:
            if candidates:
                name = candidates[int(self._rng.randint(len(candidates)))]
                self.log.append(
                    (self.steps, "replica_stall", f"{name}:{self.replica_stall_steps}")
                )
                r.stall_replica(name, self.replica_stall_steps)
        if self.verify_pools:
            for rep in r.replicas.values():
                rep.engine.pool.assert_consistent()
                if rep.engine.draft_pool is not None:
                    rep.engine.draft_pool.assert_consistent()

    def _grab_squat(self) -> None:
        """Steal free blocks through the pool's own alloc — a legitimate
        (accounted) allocation, so exhaustion looks to the engine exactly
        like a competing admission burst."""
        pool = self.engine.pool
        n = min(self.exhaust_blocks, pool.num_free)
        if n < 1:
            return
        try:
            self._squat = pool.alloc(n)
        except PoolExhausted:  # raced our own num_free read: inject nothing
            return
        self._squat_left = self.exhaust_steps
        self.log.append((self.steps, "exhaust", f"{n} blocks"))

    def _release_squat(self) -> None:
        if self._squat:
            self.engine.pool.release(self._squat)
            self._squat = []
        self._squat_left = 0
