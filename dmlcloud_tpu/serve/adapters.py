"""Multi-tenant LoRA serving: stacked adapters, gathered by request id.

``lora_merge`` folds one adapter into the base weights — perfect for a
single-tenant deployment, useless for serving MANY tenants from one base
model: a merged copy per tenant multiplies the weight memory by the tenant
count, and a batch mixing tenants has no single set of weights to run.

:class:`AdapterSet` is the merge-free alternative: every tenant's LoRA
factors are STACKED along a new leading axis (``a: [N, d_in, r]``,
``b: [N, r, d_out]`` per adapted kernel), the decode batch carries a
per-row adapter id, and each dense layer adds its row's own delta
``(x @ a[id]) @ b[id]`` inside the step (``models/lora.batched_lora_delta``
via ``transformer._adapter_add``). Cost per token is rank-r work per
adapted kernel — the base weights stream ONCE for the whole mixed batch,
which is the entire point of serving LoRA tenants together.

Index 0 is always the implicit null adapter (zero factors, exact zero
delta), so requests without an adapter ride the same gather. Adapters must
be built with ``lora_init(..., in_axes=1)``: the factored application
contracts ``a`` against the layer INPUT, so ``a`` must carry the kernel's
first axis — the historical all-but-last split merges fine but cannot be
applied factored (``AdapterSet`` rejects it when given ``base`` to check
against).
"""

from __future__ import annotations

from typing import Any, Mapping

import jax
import jax.numpy as jnp

from ..models.lora import LoraPair

__all__ = ["AdapterSet"]


def _is_pair(x) -> bool:
    return isinstance(x, LoraPair)


class AdapterSet:
    """Stacked per-tenant LoRA adapters for batched serving.

    ``adapters`` maps tenant name -> adapter tree from
    ``lora_init(base_params, rank, in_axes=1)`` (every tenant must adapt
    the same kernels at the same rank — they share one stacked gather).
    ``alpha`` is the usual LoRA scale; ``b`` is pre-scaled by
    ``alpha/rank`` at stacking time so the traced delta is two einsums and
    nothing else. ``base`` (optional) enables the factorization check.
    """

    def __init__(
        self,
        adapters: Mapping[str, Any],
        alpha: float = 16.0,
        base: Any = None,
    ):
        if not adapters:
            raise ValueError("AdapterSet needs at least one adapter")
        self.names: list[str | None] = [None] + list(adapters)
        self._ids = {name: i for i, name in enumerate(self.names)}
        trees = list(adapters.values())
        ref = jax.tree_util.tree_structure(trees[0], is_leaf=lambda x: x is None or _is_pair(x))
        for name, tree in adapters.items():
            if jax.tree_util.tree_structure(
                tree, is_leaf=lambda x: x is None or _is_pair(x)
            ) != ref:
                raise ValueError(
                    f"adapter {name!r} adapts a different kernel set than the others; "
                    "all tenants must come from the same lora_init match"
                )
        if base is not None:
            self._check_factorization(trees[0], base)

        def stack_leaf(*pairs):
            if pairs[0] is None:
                return None
            ranks = {p.a.shape[-1] for p in pairs}
            if len(ranks) != 1:
                raise ValueError(f"adapters disagree on rank for one kernel: {sorted(ranks)}")
            a = jnp.stack([jnp.zeros_like(pairs[0].a)] + [p.a for p in pairs])
            # pre-scale b by alpha/rank: the traced delta is then just
            # (x @ a[id]) @ b[id], no runtime scale
            b = jnp.stack(
                [jnp.zeros_like(pairs[0].b)] + [p.b * (alpha / p.a.shape[-1]) for p in pairs]
            )
            return LoraPair(a=a, b=b)

        self.stacked = jax.tree_util.tree_map(
            stack_leaf, *trees, is_leaf=lambda x: x is None or _is_pair(x)
        )
        self.alpha = float(alpha)

    @staticmethod
    def _check_factorization(tree: Any, base: Any) -> None:
        """``a`` must carry each base kernel's FIRST axis (in_axes=1); the
        all-but-last factorization cannot be applied per-row."""

        def check(ad, p):
            if ad is None:
                return
            if ad.a.shape[0] != p.shape[0]:
                raise ValueError(
                    f"adapter a-factor has in-dim {ad.a.shape[0]} but the base kernel's "
                    f"first axis is {p.shape[0]}: batched serving needs adapters built "
                    "with lora_init(..., in_axes=1)"
                )

        jax.tree_util.tree_map(
            check, tree, base, is_leaf=lambda x: x is None or _is_pair(x)
        )

    def __len__(self) -> int:
        return len(self.names)

    def id_of(self, name: str | None) -> int:
        """The stacked index of a tenant (None -> 0, the null adapter)."""
        try:
            return self._ids[name]
        except KeyError:
            raise KeyError(
                f"unknown adapter {name!r}; known: {[n for n in self.names if n]}"
            ) from None

    def pack(self, ids) -> tuple[Any, jnp.ndarray]:
        """The ``adapters=`` argument for a decode step: the stacked tree
        plus the per-row ids as an int32 device array."""
        return self.stacked, jnp.asarray(ids, jnp.int32)
