"""Declarative serving SLOs with multi-window burn-rate alerting.

The observability plane's third leg (doc/observability.md): the journal
records what happened and the metrics registry counts it; this module
JUDGES it against declared objectives — the per-tenant latency/
availability targets ROADMAP item 3's actuator will steer toward.

An :class:`SLO` declares per-tenant objectives in plain numbers::

    SLO("gold-latency", tenant="gold", ttft_p99_s=0.25)
    SLO("fleet-availability", availability=0.999)

and :class:`SLOMonitor` evaluates them over sliding windows off the
SAME injectable ``clock=`` the engine and router read — the whole
alerting path is unit-testable with a fake clock, no sleeps.

**Burn-rate semantics** (the SRE-workbook multi-window rule): each
objective implies an error budget — ``1 - good_fraction`` of requests
may miss a latency target, ``1 - availability`` may fail. The burn rate
of a window is ``bad_fraction / budget`` (1.0 = spending the budget
exactly as fast as allowed). An alert FIRES only when BOTH the fast and
the slow window burn at ``burn_threshold`` or more: the slow window
proves it is sustained (no paging on one slow request), the fast window
proves it is still happening (no paging an hour after recovery). Each
firing is journaled as an ``slo_alert`` span and retained in
``monitor.alerts``; the alert re-arms only after the fast window drops
back under the threshold, so a sustained breach is one alert, not one
per evaluation.

The monitor surfaces in three places: the ledger summary (``"slo"``
section when an engine is constructed with ``slos=``), ``python -m
dmlcloud_tpu diag --run`` (alert census from the journal), and the
drain/requeue verdict (``serve.slo_alerts``).
"""

from __future__ import annotations

import collections
import time
from dataclasses import dataclass
from typing import Callable

import numpy as np

from ..telemetry import journal

__all__ = ["SLO", "SLOMonitor"]

#: terminal statuses that count against an availability objective; a
#: client cancel is neither good nor bad — it spends no error budget
_BAD_STATUSES = ("error", "shed", "deadline_exceeded")


@dataclass(frozen=True)
class SLO:
    """One declarative objective. At least one of ``ttft_p99_s`` (the
    latency target: ``good_fraction`` of requests must see first token
    within it) and ``availability`` (fraction of non-cancelled requests
    that must end ``ok``) must be set. ``tenant=None`` spans all
    traffic. Windows: ``window_s`` is the slow (sustained) window,
    ``fast_window_s`` the still-happening one."""

    name: str
    tenant: str | None = None
    ttft_p99_s: float | None = None
    availability: float | None = None
    good_fraction: float = 0.99
    window_s: float = 60.0
    fast_window_s: float = 5.0
    burn_threshold: float = 2.0

    def __post_init__(self):
        if self.ttft_p99_s is None and self.availability is None:
            raise ValueError(f"SLO {self.name!r} declares no objective")
        if self.ttft_p99_s is not None and self.ttft_p99_s <= 0:
            raise ValueError(f"ttft_p99_s must be > 0, got {self.ttft_p99_s}")
        if self.availability is not None and not 0.0 < self.availability < 1.0:
            raise ValueError(f"availability must be in (0, 1), got {self.availability}")
        if not 0.0 < self.good_fraction < 1.0:
            raise ValueError(f"good_fraction must be in (0, 1), got {self.good_fraction}")
        if self.fast_window_s <= 0 or self.window_s <= self.fast_window_s:
            raise ValueError(
                f"need 0 < fast_window_s < window_s, got "
                f"{self.fast_window_s} / {self.window_s}"
            )
        if self.burn_threshold <= 0:
            raise ValueError(f"burn_threshold must be > 0, got {self.burn_threshold}")


class _Part:
    """Sliding-window state of one objective part (latency or
    availability): a deque of ``(t, good, value)`` events bounded by the
    slow window, plus the alert re-arm latch."""

    __slots__ = ("kind", "budget", "events", "alerting")

    def __init__(self, kind: str, budget: float):
        self.kind = kind
        self.budget = budget
        self.events: collections.deque = collections.deque()
        self.alerting = False

    def record(self, now: float, good: bool, value: float) -> None:
        self.events.append((now, good, value))

    def prune(self, now: float, window_s: float) -> None:
        ev = self.events
        while ev and ev[0][0] < now - window_s:
            ev.popleft()

    def burn(self, now: float, window_s: float) -> float | None:
        """``bad_fraction / budget`` over the trailing window; None with
        no events (no traffic spends no budget)."""
        n = bad = 0
        for t, good, _ in self.events:
            if t >= now - window_s:
                n += 1
                bad += 0 if good else 1
        if n == 0:
            return None
        return (bad / n) / self.budget


class SLOMonitor:
    """Evaluates a set of :class:`SLO` objectives over events the engine
    feeds it (module docstring). ``clock`` must be the same injectable
    clock the event timestamps come from."""

    def __init__(self, objectives, clock: Callable[[], float] = time.perf_counter):
        objectives = list(objectives)
        names = [o.name for o in objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self.objectives: list[SLO] = objectives
        self.clock = clock
        self.alerts: list[dict] = []
        self._parts: dict[tuple[str, str], _Part] = {}
        for o in objectives:
            if o.ttft_p99_s is not None:
                self._parts[(o.name, "ttft")] = _Part("ttft", 1.0 - o.good_fraction)
            if o.availability is not None:
                self._parts[(o.name, "availability")] = _Part(
                    "availability", 1.0 - o.availability
                )

    def _matching(self, tenant: str | None):
        for o in self.objectives:
            if o.tenant is None or o.tenant == tenant:
                yield o

    # -- event feeds ----------------------------------------------------------
    def record_ttft(self, tenant: str | None, ttft_s: float, now: float) -> None:
        for o in self._matching(tenant):
            part = self._parts.get((o.name, "ttft"))
            if part is not None:
                part.record(now, ttft_s <= o.ttft_p99_s, float(ttft_s))

    def record_terminal(self, tenant: str | None, status: str, now: float) -> None:
        if status == "cancelled":
            return  # a client cancel spends no error budget
        for o in self._matching(tenant):
            part = self._parts.get((o.name, "availability"))
            if part is not None:
                part.record(now, status not in _BAD_STATUSES, 0.0)

    # -- evaluation -----------------------------------------------------------
    def evaluate(self, now: float | None = None) -> list[dict]:
        """Check every objective's multi-window burn; returns (and
        retains, and journals as ``slo_alert`` spans) the alerts that
        FIRED on this call. Cheap enough for once per engine step:
        O(events in the slow window) per objective."""
        if now is None:
            now = self.clock()
        fired: list[dict] = []
        for o in self.objectives:
            for part_name in ("ttft", "availability"):
                part = self._parts.get((o.name, part_name))
                if part is None:
                    continue
                part.prune(now, o.window_s)
                fast = part.burn(now, o.fast_window_s)
                slow = part.burn(now, o.window_s)
                burning = (
                    fast is not None and slow is not None
                    and fast >= o.burn_threshold and slow >= o.burn_threshold
                )
                if burning and not part.alerting:
                    part.alerting = True
                    alert = {
                        "slo": o.name, "part": part_name, "tenant": o.tenant,
                        "burn_fast": round(fast, 3), "burn_slow": round(slow, 3),
                        "threshold": o.burn_threshold, "t": now,
                    }
                    self.alerts.append(alert)
                    fired.append(alert)
                    journal.emit(
                        "slo_alert", now - o.fast_window_s, now, label=o.name,
                        slo=o.name, part=part_name, tenant=o.tenant or "",
                        burn_fast=alert["burn_fast"], burn_slow=alert["burn_slow"],
                    )
                elif not burning and fast is not None and fast < o.burn_threshold:
                    part.alerting = False  # re-arm only after the fast window recovers
        return fired

    def status(self, now: float | None = None) -> dict:
        """Plain-dict scorecard per objective (the ledger summary's
        ``"slo"`` section): observed p99 / availability over the slow
        window, burn rates, alert latch and total alert count."""
        if now is None:
            now = self.clock()
        out: dict[str, dict] = {}
        for o in self.objectives:
            entry: dict = {"tenant": o.tenant}
            part = self._parts.get((o.name, "ttft"))
            if part is not None:
                part.prune(now, o.window_s)
                vals = [v for _, _, v in part.events]
                entry["ttft"] = {
                    "target_p99_s": o.ttft_p99_s,
                    "observed_p99_s": (
                        round(float(np.percentile(vals, 100 * o.good_fraction)), 6)
                        if vals else None
                    ),
                    "n": len(vals),
                    "burn_fast": part.burn(now, o.fast_window_s),
                    "burn_slow": part.burn(now, o.window_s),
                    "alerting": part.alerting,
                }
            part = self._parts.get((o.name, "availability"))
            if part is not None:
                part.prune(now, o.window_s)
                n = len(part.events)
                good = sum(1 for _, g, _ in part.events if g)
                entry["availability"] = {
                    "target": o.availability,
                    "observed": round(good / n, 6) if n else None,
                    "n": n,
                    "burn_fast": part.burn(now, o.fast_window_s),
                    "burn_slow": part.burn(now, o.window_s),
                    "alerting": part.alerting,
                }
            out[o.name] = entry
        return {"objectives": out, "alerts": len(self.alerts)}
