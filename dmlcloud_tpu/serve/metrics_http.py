"""Optional stdlib ``/metrics`` HTTP endpoint for the serve registry.

A scrape surface with zero dependencies: :class:`MetricsServer` wraps
``http.server.ThreadingHTTPServer`` in a daemon thread and answers
``GET /metrics`` with whatever Prometheus text the ``source`` callable
returns — wire it to ``engine.metrics_text`` for one replica or
``Router.metrics_text`` for the whole pool::

    with MetricsServer(router.metrics_text) as srv:
        ...  # scrape http://127.0.0.1:{srv.port}/metrics

``port=0`` (the default) binds an ephemeral port — tests and multi-
replica hosts never collide. The handler never raises into the serving
process: a ``source`` failure answers 500 with the error text instead.
This module is OPTIONAL plumbing — the engine/router never import it;
``metrics_text()`` works without any server (doc/observability.md).
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

__all__ = ["MetricsServer"]

#: the content type Prometheus scrapers expect from a text-format page
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsServer:
    """Serve ``source()`` (Prometheus text) at ``/metrics`` (module
    docstring). ``start()`` is idempotent; ``close()`` shuts the
    listener down and joins the thread."""

    def __init__(self, source: Callable[[], str], host: str = "127.0.0.1",
                 port: int = 0):
        self.source = source
        self.host = host
        self._requested_port = int(port)
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        """The bound port (the ephemeral one when constructed with 0)."""
        if self._server is None:
            raise RuntimeError("MetricsServer is not running (call start())")
        return self._server.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def start(self) -> "MetricsServer":
        if self._server is not None:
            return self
        source = self.source

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib handler API
                if self.path.split("?", 1)[0] != "/metrics":
                    self.send_error(404, "only /metrics lives here")
                    return
                try:
                    body = source().encode("utf-8")
                    status, ctype = 200, CONTENT_TYPE
                except Exception as exc:  # noqa: BLE001 — never kill serving
                    body = f"metrics source failed: {exc}\n".encode("utf-8")
                    status, ctype = 500, "text/plain; charset=utf-8"
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-request stderr spam
                pass

        self._server = ThreadingHTTPServer((self.host, self._requested_port), _Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="dml-metrics-http", daemon=True
        )
        self._thread.start()
        return self

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "MetricsServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
