"""Prefix-cache sharing: a radix tree of content-addressed KV blocks.

The multi-tenant serving workload is dominated by shared prefixes —
system prompts, few-shot templates, multi-turn history. Without sharing,
every request prefills its whole prompt from scratch into private
:class:`~dmlcloud_tpu.serve.kv_pool.KVBlockPool` blocks, paying the full
prefill compute AND the full block reservation for tokens whose K/V an
earlier request already computed bit-identically. This module makes that
work reusable at BLOCK granularity, the PagedAttention/RadixAttention
recipe:

- **Content addressing.** A FULL block of ``block_size`` tokens is keyed
  by the tokens it holds, chained from its parent block — node key =
  ``hash((parent.key, tokens))`` — so a block's address commits to the
  entire prefix behind it, never just its own slice (the same 16 tokens
  after two different prefixes are two different nodes). Partial trailing
  blocks are never cached: their pages interleave with live decode writes.
- **The radix tree.** One node per cached full block, children keyed by
  their token tuple, one root per LoRA adapter id (adapter deltas change
  the K/V projections, so cross-tenant sharing would be silently wrong —
  tenant id is part of the address). :meth:`match` walks the tree with a
  new prompt's full blocks and returns the longest cached chain;
  :meth:`lock` re-validates that chain (an eviction may have raced
  between match and admit) and pins the surviving prefix with one
  :meth:`~KVBlockPool.retain` per block. The scheduler maps those blocks
  READ-ONLY into the request's table and starts chunked prefill at the
  divergence point — the matched tokens' prefill is skipped entirely.
- **Copy-on-write.** A shared block (``pool.refcount > 1``) is read-only;
  the one flow that must write into one — an exact full-block re-request,
  where the last prompt token is re-fed for its logits and its K/V
  scatter targets the final MATCHED block — forks first: the engine
  copies the page to a private block reserved at admission and swaps the
  table entry (``ServeEngine._cow_guard``; lint rule DML211 enforces the
  guard-before-scatter ordering statically).
- **Eviction: leaf-first LRU over refcount.** The tree holds one
  reference per cached block, so an idle cached block has
  ``refcount == 1`` — evictable; a block any live request maps (or whose
  descendants a request pinned) has ``refcount > 1`` — pinned. When
  admission needs more free blocks than the pool has, :meth:`evict`
  releases least-recently-used UNPINNED LEAVES first (interior nodes
  become leaves as their children go), which is exactly LRU over the
  refcount-0-holders set and never tears a cached chain in the middle.

Everything here is host-side bookkeeping over the pool's free list and
refcounts — the device never sees the tree; it only sees block tables in
which the same physical page id now appears in many rows (the paged
gather already supports that; the scatter must not target it, hence COW).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import numpy as np

from .kv_pool import KVBlockPool

__all__ = ["PrefixCache", "PrefixMatch", "content_key", "prefix_keys", "root_key"]

# -- stable content addresses -----------------------------------------------
#
# Builtin hash() salts str/bytes per interpreter (PYTHONHASHSEED), so two
# processes computed DIFFERENT addresses for the same prefix — fine while
# the tree was private to one engine, fatal the moment replicas exchange
# affinity hints keyed on the address (serve/router.py). These use the same
# splitmix64-style counter mix as data/datasets._mix_u64 (MixPipeline's
# mixing draws): a pure function of the inputs, identical in every process
# and on every platform.

_M64 = (1 << 64) - 1
_ROOT_TAG = 0x726F6F74  # b"root": the per-adapter tree anchor


def _mix_u64(a: int, b: int) -> int:
    x = (int(a) * 0x9E3779B97F4A7C15 + (int(b) + 1) * 0xD1B54A32D192ED03) & _M64
    x ^= x >> 30
    x = (x * 0xBF58476D1CE4E5B9) & _M64
    x ^= x >> 27
    x = (x * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


def root_key(adapter: int) -> int:
    """The content address of an adapter's tree root."""
    return _mix_u64(_ROOT_TAG, int(adapter))


def content_key(parent_key: int, tokens) -> int:
    """One full block's chained content address: every token id folded
    into the parent's key, committing to the entire prefix behind it."""
    k = int(parent_key)
    for t in tokens:
        k = _mix_u64(k, int(t))
    return k


def prefix_keys(tokens, block_size: int, adapter: int = 0) -> list[int]:
    """The content-address chain of a prompt's full blocks, deepest last —
    computable WITHOUT a cache instance, which is how the router derives
    prefix-affinity hints (the deepest key names the warmest replica) and
    how two replicas agree on what "the same template" means."""
    toks = np.asarray(tokens).reshape(-1)
    bs = int(block_size)
    keys: list[int] = []
    k = root_key(adapter)
    for i in range(0, (toks.size // bs) * bs, bs):
        k = content_key(k, (int(t) for t in toks[i : i + bs]))
        keys.append(k)
    return keys


class _Node:
    """One cached full block: ``tokens`` (its block_size token ids),
    the physical ``block`` it pinned in the pool, a content address
    chained from the parent, and an LRU tick."""

    __slots__ = ("tokens", "block", "key", "parent", "children", "tick", "dead")

    def __init__(self, tokens: tuple, block: int, parent: "_Node | None"):
        self.tokens = tokens
        self.block = block
        #: chained content address: commits to the whole prefix behind it
        #: (splitmix64 chain — stable across processes, see content_key)
        self.key = content_key(parent.key if parent is not None else 0, tokens)
        self.parent = parent
        self.children: dict[tuple, _Node] = {}
        self.tick = 0
        self.dead = False  # set at eviction: invalidates stale PrefixMatch handles


@dataclass
class PrefixMatch:
    """A :meth:`PrefixCache.match` result: the cached chain for a prompt.
    NOT a lease — nothing is pinned until :meth:`PrefixCache.lock`
    re-validates it (any node may be evicted in between; lock truncates
    at the first dead node instead of handing out a recycled page)."""

    nodes: list = field(default_factory=list)
    #: tokens covered by ``nodes`` (always a multiple of block_size)
    tokens: int = 0

    @property
    def blocks(self) -> list[int]:
        return [n.block for n in self.nodes]


class PrefixCache:
    """Radix tree of content-addressed, refcounted KV blocks over one
    :class:`KVBlockPool` (the TARGET pool only — a speculative engine's
    draft pool has no tree; draft prefill skips via the target's match
    length and the verifier guarantees token identity regardless)."""

    def __init__(self, pool: KVBlockPool):
        self.pool = pool
        self.block_size = pool.block_size
        self._roots: dict[int, _Node] = {}  # adapter id -> tree root
        self._tick = 0  # monotonic LRU clock (deterministic, never wall time)
        self._nodes = 0
        # observables (the ledger carries the per-request twins)
        self.lookups = 0
        self.hits = 0
        self.evictions = 0

    # -- internals -----------------------------------------------------------
    def _root(self, adapter: int) -> _Node:
        root = self._roots.get(int(adapter))
        if root is None:
            root = self._roots[int(adapter)] = _Node((), -1, None)
            root.key = root_key(int(adapter))
        return root

    def _touch(self, node: _Node) -> None:
        self._tick += 1
        node.tick = self._tick

    def _full_blocks(self, tokens) -> list[tuple]:
        toks = np.asarray(tokens).reshape(-1)
        bs = self.block_size
        return [
            tuple(int(t) for t in toks[i : i + bs])
            for i in range(0, (toks.size // bs) * bs, bs)
        ]

    # -- lookup --------------------------------------------------------------
    def match(self, tokens, adapter: int = 0) -> PrefixMatch:
        """The longest cached chain covering ``tokens``' full blocks for
        this adapter. Pure lookup — pins nothing (see :meth:`lock`)."""
        self.lookups += 1
        node = self._root(adapter)
        out = PrefixMatch()
        for chunk in self._full_blocks(tokens):
            child = node.children.get(chunk)
            if child is None:
                break
            out.nodes.append(child)
            out.tokens += self.block_size
            node = child
        if out.nodes:
            self.hits += 1
        return out

    def lock(self, match: PrefixMatch) -> tuple[list[int], int]:
        """Pin a matched chain for admission: re-validate every node (an
        eviction between match and admit marks nodes dead — the chain is
        truncated at the first one, never a recycled page), then retain
        each surviving block ONCE for the admitting request. Returns
        ``(blocks, tokens)`` for the still-valid prefix; the caller owns
        one reference per returned block and must :meth:`KVBlockPool.release`
        them (directly on a failed admit, or via the sequence's normal
        block release at finish)."""
        blocks: list[int] = []
        for node in match.nodes:
            if node.dead:
                break
            blocks.append(node.block)
            self._touch(node)
        if blocks:
            self.pool.retain(blocks)
        return blocks, len(blocks) * self.block_size

    # -- insertion -----------------------------------------------------------
    def insert(self, tokens, blocks, adapter: int = 0) -> int:
        """Register a sequence's written full blocks: ``blocks[i]`` must
        hold the K/V of ``tokens[i*bs:(i+1)*bs]`` (the caller only passes
        fully-written prefixes — stale speculative slots live past the
        fill boundary, in blocks this never sees). Existing nodes are
        LRU-touched and keep THEIR block (the caller's duplicate stays
        private and releases normally); each new node adopts the caller's
        block with one tree-held reference, which is what keeps the page
        alive after the request itself finishes. Returns the number of
        newly adopted blocks."""
        node = self._root(adapter)
        adopted = 0
        for i, chunk in enumerate(self._full_blocks(tokens)):
            child = node.children.get(chunk)
            if child is None:
                if i >= len(blocks):
                    break  # caller owns fewer blocks than full chunks (defensive)
                child = _Node(chunk, int(blocks[i]), node)
                self.pool.retain([child.block])
                node.children[chunk] = child
                self._nodes += 1
                adopted += 1
            self._touch(child)
            node = child
        return adopted

    # -- eviction ------------------------------------------------------------
    def _evictable_leaves(self) -> list[_Node]:
        out = []
        stack = [c for root in self._roots.values() for c in root.children.values()]
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            elif self.pool.refcount(n.block) == 1:  # only the tree holds it
                out.append(n)
        return out

    def _drop(self, node: _Node) -> None:
        node.dead = True
        if node.parent is not None:
            node.parent.children.pop(node.tokens, None)
        self._nodes -= 1
        self.evictions += 1
        self.pool.release([node.block])

    def evict(self, need_free: int) -> int:
        """Free cached blocks until the pool has ``need_free`` free blocks
        (or nothing evictable remains): least-recently-used UNPINNED leaf
        first — a block a live request still maps has ``refcount > 1``
        and is never touched, and dropping leaves before parents keeps
        every surviving chain contiguous. Returns ``pool.num_free``."""
        while self.pool.num_free < need_free:
            leaves = self._evictable_leaves()
            if not leaves:
                break
            self._drop(min(leaves, key=lambda n: n.tick))
        return self.pool.num_free

    def evictable(self) -> int:
        """Cached blocks reclaimable RIGHT NOW plus those reclaimable once
        running requests release their pins — for admission this is every
        tree-held block not pinned by a live mapping, counted by walking
        the tree (pinned interior nodes unwind leaf-first as requests
        finish, so all refcount-1 nodes are eventually reachable)."""
        count = 0
        stack = [c for root in self._roots.values() for c in root.children.values()]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if self.pool.refcount(n.block) == 1:
                count += 1
        return count

    # -- observability -------------------------------------------------------
    def leaked_locks(self) -> list[int]:
        """Tree blocks with MORE holders than the tree's own reference —
        call when no request is live (engine idle): every extra holder is
        a lock some admission or exit path forgot to release. Empty list
        = zero lock leaks, the chaos drill's prefix observable."""
        out = []
        stack = [c for root in self._roots.values() for c in root.children.values()]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if self.pool.refcount(n.block) > 1:
                out.append(n.block)
        return out

    def stats(self) -> dict:
        return {
            "nodes": self._nodes,
            "cached_blocks": self._nodes,
            "evictable_now": len(self._evictable_leaves()),
            "lookups": self.lookups,
            "hits": self.hits,
            "hit_rate": round(self.hits / self.lookups, 4) if self.lookups else None,
            "evictions": self.evictions,
        }
