"""dmlcloud_tpu.serve — continuous-batching inference for heavy traffic.

The training stack's inference half (``models/generate.py``) runs one
static batch per call; this package turns it into a serving engine:

- :class:`KVBlockPool` (kv_pool.py): paged KV cache — fixed device pages,
  per-sequence block tables, host free list. Memory scales with live
  tokens; freed blocks recycle immediately.
- :class:`Scheduler` / :class:`Request` (scheduler.py): FIFO
  continuous-batching admission with chunked prefill — no drain barrier,
  no starvation.
- :class:`ServeEngine` (engine.py): the loop — bucketed decode shapes
  (0 mid-run recompiles, TraceGuard-enforced), greedy output
  token-identical to serial ``generate()``; per-REQUEST sampling params
  (mixed greedy/sampled tenants in one batch) and TWO speculative modes:
  draft-model decoding (``spec_k`` proposals per round against a second
  page pool, one k+1-position verify pass, partial-accept rewind by fill
  counters) and Medusa decoding (``medusa_k`` proposals from extra decode
  heads on the frozen base model — same verify and rewind, but the draft
  model, its prefill mirror and the whole second page pool are gone;
  ``models.speculative.init_medusa_heads`` shapes the heads).
- :class:`PrefixCache` (prefix_cache.py): radix-tree prefix sharing over
  content-addressed, refcounted pool blocks — a warm template's prefill
  shrinks to its unique suffix; copy-on-write forks protect shared pages;
  eviction is leaf-first LRU over refcount (``prefix_cache=True``).
- :class:`AdapterSet` (adapters.py): multi-tenant LoRA serving, one base
  model + per-request adapter deltas inside the decode step.
- :class:`ServeLedger` (ledger.py): TTFT / per-token / queue-depth
  latency accounting plus drafted/accepted counters and accept rates,
  journal span kinds ``queue_wait`` / ``prefill`` / ``decode_batch`` /
  ``draft`` / ``verify`` (``fault`` / ``drain`` on the failure paths);
  bounded retention (``max_records``) keeps the aggregates exact while
  per-request detail evicts FIFO.
- **Overload control & failure semantics** (scheduler.py + engine.py):
  per-request ``deadline_s`` / ``priority`` / ``tenant``, ``cancel(rid)``
  at any phase, one terminal status per request (``ok | cancelled |
  deadline_exceeded | shed | error``), bounded admission queue with load
  shedding (``max_waiting`` + ``shed_policy``), per-tenant deficit-
  round-robin fairness (``fairness="tenant"``), per-request fault
  isolation and graceful drain (``drain()`` — admission stops, in-flight
  work finishes inside ``drain_budget_s``, the ``requeue.json`` verdict
  is written).
- :class:`ChaosMonkey` (chaos.py): seeded deterministic fault injection
  — step exceptions, pool-exhaustion squats, slow-clock stalls, random
  cancels, and (attached to a router) replica kills and stalls — the
  drill that proves the above under fire.
- :class:`Router` (router.py): the multi-replica front door — N engine
  replicas behind one submit/step surface: heartbeat health detection,
  at-most-once failover via idempotency tokens (``DuplicateRequest`` is
  the engine-side guard), per-tenant deficit-round-robin placement with
  stable prefix-affinity hints (``prefix_keys``), per-replica circuit
  breakers, and router-coordinated graceful drain of one replica.
- **Observability plane** (doc/observability.md): request-scoped tracing
  — the router mints one trace id per request and every span it touches
  (``route``/``queue_wait``/``admission``/``prefix_lookup``/``prefill``/
  ``cow_fork``/decode batches/``failover``) links into a single causal
  trace across replicas and retries; a typed metrics registry
  (``ServeEngine(metrics=True)``, ``engine.metrics_text()`` /
  ``Router.metrics_text()``, optional :class:`MetricsServer` HTTP
  endpoint, ``python -m dmlcloud_tpu top``); and declarative
  :class:`SLO` objectives with multi-window burn-rate alerting
  (:class:`SLOMonitor`, ``slos=`` — alerts journal as ``slo_alert``
  spans and surface in the ledger summary, ``diag --run`` and the drain
  verdict).

Quick start::

    from dmlcloud_tpu.serve import ServeEngine

    engine = ServeEngine(model, params, num_blocks=256, block_size=16,
                         max_slots=8)
    rid = engine.submit(prompt_tokens, max_new_tokens=64)
    engine.run()
    tokens = engine.output(rid)

See doc/serving.md for the architecture, memory math and bench receipts.
"""

from .adapters import AdapterSet
from .chaos import ChaosError, ChaosMonkey
from .engine import DuplicateRequest, ServeEngine
from .kv_pool import KVBlockPool, PoolExhausted
from .ledger import ServeLedger
from .metrics_http import MetricsServer
from .prefix_cache import PrefixCache, PrefixMatch, prefix_keys
from .router import Router
from .scheduler import Request, Scheduler, TERMINAL_STATUSES
from .slo import SLO, SLOMonitor

__all__ = [
    "AdapterSet",
    "ChaosError",
    "ChaosMonkey",
    "DuplicateRequest",
    "KVBlockPool",
    "MetricsServer",
    "PoolExhausted",
    "PrefixCache",
    "PrefixMatch",
    "Request",
    "Router",
    "SLO",
    "SLOMonitor",
    "Scheduler",
    "ServeEngine",
    "ServeLedger",
    "TERMINAL_STATUSES",
    "prefix_keys",
]
