"""Per-request latency ledger: TTFT, per-token pace, queue depth.

The training side's goodput ledger decomposes epochs; serving needs the
request-centric twin. The engine records, per request: arrival ->
admission (queue wait), admission -> first emitted token (prefill +
scheduling), token count and completion — all ``time.perf_counter``
readings (the journal's clock discipline; wall clock never enters a
duration). ``summary()`` reduces them to the numbers a capacity planner
asks for: p50/p99 TTFT, mean queue wait, served tokens/s over the busy
window, and the queue-depth profile the engine samples once per step.

Prefix sharing adds the cache observables: per request, the tokens the
radix tree matched at admission (``cached_tokens``), the prefill tokens
the skip actually saved (``saved_tokens`` — the divergence point), and
the prompt length, reduced in ``summary()`` to the hit rate, the
cached-token fraction and the prefill-tokens-saved fraction — the numbers
the ``BENCH_serve_prefix_*`` receipt gates.

Speculative serving adds the accept-rate observables: per request, the
tokens the draft proposed (``drafted``) and the tokens the verifier
accepted (``accepted``) — counters that arrive packed in the same device
fetch as the round's tokens (no extra readback; lint DML210), reduced in
``summary()`` to total and per-request-mean accept rates.

The ledger is pure host bookkeeping — O(1) dict/list appends per event,
no device interaction — and rides next to the span journal: every record
here corresponds to ``queue_wait`` / ``prefill`` / ``decode_batch`` (and
``draft`` / ``verify`` in spec mode) spans when telemetry is armed, so a
Perfetto timeline and this summary never disagree about what the engine
did.
"""

from __future__ import annotations

import numpy as np

__all__ = ["ServeLedger"]


def _pct(values, q):
    return float(np.percentile(np.asarray(values, np.float64), q)) if values else None


class ServeLedger:
    """Accumulates per-request timing records and step-level samples."""

    def __init__(self):
        self.records: dict[int, dict] = {}
        self.queue_depths: list[int] = []
        self.batch_sizes: list[int] = []
        self.decode_steps = 0

    # -- per-request events --------------------------------------------------
    def arrived(self, rid: int, now: float) -> None:
        self.records[rid] = {"arrival": now, "tokens": 0, "drafted": 0, "accepted": 0}

    def admitted(self, rid: int, now: float) -> None:
        self.records[rid]["admitted"] = now

    def first_token(self, rid: int, now: float) -> None:
        self.records[rid]["first_token"] = now

    def token(self, rid: int) -> None:
        self.records[rid]["tokens"] += 1

    def finished(self, rid: int, now: float) -> None:
        self.records[rid]["finished"] = now

    def prefix_match(self, rid: int, cached: int, saved: int, prompt: int) -> None:
        """The request's prefix-cache outcome at admission: ``cached``
        tokens matched in the radix tree, ``saved`` prefill tokens
        actually skipped (the divergence point — ``cached`` minus the one
        re-fed token of an exact full-block match), out of ``prompt``
        prompt tokens. Host bookkeeping only; the tree itself never
        appears on device."""
        rec = self.records[rid]
        rec["cached_tokens"] = int(cached)
        rec["saved_tokens"] = int(saved)
        rec["prompt_tokens"] = int(prompt)

    def spec_round(self, rid: int, drafted: int, accepted: int) -> None:
        """One speculative verification round's counters for a request.
        The counts arrive packed in the SAME device fetch as the round's
        tokens (serve/engine.py) — this is pure host accounting, never an
        extra readback (lint DML210)."""
        rec = self.records[rid]
        rec["drafted"] += int(drafted)
        rec["accepted"] += int(accepted)

    def accept_rate(self, rid: int) -> float | None:
        """The request's measured draft accept rate
        (``accepted / drafted``); None before any verification round."""
        rec = self.records[rid]
        return rec["accepted"] / rec["drafted"] if rec["drafted"] else None

    # -- per-step samples ----------------------------------------------------
    def step_sample(self, queue_depth: int, batch_size: int) -> None:
        self.decode_steps += 1
        self.queue_depths.append(int(queue_depth))
        self.batch_sizes.append(int(batch_size))

    # -- reduction -----------------------------------------------------------
    def ttfts(self) -> list[float]:
        return [
            r["first_token"] - r["arrival"]
            for r in self.records.values()
            if "first_token" in r
        ]

    def summary(self) -> dict:
        """The serving scorecard. ``tokens_per_sec`` covers the busy window
        (first arrival -> last completion) — the end-to-end number a trace
        replay compares, queueing included."""
        done = [r for r in self.records.values() if "finished" in r]
        ttft = self.ttfts()
        waits = [r["admitted"] - r["arrival"] for r in self.records.values() if "admitted" in r]
        total_tokens = sum(r["tokens"] for r in self.records.values())
        span = None
        if done and self.records:
            t0 = min(r["arrival"] for r in self.records.values())
            t1 = max(r["finished"] for r in done)
            span = max(t1 - t0, 1e-9)
        # prefix-cache observables (None on an engine without the cache):
        # hit rate over admitted requests, fraction of prompt tokens served
        # from cache, and the prefill tokens the skip actually saved
        pref = [r for r in self.records.values() if "prompt_tokens" in r]
        prompt_tok = sum(r["prompt_tokens"] for r in pref)
        cached_tok = sum(r["cached_tokens"] for r in pref)
        saved_tok = sum(r["saved_tokens"] for r in pref)
        drafted = sum(r.get("drafted", 0) for r in self.records.values())
        accepted = sum(r.get("accepted", 0) for r in self.records.values())
        rates = [
            r["accepted"] / r["drafted"]
            for r in self.records.values()
            if r.get("drafted", 0)
        ]
        return {
            "requests": len(self.records),
            "completed": len(done),
            "total_tokens": total_tokens,
            "tokens_per_sec": round(total_tokens / span, 1) if span else None,
            "p50_ttft_s": _pct(ttft, 50),
            "p99_ttft_s": _pct(ttft, 99),
            "mean_queue_wait_s": float(np.mean(waits)) if waits else None,
            "max_queue_depth": max(self.queue_depths, default=0),
            "mean_batch_size": float(np.mean(self.batch_sizes)) if self.batch_sizes else None,
            "decode_steps": self.decode_steps,
            # speculative-decode counters (zero / None on a plain engine):
            # totals across requests plus the per-request mean — the
            # scorecard's accept-rate observable
            # prefix-cache scorecard (None without prefix_cache=True)
            "prefix_hit_rate": (
                round(sum(1 for r in pref if r["cached_tokens"] > 0) / len(pref), 4)
                if pref else None
            ),
            "cached_token_frac": (
                round(cached_tok / prompt_tok, 4) if prompt_tok else None
            ),
            "prefill_tokens_saved": saved_tok if pref else None,
            "prefill_tokens_saved_frac": (
                round(saved_tok / prompt_tok, 4) if prompt_tok else None
            ),
            "drafted_tokens": drafted,
            "accepted_tokens": accepted,
            "accept_rate": round(accepted / drafted, 4) if drafted else None,
            "mean_request_accept_rate": round(float(np.mean(rates)), 4) if rates else None,
        }
