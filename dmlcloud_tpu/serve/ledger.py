"""Per-request latency ledger: TTFT, per-token pace, queue depth.

The training side's goodput ledger decomposes epochs; serving needs the
request-centric twin. The engine records, per request: arrival ->
admission (queue wait), admission -> first emitted token (prefill +
scheduling), token count, terminal status and completion — all
``time.perf_counter`` readings (the journal's clock discipline; wall
clock never enters a duration). ``summary()`` reduces them to the
numbers a capacity planner asks for: p50/p99 TTFT, mean queue wait,
served tokens/s over the busy window, GOODPUT (tokens of ``ok``
requests only — work shed or errored is not goodput), the terminal
status census and the queue-depth profile the engine samples once per
step.

**Bounded retention.** ``max_records`` caps the per-request detail dict
(``records``): once a request is terminal it becomes evictable, and the
oldest terminal records are dropped FIFO beyond the cap — live requests
are NEVER evicted (their events must still land somewhere). Eviction
folds each record into running aggregates first, so every count, sum
and rate in ``summary()`` stays EXACT over the full history; only the
TTFT/queue-wait percentiles narrow to a bounded most-recent window
(``_WINDOW`` samples — a sliding-window percentile, the standard
dashboard semantic). Unbounded by default (``max_records=None``), which
is the pre-PR-13 behavior; "millions of users" deployments set the cap
and hold host memory constant.

Prefix sharing adds the cache observables: per request, the tokens the
radix tree matched at admission (``cached_tokens``), the prefill tokens
the skip actually saved (``saved_tokens`` — the divergence point), and
the prompt length, reduced in ``summary()`` to the hit rate, the
cached-token fraction and the prefill-tokens-saved fraction — the numbers
the ``BENCH_serve_prefix_*`` receipt gates.

Speculative serving adds the accept-rate observables: per request, the
tokens proposed per round (``drafted`` — the spec draft model's, or the
Medusa heads' in ``medusa_k`` mode) and the tokens the verifier accepted
(``accepted``) — counters that arrive packed in the same device fetch as
the round's tokens (no extra readback; lint DML210), reduced in
``summary()`` to total and per-request-mean accept rates.

The ledger is pure host bookkeeping — O(1) dict/list appends per event,
no device interaction — and rides next to the span journal: every record
here corresponds to ``queue_wait`` / ``prefill`` / ``decode_batch`` (and
``draft`` / ``verify`` in spec mode, ``medusa`` for the fused Medusa
round, ``fault`` / ``drain`` on the failure paths) spans when telemetry
is armed, so a Perfetto timeline and
this summary never disagree about what the engine did.
"""

from __future__ import annotations

import collections

import numpy as np

__all__ = ["ServeLedger"]

#: sliding-window size for the TTFT / queue-wait percentiles once
#: retention is bounded (counts and sums stay exact regardless)
_WINDOW = 4096


def _pct(values, q):
    if not values:
        return None
    return float(np.percentile(np.asarray(values, np.float64), q))


class ServeLedger:
    """Accumulates per-request timing records and step-level samples.
    ``max_records`` bounds the retained per-request detail (module
    docstring); None retains everything."""

    def __init__(self, max_records: int | None = None):
        if max_records is not None and max_records < 1:
            raise ValueError(f"max_records must be >= 1, got {max_records}")
        self.max_records = max_records
        self.records: dict[int, dict] = {}
        self.decode_steps = 0
        # terminal rids in finish order — the FIFO eviction queue
        self._evictable: collections.deque[int] = collections.deque()
        # running aggregates: per-step samples (never per-step lists) and
        # the exact sums/counts of every evicted record
        self._max_queue_depth = 0
        self._batch_size_sum = 0
        self._status_counts: dict[str, int] = {}
        window = None if max_records is None else _WINDOW
        self._window = window
        self._ttfts: collections.deque[float] = collections.deque(maxlen=window)
        self._waits: collections.deque[float] = collections.deque(maxlen=window)
        # per-tenant TTFT windows: same event-time windowing as _ttfts, so
        # the per-tenant percentiles in summary() survive record eviction
        # (the router's fairness receipt reads these)
        self._tenant_ttfts: dict[str, collections.deque] = {}
        self._agg = {
            "requests": 0, "completed": 0, "tokens": 0, "ok_tokens": 0,
            "drafted": 0, "accepted": 0, "rate_sum": 0.0, "rate_n": 0,
            "pref_n": 0, "pref_hits": 0, "prompt_tokens": 0,
            "cached_tokens": 0, "saved_tokens": 0,
            "first_arrival": None, "last_finish": None, "wait_sum": 0.0,
            "wait_n": 0,
        }

    # -- per-request events --------------------------------------------------
    def arrived(self, rid: int, now: float, tenant: str | None = None) -> None:
        rec = {"arrival": now, "tokens": 0, "drafted": 0, "accepted": 0}
        if tenant is not None:
            rec["tenant"] = tenant
        self.records[rid] = rec

    def admitted(self, rid: int, now: float) -> None:
        rec = self.records[rid]
        rec["admitted"] = now
        self._waits.append(now - rec["arrival"])
        self._agg["wait_sum"] += now - rec["arrival"]
        self._agg["wait_n"] += 1

    def first_token(self, rid: int, now: float) -> None:
        rec = self.records[rid]
        rec["first_token"] = now
        self._ttfts.append(now - rec["arrival"])
        tenant = rec.get("tenant")
        if tenant is not None:
            dq = self._tenant_ttfts.get(tenant)
            if dq is None:
                dq = self._tenant_ttfts[tenant] = collections.deque(
                    maxlen=self._window
                )
            dq.append(now - rec["arrival"])

    def token(self, rid: int) -> None:
        self.records[rid]["tokens"] += 1

    def finished(self, rid: int, now: float, status: str = "ok") -> None:
        """Terminal event — ONCE per request, with its terminal status
        (``ok | cancelled | deadline_exceeded | shed | error``). Beyond
        ``max_records`` the oldest TERMINAL record folds into the exact
        aggregates and its detail drops (FIFO)."""
        rec = self.records.get(rid)
        if rec is not None:
            rec["finished"] = now
            rec["status"] = status
        self._status_counts[status] = self._status_counts.get(status, 0) + 1
        last = self._agg["last_finish"]
        self._agg["last_finish"] = now if last is None else max(last, now)
        self._evictable.append(rid)
        if self.max_records is not None:
            while len(self.records) > self.max_records and self._evictable:
                self._evict(self._evictable.popleft())

    def _evict(self, rid: int) -> None:
        """Fold one terminal record into the aggregates and drop it."""
        rec = self.records.pop(rid, None)
        if rec is None:
            return
        agg = self._agg
        agg["requests"] += 1
        agg["tokens"] += rec["tokens"]
        if rec.get("status", "ok") == "ok":
            agg["ok_tokens"] += rec["tokens"]
        if "finished" in rec:
            agg["completed"] += 1
        first = agg["first_arrival"]
        agg["first_arrival"] = (
            rec["arrival"] if first is None else min(first, rec["arrival"])
        )
        agg["drafted"] += rec["drafted"]
        agg["accepted"] += rec["accepted"]
        if rec["drafted"]:
            agg["rate_sum"] += rec["accepted"] / rec["drafted"]
            agg["rate_n"] += 1
        if "prompt_tokens" in rec:
            agg["pref_n"] += 1
            agg["pref_hits"] += 1 if rec["cached_tokens"] > 0 else 0
            agg["prompt_tokens"] += rec["prompt_tokens"]
            agg["cached_tokens"] += rec["cached_tokens"]
            agg["saved_tokens"] += rec["saved_tokens"]

    def prefix_match(self, rid: int, cached: int, saved: int, prompt: int) -> None:
        """The request's prefix-cache outcome at admission: ``cached``
        tokens matched in the radix tree, ``saved`` prefill tokens
        actually skipped (the divergence point — ``cached`` minus the one
        re-fed token of an exact full-block match), out of ``prompt``
        prompt tokens. Host bookkeeping only; the tree itself never
        appears on device."""
        rec = self.records[rid]
        rec["cached_tokens"] = int(cached)
        rec["saved_tokens"] = int(saved)
        rec["prompt_tokens"] = int(prompt)

    def spec_round(self, rid: int, drafted: int, accepted: int) -> None:
        """One speculative verification round's counters for a request.
        The counts arrive packed in the SAME device fetch as the round's
        tokens (serve/engine.py) — this is pure host accounting, never an
        extra readback (lint DML210)."""
        rec = self.records[rid]
        rec["drafted"] += int(drafted)
        rec["accepted"] += int(accepted)

    def accept_rate(self, rid: int) -> float | None:
        """The request's measured draft accept rate
        (``accepted / drafted``); None before any verification round."""
        rec = self.records[rid]
        return rec["accepted"] / rec["drafted"] if rec["drafted"] else None

    def status_counts(self) -> dict[str, int]:
        """Terminal status census over the FULL history (exact across
        eviction)."""
        return dict(self._status_counts)

    # -- per-step samples ----------------------------------------------------
    def step_sample(self, queue_depth: int, batch_size: int) -> None:
        self.decode_steps += 1
        self._max_queue_depth = max(self._max_queue_depth, int(queue_depth))
        self._batch_size_sum += int(batch_size)

    # -- reduction -----------------------------------------------------------
    def ttfts(self, tenant: str | None = None) -> list[float]:
        """TTFT samples from the RETAINED records (optionally one
        tenant's); the summary percentiles use the wider event-time
        window, which survives eviction."""
        return [
            r["first_token"] - r["arrival"]
            for r in self.records.values()
            if "first_token" in r and (tenant is None or r.get("tenant") == tenant)
        ]

    def summary(self) -> dict:
        """The serving scorecard. ``tokens_per_sec`` covers the busy window
        (first arrival -> last completion) — the end-to-end number a trace
        replay compares, queueing included; ``goodput_tokens_per_sec``
        counts only ``ok`` requests' tokens over the same window (shed /
        errored / expired work is throughput, never goodput). Counts and
        sums are exact over the full history regardless of eviction."""
        agg = self._agg
        live = list(self.records.values())
        done = [r for r in live if "finished" in r]
        total_tokens = agg["tokens"] + sum(r["tokens"] for r in live)
        ok_tokens = agg["ok_tokens"] + sum(
            r["tokens"] for r in live if r.get("status", None) == "ok"
        )
        arrivals = [r["arrival"] for r in live]
        if agg["first_arrival"] is not None:
            arrivals.append(agg["first_arrival"])
        finishes = [r["finished"] for r in done]
        if agg["last_finish"] is not None:
            finishes.append(agg["last_finish"])
        span = None
        if arrivals and finishes:
            span = max(max(finishes) - min(arrivals), 1e-9)
        # prefix-cache observables (None on an engine without the cache):
        # hit rate over admitted requests, fraction of prompt tokens served
        # from cache, and the prefill tokens the skip actually saved
        pref = [r for r in live if "prompt_tokens" in r]
        pref_n = agg["pref_n"] + len(pref)
        pref_hits = agg["pref_hits"] + sum(1 for r in pref if r["cached_tokens"] > 0)
        prompt_tok = agg["prompt_tokens"] + sum(r["prompt_tokens"] for r in pref)
        cached_tok = agg["cached_tokens"] + sum(r["cached_tokens"] for r in pref)
        saved_tok = agg["saved_tokens"] + sum(r["saved_tokens"] for r in pref)
        drafted = agg["drafted"] + sum(r["drafted"] for r in live)
        accepted = agg["accepted"] + sum(r["accepted"] for r in live)
        rates = [r["accepted"] / r["drafted"] for r in live if r["drafted"]]
        rate_sum = agg["rate_sum"] + sum(rates)
        rate_n = agg["rate_n"] + len(rates)
        waits_mean = (
            agg["wait_sum"] / agg["wait_n"] if agg["wait_n"] else None
        )
        ttft = list(self._ttfts)
        statuses = self.status_counts()
        # the live SLO scorecard when an engine attached its monitor
        # (ServeEngine(slos=...) sets ledger.slo_monitor): declared
        # objectives judged over their sliding windows, alert count
        slo = getattr(self, "slo_monitor", None)
        slo_section = {} if slo is None else {"slo": slo.status()}
        return {
            **slo_section,
            "requests": agg["requests"] + len(self.records),
            "completed": agg["completed"] + len(done),
            "statuses": statuses,
            "total_tokens": total_tokens,
            "tokens_per_sec": round(total_tokens / span, 1) if span else None,
            "goodput_tokens_per_sec": (
                round(ok_tokens / span, 1) if span else None
            ),
            "p50_ttft_s": _pct(ttft, 50),
            "p99_ttft_s": _pct(ttft, 99),
            # per-tenant TTFT percentiles over the same windowed samples
            # (exactly what callers used to re-derive by hand from
            # ttfts(tenant=), but eviction-proof): the fairness observable
            # the router receipt gates on
            "tenant_ttft": {
                tenant: {
                    "n": len(dq),
                    "p50_s": _pct(list(dq), 50),
                    "p99_s": _pct(list(dq), 99),
                }
                for tenant, dq in sorted(self._tenant_ttfts.items())
            },
            "mean_queue_wait_s": waits_mean,
            "max_queue_depth": self._max_queue_depth,
            "mean_batch_size": (
                self._batch_size_sum / self.decode_steps
                if self.decode_steps else None
            ),
            "decode_steps": self.decode_steps,
            # speculative-decode counters (zero / None on a plain engine):
            # totals across requests plus the per-request mean — the
            # scorecard's accept-rate observable
            # prefix-cache scorecard (None without prefix_cache=True)
            "prefix_hit_rate": (
                round(pref_hits / pref_n, 4) if pref_n else None
            ),
            "cached_token_frac": (
                round(cached_tok / prompt_tok, 4) if prompt_tok else None
            ),
            "prefill_tokens_saved": saved_tok if pref_n else None,
            "prefill_tokens_saved_frac": (
                round(saved_tok / prompt_tok, 4) if prompt_tok else None
            ),
            "drafted_tokens": drafted,
            "accepted_tokens": accepted,
            "accept_rate": round(accepted / drafted, 4) if drafted else None,
            "mean_request_accept_rate": (
                round(rate_sum / rate_n, 4) if rate_n else None
            ),
        }
