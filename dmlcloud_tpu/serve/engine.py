"""The continuous-batching serving engine over the paged KV pool.

One :class:`ServeEngine` owns the four pieces the module docstrings around
it describe — the device page pool (``kv_pool``), the FIFO scheduler
(``scheduler``), the per-request latency ledger (``ledger``) and the
jitted paged steps — and runs the serving loop:

    admit waiting requests -> one prefill chunk -> one decode batch

per :meth:`step`. The decode batch advances EVERY running stream
regardless of how much prefill is pending, so a long prompt never
stalls running generations; a stream that emits EOS frees its slot and
blocks before the next step, and the next waiting request takes them —
continuous batching, no drain barrier.

**Three decode modes share that loop:**

- *Plain* (``spec_k == medusa_k == 0``): one jitted ``_paged_step``
  advances every row one token per step — the PR-8 engine, unchanged
  semantics.
- *Speculative* (``spec_k >= 1``): a draft model (or the target itself —
  shared-model self-draft, ``models/speculative.py``'s smoke config)
  proposes ``k`` tokens per round against its OWN page pool, and one
  verifier pass scores all ``k+1`` positions per row through the same
  ``ops/paged_attention.py`` scatter/gather (multi-token writes through
  the block tables; sentinel rows still drop). The accept rule is
  :func:`models.speculative.verify_proposals` with each row's own
  sampling params; a partial accept "rewinds" by advancing the host-side
  fill counters only to the accepted position — block ownership never
  moves, and the next round's contiguous writes overwrite the stale
  speculative tail before the causal mask can expose it (the same
  overwrite invariant ``speculative_generate`` proves). Per accepted
  token the target pays ``~1/(accepted+1)`` of a weight-streaming pass —
  the per-token cost of the weight-bandwidth-bound decode loop becomes a
  per-round cost.
- *Medusa* (``medusa_k >= 1``): the separate draft model, its prefill
  mirror and the entire second page pool are GONE from the speculative
  path. ``k - 1`` lightweight decode heads
  (:func:`models.speculative.init_medusa_heads` — one residual block
  each, riding the FROZEN base model) read the final hidden state out of
  the round's ONE verify forward (``decode_step(...,
  return_hidden=True)``) and emit the NEXT round's proposals on the way
  out, so a round is a single ``k``-position target pass committing up
  to ``k`` tokens — the proposals ride the round's packed token fetch as
  ``k - 1`` host ints, never a second forward. Verification is the SAME
  ``verify_proposals`` + fill-counter rewind as spec mode (proposals are
  the heads' argmax picks, i.e. one-hot draft rows — rejection sampling
  stays exact for sampled rows), fused into the ONE ``_medusa_step``
  signature per (batch x table) bucket — the signature budget SHRINKS vs
  spec mode (no draft prefill, no second per-round step) and
  ``leaked_blocks`` has no draft pool to count. Because there is only
  one model, the heads propose from the ADAPTED hidden state under
  per-row LoRA — the proposer sees the tenant delta spec mode's
  base-model draft never did.

**Prefix sharing** (``prefix_cache=True``, serve/prefix_cache.py): pool
blocks become content-addressed and refcounted, indexed by a radix tree
over token prefixes. Admission maps a prompt's longest cached full-block
prefix READ-ONLY into the new request's table and starts chunked prefill
at the divergence point — a warm template's prefill shrinks to its unique
suffix (near-zero TTFT). Every write path runs a copy-on-write guard
first (``_cow_guard``: fork any refcount>1 block the scatter would touch
— one traced ``_copy_block`` signature for every fork ever; lint DML211
enforces the ordering), and the pool evicts leaf-first by LRU over
refcount when the free list runs dry. Greedy output stays token-identical
to the uncached engine — the committed ``BENCH_serve_prefix_*.json``
receipt re-asserts it on an 80%-shared-template trace.

**Per-request sampling.** ``temperature``/``top_k``/``top_p``/``eos_id``
ride each :class:`Request` and enter the compiled steps as per-row traced
arrays (``models.generate.sample_logits_batched``), so one engine serves
mixed greedy/sampled tenants in a single batch; greedy rows stay
bit-identical to serial ``generate()``.

**Failure semantics.** Every request ends in exactly one terminal status
(``ok | cancelled | deadline_exceeded | shed | error`` — see
:data:`~dmlcloud_tpu.serve.scheduler.TERMINAL_STATUSES`), through ONE
exit path (``Scheduler.terminate``) that releases both pools, the COW
spare and any prefix-cache locks at ANY phase — queued, mid-chunked-
prefill, mid-decode, mid-spec-round. A step failure is isolated to the
request(s) it was advancing: the engine catches it, fails those rows
(status ``error``, blocks freed, a ``fault`` span in the journal) and
keeps serving everyone else — greedy survivors stay token-identical to
an un-injected run (``serve/chaos.py`` proves this deterministically).
A failed DRAFT step degrades that round to plain decode instead (the
draft is an optimization; losing one round costs accept-rate
bookkeeping nothing). Overload control bounds the admission queue
(``max_waiting`` + ``shed_policy``) and a per-tenant deficit-round-robin
mode (``fairness="tenant"``) keeps a hot tenant from starving cold ones.
Graceful drain (:meth:`ServeEngine.drain`, or automatically when the
installed ``PreemptionGuard`` trips mid-``step``) stops admission, sheds
the queue, lets in-flight work finish inside ``drain_budget_s`` (then
sheds it too) and writes the ``requeue.json`` verdict every elasticity
wrapper already reads (doc/elasticity.md).

**Zero mid-run recompiles, by construction.** Every device call's shape
signature is ``(batch_bucket, table_bucket)`` for decode (each of the
draft and verify steps in spec mode) and ``(1, prefill_chunk,
table_bucket)`` for prefill — times two prefill models in spec mode —
with both bucket sets fixed at engine construction (``compile/buckets.py``
machinery). Each jitted step is wrapped in a ``TraceGuard`` armed at
exactly its bucket product, so a signature leak is a raised
``RetraceError`` in tests rather than a silent compile stall under
production traffic.

**One host sync per device round.** The fetched array IS the output
(tokens), and in spec mode the per-row ``n_new``/``n_accept`` counters
ride THAT SAME fetch as two extra packed columns — no separate
``.item()``/``int()`` readback of accept counters anywhere in the loop
(lint rule DML210 exists because a per-round counter readback is exactly
the host sync that made the r05 speculative path 0.19×).

The decode math itself is :func:`models.generate.decode_step` — the same
primitive ``generate``/``beam_search``/``speculative_generate`` run — with
``pages=(block_tables, fill)`` steering it through the pool
(``ops/paged_attention.py``). ``prepare_decode_params`` is applied once at
construction for both models: int8 weight-only trees serve with the
fused-dequant kernels and the off-TPU operand widen pre-paid (the PR-6
decode win), with no per-call preparation left in the loop.
"""

from __future__ import annotations

import collections
import functools
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..compile.buckets import bucket_for, resolve_buckets
from ..lint.traceguard import TraceGuard
from ..telemetry import journal
from .adapters import AdapterSet
from .kv_pool import KVBlockPool
from .ledger import ServeLedger
from .prefix_cache import PrefixCache
from .scheduler import Request, Scheduler, _Sequence

__all__ = ["DuplicateRequest", "ServeEngine"]


class DuplicateRequest(ValueError):
    """``submit`` rejected an idempotency token it has already accepted —
    the original admission stands. Carries the rid it mapped to, so a
    retrying caller can re-attach instead of double-admitting."""

    def __init__(self, token: str, rid: int):
        super().__init__(
            f"idempotency token {token!r} already admitted as request {rid}"
        )
        self.token = token
        self.rid = int(rid)


def _copy_block(pools, src, dst):
    """The copy-on-write fork's device half: copy page ``src`` to page
    ``dst`` across every layer's K/V leaves. ``src``/``dst`` are TRACED
    scalars, so every fork in the engine's lifetime replays ONE compiled
    signature (a Python-int ``.at[i].set`` would bake the ids in and
    compile per (src, dst) pair — a mid-run recompile per fork).
    ``pools`` is donated: the fork is a swap, never two live pools."""
    return jax.tree_util.tree_map(lambda x: x.at[dst].set(x[src]), pools)


def _paged_step(
    pools, params, tables, fill, tokens, last_idx, rng, adapters,
    temperature, top_k, top_p, *, model,
):
    """One traced engine step (prefill chunk or plain decode batch): write
    ``tokens``' K/V through the block tables, read each row's logits at
    ``last_idx`` and sample the next token with each ROW's params (traced
    ``[B]`` arrays — mixed greedy/sampled tenants share the trace, and a
    new temperature never recompiles). ``pools`` is donated — the engine
    swaps in the returned pages (DML205: never two live copies of the
    cache)."""
    from ..models.generate import decode_step, sample_logits_batched

    logits, pools = decode_step(
        model, params, tokens, pools, pages=(tables, fill), adapters=adapters
    )
    last = jnp.take_along_axis(logits, last_idx[:, None, None], axis=1)[:, 0]  # [B, V]
    tok = sample_logits_batched(last, rng, temperature, top_k, top_p)
    return tok, pools


def _spec_draft_step(
    pools, params, tables, fill, prev_tok, last_tok, rng,
    temperature, top_k, top_p, *, model, k,
):
    """The draft half of one speculative round: ``k`` proposals per row
    against the draft page pool, all shapes static. Pass 0 feeds the last
    TWO committed tokens at positions ``fill-1``/``fill`` — the leading
    rewrite closes the draft pool's one-slot gap after a fully-accepted
    round (``models/speculative.py``'s 2-token trick) and is an identical
    rewrite otherwise; passes ``1..k-1`` feed each proposal at
    ``fill + i``. Returns ``(proposals [B, k], dlogits [B, k, V],
    pools)`` where ``dlogits`` row ``i`` is the truncated, scaled
    distribution proposal ``i+1`` was sampled from — exactly what the
    verifier's rejection rule needs as ``p_d``. ``pools`` is donated."""
    from ..models.generate import _truncate_scaled, decode_step, sample_logits_batched

    def pick(row_logits, i):
        return sample_logits_batched(
            row_logits, jax.random.fold_in(rng, i), temperature, top_k, top_p
        )

    toks2 = jnp.stack([prev_tok, last_tok], axis=1)  # [B, 2]
    logits, pools = decode_step(model, params, toks2, pools, pages=(tables, fill - 1))
    nxt = pick(logits[:, -1], 0)
    props, drows = [nxt], [logits[:, -1]]
    for i in range(1, k):  # k-1 single-token passes (unrolled: k is static)
        logits, pools = decode_step(
            model, params, nxt[:, None], pools, pages=(tables, fill + i)
        )
        nxt = pick(logits[:, 0], i)
        props.append(nxt)
        drows.append(logits[:, 0])
    proposals = jnp.stack(props, axis=1)  # [B, k]
    dlogits = _truncate_scaled(
        jnp.stack(drows, axis=1).astype(jnp.float32), temperature, top_k, top_p
    )
    return proposals, dlogits, pools


def _spec_verify_step(
    pools, params, tables, fill, last_tok, proposals, dlogits, rng,
    temperature, top_k, top_p, eos_id, adapters, *, model, k,
):
    """The verify half: ONE target pass scores all ``k+1`` positions per
    row (``[y_last, d_1..d_k]`` written at ``fill..fill+k`` through the
    block tables), then :func:`models.speculative.verify_proposals` runs
    each row's own accept rule. Returns ``(packed [B, k+3], pools)`` —
    the ``k+1`` tokens to commit plus the ``n_new``/``n_accept`` counters
    as two extra columns, so ONE host fetch carries tokens AND counters
    (no separate counter readback per round — DML210). ``adapters``
    threads per-row LoRA deltas into the TARGET pass only (spec × LoRA:
    the base-model draft proposes without the tenant's delta — it only
    costs accept rate; the verifier scores with the adapter, so output
    stays token-identical to the tenant's own model). ``pools`` is
    donated."""
    from ..models.generate import decode_step
    from ..models.speculative import verify_proposals

    x = jnp.concatenate([last_tok[:, None], proposals], axis=1)  # [B, k+1]
    tlogits, pools = decode_step(
        model, params, x, pools, pages=(tables, fill), adapters=adapters
    )
    new_tokens, n_new, n_accept = verify_proposals(
        tlogits, dlogits, proposals, rng, temperature, top_k, top_p, eos_id
    )
    packed = jnp.concatenate(
        [new_tokens, n_new[:, None], n_accept[:, None]], axis=1
    )
    return packed, pools


def _medusa_step(
    pools, params, heads, tables, fill, last_tok, proposals, rng,
    temperature, top_k, top_p, eos_id, adapters, *, model, k,
):
    """One whole Medusa round as a SINGLE model forward: the round's
    proposals were produced by the PREVIOUS round's forward (the heads
    read its final hidden state), so this step only verifies them and
    emits the next round's proposals on the way out — no draft model, no
    second pool, no second prefill, no dedicated propose pass anywhere.

    Verify: the spec-mode shape shrunk by one — ``[y_last, q_1..q_{k-1}]``
    written at ``fill..fill+k-1`` through the block tables, then
    :func:`models.speculative.verify_proposals` with each row's own
    params. Proposals are the heads' ARGMAX picks, so each draft
    distribution is exactly one-hot at the proposed token — rejection
    sampling against a one-hot ``q`` preserves every sampled row's
    truncated target distribution exactly (accept w.p. ``p_t(q)``, else
    sample the renormalised residual), and greedy rows stay
    token-identical to plain decode at ANY accept rate.

    Propose (for the NEXT round): ``hidden[:, n_accept]`` is the state
    that produced this round's correction token, so head ``h``
    (``models.speculative.medusa_head_logits`` — one fused matmul pair,
    not k-1 extra forwards) predicts the ``(h+1)``-th token after it.
    Unlike spec mode the proposer sees the tenant's LoRA delta for free —
    the heads read the ADAPTED hidden state out of the verify forward.
    ``k == 1`` has no heads and degenerates to plain one-token decode
    through the medusa signature.

    Returns ``(packed [B, 2k+1] (k>1) / [B, 3] (k=1), pools)`` — committed
    tokens, the ``n_new``/``n_accept`` counters AND the next proposals in
    ONE fetch (DML210). ``pools`` is donated."""
    from ..models.generate import decode_step, sample_logits_batched
    from ..models.speculative import medusa_head_logits, verify_proposals

    x = (
        jnp.concatenate([last_tok[:, None], proposals], axis=1)
        if k > 1 else last_tok[:, None]
    )  # [B, k]
    (tlogits, hidden), pools = decode_step(
        model, params, x, pools, pages=(tables, fill),
        adapters=adapters, return_hidden=True,
    )
    tlogits = tlogits.astype(jnp.float32)
    if k == 1:
        tok = sample_logits_batched(tlogits[:, 0], rng, temperature, top_k, top_p)
        packed = jnp.stack(
            [tok, jnp.ones_like(tok), jnp.zeros_like(tok)], axis=1
        )
        return packed, pools
    vocab = tlogits.shape[-1]
    dlogits = jnp.where(
        jax.nn.one_hot(proposals, vocab, dtype=bool), 0.0, -1e9
    )  # one-hot at the argmax pick the proposal actually was
    new_tokens, n_new, n_accept = verify_proposals(
        tlogits, dlogits, proposals, rng, temperature, top_k, top_p, eos_id
    )
    h_acc = jnp.take_along_axis(hidden, n_accept[:, None, None], axis=1)[:, 0]
    nxt = jnp.argmax(medusa_head_logits(heads, h_acc), axis=-1).astype(jnp.int32)
    packed = jnp.concatenate(
        [new_tokens, n_new[:, None], n_accept[:, None], nxt], axis=1
    )
    return packed, pools


def _pow2_buckets(limit: int) -> tuple[int, ...]:
    """1, 2, 4, ... capped at (and always including) ``limit``."""
    out, b = [], 1
    while b < limit:
        out.append(b)
        b *= 2
    out.append(int(limit))
    return resolve_buckets(out)


class ServeEngine:
    """Continuous-batching inference over a DecoderLM (module docstring).

    Construction knobs:

    - ``num_blocks`` / ``block_size``: the pool geometry. The default pool
      covers ``max_slots`` worst-case sequences — safe but dense-sized;
      real deployments size it for the EXPECTED live tokens (the whole
      point of paging) and let admission control do the rest.
    - ``max_slots``: concurrent decode streams; ``batch_buckets`` /
      ``table_buckets`` default to powers of two capped at the maxima.
    - ``prefill_chunk``: prompt tokens processed per engine step.
    - sampling (``temperature``/``top_k``/``top_p``/``eos_id``): the
      ENGINE DEFAULTS (greedy, ``generate()`` semantics); each request
      may override any of them (``submit``), and the per-row values ride
      the compiled step as traced arrays.
    - ``spec_k``: speculative proposals per verification round; 0 (the
      default) is the plain one-token-per-step engine. ``draft_model`` /
      ``draft_params`` name the proposer (both None = shared-model
      self-draft: the target drafts for itself — the correctness smoke,
      accept rate exactly 1.0 under greedy); ``draft_num_blocks`` sizes
      the draft page pool (default: the target pool's count).
    - ``medusa_k`` / ``medusa_heads``: Medusa decoding — up to ``medusa_k``
      tokens per round from ``medusa_k - 1`` extra decode heads on the
      frozen base model, one ``k``-position forward per round (mutually
      exclusive with ``spec_k``; no draft model, no draft pool).
      ``medusa_heads`` is the
      :func:`models.speculative.init_medusa_heads`-shaped stack (usually
      distilled offline); None warm-starts every head from the base
      ``lm_head`` — correct but with self-agreement accept rates only.
      ``medusa_k=1`` has no heads and degenerates to plain one-token
      decode through the medusa signature — the correctness smoke.
      Output is token-identical to plain decode at ANY accept rate.
    - ``adapters``: an :class:`AdapterSet` for multi-tenant LoRA serving;
      requests pick a tenant by name. Composes with ``spec_k``: the
      base-model draft proposes WITHOUT the tenant's delta (costing only
      accept rate on heavily-adapted tenants) while the verify pass
      scores with it, so output stays token-identical to the tenant's
      own model.
    - ``prefix_cache``: arm radix-tree prefix sharing (False by default —
      the exact PR-8/PR-10 engine). Blocks become content-addressed and
      refcounted; a request whose prompt shares full cached blocks maps
      them read-only, skips their prefill entirely (chunked prefill
      starts at the divergence point) and copy-on-write forks before any
      write into a shared page; the pool evicts leaf-first by LRU when
      the free list runs dry. See serve/prefix_cache.py + doc/serving.md.
    - ``guard``: ``TraceGuard`` action on a signature leak ("raise"/"warn").
    - ``metrics``: arm the typed metrics registry (True for a fresh
      :class:`~dmlcloud_tpu.telemetry.metrics_registry.MetricsRegistry`,
      or pass one to share). Series handles resolve at construction
      (DML215); :meth:`metrics_text` exposes Prometheus text. Off (None)
      by default — the uninstrumented hot loop is untouched.
    - ``slos``: declarative objectives
      (:class:`~dmlcloud_tpu.serve.slo.SLO` list) evaluated every step
      over the injectable clock; burn-rate alerts journal as
      ``slo_alert`` spans and surface in the ledger summary + drain
      verdict (doc/observability.md).
    """

    def __init__(
        self,
        model,
        params: Any,
        *,
        num_blocks: int | None = None,
        block_size: int = 16,
        max_slots: int = 8,
        prefill_chunk: int = 32,
        batch_buckets=None,
        table_buckets=None,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        eos_id: int = -1,
        spec_k: int = 0,
        draft_model=None,
        draft_params: Any = None,
        draft_num_blocks: int | None = None,
        medusa_k: int = 0,
        medusa_heads: Any = None,
        adapters: AdapterSet | None = None,
        prefix_cache: bool = False,
        rng: jax.Array | None = None,
        guard: str = "raise",
        cache_dtype: Any = None,
        max_waiting: int | None = None,
        shed_policy: str = "reject",
        fairness: str = "fifo",
        drr_quantum: int | None = None,
        clock: Callable[[], float] = time.perf_counter,
        run_dir: Any = None,
        drain_budget_s: float = 5.0,
        preemption=None,
        watchdog=None,
        max_done: int | None = None,
        ledger_max_records: int | None = None,
        metrics: Any = None,
        slos: Any = None,
        verify: str | None = None,
        hbm_budget: int | None = None,
    ):
        from ..models.quant import prepare_decode_params

        if spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {spec_k}")
        if medusa_k < 0:
            raise ValueError(f"medusa_k must be >= 0, got {medusa_k}")
        if spec_k and medusa_k:
            raise ValueError("spec_k and medusa_k are mutually exclusive decode modes")
        if (draft_model is None) != (draft_params is None):
            raise ValueError("draft_model and draft_params must be passed together")
        if draft_model is not None and spec_k < 1:
            raise ValueError("a draft model needs spec_k >= 1")
        if medusa_heads is not None and medusa_k < 1:
            raise ValueError("medusa_heads need medusa_k >= 1")
        self.model = model
        cfg = model.cfg
        # one-time host-side preparation: int8 kernels stay fused-quantized
        # and the off-TPU GEMM-operand widen is pre-paid (models/quant.py)
        self.params = prepare_decode_params(params, cfg.dtype)
        self.spec_k = int(spec_k)
        max_table = -(-cfg.max_seq_len // block_size)
        if num_blocks is None:
            num_blocks = max_slots * max_table
        self.pool = KVBlockPool.for_model(
            cfg, num_blocks=num_blocks, block_size=block_size, dtype=cache_dtype
        )
        self.draft_model = None
        self.draft_params = None
        self.draft_pool = None
        if self.spec_k:
            # shared-model self-draft unless a real draft is named; either
            # way the draft owns its OWN page pool — rollback is a fill
            # counter, never shared pages
            self.draft_model = draft_model if draft_model is not None else model
            dparams = draft_params if draft_params is not None else params
            self.draft_params = prepare_decode_params(dparams, self.draft_model.cfg.dtype)
            self.draft_pool = KVBlockPool.for_model(
                self.draft_model.cfg,
                num_blocks=int(draft_num_blocks or num_blocks),
                block_size=block_size,
                dtype=cache_dtype,
            )
        self.medusa_k = int(medusa_k)
        self.medusa_heads = None
        if self.medusa_k:
            # Medusa mode: NO draft model, NO draft pool, NO draft prefill
            # mirror — k-1 extra decode heads ride the target's own forward.
            # Default heads (none passed) are fresh zero-residual blocks
            # warm-started from the base lm_head: correct but untrained
            # (accept rate ~= self-agreement); callers distil real ones.
            from ..models.speculative import init_medusa_heads

            if medusa_heads is not None:
                self.medusa_heads = jax.tree.map(jnp.asarray, medusa_heads)
            else:
                kernel = None
                raw = params.get("lm_head") if hasattr(params, "get") else None
                if raw is not None and not cfg.tie_embeddings:
                    kernel = raw.get("kernel")
                self.medusa_heads = init_medusa_heads(
                    cfg, self.medusa_k, jax.random.PRNGKey(0), lm_head_kernel=kernel
                )
        # prefix sharing: the radix tree lives over the TARGET pool only —
        # the draft pool has no tree (draft prefill skips via the target's
        # match length; the verifier guarantees token identity regardless)
        self.prefix = PrefixCache(self.pool) if prefix_cache else None
        self.scheduler = Scheduler(
            self.pool, max_slots, prefill_chunk,
            draft_pool=self.draft_pool, lookahead=self.spec_k or self.medusa_k,
            prefix_cache=self.prefix,
            max_waiting=max_waiting, shed_policy=shed_policy,
            fairness=fairness, drr_quantum=drr_quantum,
        )
        self.ledger = ServeLedger(max_records=ledger_max_records)
        self.adapters = adapters
        self.eos_id = int(eos_id)
        self._temperature = float(temperature)
        self._top_k = int(top_k)
        self._top_p = float(top_p)
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        self._calls = 0
        self._next_id = 0
        self._done: dict[int, _Sequence] = {}
        # idempotency: accepted caller tokens -> rid (dedup for router
        # retries after ambiguous failures); evicts with retention
        self._tokens: dict[str, int] = {}
        # lifecycle state: every known sequence by id (live + retained
        # terminal), terminal ids in finish order (the retention bound),
        # the injectable clock the whole loop reads, and drain/fault knobs
        self._all: dict[int, _Sequence] = {}
        self._terminal: collections.deque[int] = collections.deque()
        self._max_done = None if max_done is None else int(max_done)
        self.clock = clock
        self.run_dir = run_dir
        self.drain_budget_s = float(drain_budget_s)
        self.preemption = preemption
        self.watchdog = watchdog
        #: chaos hook: ``fn(point, seqs)`` called at "step" (must not
        #: raise) and before each device phase ("prefill"/"decode"/
        #: "draft"/"verify" — the fused Medusa round fires "verify" —
        #: where raising injects a fault) — serve/chaos.py
        self.fault_injector: Callable[[str, Any], None] | None = None
        self._drain_reason: str | None = None
        self._drain_kind = "completed"
        self._drain_requeue = False
        self._drain_started: float | None = None

        # -- observability plane (doc/observability.md) -------------------
        # metrics: every series handle is resolved ONCE here — the hot
        # loop only ever touches pre-bound children (one float add per
        # event; a per-request labels() call is lint rule DML215)
        self.metrics = None
        if metrics:
            from ..telemetry.metrics_registry import (
                ITL_BUCKETS, QUEUE_DEPTH_BUCKETS, TTFT_BUCKETS, MetricsRegistry,
            )
            from .scheduler import TERMINAL_STATUSES

            reg = metrics if isinstance(metrics, MetricsRegistry) else MetricsRegistry()
            self.metrics = reg
            self._m_requests = reg.counter(
                "dml_serve_requests_total", "requests submitted")
            self._m_tokens = reg.counter(
                "dml_serve_tokens_total", "tokens emitted (all requests)")
            terminal = reg.counter(
                "dml_serve_terminal_total", "terminal statuses",
                labels=("status",), max_series=len(TERMINAL_STATUSES) + 1)
            self._m_terminal = {s: terminal.labels(status=s) for s in TERMINAL_STATUSES}
            self._m_drafted = reg.counter(
                "dml_serve_drafted_tokens_total", "speculative tokens proposed")
            self._m_accepted = reg.counter(
                "dml_serve_accepted_tokens_total", "speculative tokens accepted")
            self._m_ttft = reg.histogram(
                "dml_serve_ttft_seconds", "time to first token",
                buckets=TTFT_BUCKETS)
            self._m_itl = reg.histogram(
                "dml_serve_itl_seconds", "inter-token latency",
                buckets=ITL_BUCKETS)
            self._m_depth = reg.histogram(
                "dml_serve_queue_depth", "admission queue depth per step",
                buckets=QUEUE_DEPTH_BUCKETS)
            self._m_batch = reg.gauge(
                "dml_serve_decode_batch_size", "rows in the last decode batch")
            self._m_active = reg.gauge(
                "dml_serve_active_requests", "admitted, unfinished requests")
            self._m_free = reg.gauge(
                "dml_serve_kv_blocks_free", "free blocks in the target pool")
            self._m_live = reg.gauge(
                "dml_serve_kv_blocks_live", "live blocks in the target pool")
            self._m_shared = reg.gauge(
                "dml_serve_kv_blocks_shared", "refcount>1 blocks (prefix sharing)")
            self._m_pref_lookups = reg.counter(
                "dml_serve_prefix_lookups_total", "prefix-cache lookups at admission")
            self._m_pref_hits = reg.counter(
                "dml_serve_prefix_hits_total", "admissions with a cached prefix")
            self._m_pref_saved = reg.counter(
                "dml_serve_prefill_tokens_saved_total",
                "prefill tokens skipped via the prefix cache")
        # SLOs: declarative objectives over the SAME injectable clock
        self.slo = None
        if slos:
            from .slo import SLOMonitor

            self.slo = slos if isinstance(slos, SLOMonitor) else SLOMonitor(
                slos, clock=clock
            )
            # the summary's "slo" section reads the live monitor
            self.ledger.slo_monitor = self.slo

        self.batch_buckets = (
            resolve_buckets(batch_buckets) if batch_buckets else _pow2_buckets(max_slots)
        )
        table_cap = min(max_table, self.pool.num_blocks)
        self.table_buckets = (
            resolve_buckets(table_buckets) if table_buckets else _pow2_buckets(table_cap)
        )
        n_bb, n_tb = len(self.batch_buckets), len(self.table_buckets)
        # per-engine jit: jax keys its trace cache on the function OBJECT,
        # so a fresh partial per engine gives each engine its own cache —
        # the TraceGuard budget is then this engine's alone, not the
        # process-wide total across every engine ever built
        def _guarded(fn, budget, name, donate=(0,), statics=None):
            if statics is None:
                statics = ("model",) + (("k",) if fn is not _paged_step else ())
            return TraceGuard(
                jax.jit(
                    functools.partial(fn),
                    static_argnames=statics,
                    donate_argnums=donate,
                ),
                max_traces=budget, action=guard, name=name,
            )

        # the ONE signature-budget formula (signature_budget below) — the
        # TraceGuard arms here and the DML605 verify check both consume it
        budgets = self.signature_budget(
            n_bb, n_tb,
            spec=bool(self.spec_k), medusa=bool(self.medusa_k),
            prefix_cache=self.prefix is not None,
        )
        self._step_budget = budgets["step"]
        self.max_signatures = budgets["total"]
        if self.spec_k:
            self._spec_budget = budgets["spec"]
            self._draft_fn = _guarded(_spec_draft_step, self._spec_budget, "serve_spec_draft")
            self._verify_fn = _guarded(_spec_verify_step, self._spec_budget, "serve_spec_verify")
        elif self.medusa_k:
            self._medusa_budget = budgets["medusa"]
            self._draft_fn = self._verify_fn = None
            self._medusa_fn = _guarded(_medusa_step, self._medusa_budget, "serve_medusa_step")
        else:
            self._draft_fn = self._verify_fn = None
        if not self.medusa_k:
            self._medusa_fn = None
        self._step_fn = _guarded(_paged_step, self._step_budget, "serve_paged_step")
        self._copy_fn = None
        if self.prefix is not None:
            # COW fork: traced src/dst -> ONE signature for every fork the
            # engine ever performs (counted in the budget)
            self._copy_fn = _guarded(_copy_block, 1, "serve_cow_copy", statics=())

        if verify not in (None, "warn", "error"):
            raise ValueError(f'verify must be None, "warn" or "error", got {verify!r}')
        self._verify_mode = verify
        self.hbm_budget = None if hbm_budget is None else int(hbm_budget)
        #: findings of the construction-time verify preflight (if armed)
        self.verify_findings: list = []
        if verify:
            self._run_verify_preflight(verify)

    @staticmethod
    def signature_budget(
        n_batch_buckets: int,
        n_table_buckets: int,
        *,
        spec: bool = False,
        medusa: bool = False,
        prefix_cache: bool = False,
    ) -> dict:
        """THE signature-budget formula — every compiled signature a healthy
        engine can legitimately own, by decode mode. The constructor's
        TraceGuard arms and the DML605 verify check both read this one
        function, asserted equal to the historical per-mode math by
        ``tests/test_verify.py`` — so the budget can never again drift
        between the runtime guard and the static check.

        Returns ``{"step", "spec", "medusa", "copy", "total"}``:

        - plain decode: ``step`` is (batch bucket x table bucket) decode
          plus (1, chunk) x table-bucket prefill — ``n_bb*n_tb + n_tb``.
        - spec mode: prefill doubles (target + draft mirror through
          ``_paged_step``: ``2*n_tb``) and plain decode stays as the
          degraded-round fallback (``n_bb*n_tb``); each healthy round adds
          one draft + one verify signature per (batch x table) bucket —
          ``spec = n_bb*n_tb``, counted twice in ``total``.
        - Medusa mode: target-only prefill (no draft mirror), the plain
          decode fallback, and ONE fused propose+verify signature per
          (batch x table) bucket — ``medusa = n_bb*n_tb``.
        - ``prefix_cache`` adds the single traced COW-copy signature.
        """
        n_bb, n_tb = int(n_batch_buckets), int(n_table_buckets)
        if spec and medusa:
            raise ValueError("spec and medusa are mutually exclusive decode modes")
        if spec:
            step, spec_b, medusa_b = 2 * n_tb + n_bb * n_tb, n_bb * n_tb, 0
            total = step + 2 * spec_b
        elif medusa:
            step, spec_b, medusa_b = n_bb * n_tb + n_tb, 0, n_bb * n_tb
            total = step + medusa_b
        else:
            step, spec_b, medusa_b = n_bb * n_tb + n_tb, 0, 0
            total = step
        copy = 1 if prefix_cache else 0
        return {"step": step, "spec": spec_b, "medusa": medusa_b, "copy": copy,
                "total": total + copy}

    def _enumerate_signature_surface(self) -> int:
        """Count every signature this engine can legitimately compile by
        EXPLICIT per-bucket enumeration — deliberately NOT a call into
        :meth:`signature_budget`, so the DML605 preflight compares two
        independent derivations and catches either one drifting."""
        surface = 0
        for _tb in self.table_buckets:
            surface += 1  # target prefill: (1, chunk) x this table bucket
            if self.spec_k:
                surface += 1  # draft prefill mirror through _paged_step
        for _bb in self.batch_buckets:
            for _tb in self.table_buckets:
                surface += 1  # plain decode (spec/medusa degraded fallback)
                if self.spec_k:
                    surface += 2  # one draft + one verify per healthy round
                if self.medusa_k:
                    surface += 1  # the fused propose+verify round
        if self.prefix is not None:
            surface += 1  # the traced COW copy
        return surface

    def _run_verify_preflight(self, mode: str) -> None:
        """Construction-time IR verify (doc/lint.md DML6xx): stage the
        worst-case (max batch bucket x max table bucket) decode step on
        CPU and audit its donation contract, baked-in host callbacks and
        memory estimate against ``hbm_budget``, plus the DML605 check
        that the enumerated signature surface fits ``max_signatures``.
        AOT lower/compile never touches the jit dispatch cache, so the
        TraceGuard budgets are unaffected. ``"warn"`` emits a warning
        with the findings; ``"error"`` raises :class:`LintError`."""
        import warnings

        from ..compile import aot
        from ..lint import LintError
        from ..lint import ir as ir_mod

        bb = max(self.batch_buckets)
        tb = max(self.table_buckets)
        sds = jax.ShapeDtypeStruct
        f32, i32 = jnp.float32, jnp.int32
        specs = [
            ir_mod.ProgramSpec(
                name="serve.signature_surface",
                fn=None,
                signature_surface=self._enumerate_signature_surface(),
                signature_budget=self.max_signatures,
                kind="serve",
            ),
            ir_mod.ProgramSpec(
                name=f"serve.paged_step[b{bb}xt{tb}]",
                fn=self._step_fn._fn,
                args=(
                    aot.abstract_spec(self.pool.pools),
                    aot.abstract_spec(self.params),
                    sds((bb, tb), i32),   # block tables
                    sds((bb,), i32),      # fill
                    sds((bb, 1), i32),    # tokens
                    sds((bb,), i32),      # last_idx
                    aot.abstract_spec(self._rng),
                    None,                 # adapters
                    sds((bb,), f32),      # temperature
                    sds((bb,), i32),      # top_k
                    sds((bb,), f32),      # top_p
                ),
                static_kwargs={"model": self.model},
                donate_argnums=(0,),
                hbm_budget_bytes=self.hbm_budget,
                kind="serve",
            ),
        ]
        stats: dict = {}
        findings = ir_mod.verify_programs(specs, stats=stats)
        self.verify_findings = list(findings)
        if not findings:
            return
        report = "\n".join(f.format() for f in findings)
        msg = (
            f"IR verifier found {len(findings)} problem(s) in the serve step "
            f"programs (doc/lint.md DML6xx; suppress with "
            f"'# dmllint: disable=ID'):\n{report}"
        )
        if mode == "error":
            raise LintError(msg, findings=findings)
        warnings.warn(msg, stacklevel=3)

    # -- request lifecycle ---------------------------------------------------
    def submit(
        self,
        prompt,
        max_new_tokens: int = 32,
        adapter: str | None = None,
        *,
        temperature: float | None = None,
        top_k: int | None = None,
        top_p: float | None = None,
        eos_id: int | None = None,
        deadline_s: float | None = None,
        priority: int = 0,
        tenant: str | None = None,
        token: str | None = None,
        trace: str | None = None,
    ) -> int:
        """Queue one request; returns its id. ``prompt`` is a 1-D int32
        token sequence (no padding — paged rows sit at their own absolute
        positions, ragged prompts are the natural case). The sampling
        knobs override the engine defaults FOR THIS REQUEST ONLY — they
        are data to the compiled step, so a batch may mix greedy and
        sampled tenants freely.

        ``deadline_s`` is a budget relative to NOW; a request that has
        not finished when it elapses terminates ``deadline_exceeded`` at
        whatever phase it is in. ``priority`` matters only to shed-victim
        selection under overload (lower sheds first). ``tenant`` keys the
        fairness scheduler (default: the adapter name, else one shared
        tenant). Submission can itself shed — the returned id's status
        may already be ``shed`` when the bounded queue chose the arrival
        as the victim.

        ``token`` is an optional caller-supplied idempotency token: a
        token the engine has already accepted raises
        :class:`DuplicateRequest` (carrying the original rid) instead of
        admitting a second copy — the at-most-once guard a router retry
        leans on after an AMBIGUOUS failure (did the dead replica's
        submit land before it died?). Tokens age out with the terminal-
        record retention (``max_done``).

        ``trace`` is the request-scoped trace id every span this request
        produces links under (doc/observability.md). A router mints one
        at ``Router.submit`` and threads it through failover, so the
        whole cross-replica history is ONE causal trace; a standalone
        engine mints ``tr-<rid>`` when none is given."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must contain at least one token")
        # spec/medusa rounds may write up to k proposals past the final
        # committed slot (plus the bonus slot) — the same slack
        # speculative_generate reserves; plain decode keeps the PR-8 bound
        lookahead = self.spec_k or self.medusa_k
        slack = lookahead + 1 if lookahead else 0
        if prompt.size + int(max_new_tokens) + slack > self.model.cfg.max_seq_len:
            knob = "spec_k" if self.spec_k else "medusa_k"
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new_tokens})"
                + (f" + {knob}+1 ({slack})" if slack else "")
                + f" exceeds max_seq_len ({self.model.cfg.max_seq_len})"
            )
        aid = 0
        if adapter is not None:
            if self.adapters is None:
                raise ValueError("request names an adapter but the engine has no AdapterSet")
            aid = self.adapters.id_of(adapter)
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        if token is not None and token in self._tokens:
            raise DuplicateRequest(token, self._tokens[token])
        now = self.clock()
        rid = self._next_id
        self._next_id += 1
        if rid in self._all:  # a reused rid would silently clobber bookkeeping
            raise RuntimeError(f"request id {rid} already exists (corrupt id counter)")
        if trace is None:
            trace = f"tr-{rid}"
        if self.metrics is not None:
            self._m_requests.inc()
        req = Request(
            prompt=prompt, max_new_tokens=int(max_new_tokens), adapter=adapter,
            temperature=temperature, top_k=top_k, top_p=top_p, eos_id=eos_id,
            deadline_s=deadline_s, priority=int(priority), tenant=tenant, id=rid,
        )
        resolved_tenant = tenant if tenant is not None else (adapter or "")
        seq = _Sequence(
            req=req, arrival=now, adapter_id=aid,
            deadline=None if deadline_s is None else now + float(deadline_s),
            tenant=resolved_tenant, priority=int(priority), token=token, trace=trace,
            temperature=self._temperature if temperature is None else float(temperature),
            top_k=self._top_k if top_k is None else int(top_k),
            top_p=self._top_p if top_p is None else float(top_p),
            eos_id=self.eos_id if eos_id is None else int(eos_id),
        )
        if self.draining:
            # drain contract: admission is closed — arrivals shed on sight
            self.ledger.arrived(rid, now, tenant=resolved_tenant)
            self._all[rid] = seq
            if token is not None:
                self._tokens[token] = rid
            self._finalize(seq, now, "shed")
            return rid
        shed = self.scheduler.submit(seq)  # validates; raising records nothing
        self.ledger.arrived(rid, now, tenant=resolved_tenant)
        self._all[rid] = seq
        if token is not None:
            self._tokens[token] = rid
        for victim in shed:
            # bounded-queue overflow: the scheduler picked the victim but
            # the engine owns its terminal bookkeeping (it may be ``seq``
            # itself, never enqueued, or a queued request holding nothing)
            self._finalize(victim, now, "shed")
        return rid

    def output(self, rid: int) -> np.ndarray:
        """The emitted tokens of a finished request."""
        return np.asarray(self._done[rid].out, np.int32)

    def results(self) -> dict[int, np.ndarray]:
        return {rid: self.output(rid) for rid in self._done}

    def cancel(self, rid: int) -> bool:
        """Cancel a live request at WHATEVER phase it is in — queued,
        mid-chunked-prefill, mid-decode, mid-spec-round. Its blocks (both
        pools), COW spare and prefix locks release immediately; status
        becomes ``cancelled``. Returns False when the request is unknown
        or already terminal (cancellation lost the race — idempotent, no
        double-free)."""
        seq = self._all.get(rid)
        if seq is None or seq.status is not None:
            return False
        return self._finalize(seq, self.clock(), "cancelled")

    def status(self, rid: int) -> str:
        """The request's phase: ``queued`` / ``running`` while live, else
        its terminal status (``ok | cancelled | deadline_exceeded | shed
        | error``)."""
        seq = self._all.get(rid)
        if seq is None:
            raise KeyError(f"unknown (or retention-evicted) request id {rid}")
        if seq.status is not None:
            return seq.status
        return "queued" if seq.admitted is None else "running"

    def statuses(self) -> dict[int, str]:
        """Every retained request's :meth:`status`, by id."""
        return {rid: self.status(rid) for rid in self._all}

    # -- terminal bookkeeping ------------------------------------------------
    def _finalize(self, seq, now: float, status: str, error: str | None = None) -> bool:
        """The engine half of the ONE exit path: scheduler terminate
        (queue removal + every block released), then ledger/journal/
        retention. False when already terminal (idempotent)."""
        if not self.scheduler.terminate(seq, now, status):
            return False
        self._record_terminal(seq, now, error)
        return True

    def _record_terminal(self, seq, now: float, error: str | None = None) -> None:
        rid = seq.req.id
        self.ledger.finished(rid, now, status=seq.status)
        if self.metrics is not None:
            child = self._m_terminal.get(seq.status)
            if child is not None:
                child.inc()
        if self.slo is not None:
            self.slo.record_terminal(seq.tenant, seq.status, now)
        if seq.status == "error":
            # the per-request fault span stamps the trace with its
            # terminal status — a chaos-injected failure is readable
            # straight off the request track
            journal.emit("fault", now, label=f"req{rid}", request=rid,
                         trace=seq.trace, status=seq.status, error=error or "")
        if seq.status == "ok":
            self._done[rid] = seq
        self._terminal.append(rid)
        if self._max_done is not None:
            while len(self._terminal) > self._max_done:
                old = self._terminal.popleft()
                self._done.pop(old, None)
                dropped = self._all.pop(old, None)
                if dropped is not None and dropped.token is not None:
                    self._tokens.pop(dropped.token, None)

    def _fail(self, seqs, exc: BaseException) -> None:
        """Isolate a step failure to the request(s) it was advancing:
        status ``error``, every resource released, everyone else keeps
        serving."""
        now = self.clock()
        msg = f"{type(exc).__name__}: {exc}"
        for s in seqs:
            self._finalize(s, now, "error", error=msg)

    @property
    def idle(self) -> bool:
        return self.scheduler.idle

    def compiled_signatures(self) -> int | None:
        """Distinct compiled signatures so far, summed over the engine's
        jitted steps (the TraceGuard probes)."""
        total = 0
        for fn in (self._step_fn, self._draft_fn, self._verify_fn,
                   self._medusa_fn, self._copy_fn):
            if fn is None:
                continue
            n = fn.cache_size()
            if n is None:
                return None
            total += n
        return total

    # -- the serving loop ----------------------------------------------------
    def step(self) -> bool:
        """One engine iteration: expire deadlines, admit (or drain), one
        prefill chunk, one decode batch (a speculative round when
        ``spec_k``, a Medusa round when ``medusa_k``). Returns whether
        any device work ran. A failure in
        either device phase is isolated to the request(s) it was
        advancing — the step itself never raises for a per-request
        fault."""
        now = self.clock()
        if self.watchdog is not None:
            self.watchdog.notify()
        self._chaos("step", None)
        for seq in self.scheduler.expire(now):
            # the scheduler already terminated them (blocks released);
            # the engine owns the ledger/journal tail
            self._record_terminal(seq, now)
        if (
            self.preemption is not None
            and self.preemption.triggered
            and not self.draining
        ):
            self.request_drain(
                f"preemption:{self.preemption.signal_name}",
                kind="preemption", requeue=True,
            )
        if self.draining:
            self._drain_step(now)
        else:
            for seq in self.scheduler.admit(now):
                rid = seq.req.id
                self.ledger.admitted(rid, now)
                if self.prefix is not None:
                    # prefill-skip accounting: saved = the divergence point the
                    # scheduler rolled prefill forward to (cached tokens, minus
                    # the one re-fed token of an exact full-block match)
                    self.ledger.prefix_match(
                        rid, cached=seq.cached_tokens, saved=seq.fill,
                        prompt=seq.prompt_len,
                    )
                    journal.emit("prefix_lookup", now, now, label=f"req{rid}",
                                 request=rid, trace=seq.trace,
                                 cached=seq.cached_tokens, saved=seq.fill,
                                 shared=seq.shared)
                    if self.metrics is not None:
                        self._m_pref_lookups.inc()
                        if seq.cached_tokens > 0:
                            self._m_pref_hits.inc()
                        self._m_pref_saved.inc(seq.fill)
                journal.emit("queue_wait", seq.arrival, now, label=f"req{rid}",
                             request=rid, trace=seq.trace,
                             depth=self.scheduler.depth())
                journal.emit("admission", now, now, label=f"req{rid}",
                             request=rid, trace=seq.trace, tenant=seq.tenant,
                             blocks=len(seq.blocks), cached=seq.cached_tokens)
        if self.metrics is not None:
            self._m_depth.observe(self.scheduler.depth())
            self._m_active.set(self.scheduler.active)
            self._m_free.set(self.pool.num_free)
            self._m_live.set(self.pool.num_live)
        if self.slo is not None:
            self.slo.evaluate(now)
        did = False
        seq = self.scheduler.next_prefill()
        if seq is not None:
            try:
                self._prefill_chunk(seq)
            except Exception as exc:  # noqa: BLE001 — isolate to this request
                self._fail([seq], exc)
            did = True
        batch = self.scheduler.decode_batch()
        if batch:
            try:
                if self.spec_k:
                    self._decode_spec(batch)
                elif self.medusa_k:
                    self._decode_medusa(batch)
                else:
                    self._decode(batch)
            except Exception as exc:  # noqa: BLE001 — isolate to these rows
                self._fail(batch, exc)
            did = True
        return did

    def _chaos(self, point: str, seqs) -> None:
        if self.fault_injector is not None:
            self.fault_injector(point, seqs)

    def run(self, max_steps: int | None = None) -> dict[int, np.ndarray]:
        """Drive :meth:`step` until every submitted request finished (or
        ``max_steps`` elapsed); returns the finished outputs."""
        steps = 0
        while not self.idle:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return self.results()

    # -- graceful drain ------------------------------------------------------
    @property
    def draining(self) -> bool:
        return self._drain_reason is not None

    def request_drain(
        self, reason: str = "drain requested", *,
        kind: str = "completed", requeue: bool = False,
    ) -> None:
        """Begin graceful drain: admission closes (arrivals shed on
        sight, the waiting queue sheds next step), in-flight requests get
        ``drain_budget_s`` from now to finish, then shed too. First call
        wins; later calls are no-ops."""
        if self._drain_reason is None:
            self._drain_reason = str(reason)
            self._drain_kind = kind
            self._drain_requeue = bool(requeue)
            self._drain_started = self.clock()

    def _drain_step(self, now: float) -> None:
        for seq in list(self.scheduler.iter_waiting()):
            self._finalize(seq, now, "shed")
        if now - self._drain_started >= self.drain_budget_s:
            # budget spent: in-flight work sheds, blocks release, the
            # verdict reports what was cut short
            for seq in [*self.scheduler.prefilling, *self.scheduler.running]:
                self._finalize(seq, now, "shed")

    def drain(self, reason: str | None = None, *, kind: str | None = None,
              requeue: bool | None = None, max_steps: int | None = None) -> dict:
        """Drain to completion and write the ``requeue.json`` verdict:
        stop admission, shed the queue, step until in-flight work
        finishes (or the drain budget sheds it), then record the verdict
        under ``run_dir`` (skipped when the engine has none) — the same
        schema every elasticity wrapper reads (doc/elasticity.md).
        Defaults: a tripped ``PreemptionGuard`` makes this a
        ``kind="preemption"``, ``requeue=True`` verdict; a manual drain
        is ``kind="completed"``, no requeue. Returns the verdict dict."""
        if not self.draining:
            preempted = self.preemption is not None and self.preemption.triggered
            if reason is None:
                reason = (
                    f"preemption:{self.preemption.signal_name}" if preempted
                    else "drain requested"
                )
            self.request_drain(
                reason,
                kind=kind or ("preemption" if preempted else "completed"),
                requeue=(preempted if requeue is None else requeue),
            )
        steps = 0
        while not self.idle:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        now = self.clock()
        counts = self.ledger.status_counts()
        verdict = {
            "requeue": self._drain_requeue,
            "kind": self._drain_kind,
            "reason": self._drain_reason,
            "serve": {
                "drain_s": round(now - self._drain_started, 6),
                "statuses": counts,
                "drained_clean": self.idle,
                "slo_alerts": len(self.slo.alerts) if self.slo is not None else 0,
            },
        }
        journal.emit("drain", self._drain_started, now, label=self._drain_kind,
                     **counts)
        if self.run_dir is not None:
            from ..checkpoint import write_requeue_verdict

            write_requeue_verdict(
                self.run_dir, verdict["requeue"], verdict["reason"],
                verdict["kind"], serve=verdict["serve"],
            )
        return verdict

    def leaked_blocks(self) -> int:
        """Blocks still live once the engine is idle beyond what the
        prefix tree legitimately holds (one reference per cached node),
        plus any excess lock references on tree blocks — the chaos
        drill's zero-leak observable. Only meaningful when :attr:`idle`."""
        held = self.prefix.stats()["nodes"] if self.prefix is not None else 0
        leaked = self.pool.num_live - held
        if self.draft_pool is not None:
            leaked += self.draft_pool.num_live
        if self.prefix is not None:
            leaked += len(self.prefix.leaked_locks())
        return leaked

    def metrics_text(self) -> str:
        """The engine's metrics registry rendered as Prometheus text
        (empty string when constructed without ``metrics=``). Pool
        occupancy gauges are refreshed at scrape time so an idle engine
        still reports truthful numbers; wire this to
        :class:`~dmlcloud_tpu.serve.metrics_http.MetricsServer` (or any
        scraper) — a scrape never touches device state."""
        snap = self.metrics_snapshot()
        if snap is None:
            return ""
        from ..telemetry.metrics_registry import to_prometheus_text

        return to_prometheus_text(snap)

    def metrics_snapshot(self) -> dict | None:
        """Gauge-refreshed registry snapshot (plain dicts; None when
        metrics are off) — what :meth:`metrics_text` renders and what the
        router merges across replicas under a ``replica`` label."""
        if self.metrics is None:
            return None
        self._m_free.set(self.pool.num_free)
        self._m_live.set(self.pool.num_live)
        self._m_shared.set(self.pool.stats()["shared"])
        self._m_active.set(self.scheduler.active)
        return self.metrics.snapshot()

    def serve_trace(self, trace, clock=None, sleep=time.sleep) -> dict:
        """Replay a timed request trace in real time: ``trace`` is a list
        of ``(offset_s, prompt, max_new_tokens[, adapter_or_kwargs])``
        tuples (offsets relative to the replay start; the optional last
        element is an adapter name, or a dict of extra :meth:`submit`
        keywords — ``tenant``/``deadline_s``/``priority``/sampling).
        Requests are submitted when the wall reaches their offset; the
        engine steps continuously in between. ``clock`` defaults to the
        engine's own (injectable) clock. Returns the ledger summary — the
        bench receipt's engine side."""
        if clock is None:
            clock = self.clock
        pending = sorted(trace, key=lambda e: e[0])
        t0 = clock()
        i = 0
        while i < len(pending) or not self.idle:
            now = clock() - t0
            while i < len(pending) and pending[i][0] <= now:
                off, prompt, max_new, *rest = pending[i]
                kw = {}
                if rest:
                    kw = dict(rest[0]) if isinstance(rest[0], dict) else {"adapter": rest[0]}
                self.submit(prompt, max_new, **kw)
                i += 1
            if self.draining:
                # drain: admission is closed — drop the unsubmitted tail
                i = len(pending)
            if not self.step() and i < len(pending):
                # idle but the trace has future arrivals: nap until the next
                sleep(min(max(pending[i][0] - (clock() - t0), 0.0), 0.001))
        return self.ledger.summary()

    # -- device calls --------------------------------------------------------
    def _next_rng(self):
        self._calls += 1
        return jax.random.fold_in(self._rng, self._calls)

    def _row_params(self, seqs, bb: int):
        """The per-row sampling-param arrays of a (padded) batch. Pad rows
        get the greedy defaults — their samples are discarded, the values
        only need to keep the traced math finite."""
        temps = np.zeros(bb, np.float32)
        topks = np.zeros(bb, np.int32)
        topps = np.ones(bb, np.float32)
        eos = np.full(bb, -1, np.int32)
        for i, s in enumerate(seqs):
            temps[i] = s.temperature
            topks[i] = s.top_k
            topps[i] = s.top_p
            eos[i] = s.eos_id
        return (
            jnp.asarray(temps), jnp.asarray(topks), jnp.asarray(topps), jnp.asarray(eos)
        )

    def _call(self, pool, model, params, tables, fill, tokens, last_idx, ids, row_params,
              use_adapters=True):
        temps, topks, topps, _ = row_params
        adapters = None
        if self.adapters is not None and use_adapters:
            adapters = (self.adapters.stacked, jnp.asarray(ids, jnp.int32))
        tok, new_pools = self._step_fn(
            pool.pools, params,
            jnp.asarray(tables, jnp.int32), jnp.asarray(fill, jnp.int32),
            jnp.asarray(tokens, jnp.int32), jnp.asarray(last_idx, jnp.int32),
            self._next_rng(), adapters, temps, topks, topps,
            model=model,
        )
        pool.swap(new_pools)
        return np.asarray(tok)  # the per-step host sync: tokens ARE the output

    def _cow_guard(self, seq, lo: int, hi: int) -> None:
        """The copy-on-write fork rule: before ANY paged scatter that will
        write positions ``[lo, hi)`` of ``seq``, fork every covered block
        whose refcount > 1 — a shared page is read-only (other tables map
        it; the radix tree pins it), so the write gets a private copy
        first. The fork consumes the COW spare the scheduler reserved at
        admission (an exact full-block match is the one flow that
        guarantees a fork; see scheduler.admit), falls back to a fresh
        alloc otherwise, device-copies the page through the ONE traced
        ``_copy_block`` signature, swaps the table entry and releases this
        sequence's reference to the shared original. No-op without a
        prefix cache (nothing is ever shared) and on the common decode
        path (writes land past the shared prefix by construction)."""
        if self.prefix is None:
            return
        bs = self.pool.block_size
        for bi in range(lo // bs, (max(hi, lo + 1) - 1) // bs + 1):
            if bi >= len(seq.blocks) or not self.pool.is_shared(seq.blocks[bi]):
                continue
            old = seq.blocks[bi]
            if seq.cow_spare > 0:
                new = seq.blocks.pop()  # the spare reserved at admission
                seq.cow_spare -= 1
            else:
                [new] = self.pool.alloc(1)
            self.pool.swap(
                self._copy_fn(self.pool.pools, jnp.int32(old), jnp.int32(new))
            )
            seq.blocks[bi] = new
            self.pool.release([old])
            seq.shared = min(seq.shared, bi)
            journal.emit("cow_fork", journal.now(), label=f"req{seq.req.id}:cow",
                         request=seq.req.id, trace=seq.trace, cow_block=bi)

    def _table_rows(self, seqs, nb: int, draft: bool = False) -> np.ndarray:
        pool = self.draft_pool if draft else self.pool
        rows = np.full((len(seqs), nb), pool.sentinel, np.int32)
        for i, s in enumerate(seqs):
            owned = s.draft_blocks if draft else s.blocks
            blocks = owned[: min(len(owned), nb)]
            rows[i, : len(blocks)] = blocks
        return rows

    def _prefill_chunk(self, seq) -> None:
        self._chaos("prefill", [seq])
        c = self.scheduler.prefill_chunk
        n = min(c, seq.prompt_len - seq.fill)
        # COW-fork before the scatter: an exact full-block prefix match
        # re-feeds the final prompt token, whose write lands in the last
        # SHARED block (the one write the sharing design ever aims at a
        # refcount>1 page)
        self._cow_guard(seq, seq.fill, seq.fill + n)
        tokens = np.zeros((1, c), np.int32)
        tokens[0, :n] = seq.req.prompt[seq.fill : seq.fill + n]
        nb = bucket_for(self.pool.blocks_for(seq.fill + n), self.table_buckets)
        final = seq.fill + n >= seq.prompt_len
        row_params = self._row_params([seq], 1)
        fill = np.asarray([seq.fill], np.int32)
        last = np.asarray([n - 1], np.int32)
        t0 = journal.now()
        tok = self._call(
            self.pool, self.model, self.params,
            self._table_rows([seq], nb), fill, tokens, last,
            [seq.adapter_id], row_params,
        )
        journal.emit("prefill", t0, label=f"req{seq.req.id}", request=seq.req.id,
                     trace=seq.trace, chunk=n, fill=seq.fill + n, blocks=nb)
        if self.spec_k:
            # the draft pool needs the same prompt K/V: one mirrored chunk
            # through the draft model (its sampled token is discarded)
            t1 = journal.now()
            self._call(
                self.draft_pool, self.draft_model, self.draft_params,
                self._table_rows([seq], nb, draft=True), fill, tokens, last,
                [seq.adapter_id], row_params,
                use_adapters=False,  # the draft proposes base-model (spec x LoRA)
            )
            journal.emit("draft", t1, label=f"req{seq.req.id}:prefill",
                         request=seq.req.id, traces=[seq.trace], chunk=n, blocks=nb)
        seq.fill += n
        if final:
            # the last real prompt position's logits ARE the first token —
            # time-to-first-token ends here, before any decode step
            now = self.clock()
            self.ledger.first_token(seq.req.id, now)
            if self.metrics is not None:
                self._m_ttft.observe(now - seq.arrival)
            if self.slo is not None:
                self.slo.record_ttft(seq.tenant, now - seq.arrival, now)
            self.scheduler.prefill_done(seq)
            seq.prev_token = int(seq.req.prompt[-1])
            if self.prefix is not None:
                # the prompt's full blocks now hold correct K/V: publish
                # them so the NEXT request with this prefix skips prefill
                self.prefix.insert(seq.req.prompt, seq.blocks, adapter=seq.adapter_id)
            self._emit(seq, int(tok[0]), now)

    def _decode(self, batch) -> None:
        self._chaos("decode", batch)
        for s in batch:
            # refcount check before the scatter (DML211): decode writes at
            # fill, past the shared prefix by construction — a fork here
            # means an invariant broke upstream, but the guard is cheap
            self._cow_guard(s, s.fill, s.fill + 1)
        bb = bucket_for(len(batch), self.batch_buckets)
        needed = max(s.needed_blocks(self.pool.block_size) for s in batch)
        nb = bucket_for(needed, self.table_buckets)
        tables = np.full((bb, nb), self.pool.sentinel, np.int32)
        tables[: len(batch)] = self._table_rows(batch, nb)
        fill = np.zeros(bb, np.int32)
        tokens = np.zeros((bb, 1), np.int32)
        ids = np.zeros(bb, np.int64)
        for i, s in enumerate(batch):
            fill[i] = s.fill
            tokens[i, 0] = s.last_token
            ids[i] = s.adapter_id
        row_params = self._row_params(batch, bb)
        t0 = journal.now()
        tok = self._call(
            self.pool, self.model, self.params, tables, fill, tokens,
            np.zeros(bb, np.int32), ids, row_params,
        )
        now = self.clock()
        journal.emit("decode_batch", t0, label=f"b{bb}", active=len(batch),
                     bucket=bb, blocks=nb, traces=[s.trace for s in batch])
        self.ledger.step_sample(self.scheduler.depth(), len(batch))
        if self.metrics is not None:
            self._m_batch.set(len(batch))
        for i, s in enumerate(batch):
            s.fill += 1  # the fed token's K/V landed at its position
            self._emit(s, int(tok[i]), now)

    def _decode_spec(self, batch) -> None:
        """One speculative round for the whole decode batch: k draft
        passes, one k+1-position verify, then the host commits each row's
        accepted prefix. The partial-accept rewind is exactly the
        ``fill += n_new`` below — fill counters roll forward only to the
        accepted position; the stale speculative K/V past it is
        overwritten by the next round's contiguous writes before the
        causal mask can expose it, and block ownership never changes."""
        k = self.spec_k
        for s in batch:
            # a spec round writes fill..fill+k (verify) — COW/refcount
            # check before the multi-token scatter (DML211)
            self._cow_guard(s, s.fill, s.fill + k + 1)
        bb = bucket_for(len(batch), self.batch_buckets)
        needed = max(
            s.needed_blocks(self.pool.block_size, lookahead=k) for s in batch
        )
        nb = bucket_for(needed, self.table_buckets)
        tables = np.full((bb, nb), self.pool.sentinel, np.int32)
        tables[: len(batch)] = self._table_rows(batch, nb)
        dtables = np.full((bb, nb), self.draft_pool.sentinel, np.int32)
        dtables[: len(batch)] = self._table_rows(batch, nb, draft=True)
        # pad rows: fill=1 keeps every traced position >= 0 and the
        # attention mask non-empty; their sentinel tables drop all writes
        fill = np.ones(bb, np.int32)
        prev = np.zeros(bb, np.int32)
        last = np.zeros(bb, np.int32)
        for i, s in enumerate(batch):
            fill[i] = s.fill
            prev[i] = s.prev_token
            last[i] = s.last_token
        temps, topks, topps, eos = self._row_params(batch, bb)
        adapters = None
        if self.adapters is not None:
            # spec x LoRA: the VERIFY pass scores with each row's adapter
            # (the draft proposed base-model — only accept rate pays)
            ids = np.zeros(bb, np.int32)
            for i, s in enumerate(batch):
                ids[i] = s.adapter_id
            adapters = (self.adapters.stacked, jnp.asarray(ids, jnp.int32))
        tables = jnp.asarray(tables, jnp.int32)
        dtables = jnp.asarray(dtables, jnp.int32)
        fill = jnp.asarray(fill, jnp.int32)
        prev = jnp.asarray(prev, jnp.int32)
        last = jnp.asarray(last, jnp.int32)

        t0 = journal.now()
        try:
            self._chaos("draft", batch)
            proposals, dlogits, dpools = self._draft_fn(
                self.draft_pool.pools, self.draft_params, dtables, fill, prev, last,
                self._next_rng(), temps, topks, topps,
                model=self.draft_model, k=k,
            )
        except Exception as exc:  # noqa: BLE001 — the draft is an optimization
            self._degrade_round(batch, t0, bb, exc)
            return
        self.draft_pool.swap(dpools)
        journal.emit("draft", t0, label=f"b{bb}", active=len(batch),
                     bucket=bb, blocks=nb, k=k, traces=[s.trace for s in batch])
        t1 = journal.now()
        self._chaos("verify", batch)
        packed, tpools = self._verify_fn(
            self.pool.pools, self.params, tables, fill, last, proposals, dlogits,
            self._next_rng(), temps, topks, topps, eos, adapters,
            model=self.model, k=k,
        )
        self.pool.swap(tpools)
        # ONE fetch: tokens and the n_new/n_accept counters ride together
        out = np.asarray(packed)
        now = time.perf_counter()
        journal.emit("verify", t1, label=f"b{bb}", active=len(batch),
                     bucket=bb, blocks=nb, k=k, traces=[s.trace for s in batch])
        self.ledger.step_sample(self.scheduler.depth(), len(batch))
        if self.metrics is not None:
            self._m_batch.set(len(batch))
        for i, s in enumerate(batch):
            n_new = int(out[i, k + 1])
            self.ledger.spec_round(s.req.id, drafted=k, accepted=int(out[i, k + 2]))
            if self.metrics is not None:
                self._m_drafted.inc(k)
                self._m_accepted.inc(int(out[i, k + 2]))
            for tok in out[i, :n_new]:
                prev_last = s.last_token
                s.fill += 1  # this token's K/V was written by the round
                self._emit(s, int(tok), now)
                if s.finished is not None:
                    break
                s.prev_token = prev_last

    def _decode_medusa(self, batch) -> None:
        """One Medusa round for the whole decode batch: ONE model forward
        (``_medusa_step``) verifies the proposals the PREVIOUS round's
        forward emitted — no draft model, no draft pool, no draft tables,
        no propose pass. Each sequence carries its pending proposals as
        ``k-1`` host ints (``seq.medusa_pending``, part of the round's
        single packed fetch); a row's FIRST round after prefill has none
        yet and runs on sentinel proposals (one near-plain round, never a
        correctness cost — the verify rule rejects them). The commit loop
        and partial-accept rewind are exactly the spec-mode ones: fill
        counters roll forward only to the accepted position; stale
        speculative K/V past fill is overwritten by the next round's
        contiguous writes before the causal mask can expose it."""
        k = self.medusa_k
        for s in batch:
            # a round writes fill..fill+k-1 (verify) — COW/refcount check
            # before the multi-token scatter (DML211)
            self._cow_guard(s, s.fill, s.fill + k)
        bb = bucket_for(len(batch), self.batch_buckets)
        needed = max(
            s.needed_blocks(self.pool.block_size, lookahead=k) for s in batch
        )
        nb = bucket_for(needed, self.table_buckets)
        tables = np.full((bb, nb), self.pool.sentinel, np.int32)
        tables[: len(batch)] = self._table_rows(batch, nb)
        # pad rows: fill=1 keeps every traced position >= 0 and the
        # attention mask non-empty; their sentinel tables drop all writes
        fill = np.ones(bb, np.int32)
        last = np.zeros(bb, np.int32)
        prop = np.zeros((bb, max(k - 1, 0)), np.int32)
        for i, s in enumerate(batch):
            fill[i] = s.fill
            last[i] = s.last_token
            pending = getattr(s, "medusa_pending", None)
            if pending is not None and k > 1:
                prop[i] = pending
        temps, topks, topps, eos = self._row_params(batch, bb)
        adapters = None
        if self.adapters is not None:
            # medusa x LoRA: ONE model means the heads propose from the
            # ADAPTED hidden state — unlike spec mode, the proposer sees
            # the tenant's delta for free
            ids = np.zeros(bb, np.int32)
            for i, s in enumerate(batch):
                ids[i] = s.adapter_id
            adapters = (self.adapters.stacked, jnp.asarray(ids, jnp.int32))
        tables = jnp.asarray(tables, jnp.int32)
        fill = jnp.asarray(fill, jnp.int32)
        last = jnp.asarray(last, jnp.int32)
        prop = jnp.asarray(prop, jnp.int32)

        t0 = journal.now()
        try:
            self._chaos("verify", batch)
            packed, tpools = self._medusa_fn(
                self.pool.pools, self.params, self.medusa_heads, tables, fill,
                last, prop, self._next_rng(), temps, topks, topps, eos, adapters,
                model=self.model, k=k,
            )
        except Exception as exc:  # noqa: BLE001 — the heads are an optimization
            for s in batch:
                # the degraded plain step shifts every row one position, so
                # carried proposals would be stale by one — drop them
                s.medusa_pending = None
            self._degrade_round(batch, t0, bb, exc, label="medusa_degrade")
            return
        self.pool.swap(tpools)
        # ONE fetch: tokens and the n_new/n_accept counters ride together
        out = np.asarray(packed)
        now = time.perf_counter()
        journal.emit("medusa", t0, label=f"b{bb}", active=len(batch),
                     bucket=bb, blocks=nb, k=k, traces=[s.trace for s in batch])
        self.ledger.step_sample(self.scheduler.depth(), len(batch))
        if self.metrics is not None:
            self._m_batch.set(len(batch))
        for i, s in enumerate(batch):
            n_new = int(out[i, k])
            if k > 1:
                self.ledger.spec_round(
                    s.req.id, drafted=k - 1, accepted=int(out[i, k + 1])
                )
                if self.metrics is not None:
                    self._m_drafted.inc(k - 1)
                    self._m_accepted.inc(int(out[i, k + 1]))
                s.medusa_pending = out[i, k + 2 : 2 * k + 1].copy()
            for tok in out[i, :n_new]:
                prev_last = s.last_token
                s.fill += 1  # this token's K/V was written by the round
                self._emit(s, int(tok), now)
                if s.finished is not None:
                    break
                s.prev_token = prev_last

    def _degrade_round(self, batch, t0: float, bb: int, exc: BaseException,
                       label: str = "draft_degrade") -> None:
        """A failed PROPOSE step (the spec draft or the fused Medusa
        round) degrades the round to plain decode: proposals are an
        optimization, so losing them costs throughput (no ``spec_round``
        events this round — accept counters stay exact), never
        correctness or identity. The draft cache misses the degraded
        token's slot; the next healthy round's 2-token leading rewrite
        closes one slot and any unwritten remainder only costs accept
        rate (the same posture as prefix-skipped draft prefill). Medusa
        has no second cache, so its degraded round loses nothing at all.
        A failure inside the fallback decode propagates to ``step``'s
        handler, which fails the batch."""
        journal.emit("fault", t0, label=f"b{bb}:{label}", active=bb,
                     error=f"{type(exc).__name__}: {exc}",
                     traces=[s.trace for s in batch])
        self._decode(batch)

    def _emit(self, seq, tok: int, now: float) -> None:
        seq.out.append(tok)
        self.ledger.token(seq.req.id)
        if self.metrics is not None:
            self._m_tokens.inc()
            t_prev = getattr(seq, "_last_tok_t", None)
            if t_prev is not None:
                self._m_itl.observe(now - t_prev)
            seq._last_tok_t = now
        if tok == seq.eos_id or len(seq.out) >= seq.req.max_new_tokens:
            if self.prefix is not None and seq.fill > seq.prompt_len:
                # multi-turn sharing: publish the full blocks the decode
                # extended (K/V written through position fill-1; a spec
                # round's stale tail lives past fill, in blocks this
                # slice never reaches). finish() then drops only this
                # request's references — adopted pages stay cached.
                written = np.concatenate(
                    [np.asarray(seq.req.prompt, np.int32),
                     np.asarray(seq.out, np.int32)]
                )[: seq.fill]
                self.prefix.insert(written, seq.blocks, adapter=seq.adapter_id)
            self.scheduler.finish(seq, now)
            self._record_terminal(seq, now)
        else:
            seq.last_token = tok
