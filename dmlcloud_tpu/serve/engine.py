"""The continuous-batching serving engine over the paged KV pool.

One :class:`ServeEngine` owns the four pieces the module docstrings around
it describe — the device page pool (``kv_pool``), the FIFO scheduler
(``scheduler``), the per-request latency ledger (``ledger``) and the
jitted paged steps — and runs the serving loop:

    admit waiting requests -> one prefill chunk -> one decode batch

per :meth:`step`. The decode batch advances EVERY running stream
regardless of how much prefill is pending, so a long prompt never
stalls running generations; a stream that emits EOS frees its slot and
blocks before the next step, and the next waiting request takes them —
continuous batching, no drain barrier.

**Two decode modes share that loop:**

- *Plain* (``spec_k == 0``): one jitted ``_paged_step`` advances every
  row one token per step — the PR-8 engine, unchanged semantics.
- *Speculative* (``spec_k >= 1``): a draft model (or the target itself —
  shared-model self-draft, ``models/speculative.py``'s smoke config)
  proposes ``k`` tokens per round against its OWN page pool, and one
  verifier pass scores all ``k+1`` positions per row through the same
  ``ops/paged_attention.py`` scatter/gather (multi-token writes through
  the block tables; sentinel rows still drop). The accept rule is
  :func:`models.speculative.verify_proposals` with each row's own
  sampling params; a partial accept "rewinds" by advancing the host-side
  fill counters only to the accepted position — block ownership never
  moves, and the next round's contiguous writes overwrite the stale
  speculative tail before the causal mask can expose it (the same
  overwrite invariant ``speculative_generate`` proves). Per accepted
  token the target pays ``~1/(accepted+1)`` of a weight-streaming pass —
  the per-token cost of the weight-bandwidth-bound decode loop becomes a
  per-round cost.

**Prefix sharing** (``prefix_cache=True``, serve/prefix_cache.py): pool
blocks become content-addressed and refcounted, indexed by a radix tree
over token prefixes. Admission maps a prompt's longest cached full-block
prefix READ-ONLY into the new request's table and starts chunked prefill
at the divergence point — a warm template's prefill shrinks to its unique
suffix (near-zero TTFT). Every write path runs a copy-on-write guard
first (``_cow_guard``: fork any refcount>1 block the scatter would touch
— one traced ``_copy_block`` signature for every fork ever; lint DML211
enforces the ordering), and the pool evicts leaf-first by LRU over
refcount when the free list runs dry. Greedy output stays token-identical
to the uncached engine — the committed ``BENCH_serve_prefix_*.json``
receipt re-asserts it on an 80%-shared-template trace.

**Per-request sampling.** ``temperature``/``top_k``/``top_p``/``eos_id``
ride each :class:`Request` and enter the compiled steps as per-row traced
arrays (``models.generate.sample_logits_batched``), so one engine serves
mixed greedy/sampled tenants in a single batch; greedy rows stay
bit-identical to serial ``generate()``.

**Zero mid-run recompiles, by construction.** Every device call's shape
signature is ``(batch_bucket, table_bucket)`` for decode (each of the
draft and verify steps in spec mode) and ``(1, prefill_chunk,
table_bucket)`` for prefill — times two prefill models in spec mode —
with both bucket sets fixed at engine construction (``compile/buckets.py``
machinery). Each jitted step is wrapped in a ``TraceGuard`` armed at
exactly its bucket product, so a signature leak is a raised
``RetraceError`` in tests rather than a silent compile stall under
production traffic.

**One host sync per device round.** The fetched array IS the output
(tokens), and in spec mode the per-row ``n_new``/``n_accept`` counters
ride THAT SAME fetch as two extra packed columns — no separate
``.item()``/``int()`` readback of accept counters anywhere in the loop
(lint rule DML210 exists because a per-round counter readback is exactly
the host sync that made the r05 speculative path 0.19×).

The decode math itself is :func:`models.generate.decode_step` — the same
primitive ``generate``/``beam_search``/``speculative_generate`` run — with
``pages=(block_tables, fill)`` steering it through the pool
(``ops/paged_attention.py``). ``prepare_decode_params`` is applied once at
construction for both models: int8 weight-only trees serve with the
fused-dequant kernels and the off-TPU operand widen pre-paid (the PR-6
decode win), with no per-call preparation left in the loop.
"""

from __future__ import annotations

import functools
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..compile.buckets import bucket_for, resolve_buckets
from ..lint.traceguard import TraceGuard
from ..telemetry import journal
from .adapters import AdapterSet
from .kv_pool import KVBlockPool
from .ledger import ServeLedger
from .prefix_cache import PrefixCache
from .scheduler import Request, Scheduler, _Sequence

__all__ = ["ServeEngine"]


def _copy_block(pools, src, dst):
    """The copy-on-write fork's device half: copy page ``src`` to page
    ``dst`` across every layer's K/V leaves. ``src``/``dst`` are TRACED
    scalars, so every fork in the engine's lifetime replays ONE compiled
    signature (a Python-int ``.at[i].set`` would bake the ids in and
    compile per (src, dst) pair — a mid-run recompile per fork).
    ``pools`` is donated: the fork is a swap, never two live pools."""
    return jax.tree_util.tree_map(lambda x: x.at[dst].set(x[src]), pools)


def _paged_step(
    pools, params, tables, fill, tokens, last_idx, rng, adapters,
    temperature, top_k, top_p, *, model,
):
    """One traced engine step (prefill chunk or plain decode batch): write
    ``tokens``' K/V through the block tables, read each row's logits at
    ``last_idx`` and sample the next token with each ROW's params (traced
    ``[B]`` arrays — mixed greedy/sampled tenants share the trace, and a
    new temperature never recompiles). ``pools`` is donated — the engine
    swaps in the returned pages (DML205: never two live copies of the
    cache)."""
    from ..models.generate import decode_step, sample_logits_batched

    logits, pools = decode_step(
        model, params, tokens, pools, pages=(tables, fill), adapters=adapters
    )
    last = jnp.take_along_axis(logits, last_idx[:, None, None], axis=1)[:, 0]  # [B, V]
    tok = sample_logits_batched(last, rng, temperature, top_k, top_p)
    return tok, pools


def _spec_draft_step(
    pools, params, tables, fill, prev_tok, last_tok, rng,
    temperature, top_k, top_p, *, model, k,
):
    """The draft half of one speculative round: ``k`` proposals per row
    against the draft page pool, all shapes static. Pass 0 feeds the last
    TWO committed tokens at positions ``fill-1``/``fill`` — the leading
    rewrite closes the draft pool's one-slot gap after a fully-accepted
    round (``models/speculative.py``'s 2-token trick) and is an identical
    rewrite otherwise; passes ``1..k-1`` feed each proposal at
    ``fill + i``. Returns ``(proposals [B, k], dlogits [B, k, V],
    pools)`` where ``dlogits`` row ``i`` is the truncated, scaled
    distribution proposal ``i+1`` was sampled from — exactly what the
    verifier's rejection rule needs as ``p_d``. ``pools`` is donated."""
    from ..models.generate import _truncate_scaled, decode_step, sample_logits_batched

    def pick(row_logits, i):
        return sample_logits_batched(
            row_logits, jax.random.fold_in(rng, i), temperature, top_k, top_p
        )

    toks2 = jnp.stack([prev_tok, last_tok], axis=1)  # [B, 2]
    logits, pools = decode_step(model, params, toks2, pools, pages=(tables, fill - 1))
    nxt = pick(logits[:, -1], 0)
    props, drows = [nxt], [logits[:, -1]]
    for i in range(1, k):  # k-1 single-token passes (unrolled: k is static)
        logits, pools = decode_step(
            model, params, nxt[:, None], pools, pages=(tables, fill + i)
        )
        nxt = pick(logits[:, 0], i)
        props.append(nxt)
        drows.append(logits[:, 0])
    proposals = jnp.stack(props, axis=1)  # [B, k]
    dlogits = _truncate_scaled(
        jnp.stack(drows, axis=1).astype(jnp.float32), temperature, top_k, top_p
    )
    return proposals, dlogits, pools


def _spec_verify_step(
    pools, params, tables, fill, last_tok, proposals, dlogits, rng,
    temperature, top_k, top_p, eos_id, adapters, *, model, k,
):
    """The verify half: ONE target pass scores all ``k+1`` positions per
    row (``[y_last, d_1..d_k]`` written at ``fill..fill+k`` through the
    block tables), then :func:`models.speculative.verify_proposals` runs
    each row's own accept rule. Returns ``(packed [B, k+3], pools)`` —
    the ``k+1`` tokens to commit plus the ``n_new``/``n_accept`` counters
    as two extra columns, so ONE host fetch carries tokens AND counters
    (no separate counter readback per round — DML210). ``adapters``
    threads per-row LoRA deltas into the TARGET pass only (spec × LoRA:
    the base-model draft proposes without the tenant's delta — it only
    costs accept rate; the verifier scores with the adapter, so output
    stays token-identical to the tenant's own model). ``pools`` is
    donated."""
    from ..models.generate import decode_step
    from ..models.speculative import verify_proposals

    x = jnp.concatenate([last_tok[:, None], proposals], axis=1)  # [B, k+1]
    tlogits, pools = decode_step(
        model, params, x, pools, pages=(tables, fill), adapters=adapters
    )
    new_tokens, n_new, n_accept = verify_proposals(
        tlogits, dlogits, proposals, rng, temperature, top_k, top_p, eos_id
    )
    packed = jnp.concatenate(
        [new_tokens, n_new[:, None], n_accept[:, None]], axis=1
    )
    return packed, pools


def _pow2_buckets(limit: int) -> tuple[int, ...]:
    """1, 2, 4, ... capped at (and always including) ``limit``."""
    out, b = [], 1
    while b < limit:
        out.append(b)
        b *= 2
    out.append(int(limit))
    return resolve_buckets(out)


class ServeEngine:
    """Continuous-batching inference over a DecoderLM (module docstring).

    Construction knobs:

    - ``num_blocks`` / ``block_size``: the pool geometry. The default pool
      covers ``max_slots`` worst-case sequences — safe but dense-sized;
      real deployments size it for the EXPECTED live tokens (the whole
      point of paging) and let admission control do the rest.
    - ``max_slots``: concurrent decode streams; ``batch_buckets`` /
      ``table_buckets`` default to powers of two capped at the maxima.
    - ``prefill_chunk``: prompt tokens processed per engine step.
    - sampling (``temperature``/``top_k``/``top_p``/``eos_id``): the
      ENGINE DEFAULTS (greedy, ``generate()`` semantics); each request
      may override any of them (``submit``), and the per-row values ride
      the compiled step as traced arrays.
    - ``spec_k``: speculative proposals per verification round; 0 (the
      default) is the plain one-token-per-step engine. ``draft_model`` /
      ``draft_params`` name the proposer (both None = shared-model
      self-draft: the target drafts for itself — the correctness smoke,
      accept rate exactly 1.0 under greedy); ``draft_num_blocks`` sizes
      the draft page pool (default: the target pool's count).
    - ``adapters``: an :class:`AdapterSet` for multi-tenant LoRA serving;
      requests pick a tenant by name. Composes with ``spec_k``: the
      base-model draft proposes WITHOUT the tenant's delta (costing only
      accept rate on heavily-adapted tenants) while the verify pass
      scores with it, so output stays token-identical to the tenant's
      own model.
    - ``prefix_cache``: arm radix-tree prefix sharing (False by default —
      the exact PR-8/PR-10 engine). Blocks become content-addressed and
      refcounted; a request whose prompt shares full cached blocks maps
      them read-only, skips their prefill entirely (chunked prefill
      starts at the divergence point) and copy-on-write forks before any
      write into a shared page; the pool evicts leaf-first by LRU when
      the free list runs dry. See serve/prefix_cache.py + doc/serving.md.
    - ``guard``: ``TraceGuard`` action on a signature leak ("raise"/"warn").
    """

    def __init__(
        self,
        model,
        params: Any,
        *,
        num_blocks: int | None = None,
        block_size: int = 16,
        max_slots: int = 8,
        prefill_chunk: int = 32,
        batch_buckets=None,
        table_buckets=None,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        eos_id: int = -1,
        spec_k: int = 0,
        draft_model=None,
        draft_params: Any = None,
        draft_num_blocks: int | None = None,
        adapters: AdapterSet | None = None,
        prefix_cache: bool = False,
        rng: jax.Array | None = None,
        guard: str = "raise",
        cache_dtype: Any = None,
    ):
        from ..models.quant import prepare_decode_params

        if spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {spec_k}")
        if (draft_model is None) != (draft_params is None):
            raise ValueError("draft_model and draft_params must be passed together")
        if draft_model is not None and spec_k < 1:
            raise ValueError("a draft model needs spec_k >= 1")
        self.model = model
        cfg = model.cfg
        # one-time host-side preparation: int8 kernels stay fused-quantized
        # and the off-TPU GEMM-operand widen is pre-paid (models/quant.py)
        self.params = prepare_decode_params(params, cfg.dtype)
        self.spec_k = int(spec_k)
        max_table = -(-cfg.max_seq_len // block_size)
        if num_blocks is None:
            num_blocks = max_slots * max_table
        self.pool = KVBlockPool.for_model(
            cfg, num_blocks=num_blocks, block_size=block_size, dtype=cache_dtype
        )
        self.draft_model = None
        self.draft_params = None
        self.draft_pool = None
        if self.spec_k:
            # shared-model self-draft unless a real draft is named; either
            # way the draft owns its OWN page pool — rollback is a fill
            # counter, never shared pages
            self.draft_model = draft_model if draft_model is not None else model
            dparams = draft_params if draft_params is not None else params
            self.draft_params = prepare_decode_params(dparams, self.draft_model.cfg.dtype)
            self.draft_pool = KVBlockPool.for_model(
                self.draft_model.cfg,
                num_blocks=int(draft_num_blocks or num_blocks),
                block_size=block_size,
                dtype=cache_dtype,
            )
        # prefix sharing: the radix tree lives over the TARGET pool only —
        # the draft pool has no tree (draft prefill skips via the target's
        # match length; the verifier guarantees token identity regardless)
        self.prefix = PrefixCache(self.pool) if prefix_cache else None
        self.scheduler = Scheduler(
            self.pool, max_slots, prefill_chunk,
            draft_pool=self.draft_pool, lookahead=self.spec_k,
            prefix_cache=self.prefix,
        )
        self.ledger = ServeLedger()
        self.adapters = adapters
        self.eos_id = int(eos_id)
        self._temperature = float(temperature)
        self._top_k = int(top_k)
        self._top_p = float(top_p)
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        self._calls = 0
        self._next_id = 0
        self._done: dict[int, _Sequence] = {}

        self.batch_buckets = (
            resolve_buckets(batch_buckets) if batch_buckets else _pow2_buckets(max_slots)
        )
        table_cap = min(max_table, self.pool.num_blocks)
        self.table_buckets = (
            resolve_buckets(table_buckets) if table_buckets else _pow2_buckets(table_cap)
        )
        n_bb, n_tb = len(self.batch_buckets), len(self.table_buckets)
        # per-engine jit: jax keys its trace cache on the function OBJECT,
        # so a fresh partial per engine gives each engine its own cache —
        # the TraceGuard budget is then this engine's alone, not the
        # process-wide total across every engine ever built
        def _guarded(fn, budget, name, donate=(0,), statics=None):
            if statics is None:
                statics = ("model",) + (("k",) if fn is not _paged_step else ())
            return TraceGuard(
                jax.jit(
                    functools.partial(fn),
                    static_argnames=statics,
                    donate_argnums=donate,
                ),
                max_traces=budget, action=guard, name=name,
            )

        if self.spec_k:
            #: spec-mode signature budget: prefill is (1, chunk) x table
            #: bucket x {target, draft} through _paged_step; each decode
            #: round is one draft + one verify signature per (batch bucket
            #: x table bucket). TraceGuard turns any growth into an error.
            self._step_budget = 2 * n_tb
            self._spec_budget = n_bb * n_tb
            self.max_signatures = self._step_budget + 2 * self._spec_budget
            self._draft_fn = _guarded(_spec_draft_step, self._spec_budget, "serve_spec_draft")
            self._verify_fn = _guarded(_spec_verify_step, self._spec_budget, "serve_spec_verify")
        else:
            #: the engine's whole compiled-signature budget: decode is
            #: (batch bucket x table bucket), prefill is (1, chunk) x table
            #: bucket.
            self._step_budget = n_bb * n_tb + n_tb
            self.max_signatures = self._step_budget
            self._draft_fn = self._verify_fn = None
        self._step_fn = _guarded(_paged_step, self._step_budget, "serve_paged_step")
        self._copy_fn = None
        if self.prefix is not None:
            # COW fork: traced src/dst -> ONE signature for every fork the
            # engine ever performs (counted in the budget)
            self._copy_fn = _guarded(_copy_block, 1, "serve_cow_copy", statics=())
            self.max_signatures += 1

    # -- request lifecycle ---------------------------------------------------
    def submit(
        self,
        prompt,
        max_new_tokens: int = 32,
        adapter: str | None = None,
        *,
        temperature: float | None = None,
        top_k: int | None = None,
        top_p: float | None = None,
        eos_id: int | None = None,
    ) -> int:
        """Queue one request; returns its id. ``prompt`` is a 1-D int32
        token sequence (no padding — paged rows sit at their own absolute
        positions, ragged prompts are the natural case). The sampling
        knobs override the engine defaults FOR THIS REQUEST ONLY — they
        are data to the compiled step, so a batch may mix greedy and
        sampled tenants freely."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must contain at least one token")
        # spec rounds may write up to k proposals past the final committed
        # slot (plus the bonus slot) — the same slack speculative_generate
        # reserves; plain decode keeps the exact PR-8 bound
        slack = self.spec_k + 1 if self.spec_k else 0
        if prompt.size + int(max_new_tokens) + slack > self.model.cfg.max_seq_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new_tokens})"
                + (f" + spec_k+1 ({slack})" if slack else "")
                + f" exceeds max_seq_len ({self.model.cfg.max_seq_len})"
            )
        aid = 0
        if adapter is not None:
            if self.adapters is None:
                raise ValueError("request names an adapter but the engine has no AdapterSet")
            aid = self.adapters.id_of(adapter)
        now = time.perf_counter()
        rid = self._next_id
        self._next_id += 1
        req = Request(
            prompt=prompt, max_new_tokens=int(max_new_tokens), adapter=adapter,
            temperature=temperature, top_k=top_k, top_p=top_p, eos_id=eos_id, id=rid,
        )
        seq = _Sequence(
            req=req, arrival=now, adapter_id=aid,
            temperature=self._temperature if temperature is None else float(temperature),
            top_k=self._top_k if top_k is None else int(top_k),
            top_p=self._top_p if top_p is None else float(top_p),
            eos_id=self.eos_id if eos_id is None else int(eos_id),
        )
        self.ledger.arrived(rid, now)
        self.scheduler.submit(seq)
        return rid

    def output(self, rid: int) -> np.ndarray:
        """The emitted tokens of a finished request."""
        return np.asarray(self._done[rid].out, np.int32)

    def results(self) -> dict[int, np.ndarray]:
        return {rid: self.output(rid) for rid in self._done}

    @property
    def idle(self) -> bool:
        return self.scheduler.idle

    def compiled_signatures(self) -> int | None:
        """Distinct compiled signatures so far, summed over the engine's
        jitted steps (the TraceGuard probes)."""
        total = 0
        for fn in (self._step_fn, self._draft_fn, self._verify_fn, self._copy_fn):
            if fn is None:
                continue
            n = fn.cache_size()
            if n is None:
                return None
            total += n
        return total

    # -- the serving loop ----------------------------------------------------
    def step(self) -> bool:
        """One engine iteration: admit, one prefill chunk, one decode
        batch (a speculative round when ``spec_k``). Returns whether any
        device work ran."""
        now = time.perf_counter()
        for seq in self.scheduler.admit(now):
            self.ledger.admitted(seq.req.id, now)
            if self.prefix is not None:
                # prefill-skip accounting: saved = the divergence point the
                # scheduler rolled prefill forward to (cached tokens, minus
                # the one re-fed token of an exact full-block match)
                self.ledger.prefix_match(
                    seq.req.id, cached=seq.cached_tokens, saved=seq.fill,
                    prompt=seq.prompt_len,
                )
            journal.emit("queue_wait", seq.arrival, now, label=f"req{seq.req.id}",
                         request=seq.req.id, depth=self.scheduler.depth())
        did = False
        seq = self.scheduler.next_prefill()
        if seq is not None:
            self._prefill_chunk(seq)
            did = True
        batch = self.scheduler.decode_batch()
        if batch:
            if self.spec_k:
                self._decode_spec(batch)
            else:
                self._decode(batch)
            did = True
        return did

    def run(self, max_steps: int | None = None) -> dict[int, np.ndarray]:
        """Drive :meth:`step` until every submitted request finished (or
        ``max_steps`` elapsed); returns the finished outputs."""
        steps = 0
        while not self.idle:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return self.results()

    def serve_trace(self, trace, clock=time.perf_counter, sleep=time.sleep) -> dict:
        """Replay a timed request trace in real time: ``trace`` is a list
        of ``(offset_s, prompt, max_new_tokens[, adapter])`` tuples
        (offsets relative to the replay start). Requests are submitted
        when the wall reaches their offset; the engine steps continuously
        in between. Returns the ledger summary — the bench receipt's
        engine side."""
        pending = sorted(trace, key=lambda e: e[0])
        t0 = clock()
        i = 0
        while i < len(pending) or not self.idle:
            now = clock() - t0
            while i < len(pending) and pending[i][0] <= now:
                off, prompt, max_new, *rest = pending[i]
                self.submit(prompt, max_new, adapter=rest[0] if rest else None)
                i += 1
            if not self.step() and i < len(pending):
                # idle but the trace has future arrivals: nap until the next
                sleep(min(max(pending[i][0] - (clock() - t0), 0.0), 0.001))
        return self.ledger.summary()

    # -- device calls --------------------------------------------------------
    def _next_rng(self):
        self._calls += 1
        return jax.random.fold_in(self._rng, self._calls)

    def _row_params(self, seqs, bb: int):
        """The per-row sampling-param arrays of a (padded) batch. Pad rows
        get the greedy defaults — their samples are discarded, the values
        only need to keep the traced math finite."""
        temps = np.zeros(bb, np.float32)
        topks = np.zeros(bb, np.int32)
        topps = np.ones(bb, np.float32)
        eos = np.full(bb, -1, np.int32)
        for i, s in enumerate(seqs):
            temps[i] = s.temperature
            topks[i] = s.top_k
            topps[i] = s.top_p
            eos[i] = s.eos_id
        return (
            jnp.asarray(temps), jnp.asarray(topks), jnp.asarray(topps), jnp.asarray(eos)
        )

    def _call(self, pool, model, params, tables, fill, tokens, last_idx, ids, row_params,
              use_adapters=True):
        temps, topks, topps, _ = row_params
        adapters = None
        if self.adapters is not None and use_adapters:
            adapters = (self.adapters.stacked, jnp.asarray(ids, jnp.int32))
        tok, new_pools = self._step_fn(
            pool.pools, params,
            jnp.asarray(tables, jnp.int32), jnp.asarray(fill, jnp.int32),
            jnp.asarray(tokens, jnp.int32), jnp.asarray(last_idx, jnp.int32),
            self._next_rng(), adapters, temps, topks, topps,
            model=model,
        )
        pool.swap(new_pools)
        return np.asarray(tok)  # the per-step host sync: tokens ARE the output

    def _cow_guard(self, seq, lo: int, hi: int) -> None:
        """The copy-on-write fork rule: before ANY paged scatter that will
        write positions ``[lo, hi)`` of ``seq``, fork every covered block
        whose refcount > 1 — a shared page is read-only (other tables map
        it; the radix tree pins it), so the write gets a private copy
        first. The fork consumes the COW spare the scheduler reserved at
        admission (an exact full-block match is the one flow that
        guarantees a fork; see scheduler.admit), falls back to a fresh
        alloc otherwise, device-copies the page through the ONE traced
        ``_copy_block`` signature, swaps the table entry and releases this
        sequence's reference to the shared original. No-op without a
        prefix cache (nothing is ever shared) and on the common decode
        path (writes land past the shared prefix by construction)."""
        if self.prefix is None:
            return
        bs = self.pool.block_size
        for bi in range(lo // bs, (max(hi, lo + 1) - 1) // bs + 1):
            if bi >= len(seq.blocks) or not self.pool.is_shared(seq.blocks[bi]):
                continue
            old = seq.blocks[bi]
            if seq.cow_spare > 0:
                new = seq.blocks.pop()  # the spare reserved at admission
                seq.cow_spare -= 1
            else:
                [new] = self.pool.alloc(1)
            self.pool.swap(
                self._copy_fn(self.pool.pools, jnp.int32(old), jnp.int32(new))
            )
            seq.blocks[bi] = new
            self.pool.release([old])
            seq.shared = min(seq.shared, bi)
            journal.emit("prefill", journal.now(), label=f"req{seq.req.id}:cow",
                         request=seq.req.id, cow_block=bi)

    def _table_rows(self, seqs, nb: int, draft: bool = False) -> np.ndarray:
        pool = self.draft_pool if draft else self.pool
        rows = np.full((len(seqs), nb), pool.sentinel, np.int32)
        for i, s in enumerate(seqs):
            owned = s.draft_blocks if draft else s.blocks
            blocks = owned[: min(len(owned), nb)]
            rows[i, : len(blocks)] = blocks
        return rows

    def _prefill_chunk(self, seq) -> None:
        c = self.scheduler.prefill_chunk
        n = min(c, seq.prompt_len - seq.fill)
        # COW-fork before the scatter: an exact full-block prefix match
        # re-feeds the final prompt token, whose write lands in the last
        # SHARED block (the one write the sharing design ever aims at a
        # refcount>1 page)
        self._cow_guard(seq, seq.fill, seq.fill + n)
        tokens = np.zeros((1, c), np.int32)
        tokens[0, :n] = seq.req.prompt[seq.fill : seq.fill + n]
        nb = bucket_for(self.pool.blocks_for(seq.fill + n), self.table_buckets)
        final = seq.fill + n >= seq.prompt_len
        row_params = self._row_params([seq], 1)
        fill = np.asarray([seq.fill], np.int32)
        last = np.asarray([n - 1], np.int32)
        t0 = journal.now()
        tok = self._call(
            self.pool, self.model, self.params,
            self._table_rows([seq], nb), fill, tokens, last,
            [seq.adapter_id], row_params,
        )
        journal.emit("prefill", t0, label=f"req{seq.req.id}", request=seq.req.id,
                     chunk=n, fill=seq.fill + n, blocks=nb)
        if self.spec_k:
            # the draft pool needs the same prompt K/V: one mirrored chunk
            # through the draft model (its sampled token is discarded)
            t1 = journal.now()
            self._call(
                self.draft_pool, self.draft_model, self.draft_params,
                self._table_rows([seq], nb, draft=True), fill, tokens, last,
                [seq.adapter_id], row_params,
                use_adapters=False,  # the draft proposes base-model (spec x LoRA)
            )
            journal.emit("draft", t1, label=f"req{seq.req.id}:prefill",
                         request=seq.req.id, chunk=n, blocks=nb)
        seq.fill += n
        if final:
            # the last real prompt position's logits ARE the first token —
            # time-to-first-token ends here, before any decode step
            now = time.perf_counter()
            self.ledger.first_token(seq.req.id, now)
            self.scheduler.prefill_done(seq)
            seq.prev_token = int(seq.req.prompt[-1])
            if self.prefix is not None:
                # the prompt's full blocks now hold correct K/V: publish
                # them so the NEXT request with this prefix skips prefill
                self.prefix.insert(seq.req.prompt, seq.blocks, adapter=seq.adapter_id)
            self._emit(seq, int(tok[0]), now)

    def _decode(self, batch) -> None:
        for s in batch:
            # refcount check before the scatter (DML211): decode writes at
            # fill, past the shared prefix by construction — a fork here
            # means an invariant broke upstream, but the guard is cheap
            self._cow_guard(s, s.fill, s.fill + 1)
        bb = bucket_for(len(batch), self.batch_buckets)
        needed = max(s.needed_blocks(self.pool.block_size) for s in batch)
        nb = bucket_for(needed, self.table_buckets)
        tables = np.full((bb, nb), self.pool.sentinel, np.int32)
        tables[: len(batch)] = self._table_rows(batch, nb)
        fill = np.zeros(bb, np.int32)
        tokens = np.zeros((bb, 1), np.int32)
        ids = np.zeros(bb, np.int64)
        for i, s in enumerate(batch):
            fill[i] = s.fill
            tokens[i, 0] = s.last_token
            ids[i] = s.adapter_id
        row_params = self._row_params(batch, bb)
        t0 = journal.now()
        tok = self._call(
            self.pool, self.model, self.params, tables, fill, tokens,
            np.zeros(bb, np.int32), ids, row_params,
        )
        now = time.perf_counter()
        journal.emit("decode_batch", t0, label=f"b{bb}", active=len(batch),
                     bucket=bb, blocks=nb)
        self.ledger.step_sample(self.scheduler.depth(), len(batch))
        for i, s in enumerate(batch):
            s.fill += 1  # the fed token's K/V landed at its position
            self._emit(s, int(tok[i]), now)

    def _decode_spec(self, batch) -> None:
        """One speculative round for the whole decode batch: k draft
        passes, one k+1-position verify, then the host commits each row's
        accepted prefix. The partial-accept rewind is exactly the
        ``fill += n_new`` below — fill counters roll forward only to the
        accepted position; the stale speculative K/V past it is
        overwritten by the next round's contiguous writes before the
        causal mask can expose it, and block ownership never changes."""
        k = self.spec_k
        for s in batch:
            # a spec round writes fill..fill+k (verify) — COW/refcount
            # check before the multi-token scatter (DML211)
            self._cow_guard(s, s.fill, s.fill + k + 1)
        bb = bucket_for(len(batch), self.batch_buckets)
        needed = max(
            s.needed_blocks(self.pool.block_size, lookahead=k) for s in batch
        )
        nb = bucket_for(needed, self.table_buckets)
        tables = np.full((bb, nb), self.pool.sentinel, np.int32)
        tables[: len(batch)] = self._table_rows(batch, nb)
        dtables = np.full((bb, nb), self.draft_pool.sentinel, np.int32)
        dtables[: len(batch)] = self._table_rows(batch, nb, draft=True)
        # pad rows: fill=1 keeps every traced position >= 0 and the
        # attention mask non-empty; their sentinel tables drop all writes
        fill = np.ones(bb, np.int32)
        prev = np.zeros(bb, np.int32)
        last = np.zeros(bb, np.int32)
        for i, s in enumerate(batch):
            fill[i] = s.fill
            prev[i] = s.prev_token
            last[i] = s.last_token
        temps, topks, topps, eos = self._row_params(batch, bb)
        adapters = None
        if self.adapters is not None:
            # spec x LoRA: the VERIFY pass scores with each row's adapter
            # (the draft proposed base-model — only accept rate pays)
            ids = np.zeros(bb, np.int32)
            for i, s in enumerate(batch):
                ids[i] = s.adapter_id
            adapters = (self.adapters.stacked, jnp.asarray(ids, jnp.int32))
        tables = jnp.asarray(tables, jnp.int32)
        dtables = jnp.asarray(dtables, jnp.int32)
        fill = jnp.asarray(fill, jnp.int32)
        prev = jnp.asarray(prev, jnp.int32)
        last = jnp.asarray(last, jnp.int32)

        t0 = journal.now()
        proposals, dlogits, dpools = self._draft_fn(
            self.draft_pool.pools, self.draft_params, dtables, fill, prev, last,
            self._next_rng(), temps, topks, topps,
            model=self.draft_model, k=k,
        )
        self.draft_pool.swap(dpools)
        journal.emit("draft", t0, label=f"b{bb}", active=len(batch),
                     bucket=bb, blocks=nb, k=k)
        t1 = journal.now()
        packed, tpools = self._verify_fn(
            self.pool.pools, self.params, tables, fill, last, proposals, dlogits,
            self._next_rng(), temps, topks, topps, eos, adapters,
            model=self.model, k=k,
        )
        self.pool.swap(tpools)
        # ONE fetch: tokens and the n_new/n_accept counters ride together
        out = np.asarray(packed)
        now = time.perf_counter()
        journal.emit("verify", t1, label=f"b{bb}", active=len(batch),
                     bucket=bb, blocks=nb, k=k)
        self.ledger.step_sample(self.scheduler.depth(), len(batch))
        for i, s in enumerate(batch):
            n_new = int(out[i, k + 1])
            self.ledger.spec_round(s.req.id, drafted=k, accepted=int(out[i, k + 2]))
            for tok in out[i, :n_new]:
                prev_last = s.last_token
                s.fill += 1  # this token's K/V was written by the round
                self._emit(s, int(tok), now)
                if s.finished is not None:
                    break
                s.prev_token = prev_last

    def _emit(self, seq, tok: int, now: float) -> None:
        seq.out.append(tok)
        self.ledger.token(seq.req.id)
        if tok == seq.eos_id or len(seq.out) >= seq.req.max_new_tokens:
            if self.prefix is not None and seq.fill > seq.prompt_len:
                # multi-turn sharing: publish the full blocks the decode
                # extended (K/V written through position fill-1; a spec
                # round's stale tail lives past fill, in blocks this
                # slice never reaches). finish() then drops only this
                # request's references — adopted pages stay cached.
                written = np.concatenate(
                    [np.asarray(seq.req.prompt, np.int32),
                     np.asarray(seq.out, np.int32)]
                )[: seq.fill]
                self.prefix.insert(written, seq.blocks, adapter=seq.adapter_id)
            self.scheduler.finish(seq, now)
            self.ledger.finished(seq.req.id, now)
            self._done[seq.req.id] = seq
        else:
            seq.last_token = tok
