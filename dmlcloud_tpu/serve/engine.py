"""The continuous-batching serving engine over the paged KV pool.

One :class:`ServeEngine` owns the four pieces the module docstrings around
it describe — the device page pool (``kv_pool``), the FIFO scheduler
(``scheduler``), the per-request latency ledger (``ledger``) and ONE
jitted paged decode step — and runs the serving loop:

    admit waiting requests -> one prefill chunk -> one decode batch

per :meth:`step`. The decode batch advances EVERY running stream by one
token regardless of how much prefill is pending, so a long prompt never
stalls running generations; a stream that emits EOS frees its slot and
blocks before the next step, and the next waiting request takes them —
continuous batching, no drain barrier.

**Zero mid-run recompiles, by construction.** Every device call's shape
signature is ``(batch_bucket, table_bucket)`` for decode and
``(1, prefill_chunk, table_bucket)`` for prefill, with both bucket sets
fixed at engine construction (``compile/buckets.py`` machinery — the same
bounded-signature contract the training loop's ragged batches use). The
jitted step is wrapped in a ``TraceGuard`` armed at exactly the bucket
product, so a signature leak is a raised ``RetraceError`` in tests rather
than a silent compile stall under production traffic.

The decode math itself is :func:`models.generate.decode_step` — the same
primitive ``generate``/``beam_search``/``speculative_generate`` run — with
``pages=(block_tables, fill)`` steering it through the pool
(``ops/paged_attention.py``), so greedy engine output is token-identical
to serial ``generate()`` of the same prompts. ``prepare_decode_params`` is
applied once at construction: int8 weight-only trees serve with the
fused-dequant kernels and the off-TPU operand widen pre-paid (the PR-6
decode win), with no per-call preparation left in the loop.
"""

from __future__ import annotations

import functools
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..compile.buckets import bucket_for, resolve_buckets
from ..lint.traceguard import TraceGuard
from ..telemetry import journal
from .adapters import AdapterSet
from .kv_pool import KVBlockPool
from .ledger import ServeLedger
from .scheduler import Request, Scheduler, _Sequence

__all__ = ["ServeEngine"]


def _paged_step(
    pools, params, tables, fill, tokens, last_idx, rng, adapters,
    *, model, temperature, top_k, top_p,
):
    """One traced engine step (prefill chunk or decode batch): write
    ``tokens``' K/V through the block tables, read each row's logits at
    ``last_idx`` and sample the next token. ``pools`` is donated — the
    engine swaps in the returned pages (DML205: never two live copies of
    the cache)."""
    from ..models.generate import decode_step, sample_logits

    logits, pools = decode_step(
        model, params, tokens, pools, pages=(tables, fill), adapters=adapters
    )
    last = jnp.take_along_axis(logits, last_idx[:, None, None], axis=1)[:, 0]  # [B, V]
    tok = sample_logits(last, rng, temperature, top_k, top_p)
    return tok, pools


def _pow2_buckets(limit: int) -> tuple[int, ...]:
    """1, 2, 4, ... capped at (and always including) ``limit``."""
    out, b = [], 1
    while b < limit:
        out.append(b)
        b *= 2
    out.append(int(limit))
    return resolve_buckets(out)


class ServeEngine:
    """Continuous-batching inference over a DecoderLM (module docstring).

    Construction knobs:

    - ``num_blocks`` / ``block_size``: the pool geometry. The default pool
      covers ``max_slots`` worst-case sequences — safe but dense-sized;
      real deployments size it for the EXPECTED live tokens (the whole
      point of paging) and let admission control do the rest.
    - ``max_slots``: concurrent decode streams; ``batch_buckets`` /
      ``table_buckets`` default to powers of two capped at the maxima.
    - ``prefill_chunk``: prompt tokens processed per engine step.
    - sampling (``temperature``/``top_k``/``top_p``/``eos_id``) is
      engine-level: one compiled sampler for every request (greedy
      default, ``generate()`` semantics).
    - ``adapters``: an :class:`AdapterSet` for multi-tenant LoRA serving;
      requests pick a tenant by name.
    - ``guard``: ``TraceGuard`` action on a signature leak ("raise"/"warn").
    """

    def __init__(
        self,
        model,
        params: Any,
        *,
        num_blocks: int | None = None,
        block_size: int = 16,
        max_slots: int = 8,
        prefill_chunk: int = 32,
        batch_buckets=None,
        table_buckets=None,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        eos_id: int = -1,
        adapters: AdapterSet | None = None,
        rng: jax.Array | None = None,
        guard: str = "raise",
        cache_dtype: Any = None,
    ):
        from ..models.quant import prepare_decode_params

        self.model = model
        cfg = model.cfg
        # one-time host-side preparation: int8 kernels stay fused-quantized
        # and the off-TPU GEMM-operand widen is pre-paid (models/quant.py)
        self.params = prepare_decode_params(params, cfg.dtype)
        max_table = -(-cfg.max_seq_len // block_size)
        if num_blocks is None:
            num_blocks = max_slots * max_table
        self.pool = KVBlockPool.for_model(
            cfg, num_blocks=num_blocks, block_size=block_size, dtype=cache_dtype
        )
        self.scheduler = Scheduler(self.pool, max_slots, prefill_chunk)
        self.ledger = ServeLedger()
        self.adapters = adapters
        self.eos_id = int(eos_id)
        self._temperature = float(temperature)
        self._top_k = int(top_k)
        self._top_p = float(top_p)
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        self._calls = 0
        self._next_id = 0
        self._done: dict[int, _Sequence] = {}

        self.batch_buckets = (
            resolve_buckets(batch_buckets) if batch_buckets else _pow2_buckets(max_slots)
        )
        table_cap = min(max_table, self.pool.num_blocks)
        self.table_buckets = (
            resolve_buckets(table_buckets) if table_buckets else _pow2_buckets(table_cap)
        )
        #: the engine's whole compiled-signature budget: decode is
        #: (batch bucket x table bucket), prefill is (1, chunk) x table
        #: bucket. TraceGuard turns any growth past this into an error.
        self.max_signatures = (
            len(self.batch_buckets) * len(self.table_buckets) + len(self.table_buckets)
        )
        # per-engine jit: jax keys its trace cache on the function OBJECT,
        # so a fresh partial per engine gives each engine its own cache —
        # the TraceGuard budget is then this engine's alone, not the
        # process-wide total across every engine ever built
        self._step_fn = TraceGuard(
            jax.jit(
                functools.partial(_paged_step),
                static_argnames=("model", "temperature", "top_k", "top_p"),
                donate_argnums=(0,),
            ),
            max_traces=self.max_signatures,
            action=guard,
            name="serve_paged_step",
        )

    # -- request lifecycle ---------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 32, adapter: str | None = None) -> int:
        """Queue one request; returns its id. ``prompt`` is a 1-D int32
        token sequence (no padding — paged rows sit at their own absolute
        positions, ragged prompts are the natural case)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must contain at least one token")
        if prompt.size + int(max_new_tokens) > self.model.cfg.max_seq_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new_tokens}) exceeds "
                f"max_seq_len ({self.model.cfg.max_seq_len})"
            )
        aid = 0
        if adapter is not None:
            if self.adapters is None:
                raise ValueError("request names an adapter but the engine has no AdapterSet")
            aid = self.adapters.id_of(adapter)
        now = time.perf_counter()
        rid = self._next_id
        self._next_id += 1
        req = Request(
            prompt=prompt, max_new_tokens=int(max_new_tokens), adapter=adapter, id=rid
        )
        seq = _Sequence(req=req, arrival=now, adapter_id=aid)
        self.ledger.arrived(rid, now)
        self.scheduler.submit(seq)
        return rid

    def output(self, rid: int) -> np.ndarray:
        """The emitted tokens of a finished request."""
        return np.asarray(self._done[rid].out, np.int32)

    def results(self) -> dict[int, np.ndarray]:
        return {rid: self.output(rid) for rid in self._done}

    @property
    def idle(self) -> bool:
        return self.scheduler.idle

    def compiled_signatures(self) -> int | None:
        """Distinct compiled signatures so far (the TraceGuard probe)."""
        return self._step_fn.cache_size()

    # -- the serving loop ----------------------------------------------------
    def step(self) -> bool:
        """One engine iteration: admit, one prefill chunk, one decode
        batch. Returns whether any device work ran."""
        now = time.perf_counter()
        for seq in self.scheduler.admit(now):
            self.ledger.admitted(seq.req.id, now)
            journal.emit("queue_wait", seq.arrival, now, label=f"req{seq.req.id}",
                         request=seq.req.id, depth=self.scheduler.depth())
        did = False
        seq = self.scheduler.next_prefill()
        if seq is not None:
            self._prefill_chunk(seq)
            did = True
        batch = self.scheduler.decode_batch()
        if batch:
            self._decode(batch)
            did = True
        return did

    def run(self, max_steps: int | None = None) -> dict[int, np.ndarray]:
        """Drive :meth:`step` until every submitted request finished (or
        ``max_steps`` elapsed); returns the finished outputs."""
        steps = 0
        while not self.idle:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return self.results()

    def serve_trace(self, trace, clock=time.perf_counter, sleep=time.sleep) -> dict:
        """Replay a timed request trace in real time: ``trace`` is a list
        of ``(offset_s, prompt, max_new_tokens[, adapter])`` tuples
        (offsets relative to the replay start). Requests are submitted
        when the wall reaches their offset; the engine steps continuously
        in between. Returns the ledger summary — the bench receipt's
        engine side."""
        pending = sorted(trace, key=lambda e: e[0])
        t0 = clock()
        i = 0
        while i < len(pending) or not self.idle:
            now = clock() - t0
            while i < len(pending) and pending[i][0] <= now:
                off, prompt, max_new, *rest = pending[i]
                self.submit(prompt, max_new, adapter=rest[0] if rest else None)
                i += 1
            if not self.step() and i < len(pending):
                # idle but the trace has future arrivals: nap until the next
                sleep(min(max(pending[i][0] - (clock() - t0), 0.0), 0.001))
        return self.ledger.summary()

    # -- device calls --------------------------------------------------------
    def _call(self, tables, fill, tokens, last_idx, ids):
        self._calls += 1
        rng = jax.random.fold_in(self._rng, self._calls)
        adapters = None
        if self.adapters is not None:
            adapters = (self.adapters.stacked, jnp.asarray(ids, jnp.int32))
        tok, new_pools = self._step_fn(
            self.pool.pools, self.params,
            jnp.asarray(tables, jnp.int32), jnp.asarray(fill, jnp.int32),
            jnp.asarray(tokens, jnp.int32), jnp.asarray(last_idx, jnp.int32),
            rng, adapters,
            model=self.model, temperature=self._temperature,
            top_k=self._top_k, top_p=self._top_p,
        )
        self.pool.swap(new_pools)
        return np.asarray(tok)  # the per-step host sync: tokens ARE the output

    def _table_rows(self, seqs, nb: int) -> np.ndarray:
        rows = np.full((len(seqs), nb), self.pool.sentinel, np.int32)
        for i, s in enumerate(seqs):
            blocks = s.blocks[: min(len(s.blocks), nb)]
            rows[i, : len(blocks)] = blocks
        return rows

    def _prefill_chunk(self, seq) -> None:
        c = self.scheduler.prefill_chunk
        n = min(c, seq.prompt_len - seq.fill)
        tokens = np.zeros((1, c), np.int32)
        tokens[0, :n] = seq.req.prompt[seq.fill : seq.fill + n]
        nb = bucket_for(self.pool.blocks_for(seq.fill + n), self.table_buckets)
        final = seq.fill + n >= seq.prompt_len
        t0 = journal.now()
        tok = self._call(
            self._table_rows([seq], nb), np.asarray([seq.fill], np.int32), tokens,
            np.asarray([n - 1], np.int32), [seq.adapter_id],
        )
        seq.fill += n
        journal.emit("prefill", t0, label=f"req{seq.req.id}", request=seq.req.id,
                     chunk=n, fill=seq.fill, blocks=nb)
        if final:
            # the last real prompt position's logits ARE the first token —
            # time-to-first-token ends here, before any decode step
            now = time.perf_counter()
            self.ledger.first_token(seq.req.id, now)
            self.scheduler.prefill_done(seq)
            self._emit(seq, int(tok[0]), now)

    def _decode(self, batch) -> None:
        bb = bucket_for(len(batch), self.batch_buckets)
        needed = max(s.needed_blocks(self.pool.block_size) for s in batch)
        nb = bucket_for(needed, self.table_buckets)
        tables = np.full((bb, nb), self.pool.sentinel, np.int32)
        tables[: len(batch)] = self._table_rows(batch, nb)
        fill = np.zeros(bb, np.int32)
        tokens = np.zeros((bb, 1), np.int32)
        ids = np.zeros(bb, np.int64)
        for i, s in enumerate(batch):
            fill[i] = s.fill
            tokens[i, 0] = s.last_token
            ids[i] = s.adapter_id
        t0 = journal.now()
        tok = self._call(tables, fill, tokens, np.zeros(bb, np.int32), ids)
        now = time.perf_counter()
        journal.emit("decode_batch", t0, label=f"b{bb}", active=len(batch),
                     bucket=bb, blocks=nb)
        self.ledger.step_sample(self.scheduler.depth(), len(batch))
        for i, s in enumerate(batch):
            s.fill += 1  # the fed token's K/V landed at its position
            self._emit(s, int(tok[i]), now)

    def _emit(self, seq, tok: int, now: float) -> None:
        seq.out.append(tok)
        self.ledger.token(seq.req.id)
        if tok == self.eos_id or len(seq.out) >= seq.req.max_new_tokens:
            self.scheduler.finish(seq, now)
            self.ledger.finished(seq.req.id, now)
            self._done[seq.req.id] = seq
        else:
            seq.last_token = tok
