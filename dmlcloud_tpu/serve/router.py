"""The multi-replica front door: health-checked routing, failover, drain.

One :class:`~dmlcloud_tpu.serve.engine.ServeEngine` saturates one
accelerator; production traffic needs N of them behind a single
submit/step surface that keeps the PR-13 promises — one terminal status
per request, zero leaked blocks, tenant fairness — when a whole REPLICA
dies, stalls, or drains mid-request. :class:`Router` is that surface, at
CPU-smoke scale: the replicas are in-process engine objects (the process
boundary is simulated) but every contract is the real one, which is why
each piece below is written against observable engine behavior rather
than shared Python state.

**Health.** Each replica carries a heartbeat: its ``last_beat`` advances
every time ``step()`` returns. A replica that raises out of ``step()``
or goes ``heartbeat_timeout_s`` without beating (a stalled process, a
GC pause, a dead host) is marked unhealthy and its live requests are
re-routed. Everything reads ONE injectable ``clock=`` (the PR-13
pattern), so the failure detector is unit-testable with a fake clock —
no sleeps, no flaky wall-time races.

**Failover, at-most-once.** The router owns the request of record: the
prompt and submit kwargs stay with the router record, so an incomplete
request on a dead replica is re-submitted to a healthy sibling FROM
SCRATCH — re-prefill, no cross-replica KV handoff (prefix affinity makes
the retry cheap when the template is warm on the new replica). Each
record carries a router-side idempotency token forwarded to
``ServeEngine.submit(token=)``; if a "dead" replica actually admitted
the original (the ambiguous-failure window), the retry raises
:class:`~dmlcloud_tpu.serve.engine.DuplicateRequest` and the router
re-attaches to the existing admission instead of double-admitting.
Retries are bounded (``max_retries``) with exponential backoff
(``backoff_base_s`` doubling per attempt); a request that exhausts them
ends terminal ``error``. Router-wide, every request still ends in
exactly one ``TERMINAL_STATUSES`` state.

**Placement.** Per-tenant deficit round-robin across replicas — PR 13's
DRR lifted from decode slots to replicas: tenants with pending work sit
on a ring, each visit grants a quantum of block-credits, and a tenant
places its FIFO head only when its deficit covers the request's full
block reservation. A hot tenant can burst all it likes; it cannot buy
more than its credit share of ANY replica, and per-tenant FIFO order is
preserved end to end. Within a placement, the target replica is chosen
by (1) prefix affinity — the deepest stable content address of the
prompt (:func:`~dmlcloud_tpu.serve.prefix_cache.prefix_keys`; stable
across processes, so real replicas could exchange these hints) names the
replica that served that template last — then (2) least outstanding
load, ties broken by replica order. A per-replica circuit breaker guards
both paths: ``breaker_threshold`` consecutive failures trip it open
(placements shed to siblings), after ``breaker_cooldown_s`` it goes
half-open and risks ONE probe request, and only a probe that terminates
``ok`` closes it again.

**Replica chaos + drain.** ``ChaosMonkey.attach_router`` injects
``replica_kill`` (permanent death — the router reaps the in-process
engine so its pool accounting stays auditable, the stand-in for the OS
reclaiming a dead process) and ``replica_stall`` (the replica misses
steps; the heartbeat detector decides whether it died) into the same
deterministic, replayable event log as the engine-level faults.
:meth:`Router.drain_replica` is the graceful exit: admission to that
replica closes, its QUEUED requests migrate to siblings (cancel +
resubmit — they hold nothing yet), its RUNNING requests finish in place,
and when it empties the replica is removed and a PR-7 ``requeue.json``
verdict records the drain. The receipt (``BENCH_serve_router_pr15``)
drills exactly this: a 3-replica Poisson multi-tenant trace, one replica
killed mid-trace and one drained, gated on every-request-terminal, zero
leaks, survivor token-identity and bounded cold-tenant TTFT.
"""

from __future__ import annotations

import collections
import time
from typing import Any, Callable, Iterable

import numpy as np

from ..telemetry import journal
from .engine import DuplicateRequest, ServeEngine
from .prefix_cache import prefix_keys
from .scheduler import TERMINAL_STATUSES

__all__ = ["Router"]


class _Replica:
    """Router-side state of one engine replica."""

    __slots__ = (
        "name", "engine", "alive", "removed", "draining", "last_beat",
        "stall_steps", "consec_failures", "breaker", "breaker_until",
        "cooldown", "probe_rid", "drain_started", "migrated",
    )

    def __init__(self, name: str, engine: ServeEngine, now: float, cooldown: float):
        self.name = name
        self.engine = engine
        self.alive = True  # False once killed or drain-removed
        self.removed = False  # drained out (vs died)
        self.draining = False
        self.last_beat = now
        self.stall_steps = 0  # injected: skip this many step() calls
        self.consec_failures = 0
        self.breaker = "closed"  # closed | open | half_open
        self.breaker_until = 0.0
        self.cooldown = cooldown
        self.probe_rid: int | None = None  # the half-open probe request
        self.drain_started: float | None = None
        self.migrated = 0  # queued requests moved off during drain


class _Record:
    """The router's request of record — survives its replica."""

    __slots__ = (
        "rid", "prompt", "max_new", "kwargs", "tenant", "token", "trace",
        "status", "replica", "engine_rid", "retries", "not_before",
        "affinity", "arrival",
    )

    def __init__(self, rid, prompt, max_new, kwargs, tenant, token, affinity, now):
        self.rid = rid
        self.prompt = prompt
        self.max_new = int(max_new)
        self.kwargs = kwargs  # submit passthrough (deadline_s, priority, ...)
        self.tenant = tenant
        self.token = token
        # one trace id for the request's WHOLE life: the token rotates on
        # failover (.fN suffixes) but the trace never does, so every
        # placement attempt links into a single causal trace
        self.trace = f"tr-{rid}"
        self.status: str | None = None  # router-terminal, else None
        self.replica: str | None = None  # current assignment
        self.engine_rid: int | None = None
        self.retries = 0  # failure-driven resubmits (bounded; migrations free)
        self.not_before = now  # backoff gate for the next placement
        self.affinity = affinity  # deepest stable prefix key, or None
        self.arrival = now


class Router:
    """Front door over N in-process ``ServeEngine`` replicas (module
    docstring). Replicas must be homogeneous enough to serve any request
    (same model/tokenizer); block geometry is read from the first."""

    def __init__(
        self,
        replicas: Iterable[ServeEngine],
        *,
        clock: Callable[[], float] = time.perf_counter,
        heartbeat_timeout_s: float = 1.0,
        max_retries: int = 2,
        backoff_base_s: float = 0.05,
        breaker_threshold: int = 3,
        breaker_cooldown_s: float = 1.0,
        drr_quantum: int | None = None,
        run_dir: Any = None,
    ):
        engines = list(replicas)
        if not engines:
            raise ValueError("a router needs at least one replica")
        if heartbeat_timeout_s <= 0:
            raise ValueError(f"heartbeat_timeout_s must be > 0, got {heartbeat_timeout_s}")
        if max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got {max_retries}")
        if breaker_threshold < 1:
            raise ValueError(f"breaker_threshold must be >= 1, got {breaker_threshold}")
        self.clock = clock
        now = clock()
        self.replicas: dict[str, _Replica] = {}
        for i, eng in enumerate(engines):
            self.replicas[f"r{i}"] = _Replica(f"r{i}", eng, now, float(breaker_cooldown_s))
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.max_retries = int(max_retries)
        self.backoff_base_s = float(backoff_base_s)
        self.breaker_threshold = int(breaker_threshold)
        self.run_dir = run_dir
        pool = engines[0].pool
        self.drr_quantum = int(
            drr_quantum if drr_quantum is not None
            else max(1, pool.blocks_for(engines[0].scheduler.prefill_chunk))
        )
        self._block_size = pool.block_size
        self._blocks_for = pool.blocks_for
        self._next_id = 0
        self._records: dict[int, _Record] = {}
        # placement state: per-tenant FIFO queues of unplaced records, the
        # DRR ring of tenants with pending work, their block-credit
        # deficits, and the affinity hint table (stable prefix key -> the
        # replica that served that template last)
        self._queues: dict[str, collections.deque[_Record]] = {}
        self._ring: collections.deque[str] = collections.deque()
        self._deficit: dict[str, float] = {}
        self._affinity: dict[tuple[int, int], str] = {}
        #: chaos hook: ``fn("router_step", None)`` each step — may kill or
        #: stall replicas (serve/chaos.py attach_router)
        self.fault_injector: Callable[[str, Any], None] | None = None
        self.steps = 0
        #: failure-handling counters (the receipt's observables)
        self.failovers = 0
        self.kills = 0

    # -- submission -----------------------------------------------------------
    def submit(
        self,
        prompt,
        max_new_tokens: int = 32,
        *,
        tenant: str | None = None,
        token: str | None = None,
        **kwargs: Any,
    ) -> int:
        """Queue one request router-wide; returns its ROUTER id (replica
        ids are an implementation detail). Placement happens in
        :meth:`step` under the per-tenant DRR. ``token`` is an optional
        caller idempotency token (defaults to a router-generated one);
        the rest of the kwargs pass through to ``ServeEngine.submit``."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        now = self.clock()
        rid = self._next_id
        self._next_id += 1
        resolved_tenant = tenant if tenant is not None else (kwargs.get("adapter") or "")
        keys = prefix_keys(prompt, self._block_size)
        rec = _Record(
            rid, prompt, max_new_tokens,
            dict(kwargs, tenant=resolved_tenant),
            resolved_tenant, token if token is not None else f"rt-{rid}",
            keys[-1] if keys else None, now,
        )
        self._records[rid] = rec
        self._enqueue(rec)
        return rid

    def _enqueue(self, rec: _Record) -> None:
        q = self._queues.get(rec.tenant)
        if q is None:
            q = self._queues[rec.tenant] = collections.deque()
        if not q and rec.tenant not in self._ring:
            self._ring.append(rec.tenant)
            self._deficit.setdefault(rec.tenant, 0.0)
        q.append(rec)

    def _requeue_front(self, recs: list[_Record]) -> None:
        """Put failed-over records back at the FRONT of their tenant
        queues, oldest last-in — per-tenant FIFO by arrival survives the
        round trip through a dead replica."""
        for rec in sorted(recs, key=lambda r: r.rid, reverse=True):
            q = self._queues.get(rec.tenant)
            if q is None:
                q = self._queues[rec.tenant] = collections.deque()
            if not q and rec.tenant not in self._ring:
                self._ring.append(rec.tenant)
                self._deficit.setdefault(rec.tenant, 0.0)
            q.appendleft(rec)

    # -- status surface -------------------------------------------------------
    def status(self, rid: int) -> str:
        """``queued`` / ``running`` while live (backoff and re-placement
        included), else the ONE router-wide terminal status."""
        rec = self._records.get(rid)
        if rec is None:
            raise KeyError(f"unknown router request id {rid}")
        if rec.status is not None:
            return rec.status
        if rec.replica is not None:
            rep = self.replicas[rec.replica]
            try:
                return rep.engine.status(rec.engine_rid)
            except KeyError:
                return "queued"
        return "queued"

    def statuses(self) -> dict[int, str]:
        return {rid: self.status(rid) for rid in self._records}

    def output(self, rid: int) -> np.ndarray:
        """The emitted tokens of a request that finished ``ok`` — read
        from whichever replica completed it."""
        rec = self._records[rid]
        if rec.replica is None or rec.engine_rid is None:
            raise KeyError(f"request {rid} has no completed output")
        return self.replicas[rec.replica].engine.output(rec.engine_rid)

    def cancel(self, rid: int) -> bool:
        """Cancel router-wide: forwarded to the owning replica when
        placed, locally terminal when still queued."""
        rec = self._records.get(rid)
        if rec is None or rec.status is not None:
            return False
        if rec.replica is not None:
            rep = self.replicas[rec.replica]
            if rep.alive and rep.engine.cancel(rec.engine_rid):
                rec.status = "cancelled"
                return True
            return False
        self._discard_queued(rec)
        rec.status = "cancelled"
        return True

    def _discard_queued(self, rec: _Record) -> None:
        q = self._queues.get(rec.tenant)
        if q is not None and rec in q:
            q.remove(rec)
            if not q:
                self._queues.pop(rec.tenant, None)
                self._deficit.pop(rec.tenant, None)
                if rec.tenant in self._ring:
                    self._ring.remove(rec.tenant)

    @property
    def idle(self) -> bool:
        """Every submitted request terminal and nothing pending."""
        return all(rec.status is not None for rec in self._records.values())

    def healthy(self) -> dict[str, bool]:
        """Per-replica health as the failure detector currently sees it."""
        now = self.clock()
        return {
            name: rep.alive and not rep.removed
            and (now - rep.last_beat) <= self.heartbeat_timeout_s
            for name, rep in self.replicas.items()
        }

    def leaked_blocks(self) -> int:
        """Sum of every replica's leak observable (killed replicas were
        reaped at kill time, so they audit too)."""
        return sum(rep.engine.leaked_blocks() for rep in self.replicas.values())

    # -- chaos / operator controls -------------------------------------------
    def kill_replica(self, name: str, reason: str = "killed") -> None:
        """Simulate replica death: never stepped again, live requests
        failed over, the in-process engine reaped (its live sequences
        cancelled so the pool audit stays meaningful — the stand-in for
        the OS reclaiming a dead process's memory)."""
        rep = self.replicas[name]
        if not rep.alive:
            return
        self._fail_replica(rep, f"killed: {reason}", fatal=True)

    def stall_replica(self, name: str, steps: int) -> None:
        """Simulate a stalled replica: it misses the next ``steps`` step
        calls. Whether that is a blip or a death is the heartbeat
        detector's call, exactly as in production."""
        rep = self.replicas[name]
        if rep.alive:
            rep.stall_steps = max(rep.stall_steps, int(steps))

    def drain_replica(self, name: str, reason: str = "drain requested") -> None:
        """Begin the graceful exit of one replica: no new placements,
        queued requests migrate to siblings now (they hold nothing),
        running requests finish in place; :meth:`step` removes the
        replica once it empties and writes the requeue verdict."""
        rep = self.replicas[name]
        if not rep.alive or rep.draining:
            return
        rep.draining = True
        rep.drain_started = self.clock()
        migrated = []
        for rec in self._records.values():
            if rec.status is not None or rec.replica != name:
                continue
            try:
                st = rep.engine.status(rec.engine_rid)
            except KeyError:
                st = None
            if st == "queued":
                # a queued request holds nothing: cancel it out of the
                # draining replica's queue and re-place it on a sibling.
                # Detach FIRST so the terminal sync never mistakes the
                # migration cancel for a real terminal status. A
                # migration is not a failure retry: no backoff, no
                # budget spent, but a fresh token (the old one stays
                # burned in the draining engine's dedup map).
                erid = rec.engine_rid
                rec.replica = None
                rec.engine_rid = None
                rec.token = f"{rec.token}.m"
                rep.engine.cancel(erid)
                migrated.append(rec)
        self._requeue_front(migrated)
        rep.migrated = len(migrated)

    # -- failure handling -----------------------------------------------------
    def _fail_replica(self, rep: _Replica, reason: str, *, fatal: bool) -> None:
        """Handle one replica failure. ``fatal`` (a kill): the replica is
        never stepped again and its engine is reaped — every live
        sequence cancelled so the pool audit stays meaningful (the
        stand-in for the OS reclaiming a dead process). Transient (a
        ``step()`` raise, a missed heartbeat): the replica stays in the
        pool under circuit-breaker control. Either way its live requests
        re-route with bounded retries and exponential backoff."""
        now = self.clock()
        rep.consec_failures += 1
        if fatal:
            rep.alive = False
            self.kills += 1
        elif rep.breaker == "half_open":
            rep.cooldown *= 2.0  # failed its probe: back off harder
            rep.breaker = "open"
            rep.breaker_until = now + rep.cooldown
            rep.probe_rid = None
        elif rep.breaker == "closed" and rep.consec_failures >= self.breaker_threshold:
            rep.breaker = "open"
            rep.breaker_until = now + rep.cooldown
        failed: list[_Record] = []
        for rec in self._records.values():
            if rec.status is not None or rec.replica != rep.name:
                continue
            try:
                st = rep.engine.status(rec.engine_rid)
            except KeyError:
                st = None
            if st in TERMINAL_STATUSES:
                rec.status = st  # finished before the failure: keep it
                continue
            failed.append(rec)
        retry: list[_Record] = []
        for rec in failed:
            erid = rec.engine_rid
            rec.replica = None
            rec.engine_rid = None
            if not fatal:
                # the replica survives: pull the re-routed request out of
                # it so it cannot burn slots on (or double-complete) work
                # that now belongs to a sibling. The old admission is now
                # DEFINITIVELY cancelled, so the retry gets a fresh token;
                # after a fatal kill the token stays — if the "dead"
                # replica ever sees the retry, dedup re-attaches instead
                # of double-admitting (the at-most-once guard).
                rep.engine.cancel(erid)
                rec.token = f"{rec.token}.f{rec.retries + 1}"
            rec.retries += 1
            if rec.retries > self.max_retries:
                rec.status = "error"
                journal.emit(
                    "failover", now, label=f"req{rec.rid}", request=rec.rid,
                    trace=rec.trace, replica=rep.name,
                    outcome="retries_exhausted",
                )
                # the router-side terminal: stamp the trace the same way
                # the engine's fault path does, so linked_trace_report
                # surfaces the status even when no engine ever erred
                journal.emit(
                    "fault", now, label=f"req{rec.rid}", request=rec.rid,
                    trace=rec.trace, status="error",
                    reason="retries_exhausted",
                )
                continue
            rec.not_before = now + self.backoff_base_s * (2.0 ** (rec.retries - 1))
            self.failovers += 1
            journal.emit(
                "failover", now, label=f"req{rec.rid}", request=rec.rid,
                trace=rec.trace, replica=rep.name, retry=rec.retries,
                reason=reason,
            )
            retry.append(rec)
        self._requeue_front(retry)
        if fatal:
            # reap the in-process engine: cancel everything still live so
            # its pools release (otherwise "dead" pages leak forever)
            for erid, st in list(rep.engine.statuses().items()):
                if st in ("queued", "running"):
                    rep.engine.cancel(erid)

    # -- placement ------------------------------------------------------------
    def _placeable(self, rep: _Replica, now: float) -> bool:
        if not rep.alive or rep.removed or rep.draining or rep.stall_steps > 0:
            return False
        if (now - rep.last_beat) > self.heartbeat_timeout_s:
            return False
        if rep.breaker == "open":
            if now < rep.breaker_until:
                return False
            rep.breaker = "half_open"  # cooldown over: risk one probe
            rep.probe_rid = None
        if rep.breaker == "half_open" and rep.probe_rid is not None:
            return False  # one probe at a time
        return True

    def _outstanding(self, name: str) -> int:
        return sum(
            1 for rec in self._records.values()
            if rec.status is None and rec.replica == name
        )

    def _choose_replica(self, rec: _Record, now: float) -> _Replica | None:
        """Affinity first, then least-outstanding among placeable
        replicas (ties: replica order — deterministic)."""
        if rec.affinity is not None:
            hint = self._affinity.get((rec.kwargs.get("adapter") or "", rec.affinity))
            if hint is not None:
                rep = self.replicas.get(hint)
                if rep is not None and self._placeable(rep, now):
                    return rep
        best = None
        best_load = None
        for rep in self.replicas.values():
            if not self._placeable(rep, now):
                continue
            load = self._outstanding(rep.name)
            if best_load is None or load < best_load:
                best, best_load = rep, load
        return best

    def _place(self, rec: _Record, rep: _Replica, now: float) -> None:
        try:
            rec.engine_rid = rep.engine.submit(
                rec.prompt, rec.max_new, token=rec.token, trace=rec.trace,
                **rec.kwargs
            )
        except DuplicateRequest as dup:
            # the ambiguous-failure window: the "failed" submit actually
            # landed — re-attach, never double-admit
            rec.engine_rid = dup.rid
        rec.replica = rep.name
        if rec.affinity is not None:
            self._affinity[(rec.kwargs.get("adapter") or "", rec.affinity)] = rep.name
        if rep.breaker == "half_open" and rep.probe_rid is None:
            rep.probe_rid = rec.rid
        journal.emit(
            "route", now, label=f"req{rec.rid}", request=rec.rid,
            trace=rec.trace, replica=rep.name, tenant=rec.tenant,
            retry=rec.retries,
        )

    def _place_pending(self, now: float) -> None:
        """Per-tenant DRR over the pending queues: visit the ring head,
        place its FIFO head while its deficit covers the request's block
        reservation, else grant a quantum and rotate. Stops when no
        replica is placeable or every queue is empty/backing off."""
        if not any(self._placeable(rep, now) for rep in self.replicas.values()):
            return
        rotations = 0
        while self._ring and rotations <= len(self._ring):
            tenant = self._ring[0]
            q = self._queues.get(tenant)
            if not q:
                self._queues.pop(tenant, None)
                self._deficit.pop(tenant, None)
                self._ring.popleft()
                rotations = 0
                continue
            head = q[0]
            if head.status is not None:  # cancelled while queued
                q.popleft()
                continue
            if head.not_before > now:  # backoff: sticky head, try later
                self._ring.rotate(-1)
                rotations += 1
                continue
            need = self._blocks_for(len(head.prompt) + head.max_new)
            if self._deficit[tenant] >= need:
                rep = self._choose_replica(head, now)
                if rep is None:
                    return  # nowhere to place anything right now
                q.popleft()
                self._deficit[tenant] -= need
                if not q:
                    self._queues.pop(tenant, None)
                    self._deficit.pop(tenant, None)
                    self._ring.remove(tenant)
                self._place(head, rep, now)
                rotations = 0
                continue
            self._deficit[tenant] += self.drr_quantum
            self._ring.rotate(-1)
            rotations += 1

    # -- the routing loop -----------------------------------------------------
    def step(self) -> bool:
        """One router iteration: chaos hook, step every live replica
        (heartbeats advance on success; raises and missed beats fail the
        replica over), sync terminal statuses, finish drains, place
        pending work. Returns whether any replica did device work."""
        self.steps += 1
        now = self.clock()
        if self.fault_injector is not None:
            self.fault_injector("router_step", None)
        did = False
        for rep in self.replicas.values():
            if not rep.alive or rep.removed:
                continue
            if rep.stall_steps > 0:
                rep.stall_steps -= 1  # stalled: no step, no heartbeat
                continue
            try:
                did = rep.engine.step() or did
                rep.last_beat = self.clock()
            except Exception as exc:  # noqa: BLE001 — a replica crash is survivable
                # unhealthy, not (necessarily) dead: requests re-route,
                # the breaker decides when to trust it with work again
                self._fail_replica(
                    rep, f"step raised {type(exc).__name__}: {exc}", fatal=False
                )
                rep.last_beat = self.clock()  # re-arm the detector
        now = self.clock()
        for rep in self.replicas.values():
            if not rep.alive or rep.removed:
                continue
            if (now - rep.last_beat) > self.heartbeat_timeout_s:
                # missed its heartbeat deadline: mark unhealthy, re-route
                # its live requests, re-arm — if it revives, the breaker
                # gates its way back; if not, it just stays empty
                self._fail_replica(rep, "missed heartbeat", fatal=False)
                rep.last_beat = now
        self._sync_terminals()
        self._finish_drains(now)
        self._place_pending(now)
        return did

    def _sync_terminals(self) -> None:
        for rec in self._records.values():
            if rec.status is not None or rec.replica is None:
                continue
            rep = self.replicas[rec.replica]
            try:
                st = rep.engine.status(rec.engine_rid)
            except KeyError:
                continue
            if st in TERMINAL_STATUSES:
                rec.status = st
                if rep.probe_rid == rec.rid:
                    rep.probe_rid = None
                    if st == "ok":  # the probe survived: close the breaker
                        rep.breaker = "closed"
                        rep.consec_failures = 0
                elif st == "ok" and rep.breaker == "closed":
                    rep.consec_failures = 0

    def _finish_drains(self, now: float) -> None:
        for rep in self.replicas.values():
            if not rep.draining or rep.removed or not rep.alive:
                continue
            live = self._outstanding(rep.name)
            if live == 0 and rep.engine.idle:
                rep.removed = True
                rep.alive = False
                journal.emit(
                    "replica_drain", rep.drain_started, now, label=rep.name,
                    replica=rep.name, migrated=rep.migrated,
                )
                if self.run_dir is not None:
                    from ..checkpoint import write_requeue_verdict

                    write_requeue_verdict(
                        self.run_dir, False, f"replica {rep.name} drained",
                        "completed",
                        serve={
                            "replica": rep.name,
                            "drain_s": round(now - rep.drain_started, 6),
                            "migrated": rep.migrated,
                            "statuses": rep.engine.ledger.status_counts(),
                            "drained_clean": True,
                        },
                    )

    def run(self, max_steps: int | None = None) -> dict[int, np.ndarray]:
        """Drive :meth:`step` until every request is terminal (or
        ``max_steps``); returns the ``ok`` outputs by router id."""
        steps = 0
        while not self.idle:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return {
            rid: self.output(rid)
            for rid, rec in self._records.items()
            if rec.status == "ok"
        }

    def serve_trace(self, trace, clock=None, sleep=time.sleep) -> dict:
        """Replay a timed trace against the whole pool (same shape as
        ``ServeEngine.serve_trace``: ``(offset_s, prompt, max_new[,
        kwargs])``); returns :meth:`summary`."""
        if clock is None:
            clock = self.clock
        pending = sorted(trace, key=lambda e: e[0])
        t0 = clock()
        i = 0
        while i < len(pending) or not self.idle:
            now = clock() - t0
            while i < len(pending) and pending[i][0] <= now:
                off, prompt, max_new, *rest = pending[i]
                kw = {}
                if rest:
                    kw = dict(rest[0]) if isinstance(rest[0], dict) else {"adapter": rest[0]}
                self.submit(prompt, max_new, **kw)
                i += 1
            if not self.step() and i < len(pending):
                sleep(min(max(pending[i][0] - (clock() - t0), 0.0), 0.001))
        return self.summary()

    # -- observability --------------------------------------------------------
    def ttfts(self, tenant: str | None = None) -> list[float]:
        """ROUTER-level TTFT samples: router arrival -> first token on
        whichever replica finally produced it, so a failover's re-prefill
        and backoff are inside the number (an engine's own ledger restarts
        the clock at resubmission — honest for the replica, not for the
        client). Requires the replicas to share the router's clock, which
        is how :class:`Router` is meant to be wired."""
        out: list[float] = []
        for rec in self._records.values():
            if rec.replica is None or rec.engine_rid is None:
                continue
            if tenant is not None and rec.tenant != tenant:
                continue
            erec = self.replicas[rec.replica].engine.ledger.records.get(rec.engine_rid)
            if erec is not None and "first_token" in erec:
                out.append(erec["first_token"] - rec.arrival)
        return out

    def summary(self) -> dict:
        """The router scorecard: terminal census router-wide, failure
        handling counters, and per-replica health/breaker state."""
        census: dict[str, int] = {}
        for rec in self._records.values():
            key = rec.status if rec.status is not None else "live"
            census[key] = census.get(key, 0) + 1
        return {
            "requests": len(self._records),
            "statuses": census,
            "failovers": self.failovers,
            "kills": self.kills,
            "steps": self.steps,
            "replicas": {
                name: {
                    "alive": rep.alive,
                    "removed": rep.removed,
                    "draining": rep.draining,
                    "breaker": rep.breaker,
                    "consec_failures": rep.consec_failures,
                    "outstanding": self._outstanding(name),
                }
                for name, rep in self.replicas.items()
            },
        }

    def metrics_text(self) -> str:
        """One Prometheus page for the whole pool: every replica's
        registry snapshot (gauges refreshed) merged under a ``replica``
        label, plus the router's own failure-handling series
        (``dml_router_failovers_total`` / ``dml_router_kills_total`` /
        ``dml_router_pending_requests`` and a per-replica
        ``dml_router_breaker_state`` gauge: 0=closed, 1=half_open,
        2=open). Families keep ONE ``# HELP``/``# TYPE`` header across
        replicas — the page parses as a single valid exposition. Replicas
        constructed without ``metrics=`` simply contribute nothing."""
        from ..telemetry.metrics_registry import MetricsRegistry, to_prometheus_text

        reg = MetricsRegistry()
        reg.counter("dml_router_failovers_total",
                    "failure-driven resubmissions").inc(self.failovers)
        reg.counter("dml_router_kills_total",
                    "replicas declared dead").inc(self.kills)
        reg.gauge("dml_router_pending_requests",
                  "records awaiting placement").set(
            sum(len(q) for q in self._queues.values()))
        breaker = reg.gauge(
            "dml_router_breaker_state",
            "per-replica circuit breaker (0=closed, 1=half_open, 2=open)",
            labels=("replica",), max_series=len(self.replicas) + 1)
        state_code = {"closed": 0, "half_open": 1, "open": 2}
        for name, rep in self.replicas.items():
            breaker.labels(replica=name).set(state_code[rep.breaker])
        pages: list = [reg.snapshot()]
        for name, rep in self.replicas.items():
            snap = rep.engine.metrics_snapshot()
            if snap is not None:
                pages.append((snap, {"replica": name}))
        return to_prometheus_text(*pages)
